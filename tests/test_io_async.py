"""Async IO subsystem (ISSUE 4): readahead, coalescing, memcache, work stealing.

The acceptance contracts pinned here:

- readahead/coalesce deliver BYTE-IDENTICAL results to the synchronous path,
  on every pool type;
- each feature is independently disableable, and fallbacks (cancelled reads,
  failed pool construction) engage the degradation log instead of failing or
  silently changing behavior;
- checkpoint resume (``state_dict``/``load_state_dict``) stays exact under
  work stealing — at-least-once delivery at row-group granularity;
- a failed background read surfaces the SAME exception budgeted the SAME way
  as the synchronous path (covered in tests/test_io_retry.py).
"""
import time

import numpy as np
import pytest

from petastorm_tpu.io import IoOptions
from petastorm_tpu.io.coalesce import plan_runs, split_run_table
from petastorm_tpu.io.memcache import MemCache, payload_nbytes, shared_store
from petastorm_tpu.io.readahead import ReadaheadPool
from petastorm_tpu.obs.log import degradation_counts
from petastorm_tpu.reader import make_batch_reader
from petastorm_tpu.workers import PullDispatcher


# -- fixtures ---------------------------------------------------------------------------


@pytest.fixture()
def parquet_store(tmp_path):
    """Two files × 8 row groups × 5 rows, with an id and a payload column."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    d = tmp_path / "store"
    d.mkdir()
    for f in range(2):
        base = f * 40
        ids = np.arange(base, base + 40, dtype=np.int64)
        pq.write_table(
            pa.table({"id": ids, "payload": [bytes([i % 251]) * 64 for i in ids]}),
            str(d / ("part-%d.parquet" % f)), row_group_size=5)
    return str(d)


def _drain_ids(reader):
    return np.concatenate([np.asarray(b.id) for b in reader])


class _FakePiece:
    def __init__(self, path, row_group):
        self.path = path
        self.row_group = row_group


# -- IoOptions --------------------------------------------------------------------------


def test_io_options_defaults_and_env(monkeypatch):
    opts = IoOptions()
    assert opts.readahead and opts.coalesce and opts.work_stealing
    assert opts.readahead_depth == 3 and opts.memcache_bytes == 0
    assert opts.lookahead == 3
    monkeypatch.setenv("PTPU_READAHEAD", "0")
    monkeypatch.setenv("PTPU_MEMCACHE_BYTES", "1048576")
    opts = IoOptions()
    assert not opts.readahead and opts.lookahead == 0
    assert opts.memcache_bytes == 1 << 20
    # explicit kwargs beat the env
    assert IoOptions(readahead=True).readahead


def test_io_options_normalize_and_pickle():
    import pickle

    assert IoOptions.normalize(None).readahead
    opts = IoOptions.normalize({"readahead_depth": 7, "work_stealing": False})
    assert opts.readahead_depth == 7 and not opts.work_stealing
    assert IoOptions.normalize(opts) is opts
    clone = pickle.loads(pickle.dumps(opts))
    assert clone.readahead_depth == 7 and not clone.work_stealing
    with pytest.raises(TypeError):
        IoOptions.normalize("fast")


# -- coalesce planning ------------------------------------------------------------------


def test_plan_runs_merges_adjacent_same_file():
    pieces = [_FakePiece("a", i) for i in (0, 1, 2)]
    runs = plan_runs([(p, ("x",)) for p in pieces])
    assert len(runs) == 1
    assert [p.row_group for p in runs[0][0]] == [0, 1, 2]


def test_plan_runs_splits_on_gap_file_and_columns():
    reqs = [
        (_FakePiece("a", 0), ("x",)),
        (_FakePiece("a", 2), ("x",)),   # gap
        (_FakePiece("b", 3), ("x",)),   # other file
        (_FakePiece("a", 3), ("y",)),   # other columns (adjacent to a:2)
    ]
    runs = plan_runs(reqs)
    assert [len(r[0]) for r in runs] == [1, 1, 1, 1]


def test_plan_runs_caps_run_length():
    pieces = [_FakePiece("a", i) for i in range(7)]
    runs = plan_runs([(p, None) for p in pieces], max_run=3)
    assert [len(r[0]) for r in runs] == [3, 3, 1]


def test_split_run_table_roundtrip():
    import pyarrow as pa

    table = pa.table({"v": list(range(10))})
    parts = split_run_table(table, [3, 5, 2])
    assert [p.num_rows for p in parts] == [3, 5, 2]
    assert parts[1].column("v").to_pylist() == [3, 4, 5, 6, 7]
    with pytest.raises(ValueError):
        split_run_table(table, [3, 3])


# -- ReadaheadPool unit contracts -------------------------------------------------------


def _table(tag, nbytes=0):
    class T:
        pass

    t = T()
    t.tag = tag
    t.nbytes = nbytes
    return t


def test_readahead_hit_and_miss_counters():
    reads = []

    def read_fn(piece, columns):
        reads.append(piece.row_group)
        return _table(piece.row_group)

    pool = ReadaheadPool(read_fn, depth=4)
    try:
        p0, p1 = _FakePiece("a", 0), _FakePiece("a", 1)
        assert pool.schedule([(p0, None), (p1, None)]) == 2
        assert pool.get(p0, None).tag == 0
        assert pool.get(p1, None).tag == 1
        assert pool.get(_FakePiece("a", 9), None) is None  # never scheduled
        stats = pool.stats()
        assert stats["readahead_hits"] >= 2
        assert sorted(reads) == [0, 1]
    finally:
        pool.shutdown()


def test_readahead_dedups_repeat_hints():
    calls = []

    def read_fn(piece, columns):
        calls.append(piece.row_group)
        return _table(piece.row_group)

    pool = ReadaheadPool(read_fn, depth=8)
    try:
        p = _FakePiece("a", 0)
        pool.schedule([(p, None)])
        assert pool.get(p, None) is not None
        # re-hinting the SAME key after consumption schedules a fresh read;
        # re-hinting while queued must not
        pool.schedule([(p, None), (p, None)])
        assert pool.get(p, None) is not None
        assert calls == [0, 0]
    finally:
        pool.shutdown()


def test_readahead_depth_bound():
    import threading

    release = threading.Event()

    def read_fn(piece, columns):
        release.wait(10)
        return _table(piece.row_group)

    pool = ReadaheadPool(read_fn, depth=2, io_threads=1)
    try:
        reqs = [(_FakePiece("a", i), None) for i in range(5)]
        assert pool.schedule(reqs) == 2  # capacity-capped
        assert pool.schedule(reqs[2:]) == 0  # still full
    finally:
        release.set()
        pool.shutdown()


def test_readahead_error_reraised_at_get():
    def read_fn(piece, columns):
        raise ConnectionResetError("flaky object store")

    pool = ReadaheadPool(read_fn, depth=2)
    try:
        p = _FakePiece("a", 0)
        pool.schedule([(p, None)])
        with pytest.raises(ConnectionResetError):
            pool.get(p, None)
    finally:
        pool.shutdown()


def test_readahead_shutdown_cancels_to_sync_fallback():
    import threading

    started = threading.Event()
    release = threading.Event()

    def read_fn(piece, columns):
        started.set()
        release.wait(10)
        return _table(piece.row_group)

    pool = ReadaheadPool(read_fn, depth=2, io_threads=1)
    try:
        p = _FakePiece("a", 0)
        pool.schedule([(p, None)])
        started.wait(5)
        before = degradation_counts().get("readahead_fallback", 0)
        pool.shutdown()
        release.set()
        assert pool.get(p, None) is None  # cancelled: caller reads synchronously
        # entry was cleared by shutdown → miss, not a degradation; scheduling
        # after shutdown is a no-op
        assert pool.schedule([(p, None)]) == 0
        assert degradation_counts().get("readahead_fallback", 0) >= before
    finally:
        pool.shutdown()


def test_readahead_byte_budget_evicts_oldest():
    def read_fn(piece, columns):
        return _table(piece.row_group, nbytes=600)

    pool = ReadaheadPool(read_fn, depth=8, byte_budget=1000)
    try:
        pieces = [_FakePiece("a", i) for i in range(3)]
        pool.schedule([(p, None) for p in pieces])
        deadline = time.time() + 5
        while pool.stats()["readahead_pending"] and time.time() < deadline:
            time.sleep(0.01)
        stats = pool.stats()
        assert stats["readahead_evictions"] >= 1
        assert stats["readahead_held_bytes"] <= 1000
    finally:
        pool.shutdown()


def test_readahead_coalesces_adjacent_reads():
    run_lengths = []

    def read_fn(piece, columns):
        run_lengths.append(1)
        return _table(piece.row_group)

    def read_run_fn(pieces, columns):
        run_lengths.append(len(pieces))
        return [_table(p.row_group) for p in pieces]

    pool = ReadaheadPool(read_fn, read_run_fn=read_run_fn, depth=8,
                         coalesce=True, coalesce_max_run=4)
    try:
        pieces = [_FakePiece("a", i) for i in range(3)]
        pool.schedule([(p, None) for p in pieces])
        for p in pieces:
            assert pool.get(p, None).tag == p.row_group
        assert run_lengths == [3]
        assert pool.stats()["coalesced_reads"] == 1
        assert pool.stats()["coalesced_items"] == 3
    finally:
        pool.shutdown()


# -- MemCache ---------------------------------------------------------------------------


def test_memcache_hit_skips_fill_and_serves_readonly_views():
    """Lease contract (ISSUE 6): hits AND the admit-path return are zero-copy
    READ-ONLY views of the stored entry — a mutating consumer fails loud
    (ValueError) instead of silently poisoning later epochs' hits, and the
    per-hit memcpy of the old defensive-copy contract is gone."""
    shared_store().clear()
    cache = MemCache(1 << 20)
    try:
        fills = []

        def fill():
            fills.append(1)
            return {"x": np.arange(8, dtype=np.int64)}

        first = cache.get("k1", fill)
        second = cache.get("k1", fill)
        assert len(fills) == 1
        np.testing.assert_array_equal(first["x"], second["x"])
        assert not first["x"].flags.writeable
        assert not second["x"].flags.writeable
        with pytest.raises(ValueError):
            second["x"][:] = -1  # fail-loud, never cache poisoning
        # fresh CONTAINERS per serve: key removal stays consumer-local
        second.pop("x")
        third = cache.get("k1", fill)
        np.testing.assert_array_equal(third["x"], np.arange(8))
        assert len(fills) == 1
        assert cache.contains("k1") and not cache.contains("k2")
    finally:
        cache.clear()


def test_memcache_get_writable_is_cow_and_never_aliases_store():
    """get_writable is the copy-on-write escalation (host TransformSpec): an
    owned writable deep copy on BOTH the miss and the hit path, never aliasing
    the read-only entry other consumers' views share."""
    shared_store().clear()
    cache = MemCache(1 << 20)
    try:
        fills = []

        def fill():
            fills.append(1)
            return {"x": np.arange(8, dtype=np.int64)}

        first = cache.get_writable("k1", fill)  # miss path
        assert first["x"].flags.writeable
        first["x"][:] = -1
        second = cache.get_writable("k1", fill)  # hit path
        assert len(fills) == 1
        assert second["x"].flags.writeable
        np.testing.assert_array_equal(second["x"], np.arange(8))
        second["x"][:] = -2
        np.testing.assert_array_equal(cache.get("k1", fill)["x"], np.arange(8))
    finally:
        cache.clear()


def test_memcache_writable_hits_restores_legacy_copy_contract():
    """writable_hits=True is the copying baseline `petastorm-tpu-bench copies`
    measures against: every serve is an owned writable deep copy."""
    shared_store().clear()
    cache = MemCache(1 << 20, writable_hits=True)
    try:
        fill = lambda: {"x": np.arange(4, dtype=np.int64)}  # noqa: E731
        first = cache.get("k", fill)
        assert first["x"].flags.writeable
        first["x"][:] = -1
        second = cache.get("k", fill)
        assert second["x"].flags.writeable
        np.testing.assert_array_equal(second["x"], np.arange(4))
    finally:
        cache.clear()


def test_memcache_object_dtype_elements_readonly_and_cow_not_aliased():
    """Ragged columns decode to object-dtype arrays whose ELEMENTS are
    ndarrays. Served views freeze the elements too (an element write fails
    loud), and the get_writable escalation deep-copies them — a shallow outer
    copy would leave the element arrays aliased to the store."""
    shared_store().clear()
    cache = MemCache(1 << 20)
    try:
        def fill():
            col = np.empty(2, dtype=object)
            col[0] = np.zeros((2, 2), np.float32)
            col[1] = np.zeros((3, 2), np.float32)
            return {"ragged": col}

        first = cache.get("k", fill)
        with pytest.raises(ValueError):
            first["ragged"][0][0, 0] = 777.0  # ELEMENT arrays frozen too
        # outer pointer reassignment is consumer-local (fresh outer array)
        first["ragged"][0] = None
        writable = cache.get_writable("k", fill)
        writable["ragged"][0][0, 0] = 777.0  # owned deep copy: mutable
        writable["ragged"][1][0, 0] = -5.0
        third = cache.get("k", fill)
        assert third["ragged"][0][0, 0] == 0.0  # store never poisoned
        assert third["ragged"][1][0, 0] == 0.0
    finally:
        cache.clear()


def test_readahead_zero_byte_budget_means_unbounded():
    """readahead_bytes=0 is 'no cap' (the 0-is-special convention), not 'veto
    every schedule while reporting readahead enabled'."""
    pool = ReadaheadPool(lambda piece, columns: _table(piece.row_group, nbytes=64),
                         depth=4, byte_budget=0)
    try:
        p = _FakePiece("a", 0)
        assert pool.schedule([(p, None)]) == 1
        assert pool.get(p, None).tag == 0
    finally:
        pool.shutdown()


def test_readahead_stale_read_does_not_double_count_bytes():
    """An abandoned (timed-out) read completing AFTER its key was re-scheduled
    must not fill the fresh entry a second time — held bytes would inflate
    permanently and eventually veto all scheduling."""
    import threading

    gates = [threading.Event(), threading.Event()]
    calls = []

    def read_fn(piece, columns):
        gate = gates[len(calls)]
        calls.append(piece.row_group)
        gate.wait(10)
        return _table(piece.row_group, nbytes=100)

    pool = ReadaheadPool(read_fn, depth=2, io_threads=2, byte_budget=10_000,
                         wait_timeout_s=0.05)
    try:
        p = _FakePiece("a", 0)
        pool.schedule([(p, None)])
        assert pool.get(p, None) is None  # times out: entry abandoned
        pool.schedule([(p, None)])  # re-registered; second read starts
        gates[1].set()  # fresh read completes first, fills the new entry
        deadline = time.time() + 5
        while len(calls) < 2 and time.time() < deadline:
            time.sleep(0.01)
        while pool.stats()["readahead_held_bytes"] == 0 and time.time() < deadline:
            time.sleep(0.01)
        gates[0].set()  # stale read completes into an already-filled entry
        time.sleep(0.1)
        table = pool.get(p, None)
        assert table is not None and table.nbytes == 100
        assert pool.stats()["readahead_held_bytes"] == 0  # subtracted exactly once
    finally:
        for g in gates:
            g.set()
        pool.shutdown()


def test_readahead_error_entries_age_out():
    """A failed background read whose piece is never claimed (stolen, or the
    consumer stopped) must not pin its exception forever: the entry-count cap
    sweeps completed unclaimed entries, errors included."""
    def read_fn(piece, columns):
        if piece.row_group < 2:
            raise ConnectionResetError("flap")
        return _table(piece.row_group, nbytes=1)

    pool = ReadaheadPool(read_fn, depth=2, byte_budget=1 << 20)
    try:
        # two failed reads nobody ever claims...
        pool.schedule([(_FakePiece("a", 0), None), (_FakePiece("a", 1), None)])
        deadline = time.time() + 5
        while pool.stats()["readahead_pending"] and time.time() < deadline:
            time.sleep(0.01)
        # ...then keep scheduling fresh work past the entry cap (4*depth)
        for i in range(2, 16, 2):
            pool.schedule([(_FakePiece("a", i), None),
                           (_FakePiece("a", i + 1), None)])
            deadline = time.time() + 5
            while pool.stats()["readahead_pending"] and time.time() < deadline:
                time.sleep(0.01)
        with pool._lock:
            keys = set(pool._entries)
        assert len(keys) <= max(8, 4 * 2)  # entry count bounded by the cap
        assert ("a", 0, None) not in keys  # the error entries were swept
        assert ("a", 1, None) not in keys
    finally:
        pool.shutdown()


def test_memcache_miss_path_serves_readonly_too():
    """The FIRST consumer (miss/admit path) gets the same read-only-view
    contract as a hit — a mutation there would poison the just-admitted entry
    exactly like a hit-path mutation, so it fails loud the same way."""
    shared_store().clear()
    cache = MemCache(1 << 20)
    try:
        first = cache.get("k", lambda: {"x": np.arange(4, dtype=np.int64)})
        assert not first["x"].flags.writeable
        with pytest.raises(ValueError):
            first["x"][:] = -1
        second = cache.get("k", lambda: {"x": np.zeros(4, np.int64)})
        np.testing.assert_array_equal(second["x"], np.arange(4))
    finally:
        cache.clear()


def test_memcache_budget_eviction_and_oversized():
    from petastorm_tpu.io.memcache import _Store

    # private store: the process-wide one has a raise-only budget (another
    # reader's bigger request would mask this test's tiny one)
    cache = MemCache(4096, store=_Store())
    try:
        big = {"x": np.zeros(8192, np.uint8)}  # > whole budget: skipped
        before = degradation_counts().get("memcache_oversized", 0)
        cache.get("big", lambda: big)
        assert not cache.contains("big")
        assert degradation_counts().get("memcache_oversized", 0) == before + 1
        for i in range(4):
            cache.get("k%d" % i, lambda: {"x": np.zeros(1500, np.uint8)})
        stats = cache.stats()
        assert stats["memcache_held_bytes"] <= 4096
        assert stats["memcache_evictions"] >= 2
    finally:
        cache.clear()


def test_memcache_layers_over_inner_cache():
    shared_store().clear()

    class CountingCache:
        def __init__(self):
            self.gets = 0

        def get(self, key, fill):
            self.gets += 1
            return fill()

        def contains(self, key):
            return False

        def cleanup(self):
            pass

    inner = CountingCache()
    cache = MemCache(1 << 20, inner=inner)
    try:
        cache.get("k", lambda: [1, 2])
        cache.get("k", lambda: [1, 2])
        assert inner.gets == 1  # second get never reached the inner cache
    finally:
        cache.clear()


def test_payload_nbytes_shapes():
    assert payload_nbytes(np.zeros((4, 4), np.float32)) == 64
    assert payload_nbytes(b"abcd") == 4
    assert payload_nbytes({"a": np.zeros(8, np.uint8)}) >= 8
    assert payload_nbytes([np.zeros(8, np.uint8)] * 2) >= 16


def test_memcache_rejects_nonpositive_budget():
    with pytest.raises(ValueError):
        MemCache(0)


# -- PullDispatcher ---------------------------------------------------------------------


def _tagged_plan(n):
    from petastorm_tpu.plan import EpochPlan

    return EpochPlan(list(range(n)), num_epochs=1, with_epoch=True)


def test_dispatcher_claims_in_plan_order_with_hints():
    d = PullDispatcher(_tagged_plan(6), workers_count=2, lookahead=2)
    item, upcoming = d.next(0)
    assert item[2] == 0 and [u[2] for u in upcoming] == [1, 2]
    item, upcoming = d.next(1)
    assert item[2] == 3 and [u[2] for u in upcoming] == [4, 5]


def test_dispatcher_steals_from_longest_claim_tail():
    d = PullDispatcher(_tagged_plan(4), workers_count=2, lookahead=3)
    item, upcoming = d.next(0)  # worker 0 claims 0 and holds [1, 2, 3]
    assert item[2] == 0 and len(upcoming) == 3
    # plan is exhausted: worker 1 must steal worker 0's furthest item
    item, _ = d.next(1)
    assert item[2] == 3
    assert d.steals == 1
    # worker 0 keeps its remaining claim in order
    assert d.next(0)[0][2] == 1
    assert d.next(0)[0][2] == 2
    assert d.next(0) is None and d.next(1) is None


def test_dispatcher_stealing_disableable():
    d = PullDispatcher(_tagged_plan(4), workers_count=2, lookahead=3,
                       stealing=False)
    d.next(0)  # claims everything
    assert d.next(1) is None  # starves rather than steals
    assert d.steals == 0


def test_dispatcher_zero_lookahead_is_plain_pull():
    d = PullDispatcher(_tagged_plan(3), workers_count=2, lookahead=0)
    seen = []
    while True:
        nxt = d.next(len(seen) % 2)
        if nxt is None:
            break
        item, upcoming = nxt
        assert upcoming == ()
        seen.append(item[2])
    assert seen == [0, 1, 2]
    assert d.steals == 0


# -- EpochPlan.peek ---------------------------------------------------------------------


def test_plan_peek_matches_next_without_advancing():
    from petastorm_tpu.plan import EpochPlan

    plan = EpochPlan(list("abcd"), num_epochs=2, shuffle=True, seed=3,
                     with_epoch=True)
    ahead = plan.peek(6)
    assert len(ahead) == 6
    got = [next(plan) for _ in range(6)]
    assert got == ahead  # peek crossed the epoch boundary exactly like __next__
    assert plan.peek(99) == [next(plan), next(plan)]  # truncates at exhaustion


def test_plan_peek_respects_skip():
    from petastorm_tpu.plan import EpochPlan

    plan = EpochPlan(list("abcd"), num_epochs=1, with_epoch=True,
                     skip={0: {0, 2}})
    assert [t[1] for t in plan.peek(10)] == [1, 3]
    assert [t[1] for t in plan] == [1, 3]


# -- end-to-end identity + independence of features -------------------------------------


@pytest.mark.parametrize("pool_type", ["dummy", "thread"])
def test_readahead_identity_with_sync(parquet_store, pool_type):
    url = "file://" + parquet_store
    kwargs = dict(num_epochs=1, shuffle_row_groups=False,
                  reader_pool_type=pool_type, workers_count=2)
    with make_batch_reader(url, io_options={"readahead": False,
                                            "work_stealing": False},
                           **kwargs) as r:
        baseline = _drain_ids(r)
    with make_batch_reader(url, io_options={"readahead": True,
                                            "coalesce": False}, **kwargs) as r:
        ra = _drain_ids(r)
    with make_batch_reader(url, io_options={"readahead": True,
                                            "coalesce": True}, **kwargs) as r:
        rc = _drain_ids(r)
    assert sorted(baseline.tolist()) == sorted(ra.tolist()) == sorted(rc.tolist())
    if pool_type == "dummy":  # single consumer: bit-exact ORDER too
        np.testing.assert_array_equal(baseline, ra)
        np.testing.assert_array_equal(baseline, rc)


def test_readahead_payload_bytes_identical(parquet_store):
    url = "file://" + parquet_store
    kwargs = dict(num_epochs=1, shuffle_row_groups=False,
                  reader_pool_type="dummy")
    def payloads(r):
        return [bytes(p) for b in r for p in b.payload]

    with make_batch_reader(url, io_options={"readahead": False}, **kwargs) as r:
        base = payloads(r)
    with make_batch_reader(url, io_options={"readahead": True, "coalesce": True,
                                            "readahead_depth": 6},
                           **kwargs) as r:
        coalesced = payloads(r)
    assert base == coalesced


def test_readahead_hits_and_coalesce_engage(parquet_store):
    with make_batch_reader("file://" + parquet_store, num_epochs=1,
                           shuffle_row_groups=False, reader_pool_type="dummy",
                           io_options={"readahead": True, "coalesce": True,
                                       "readahead_depth": 4}) as r:
        _drain_ids(r)
        stats = r.io_stats()
    assert stats["readahead_hits"] > 0
    assert stats["coalesced_reads"] > 0  # sequential scan: adjacency exists


def test_work_stealing_under_slow_worker(parquet_store):
    """One worker stuck on a slow piece must not strand its claimed pieces:
    peers steal them and the read completes promptly and exactly."""
    from petastorm_tpu.transform import TransformSpec

    slow = {"done": False}

    def maybe_sleep(pdf):
        if not slow["done"]:  # first row group only: one slow piece
            slow["done"] = True
            time.sleep(1.0)
        return pdf

    with make_batch_reader("file://" + parquet_store, num_epochs=1,
                           shuffle_row_groups=False, reader_pool_type="thread",
                           workers_count=4,
                           transform_spec=TransformSpec(maybe_sleep),
                           io_options={"readahead": True, "readahead_depth": 4,
                                       "work_stealing": True}) as r:
        ids = _drain_ids(r)
        stats = r.io_stats()
    assert sorted(ids.tolist()) == list(range(80))
    assert stats.get("steals", 0) >= 0  # plan-exhaustion steals are timing-dependent


def test_memcache_reepoch_serves_from_memory(parquet_store):
    shared_store().clear()
    with make_batch_reader("file://" + parquet_store, num_epochs=3,
                           shuffle_row_groups=False, reader_pool_type="dummy",
                           io_options={"memcache_bytes": 32 << 20}) as r:
        ids = _drain_ids(r)
        stats = r.io_stats()
    assert sorted(ids.tolist()) == sorted(list(range(80)) * 3)
    assert stats["memcache_hits"] >= 16  # epochs 2+3 fully served from memory
    assert stats["memcache_misses"] >= 16
    shared_store().clear()


def test_memcache_disabled_by_default(parquet_store):
    with make_batch_reader("file://" + parquet_store, num_epochs=1,
                           reader_pool_type="dummy") as r:
        _drain_ids(r)
        stats = r.io_stats()
    assert "memcache_hits" not in stats


def test_checkpoint_resume_exact_under_stealing_and_readahead(parquet_store):
    """state_dict/load_state_dict under the full async config: at-least-once
    delivery at row-group granularity — no row lost, replay only."""
    import collections

    url = "file://" + parquet_store
    kwargs = dict(num_epochs=1, shuffle_row_groups=True, seed=11,
                  reader_pool_type="thread", workers_count=3,
                  io_options={"readahead": True, "work_stealing": True})
    r1 = make_batch_reader(url, **kwargs)
    try:
        seen = []
        it = iter(r1)
        for _ in range(6):
            seen.append(np.asarray(next(it).id))
        state = r1.state_dict()
    finally:
        r1.stop()
        r1.join()
    r2 = make_batch_reader(url, **kwargs)
    r2.load_state_dict(state)
    with r2:
        rest = [np.asarray(b.id) for b in r2]
    counts = collections.Counter(np.concatenate(seen + rest).tolist())
    assert all(counts[i] >= 1 for i in range(80))  # nothing lost
    # only whole-row-group replays: every id appears 1 or 2 times
    assert set(counts.values()) <= {1, 2}


def test_reset_rebuilds_io_runtime(parquet_store):
    with make_batch_reader("file://" + parquet_store, num_epochs=1,
                           shuffle_row_groups=False,
                           reader_pool_type="dummy") as r:
        first = _drain_ids(r)
        r.reset()
        second = _drain_ids(r)
        # the post-reset pass rebuilt the IO runtime and prefetching resumed
        # (stats read INSIDE the with block: join() releases the pool)
        assert r.io_stats().get("readahead_hits", 0) > 0
    np.testing.assert_array_equal(first, second)


def test_process_pool_hints_identity(parquet_store):
    with make_batch_reader("file://" + parquet_store, num_epochs=1,
                           shuffle_row_groups=False,
                           reader_pool_type="process", workers_count=2,
                           io_options={"readahead": True,
                                       "readahead_depth": 3}) as r:
        ids = _drain_ids(r)
    assert sorted(ids.tolist()) == list(range(80))


def test_disk_cache_read_failure_degradation(tmp_path):
    from petastorm_tpu.cache import LocalDiskCache

    cache = LocalDiskCache(str(tmp_path / "cache"))
    cache.get("k", lambda: {"v": 1})
    # corrupt the entry on disk: the next get must degrade (logged + counted)
    # and refill rather than raise
    path = cache._key_path("k")
    with open(path, "wb") as f:
        f.write(b"not a pickle")
    before = degradation_counts().get("disk_cache", 0)
    assert cache.get("k", lambda: {"v": 2}) == {"v": 2}
    assert degradation_counts().get("disk_cache", 0) == before + 1


def test_disk_cache_write_failure_degradation(tmp_path, monkeypatch):
    import pickle

    from petastorm_tpu import cache as cache_mod

    cache = cache_mod.LocalDiskCache(str(tmp_path / "cache"))

    def disk_full(*a, **k):
        raise OSError(28, "No space left on device")

    real_dump = pickle.dump
    # chmod tricks don't stop root (CI containers); fail the serialize itself
    monkeypatch.setattr(cache_mod.pickle, "dump", disk_full)
    before = degradation_counts().get("disk_cache", 0)
    assert cache.get("k", lambda: 42) == 42  # value flows, uncached
    assert degradation_counts().get("disk_cache", 0) == before + 1
    monkeypatch.setattr(cache_mod.pickle, "dump", real_dump)
    assert cache.get("k", lambda: 43) == 43  # healed disk: caches again
    assert cache.contains("k")


def test_file_handle_eviction_counter(tmp_path, monkeypatch):
    import pyarrow as pa
    import pyarrow.parquet as pq

    from petastorm_tpu.obs.metrics import default_registry
    from petastorm_tpu.reader import _WorkerBase

    d = tmp_path / "many"
    d.mkdir()
    for i in range(5):
        pq.write_table(pa.table({"v": [i]}), str(d / ("f%d.parquet" % i)))
    monkeypatch.setattr(_WorkerBase, "MAX_OPEN_FILES", 2)

    import pyarrow.fs as pafs

    from petastorm_tpu.cache import NullCache

    w = _WorkerBase(pafs.LocalFileSystem(), None, None, None, None, NullCache(),
                    1, None, None, io_options={"readahead": False})
    counter = default_registry().counter("ptpu_io_file_evictions_total")
    before = counter.value
    for i in range(5):
        w._parquet_file(str(d / ("f%d.parquet" % i)))
    assert counter.value == before + 3  # 5 opens through a 2-slot LRU


def test_degradation_on_failed_pool_construction(parquet_store, monkeypatch):
    """A worker whose readahead pool cannot build degrades the feature off —
    reads proceed synchronously with a logged cause, nothing raises."""
    import petastorm_tpu.io.readahead as ra_mod

    def boom(*a, **k):
        raise RuntimeError("no threads for you")

    monkeypatch.setattr(ra_mod.ReadaheadPool, "__init__", boom)
    before = degradation_counts().get("readahead_unavailable", 0)
    with make_batch_reader("file://" + parquet_store, num_epochs=1,
                           shuffle_row_groups=False,
                           reader_pool_type="dummy") as r:
        ids = _drain_ids(r)
    assert sorted(ids.tolist()) == list(range(80))
    assert degradation_counts().get("readahead_unavailable", 0) == before + 1
