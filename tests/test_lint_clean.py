"""The repo must lint clean against its own analyzer.

This is the self-application gate from the graftlint design: every rule family
runs over ``petastorm_tpu/``, ``tests/`` and ``examples/`` and no NON-BASELINED
finding may exist. New code that trips a rule either gets fixed or is added to
``.graftlint-baseline.json`` with a justification — silently regressing the lock
discipline of the executor/loader layer is not an option.
"""
import os

from petastorm_tpu.analysis.cli import main as lint_main

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_repo_lints_clean():
    paths = [os.path.join(REPO_ROOT, d)
             for d in ("petastorm_tpu", "tests", "examples")]
    baseline = os.path.join(REPO_ROOT, ".graftlint-baseline.json")
    rc = lint_main(paths + ["--baseline", baseline])
    assert rc == 0, (
        "petastorm-tpu-lint found new findings — run "
        "`petastorm-tpu-lint petastorm_tpu/ tests/ examples/` for details, fix "
        "them, or baseline with a justification")


def test_package_lints_clean_without_any_suppression_mechanism():
    """petastorm_tpu/ itself must be clean even with the baseline disabled:
    the concurrency fixes in workers.py/loader.py are real, not baselined."""
    rc = lint_main([os.path.join(REPO_ROOT, "petastorm_tpu"), "--no-baseline"])
    assert rc == 0


def test_executor_loader_carry_no_deadlock_rule_suppressions():
    """The whole-program deadlock rules (GL-C005/GL-C006) must hold on
    workers.py/loader.py WITHOUT inline disables: PR 13's deadlock was fixed
    by restructuring (post the sentinel outside the lock), and that fix
    staying real — not suppressed — is the point of the project phase."""
    for name in ("workers.py", "loader.py"):
        path = os.path.join(REPO_ROOT, "petastorm_tpu", name)
        with open(path, "r", encoding="utf-8") as f:
            source = f.read()
        for rule in ("GL-C005", "GL-C006"):
            assert rule not in source, (
                "%s suppresses %s inline — the deadlock rules must pass on "
                "the executor/loader layer by construction" % (name, rule))
