"""Native JPEG decoder fuzzing (VERDICT r2 #7): the C++ entropy decoder runs in-process
over raw pointers, so corrupt input must ALWAYS surface as a clean ValueError/status-slot
rejection (or a successful decode of a still-valid stream) — never a crash, hang, or an
out-of-bounds write into a NEIGHBORING stream's output slice.

Corpus: baseline / progressive / restart-interval / grayscale / optimized-Huffman seed
streams × {random byte flips, truncation, random marker splices, DHT/DQT/SOS/DRI
length-field perturbation, restart-marker injection} — >1k mutated streams, seeded RNG.

The strongest assertion is the sandwich check: decoding [good, mutant, good] must leave
the good streams' coefficient slices BIT-IDENTICAL to decoding them alone — a clamped or
stray write from the mutant's decode would scribble into its neighbors' buffers.

The reference leans on battle-tested cv2 for all decoding (petastorm/codecs.py ~L200);
our replacement earns equivalent trust here.
"""
import cv2
import numpy as np
import pytest

from petastorm_tpu.ops import native

pytestmark = pytest.mark.skipif(
    not native.native_available(),
    reason="native toolchain unavailable: %s" % native.native_error())


def _seed_streams():
    rng = np.random.RandomState(1234)
    cases = [
        ((48, 64, 3), [cv2.IMWRITE_JPEG_QUALITY, 80]),
        ((48, 64, 3), [cv2.IMWRITE_JPEG_QUALITY, 85, cv2.IMWRITE_JPEG_PROGRESSIVE, 1]),
        ((48, 64, 3), [cv2.IMWRITE_JPEG_QUALITY, 90, cv2.IMWRITE_JPEG_RST_INTERVAL, 2]),
        ((48, 64, 3), [cv2.IMWRITE_JPEG_QUALITY, 75, cv2.IMWRITE_JPEG_PROGRESSIVE, 1,
                       cv2.IMWRITE_JPEG_OPTIMIZE, 1,
                       cv2.IMWRITE_JPEG_RST_INTERVAL, 3]),
        ((48, 64), [cv2.IMWRITE_JPEG_QUALITY, 85]),  # grayscale
    ]
    streams = []
    for shape, opts in cases:
        img = rng.randint(0, 256, shape, dtype=np.uint8)
        ok, enc = cv2.imencode(".jpg", img, opts)
        assert ok
        streams.append(enc.tobytes())
    return streams


def _find_markers(data, kinds):
    """Offsets of 0xFF<kind> markers (kind bytes given as a set of ints)."""
    out = []
    i = 0
    while i < len(data) - 1:
        if data[i] == 0xFF and data[i + 1] in kinds:
            out.append(i)
        i += 1
    return out


def _mutants(stream, rng, count):
    """Deterministic mutation corpus for one seed stream."""
    muts = []
    n = len(stream)
    segment_markers = {0xC4, 0xDB, 0xDA, 0xDD, 0xC0, 0xC2}  # DHT DQT SOS DRI SOF0 SOF2
    marker_offsets = _find_markers(stream, segment_markers)
    for _ in range(count):
        kind = rng.randint(0, 5)
        b = bytearray(stream)
        if kind == 0:  # random byte flips (1-8 bytes)
            for _ in range(rng.randint(1, 9)):
                b[rng.randint(0, n)] ^= 1 << rng.randint(0, 8)
        elif kind == 1:  # truncate at a random point
            b = b[: rng.randint(2, n)]
        elif kind == 2:  # splice a random marker somewhere
            pos = rng.randint(2, n)
            b[pos:pos] = bytes([0xFF, rng.randint(0x01, 0xFF)])
        elif kind == 3 and marker_offsets:  # perturb a segment LENGTH field
            off = marker_offsets[rng.randint(0, len(marker_offsets))]
            if off + 3 < n:
                which = rng.randint(0, 3)
                if which == 0:  # zero length (self-referential)
                    b[off + 2:off + 4] = b"\x00\x00"
                elif which == 1:  # huge length (points past EOF)
                    b[off + 2:off + 4] = b"\xff\xff"
                else:  # off-by-random
                    delta = rng.randint(-8, 9)
                    cur = (b[off + 2] << 8) | b[off + 3]
                    new = max(0, min(0xFFFF, cur + delta))
                    b[off + 2], b[off + 3] = new >> 8, new & 0xFF
        else:  # inject/misplace restart markers in the scan body
            scans = _find_markers(stream, {0xDA})
            start = (scans[0] + 2) if scans else 2
            for _ in range(rng.randint(1, 4)):
                pos = rng.randint(min(start, n - 1), n)
                b[pos:pos] = bytes([0xFF, 0xD0 + rng.randint(0, 8)])
        muts.append(bytes(b))
    return muts


def test_fuzz_native_decoder_never_crashes():
    """≥1k mutated streams through layout parse + batch decode: clean rejection or
    successful decode, never a crash; outputs always sane shapes."""
    seeds = _seed_streams()
    rng = np.random.RandomState(99)
    total = 0
    rejected = 0
    for stream in seeds:
        for mut in _mutants(stream, rng, 220):  # 5 seeds x 220 = 1100 streams
            total += 1
            try:
                native.jpeg_parse_layout_native(mut)
            except (ValueError, RuntimeError):
                pass
            try:
                layout, coeffs, qtabs, kmax, status = \
                    native.jpeg_decode_coeffs_batch_native([mut])
                if int(status[0]) != 0:
                    rejected += 1
                for c in coeffs:
                    assert c.shape[0] == 1 and c.shape[2] == 64
                assert all(0 <= k <= 63 for k in kmax)
            except (ValueError, RuntimeError):
                rejected += 1
    assert total >= 1000
    # sanity: the corpus actually exercises the rejection paths (and some mutants —
    # e.g. scan-body bit flips — remain decodable, which is fine)
    assert rejected > total * 0.2, (rejected, total)


def test_fuzz_length_field_edge_cases():
    """Targeted DHT/DQT/SOS/DRI length-field edges: zero, 1, 2 (empty payload),
    max, and exactly-past-EOF, on every segment of a baseline and a progressive
    stream (classic decoder-crash surface)."""
    for stream in _seed_streams()[:2]:
        offsets = _find_markers(stream, {0xC4, 0xDB, 0xDA, 0xDD, 0xC0, 0xC2})
        assert offsets
        for off in offsets:
            for val in (0, 1, 2, 3, 0xFFFF, len(stream) - off):
                b = bytearray(stream)
                b[off + 2], b[off + 3] = (val >> 8) & 0xFF, val & 0xFF
                mut = bytes(b)
                try:
                    native.jpeg_parse_layout_native(mut)
                except (ValueError, RuntimeError):
                    pass
                try:
                    _, _, _, _, status = native.jpeg_decode_coeffs_batch_native([mut])
                except (ValueError, RuntimeError):
                    pass


def test_fuzz_sandwich_no_cross_slice_writes():
    """[good, mutant, good] batch: the good streams' coefficients must be BIT-equal to
    decoding them without the mutant — a clamped/stray write from the corrupt stream's
    decode would land in a neighbor's slice."""
    seeds = _seed_streams()
    rng = np.random.RandomState(7)
    for stream in (seeds[0], seeds[1]):  # baseline and progressive layouts
        ref_layout, ref_coeffs, ref_qtabs, _, ref_status = \
            native.jpeg_decode_coeffs_batch_native([stream, stream])
        assert (np.asarray(ref_status) == 0).all()
        checked = 0
        for mut in _mutants(stream, rng, 60):
            try:
                layout, coeffs, qtabs, kmax, status = \
                    native.jpeg_decode_coeffs_batch_native([stream, mut, stream])
            except (ValueError, RuntimeError):
                continue  # whole-batch rejection is legal when the mutant poisons
            checked += 1
            assert int(status[0]) == 0 and int(status[2]) == 0
            for c_ref, c in zip(ref_coeffs, coeffs):
                np.testing.assert_array_equal(c[0], c_ref[0])
                np.testing.assert_array_equal(c[2], c_ref[1])
            np.testing.assert_array_equal(qtabs[0], ref_qtabs[0])
            np.testing.assert_array_equal(qtabs[2], ref_qtabs[1])
            if int(status[1]) != 0:
                # a failed mutant's slice is zeroed, not leftover garbage
                for c in coeffs:
                    assert not c[1].any()
        assert checked > 10  # the sandwich actually ran against many mutants
