"""Shuffling buffer tests (reference model: petastorm/tests/test_shuffling_buffer.py)."""
import numpy as np
import pytest

from petastorm_tpu.shuffle import (
    BatchedRandomShufflingBuffer,
    NoopShufflingBuffer,
    RandomShufflingBuffer,
)


def test_noop_fifo():
    b = NoopShufflingBuffer()
    b.add_many([1, 2, 3])
    assert [b.retrieve() for _ in range(3)] == [1, 2, 3]
    assert not b.can_retrieve


def test_random_buffer_drains_all():
    b = RandomShufflingBuffer(100, 10, seed=0)
    b.add_many(range(50))
    got = []
    while b.can_retrieve:
        got.append(b.retrieve())
    assert len(got) == 50 - 10  # stops at threshold while not finished
    b.finish()
    while b.can_retrieve:
        got.append(b.retrieve())
    assert sorted(got) == list(range(50))


def test_random_buffer_shuffles():
    b = RandomShufflingBuffer(1000, 0, seed=1)
    b.add_many(range(500))
    b.finish()
    got = [b.retrieve() for _ in range(500)]
    assert got != list(range(500))
    assert sorted(got) == list(range(500))


def test_random_buffer_backpressure():
    b = RandomShufflingBuffer(10, 2, extra_capacity=5)
    b.add_many(range(10))
    assert not b.can_add
    with pytest.raises(RuntimeError, match="capacity"):
        b.add_many(range(100))


def test_random_buffer_threshold_validation():
    with pytest.raises(ValueError):
        RandomShufflingBuffer(5, 10)


def test_batched_buffer_roundtrip():
    b = BatchedRandomShufflingBuffer(100, 0, batch_size=8, seed=2)
    for start in range(0, 64, 16):
        b.add_many({"x": np.arange(start, start + 16), "y": np.ones(16)})
    b.finish()
    seen = []
    while b.can_retrieve:
        batch = b.retrieve()
        assert set(batch.keys()) == {"x", "y"}
        assert len(batch["x"]) == len(batch["y"]) <= 8
        seen.extend(batch["x"].tolist())
    assert sorted(seen) == list(range(64))
    assert seen != list(range(64))  # shuffled


def test_batched_buffer_threshold():
    b = BatchedRandomShufflingBuffer(100, min_after_retrieve=20, batch_size=10)
    b.add_many({"x": np.arange(25)})
    assert not b.can_retrieve  # 25 < 20 + 10
    b.add_many({"x": np.arange(10)})
    assert b.can_retrieve


def test_batched_buffer_ragged_rejected():
    b = BatchedRandomShufflingBuffer(10, 0, 2)
    with pytest.raises(ValueError, match="Ragged"):
        b.add_many({"x": np.arange(3), "y": np.arange(4)})


def test_batched_buffer_incremental_adds_and_retrieves_interleaved():
    """Exercises the preallocated-store path: staged chunks, growth, hole backfill."""
    rng = np.random.RandomState(0)
    b = BatchedRandomShufflingBuffer(64, min_after_retrieve=16, batch_size=8, seed=3)
    seen = []
    next_id = 0
    for _ in range(30):
        n = rng.randint(1, 20)
        ids = np.arange(next_id, next_id + n)
        b.add_many({"id": ids, "x": ids.astype(np.float64) * 0.5})
        next_id += n
        while b.can_retrieve:
            out = b.retrieve()
            np.testing.assert_array_equal(out["x"], out["id"] * 0.5)  # rows stay aligned
            seen.extend(out["id"].tolist())
    b.finish()
    while b.can_retrieve:
        out = b.retrieve()
        np.testing.assert_array_equal(out["x"], out["id"] * 0.5)
        seen.extend(out["id"].tolist())
    assert sorted(seen) == list(range(next_id))  # exact permutation, no loss/dup


def test_batched_buffer_statistical_shuffle_quality():
    """Reference asserts statistical quality (SURVEY §5.3), not just 'order differs':
    with capacity >= N the output order must be rank-decorrelated from the input."""
    n = 2000
    b = BatchedRandomShufflingBuffer(n, min_after_retrieve=0, batch_size=50, seed=7)
    for start in range(0, n, 200):
        b.add_many({"id": np.arange(start, start + 200)})
    b.finish()
    out = []
    while b.can_retrieve:
        out.extend(b.retrieve()["id"].tolist())
    assert sorted(out) == list(range(n))
    positions = np.empty(n)
    positions[np.asarray(out)] = np.arange(n)
    rho = np.corrcoef(np.arange(n), positions)[0, 1]  # Spearman on identity input
    assert abs(rho) < 0.15, rho
    displacement = np.abs(positions - np.arange(n)).mean()
    assert displacement > n / 6, displacement  # uniform shuffle expectation ~ n/3


def test_random_buffer_statistical_shuffle_quality():
    n = 2000
    b = RandomShufflingBuffer(n, 0, seed=5)
    b.add_many(range(n))
    b.finish()
    out = [b.retrieve() for _ in range(n)]
    positions = np.empty(n)
    positions[np.asarray(out)] = np.arange(n)
    rho = np.corrcoef(np.arange(n), positions)[0, 1]
    assert abs(rho) < 0.15, rho
