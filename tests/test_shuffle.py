"""Shuffling buffer tests (reference model: petastorm/tests/test_shuffling_buffer.py)."""
import numpy as np
import pytest

from petastorm_tpu.shuffle import (
    BatchedRandomShufflingBuffer,
    NoopShufflingBuffer,
    RandomShufflingBuffer,
)


def test_noop_fifo():
    b = NoopShufflingBuffer()
    b.add_many([1, 2, 3])
    assert [b.retrieve() for _ in range(3)] == [1, 2, 3]
    assert not b.can_retrieve


def test_random_buffer_drains_all():
    b = RandomShufflingBuffer(100, 10, seed=0)
    b.add_many(range(50))
    got = []
    while b.can_retrieve:
        got.append(b.retrieve())
    assert len(got) == 50 - 10  # stops at threshold while not finished
    b.finish()
    while b.can_retrieve:
        got.append(b.retrieve())
    assert sorted(got) == list(range(50))


def test_random_buffer_shuffles():
    b = RandomShufflingBuffer(1000, 0, seed=1)
    b.add_many(range(500))
    b.finish()
    got = [b.retrieve() for _ in range(500)]
    assert got != list(range(500))
    assert sorted(got) == list(range(500))


def test_random_buffer_backpressure():
    b = RandomShufflingBuffer(10, 2, extra_capacity=5)
    b.add_many(range(10))
    assert not b.can_add
    with pytest.raises(RuntimeError, match="capacity"):
        b.add_many(range(100))


def test_random_buffer_threshold_validation():
    with pytest.raises(ValueError):
        RandomShufflingBuffer(5, 10)


def test_batched_buffer_roundtrip():
    b = BatchedRandomShufflingBuffer(100, 0, batch_size=8, seed=2)
    for start in range(0, 64, 16):
        b.add_many({"x": np.arange(start, start + 16), "y": np.ones(16)})
    b.finish()
    seen = []
    while b.can_retrieve:
        batch = b.retrieve()
        assert set(batch.keys()) == {"x", "y"}
        assert len(batch["x"]) == len(batch["y"]) <= 8
        seen.extend(batch["x"].tolist())
    assert sorted(seen) == list(range(64))
    assert seen != list(range(64))  # shuffled


def test_batched_buffer_threshold():
    b = BatchedRandomShufflingBuffer(100, min_after_retrieve=20, batch_size=10)
    b.add_many({"x": np.arange(25)})
    assert not b.can_retrieve  # 25 < 20 + 10
    b.add_many({"x": np.arange(10)})
    assert b.can_retrieve


def test_batched_buffer_ragged_rejected():
    b = BatchedRandomShufflingBuffer(10, 0, 2)
    with pytest.raises(ValueError, match="Ragged"):
        b.add_many({"x": np.arange(3), "y": np.arange(4)})
