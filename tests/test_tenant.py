"""Per-tenant accounting plane (ISSUE 18): context resolution and validation,
the resource meter (counters + the event-driven arena byte·seconds integral),
the fleet-mergeable usage report, per-tenant SLO dimensioning, tenant frame
headers on the transport wire, and the end-to-end delivery charge."""
import pickle
import threading

import pytest

from petastorm_tpu.obs import tenant as tenant_mod
from petastorm_tpu.obs.metrics import MetricsRegistry, default_registry
from petastorm_tpu.obs.tenant import (
    TenantContext,
    TenantUsageReport,
    UNTAGGED,
)


@pytest.fixture(autouse=True)
def _tenant_reset():
    tenant_mod._reset_for_tests()
    yield
    tenant_mod._reset_for_tests()


# -- context validation + resolution ---------------------------------------------------


def test_context_validates_bounded_slug():
    ctx = TenantContext("team-a.prod_1", job="j42", priority="high")
    assert (ctx.tenant, ctx.job, ctx.priority) == ("team-a.prod_1", "j42",
                                                   "high")
    for bad in ("", "UPPER", "-leading", "a" * 33, "sp ace", 'q"uote',
                "unié"):
        with pytest.raises(ValueError):
            TenantContext(bad)
    with pytest.raises(ValueError):
        TenantContext("ok", job="Bad Job")
    with pytest.raises(ValueError):
        TenantContext("ok", priority="urgent")


def test_context_immutable_picklable_value_semantics():
    ctx = TenantContext("a", job="j", priority="low")
    with pytest.raises(AttributeError):
        ctx.tenant = "b"
    assert ctx == TenantContext("a", job="j", priority="low")
    assert ctx != TenantContext("a")
    assert hash(ctx) == hash(TenantContext("a", job="j", priority="low"))
    assert pickle.loads(pickle.dumps(ctx)) == ctx
    assert ctx.env() == {"PTPU_TENANT": "a", "PTPU_TENANT_JOB": "j",
                         "PTPU_TENANT_PRIORITY": "low"}


def test_from_env_degrades_on_invalid_slug():
    """A launcher typo must run untagged (tenant_label_invalid), not raise."""
    assert tenant_mod.from_env({}) is None
    assert tenant_mod.from_env({"PTPU_TENANT": "NOT A SLUG"}) is None
    # invalid job/priority are dropped, the valid tenant id survives
    ctx = tenant_mod.from_env({"PTPU_TENANT": "a", "PTPU_TENANT_JOB": "B AD",
                               "PTPU_TENANT_PRIORITY": "urgent"})
    assert (ctx.tenant, ctx.job, ctx.priority) == ("a", None, None)
    counter = default_registry().counter("ptpu_degradations_total",
                                         cause="tenant_label_invalid")
    assert counter.value >= 1


def test_resolve_order_explicit_beats_env(monkeypatch):
    monkeypatch.setenv("PTPU_TENANT", "env-tenant")
    assert tenant_mod.resolve("arg-tenant").tenant == "arg-tenant"
    ctx = TenantContext("ctx-tenant")
    assert tenant_mod.resolve(ctx) is ctx
    assert tenant_mod.resolve(None).tenant == "env-tenant"
    assert tenant_mod.resolve(None, env_default=False) is None
    # explicit invalid RAISES (the caller is right there to fix it)
    with pytest.raises(ValueError):
        tenant_mod.resolve("NOT A SLUG")
    with pytest.raises(TypeError):
        tenant_mod.resolve(42)


def test_activation_is_thread_local():
    ctx = TenantContext("a")
    assert tenant_mod.current() is None
    seen = {}

    def other_thread():
        seen["label"] = tenant_mod.current_label()

    with tenant_mod.activate(ctx):
        assert tenant_mod.current() is ctx
        t = threading.Thread(target=other_thread)
        t.start()
        t.join(timeout=10.0)
        assert not t.is_alive()
    assert seen["label"] is None  # the activation never leaked across threads
    assert tenant_mod.current() is None
    # process default applies where no thread activation is armed
    tenant_mod.set_default(ctx)
    assert tenant_mod.current_label() == "a"
    assert tenant_mod.label_of(None) == UNTAGGED


# -- the meter -------------------------------------------------------------------------


def test_charge_noop_untagged_and_labels_when_tagged():
    reg = MetricsRegistry()
    tenant_mod.charge("rows", 10, registry=reg)  # untagged: charges NOTHING
    assert not any(n.startswith("ptpu_tenant_") for n in reg.snapshot())
    with tenant_mod.activate(TenantContext("a")):
        tenant_mod.charge("rows", 10, registry=reg)
        tenant_mod.charge("read_bytes", 4096, registry=reg)
    tenant_mod.charge("rows", 5, label="b", registry=reg)
    snap = reg.snapshot()
    assert snap['ptpu_tenant_rows_total{tenant="a"}'] == 10
    assert snap['ptpu_tenant_read_bytes_total{tenant="a"}'] == 4096
    assert snap['ptpu_tenant_rows_total{tenant="b"}'] == 5


def test_arena_byte_seconds_integral_is_event_driven():
    """resident·time accrues exactly between adjustment events (explicit
    ``now=`` stamps make the integral deterministic)."""
    reg = MetricsRegistry()
    m = tenant_mod.meter(reg)
    m.arena_adjust("a", 1000.0, now=10.0)   # 1000 bytes resident from t=10
    m.arena_adjust("a", 1000.0, now=12.0)   # +2.0s * 1000B accrued
    m.arena_adjust("a", -1500.0, now=13.0)  # +1.0s * 2000B accrued; 500 left
    m.arena_settle(now=15.0)                # +2.0s * 500B accrued
    snap = reg.snapshot()
    assert snap['ptpu_tenant_arena_byte_seconds_total{tenant="a"}'] == \
        pytest.approx(2.0 * 1000 + 1.0 * 2000 + 2.0 * 500)
    assert snap['ptpu_tenant_arena_resident_bytes{tenant="a"}'] == 500.0
    # releases can never drive residency negative
    m.arena_adjust("a", -9999.0, now=16.0)
    assert reg.snapshot()[
        'ptpu_tenant_arena_resident_bytes{tenant="a"}'] == 0.0


# -- the usage report ------------------------------------------------------------------


def _usage_metrics():
    return {
        'ptpu_tenant_rows_total{tenant="a"}': 100.0,
        'ptpu_tenant_worker_seconds_total{tenant="a"}': 1.5,
        'ptpu_tenant_rows_total{tenant="b"}': 900.0,
        'ptpu_tenant_worker_seconds_total{tenant="b"}': 6.0,
        'ptpu_tenant_hedged_gets_total{tenant="b"}': 3.0,
        "ptpu_io_tier_bytes_total": 1e6,  # untagged families never report
        'ptpu_other_total{tenant="a"}': 5.0,  # non-RESOURCES family ignored
    }


def test_report_from_metrics_and_top_consumer():
    report = TenantUsageReport.from_metrics(_usage_metrics())
    assert report.tenants() == ["a", "b"]
    assert report.get("a", "rows") == 100.0
    assert report.top_consumer("worker_s") == ("b", 6.0)
    assert report.top_consumer("quarantined") == (None, 0.0)
    assert "other" not in str(report.to_dict())


def test_report_merge_sums_per_tenant():
    a = TenantUsageReport.from_metrics(_usage_metrics())
    b = TenantUsageReport({"b": {"rows": 100.0}, "c": {"rows": 7.0}})
    merged = a.merge(b)
    assert merged.get("b", "rows") == 1000.0
    assert merged.get("c", "rows") == 7.0
    assert a.get("b", "rows") == 900.0  # merge never mutates the inputs


def test_report_render_ranks_by_worker_seconds():
    lines = TenantUsageReport.from_metrics(_usage_metrics()).render()
    assert lines[0].startswith("tenants (ptpu_tenant_")
    assert lines[1].lstrip().startswith("b ")  # heaviest worker_s first
    assert lines[2].lstrip().startswith("a ")


# -- per-tenant SLO dimensioning -------------------------------------------------------


def test_slo_per_tenant_expansion_names_the_tenant():
    from petastorm_tpu.obs.slo import SloEngine, SloSpec, _strip_tenant

    assert _strip_tenant('m{tenant="x"}') == ("m", "x")
    assert _strip_tenant('m{a="1",tenant="x"}') == ('m{a="1"}', "x")
    assert _strip_tenant('m{tenant="x",a="1"}') == ('m{a="1"}', "x")
    assert _strip_tenant("m") == ("m", None)

    spec = SloSpec(name="burn", metric="ptpu_tenant_rows_total",
                   stat="delta", op="<=", threshold=100.0, breach_windows=2,
                   per_tenant=True)
    engine = SloEngine(specs=[spec])
    noisy = 'ptpu_tenant_rows_total{tenant="b"}'
    quiet = 'ptpu_tenant_rows_total{tenant="a"}'
    window = lambda qa, qb: {quiet: {"delta": qa}, noisy: {"delta": qb}}
    assert engine.evaluate(window(10.0, 500.0), t=1.0) == []  # streak 1
    assert engine.breaching() == {'burn{tenant="b"}': 1}
    alerts = engine.evaluate(window(10.0, 500.0), t=2.0)
    assert len(alerts) == 1
    alert = alerts[0]
    assert alert.tenant == "b" and alert.cause == "slo_breach"
    assert "by tenant 'b'" in alert.message
    # latched: a third breaching window must not re-fire
    assert engine.evaluate(window(10.0, 500.0), t=3.0) == []
    # the quiet tenant's debounce is independent — it can fire on its own
    assert engine.evaluate(window(400.0, 0.0), t=4.0) == []
    quiet_alerts = engine.evaluate(window(400.0, 0.0), t=5.0)
    assert [a.tenant for a in quiet_alerts] == ["a"]


def test_slo_per_tenant_alert_counter_carries_tenant_label():
    from petastorm_tpu.obs.slo import SloEngine, SloSpec

    reg = MetricsRegistry()
    spec = SloSpec(name="burn", metric="m", stat="value", op="<=",
                   threshold=1.0, breach_windows=1, per_tenant=True)
    engine = SloEngine(specs=[spec], registry=reg)
    engine.evaluate({'m{tenant="b"}': {"value": 9.0}}, t=1.0)
    assert reg.snapshot()[
        'ptpu_slo_alerts_total{slo="burn",tenant="b"}'] == 1


def test_slo_per_tenant_attribution_scoped_to_tenant():
    from petastorm_tpu.obs.slo import SloEngine, SloSpec

    calls = []

    class _Report:
        slow_top = "io.remote"

        def to_dict(self):
            return {"slow_top": "io.remote"}

    def attribution(tenant=None):
        calls.append(tenant)
        return _Report()

    spec = SloSpec(name="burn", metric="m", stat="value", op="<=",
                   threshold=1.0, breach_windows=1, per_tenant=True)
    engine = SloEngine(specs=[spec], attribution=attribution)
    alerts = engine.evaluate({'m{tenant="b"}': {"value": 9.0}}, t=1.0)
    assert calls == ["b"]
    assert alerts[0].culprit == "io.remote" and alerts[0].tenant == "b"


# -- transport frame headers -----------------------------------------------------------


def test_frame_tenant_header_round_trip_and_old_peer_compat():
    from petastorm_tpu.errors import TransportFrameCorrupt
    from petastorm_tpu.transport.framing import (
        K_OBJ,
        K_TENANT_FLAG,
        pack_frame,
        split_tenant,
        take_frame,
    )

    payload = b"result-bytes"
    buf = bytearray(pack_frame(K_OBJ, payload, tenant="team-a"))
    kind, body = take_frame(buf)
    assert kind == K_OBJ | K_TENANT_FLAG
    assert split_tenant(kind, body) == (K_OBJ, payload, "team-a")
    # old sender -> new receiver: unflagged passes through untagged
    buf = bytearray(pack_frame(K_OBJ, payload))
    kind, body = take_frame(buf)
    assert split_tenant(kind, body) == (K_OBJ, payload, None)
    # new sender on an un-negotiated link ships the OLD byte format exactly
    assert pack_frame(K_OBJ, payload, tenant=None) == \
        pack_frame(K_OBJ, payload)
    # a truncated tenant header is a corrupt frame, not garbage delivery
    with pytest.raises(TransportFrameCorrupt):
        split_tenant(K_OBJ | K_TENANT_FLAG, b"")
    with pytest.raises(TransportFrameCorrupt):
        split_tenant(K_OBJ | K_TENANT_FLAG, b"\x10ab")


# -- end-to-end: delivery charges + provenance annotation ------------------------------


def test_reader_delivery_charges_rows_to_the_tenant(scalar_dataset):
    from petastorm_tpu.reader import make_batch_reader

    registry = default_registry()
    name = 'ptpu_tenant_rows_total{tenant="t-e2e"}'
    worker_name = 'ptpu_tenant_worker_seconds_total{tenant="t-e2e"}'
    before = registry.snapshot().get(name, 0)
    rows = 0
    with make_batch_reader(scalar_dataset.url, num_epochs=1,
                           workers_count=1, tenant="t-e2e") as reader:
        assert reader.tenant_context.tenant == "t-e2e"
        for batch in reader:
            rows += len(batch.id)
    snap = registry.snapshot()
    assert rows == 30
    assert snap[name] - before == rows
    assert snap.get(worker_name, 0) > 0


def test_untagged_reader_charges_nothing(scalar_dataset):
    from petastorm_tpu.reader import make_batch_reader

    registry = default_registry()
    before = {n: v for n, v in registry.snapshot().items()
              if n.startswith("ptpu_tenant_")}
    with make_batch_reader(scalar_dataset.url, num_epochs=1,
                           workers_count=1) as reader:
        rows = sum(len(b.id) for b in reader)
    assert rows == 30
    after = {n: v for n, v in registry.snapshot().items()
             if n.startswith("ptpu_tenant_")}
    assert after == before


def test_tagged_worker_stamps_provenance_annotation(scalar_dataset):
    """The per-tenant attribution fold filters on the item annotation the
    tagged worker stamps — the alert's "whose tail is this" seam."""
    from petastorm_tpu.reader import make_batch_reader

    with make_batch_reader(scalar_dataset.url, num_epochs=1,
                           workers_count=1, provenance=True,
                           tenant="t-prov") as reader:
        rows = sum(len(b.id) for b in reader)
        recorder = reader._prov
        assert rows == 30
        items = recorder.items()
        assert items, "provenance recorded no items"
        assert all(rec["annotations"].get("tenant") == "t-prov"
                   for rec in items.values())
        # the tenant-scoped fold sees the batches; a stranger sees none
        assert recorder.report(tenant="nobody").batches == 0
