"""DataLoader tests: host re-batching, shuffling buffer, sharded device_put, device transforms.

Runs on the conftest 8-virtual-CPU-device topology so NamedSharding paths are exercised
without TPU hardware (SURVEY.md §5).
"""
import numpy as np
import pytest

from petastorm_tpu.loader import DataLoader, make_dataloader
from petastorm_tpu.reader import make_batch_reader, make_reader
from petastorm_tpu.transform import TransformSpec


def _collect(loader):
    with loader:
        return list(loader)


def test_host_batches_exact_size(scalar_dataset):
    reader = make_batch_reader(scalar_dataset.url, shuffle_row_groups=False)
    loader = DataLoader(reader, batch_size=7, to_device=False)
    batches = _collect(loader)
    assert batches, "no batches yielded"
    for b in batches:
        assert len(b["id"]) == 7  # drop policy: every batch exact
    total = sum(len(b["id"]) for b in batches)
    assert total == (len(scalar_dataset.data) // 7) * 7


def test_partial_last_batch(scalar_dataset):
    reader = make_batch_reader(scalar_dataset.url, shuffle_row_groups=False)
    loader = DataLoader(reader, batch_size=7, last_batch="partial", to_device=False)
    batches = _collect(loader)
    total = sum(len(b["id"]) for b in batches)
    assert total == len(scalar_dataset.data)
    all_ids = np.concatenate([np.asarray(b["id"]) for b in batches])
    assert sorted(all_ids.tolist()) == sorted(r["id"] for r in scalar_dataset.data)


def test_pad_last_batch(scalar_dataset):
    reader = make_batch_reader(scalar_dataset.url, shuffle_row_groups=False)
    loader = DataLoader(reader, batch_size=8, last_batch="pad", to_device=False)
    batches = _collect(loader)
    for b in batches:
        assert len(b["id"]) == 8
    # valid mask marks the padded tail
    n_valid = sum(int(np.asarray(b["__valid__"]).sum()) for b in batches)
    assert n_valid == len(scalar_dataset.data)


def test_pad_last_batch_consumer_watermark_counts_valid_rows_only(scalar_dataset):
    """ADVICE r5 loader.py:846: under ``last_batch='pad'`` the consumer watermark
    must count only rows the reader DELIVERED (sum of ``__valid__``), never the
    repeated padding — otherwise it overruns the producer's delivered-row log."""
    total = len(scalar_dataset.data)
    reader = make_batch_reader(scalar_dataset.url, shuffle_row_groups=False)
    loader = DataLoader(reader, batch_size=8, last_batch="pad", to_device=False)
    with loader:
        batches = list(loader)
    assert total % 8 != 0 and len(batches[-1]["id"]) == 8  # padding actually occurred
    assert loader._rows_consumed == total  # not rounded up to a batch multiple
    # the consumer has exactly caught the producer's log: the checkpoint is the
    # final all-delivered state, which a fresh reader restores cleanly
    state = loader.state_dict()
    with make_batch_reader(scalar_dataset.url, shuffle_row_groups=False) as r2:
        r2.load_state_dict(state)
        assert sum(1 for _ in r2) == 0  # nothing left to replay


def test_detach_slab_views_covers_nested_object_elements():
    """Review finding (PR 2): the view-wire detach must copy read-only ELEMENTS
    of object (ragged) columns and detach() staged payloads, not just top-level
    read-only arrays — the outer object array is writable, its slab-view elements
    are not."""
    from petastorm_tpu.loader import _detach_slab_views

    class _Staged:
        def __init__(self):
            self.detached = False

        def detach(self):
            self.detached = True
            return self

    ro_elem = np.arange(4)
    ro_elem.setflags(write=False)
    ragged = np.empty(3, dtype=object)
    ragged[:] = [ro_elem, np.arange(2), _Staged()]
    ro_flat = np.arange(5, dtype=np.float32)
    ro_flat.setflags(write=False)
    out = _detach_slab_views({"ragged": ragged, "flat": ro_flat,
                              "ok": np.arange(3)})
    assert out["flat"].flags.writeable and out["flat"] is not ro_flat
    assert out["ok"].flags.writeable  # already-writable column passes through
    assert out["ragged"][0].flags.writeable and out["ragged"][0] is not ro_elem
    np.testing.assert_array_equal(out["ragged"][0], np.arange(4))
    assert out["ragged"][2].detached  # staged payloads detach from their buffers


def test_shuffling_buffer_changes_order_and_preserves_set(scalar_dataset):
    def ids(shuffle_cap, seed):
        reader = make_batch_reader(scalar_dataset.url, shuffle_row_groups=False)
        loader = DataLoader(reader, batch_size=5, last_batch="partial",
                            shuffling_queue_capacity=shuffle_cap, seed=seed,
                            to_device=False)
        out = np.concatenate([np.asarray(b["id"]) for b in _collect(loader)])
        return out.tolist()

    plain = ids(0, 0)
    shuffled = ids(20, 1)
    assert sorted(plain) == sorted(shuffled)
    assert plain != shuffled


def test_device_put_default_device(scalar_dataset):
    import jax

    reader = make_batch_reader(scalar_dataset.url, shuffle_row_groups=False)
    loader = DataLoader(reader, batch_size=4)
    batches = _collect(loader)
    b = batches[0]
    assert isinstance(b["float_col"], jax.Array)
    assert b["float_col"].shape[0] == 4
    # string columns must stay host-side numpy
    if "string_col" in b:
        assert not isinstance(b["string_col"], jax.Array)


def test_device_put_named_sharding(scalar_dataset):
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devices = np.array(jax.devices()[:8]).reshape(8)
    mesh = Mesh(devices, ("dp",))
    sharding = NamedSharding(mesh, P("dp"))
    reader = make_batch_reader(scalar_dataset.url, shuffle_row_groups=False)
    loader = DataLoader(reader, batch_size=16, sharding=sharding)
    batches = _collect(loader)
    b = batches[0]
    arr = b["float_col"]
    assert arr.shape[0] == 16
    assert len(arr.sharding.device_set) == 8
    # each device holds 1/8 of the batch
    shard = arr.addressable_shards[0]
    assert shard.data.shape[0] == 2


def test_device_transform_applied(scalar_dataset):
    spec = TransformSpec(
        func=lambda batch: {**batch, "float_col": batch["float_col"] * 0.0},
        device=True,
    )
    reader = make_batch_reader(scalar_dataset.url, shuffle_row_groups=False,
                               transform_spec=spec)
    loader = DataLoader(reader, batch_size=4)
    batches = _collect(loader)
    assert float(np.abs(np.asarray(batches[0]["float_col"])).sum()) == 0.0


def test_row_reader_path(synthetic_dataset):
    reader = make_reader(synthetic_dataset.url, shuffle_row_groups=False,
                         schema_fields=["id", "matrix"])
    loader = DataLoader(reader, batch_size=6, last_batch="partial", to_device=False)
    batches = _collect(loader)
    total = sum(len(b["id"]) for b in batches)
    assert total == len(synthetic_dataset.data)
    assert batches[0]["matrix"].shape[1:] == (8, 4)


def test_make_dataloader_convenience(scalar_dataset):
    loader = make_dataloader(scalar_dataset.url, batch_size=5, shuffle_row_groups=False)
    batches = _collect(loader)
    assert len(batches[0]["id"]) == 5


def test_producer_error_propagates(scalar_dataset):
    spec = TransformSpec(func=lambda pdf: 1 / 0)  # raises in worker
    reader = make_batch_reader(scalar_dataset.url, transform_spec=spec)
    loader = DataLoader(reader, batch_size=4, to_device=False)
    with pytest.raises(Exception):
        _collect(loader)


def _write_ragged_dataset(tmp_path, n=24, seed=0):
    import pyarrow as pa
    import pyarrow.parquet as pq

    rng = np.random.RandomState(seed)
    path = tmp_path / "ragged_ds"
    path.mkdir()
    lengths = rng.randint(1, 9, n)
    vectors = [rng.standard_normal(int(k)).astype(np.float32).tolist() for k in lengths]
    table = pa.table({
        "id": np.arange(n, dtype=np.int64),
        "vec": pa.array(vectors, type=pa.list_(pa.float32())),
    })
    pq.write_table(table, str(path / "part-0.parquet"), row_group_size=8)
    return "file://" + str(path), vectors


def test_ragged_field_padded_to_device(tmp_path):
    """SURVEY §8 hard part #2: ragged rows reach the device as fixed-shape arrays with
    a validity mask; values and mask agree with the source."""
    from petastorm_tpu.loader import DataLoader
    from petastorm_tpu.reader import make_batch_reader

    url, vectors = _write_ragged_dataset(tmp_path)
    reader = make_batch_reader(url, shuffle_row_groups=False, num_epochs=1)
    with DataLoader(reader, batch_size=8, pad_shapes={"vec": (8,)}) as loader:
        total = 0
        for batch in loader:
            vec = np.asarray(batch["vec"])
            mask = np.asarray(batch["vec__mask"])
            ids = np.asarray(batch["id"])
            assert vec.shape == (8, 8) and mask.shape == (8, 8)
            for i, rid in enumerate(ids):
                src = np.asarray(vectors[int(rid)], dtype=np.float32)
                assert mask[i].sum() == len(src)
                np.testing.assert_array_equal(vec[i][: len(src)], src)
                assert (vec[i][len(src):] == 0).all()
                total += 1
        assert total == 24


def test_ragged_field_without_pad_shape_raises(tmp_path):
    from petastorm_tpu.loader import DataLoader
    from petastorm_tpu.reader import make_batch_reader

    url, _ = _write_ragged_dataset(tmp_path)
    reader = make_batch_reader(url, shuffle_row_groups=False, num_epochs=1)
    with pytest.raises(ValueError, match="pad_shapes"):
        with DataLoader(reader, batch_size=8) as loader:
            next(iter(loader))


def test_ragged_pad_max_exceeded_raises(tmp_path):
    from petastorm_tpu.loader import DataLoader
    from petastorm_tpu.reader import make_batch_reader

    url, _ = _write_ragged_dataset(tmp_path)
    reader = make_batch_reader(url, shuffle_row_groups=False, num_epochs=1)
    with pytest.raises(ValueError, match="exceeding declared pad max"):
        with DataLoader(reader, batch_size=8, pad_shapes={"vec": (4,)}) as loader:
            list(loader)


def test_transfer_error_propagates_to_device_consumer(scalar_dataset):
    """Errors raised on the transfer thread (decode/device_put) must surface in the
    consumer, not deadlock it — the sentinel is delivered even after the failure."""
    reader = make_batch_reader(scalar_dataset.url)
    loader = DataLoader(reader, batch_size=4, prefetch=2,
                        device_transform=lambda batch: 1 / 0)
    with loader, pytest.raises(ZeroDivisionError):
        for _ in loader:
            pass


def test_abandoned_iterator_stops_pipeline(scalar_dataset):
    """Breaking out of iteration mid-epoch must stop the producer and transfer threads
    (prefetched device batches would otherwise stay pinned for the process lifetime)."""
    import time

    reader = make_batch_reader(scalar_dataset.url, num_epochs=None)
    with DataLoader(reader, batch_size=4, prefetch=2) as loader:
        it = iter(loader)
        next(it)
        del it
        deadline = time.time() + 10
        while time.time() < deadline and (
                loader._transfer_thread.is_alive() or loader._producer.is_alive()):
            time.sleep(0.05)
        assert not loader._transfer_thread.is_alive()
        assert not loader._producer.is_alive()


def test_stats_populate_through_device_path(scalar_dataset):
    reader = make_batch_reader(scalar_dataset.url)
    loader = DataLoader(reader, batch_size=8, prefetch=2)
    with loader:
        n = sum(1 for _ in loader)
    snap = loader.stats.snapshot()
    assert snap["batches"] == n > 0
    assert snap["rows"] == n * 8
    assert set(snap) == {"rows", "batches", "read_s", "batch_s", "put_wait_s",
                         "decode_s", "h2d_s",
                         "queue_wait_s", "device_queue_wait_s",
                         "decode_unsharded_batches", "shm_slabs_in_flight",
                         "shm_bytes", "shm_fallbacks", "shm_acquire_wait_s"}
    assert snap["decode_unsharded_batches"] == 0  # no sharding configured → no fallback
    assert snap["read_s"] >= 0 and snap["device_queue_wait_s"] >= 0


def test_inmem_loader_epochs_and_shuffle(scalar_dataset):
    """InMemDataLoader: all rows present each epoch, deterministic by seed, epochs
    differ in order, zero reader involvement after construction."""
    from petastorm_tpu.loader import InMemDataLoader

    def ordered_reader():
        # deterministic fill order: seed determinism is relative to the store layout
        return make_batch_reader(scalar_dataset.url, num_epochs=1,
                                 shuffle_row_groups=False, workers_count=1,
                                 reader_pool_type="dummy")

    with InMemDataLoader(ordered_reader(), batch_size=8, num_epochs=2, seed=3,
                         last_batch="partial") as loader:
        n_batches = len(loader)
        epochs = [[], []]
        for i, b in enumerate(loader):
            epochs[i // n_batches].extend(np.asarray(b["id"]).tolist())
    expected = sorted(r["id"] for r in scalar_dataset.data)
    assert sorted(epochs[0]) == expected
    assert sorted(epochs[1]) == expected
    assert epochs[0] != epochs[1]  # reshuffled per epoch

    with InMemDataLoader(ordered_reader(), batch_size=8, num_epochs=2, seed=3,
                         last_batch="partial") as again:
        replay = [np.asarray(b["id"]).tolist() for b in again]
    assert [x for xs in replay for x in xs] == epochs[0] + epochs[1]  # seed-determined


def test_inmem_loader_drop_and_transform(scalar_dataset):
    from petastorm_tpu.loader import InMemDataLoader

    reader = make_batch_reader(scalar_dataset.url, num_epochs=1)
    with InMemDataLoader(reader, batch_size=7, num_epochs=1, shuffle=False,
                         device_transform=lambda b: {**b, "id2": b["id"] * 2}) as loader:
        batches = list(loader)
    total = len(scalar_dataset.data)
    assert len(batches) == total // 7  # drop: only full batches
    for b in batches:
        assert b["id"].shape[0] == 7
        np.testing.assert_array_equal(np.asarray(b["id2"]), np.asarray(b["id"]) * 2)


def test_device_transform_with_key_varies_per_batch(scalar_dataset):
    """A two-arg device_transform receives a fresh fold of the seed per batch —
    the on-device random-augmentation hook."""
    import jax

    def transform(batch, key):
        noise = jax.random.uniform(key, ())
        return {**batch, "noise": noise}

    reader = make_batch_reader(scalar_dataset.url)
    loader = DataLoader(reader, batch_size=8, seed=7, device_transform=transform)
    with loader:
        noises = [float(b["noise"]) for b in loader]
    assert len(set(noises)) == len(noises)  # fresh key each batch

    reader = make_batch_reader(scalar_dataset.url)
    with DataLoader(reader, batch_size=8, seed=7, device_transform=transform) as again:
        replay = [float(b["noise"]) for b in again]
    assert replay == noises  # deterministic in the seed


def test_sequence_sharded_batch_delivery(tmp_path):
    """SURVEY §6: the loader's context-parallel obligation — when the consumer's
    sharding splits the sequence axis (dp×sp), batches arrive laid out that way."""
    import jax
    import pyarrow as pa
    import pyarrow.parquet as pq
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    path = tmp_path / "seq_ds"
    path.mkdir()
    n, seq = 32, 16
    tokens = np.arange(n * seq, dtype=np.int32).reshape(n, seq)
    table = pa.table({
        "id": np.arange(n, dtype=np.int64),
        "tokens": pa.FixedSizeListArray.from_arrays(tokens.reshape(-1), seq),
    })
    pq.write_table(table, str(path / "part-0.parquet"), row_group_size=16)

    mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 4), ("dp", "sp"))
    sharding = {"tokens": NamedSharding(mesh, P("dp", "sp")),
                "id": NamedSharding(mesh, P("dp"))}
    # dummy pool: multi-worker completion order is not deterministic, and this test
    # asserts exact row content of the first batch
    reader = make_batch_reader("file://" + str(path), shuffle_row_groups=False,
                               num_epochs=1, reader_pool_type="dummy")
    with DataLoader(reader, batch_size=8, sharding=sharding) as loader:
        batch = next(iter(loader))
    arr = batch["tokens"]
    assert arr.shape == (8, seq)
    assert len(arr.sharding.device_set) == 8
    shard = arr.addressable_shards[0]
    assert shard.data.shape == (8 // 2, seq // 4)  # batch over dp, sequence over sp
    np.testing.assert_array_equal(np.asarray(arr), tokens[:8])


def test_inmem_loader_sharded_store_and_batches(scalar_dataset):
    """InMemDataLoader keeps the resident store AND the gathered batches laid out per
    the given sharding (batch axis over dp)."""
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from petastorm_tpu.loader import InMemDataLoader

    mesh = Mesh(np.array(jax.devices()[:8]).reshape(8), ("dp",))
    sharding = NamedSharding(mesh, P("dp"))
    reader = make_batch_reader(scalar_dataset.url, num_epochs=1)
    with InMemDataLoader(reader, batch_size=16, num_epochs=1, seed=0,
                         sharding=sharding) as loader:
        batch = next(iter(loader))
    arr = batch["float_col"]
    assert arr.shape[0] == 16
    assert len(arr.sharding.device_set) == 8
    assert arr.addressable_shards[0].data.shape[0] == 2


def test_stop_midstream_joins_promptly(scalar_dataset):
    """ADVICE r2 teardown race: stop() used to be able to consume the producer's
    end-of-stream sentinel while the transfer thread was blocked in an untimed
    queue get — join() then stalled its full 60s timeout. After the fix (sentinel
    re-put after drain) stop+join must complete in seconds regardless of where the
    pipeline threads are blocked."""
    import time

    for taken in (0, 1, 3):
        reader = make_batch_reader(scalar_dataset.url, num_epochs=None)
        with DataLoader(reader, batch_size=4, prefetch=2) as loader:
            it = iter(loader)
            for _ in range(taken):
                next(it)
            t0 = time.perf_counter()
            loader.stop()
            loader.join()
            assert time.perf_counter() - t0 < 15, "join stalled: teardown race regressed"
            if loader._producer is not None:  # taken=0: generator body never ran
                assert not loader._producer.is_alive()
            if loader._transfer_thread is not None:
                assert not loader._transfer_thread.is_alive()
            it.close()


def test_reiteration_restarts_pipeline(scalar_dataset):
    """A second __iter__ supersedes an abandoned first one: pipeline state is reset
    on the consumer thread (ADVICE r2: _stop used to be cleared on the transfer
    thread, racing stop(); re-iteration could leak a live previous thread set)."""
    reader = make_batch_reader(scalar_dataset.url, num_epochs=None,
                               shuffle_row_groups=False)
    with DataLoader(reader, batch_size=5, prefetch=2) as loader:
        it1 = iter(loader)
        next(it1)  # start, then abandon mid-epoch
        it2 = iter(loader)
        first = next(it2)
        assert len(first["id"]) == 5
        # closing the SUPERSEDED iterator runs its finalizer mid-flight of the new
        # iteration; the generation guard must keep it from stopping it2's pipeline
        it1.close()
        for _ in range(6):  # > prefetch+queue depth: proves the pipeline is live
            batch = next(it2)
            assert len(batch["id"]) == 5
        loader.stop()
        loader.join()
        # the superseded iterator's threads must be gone too
        assert not loader._producer.is_alive()
        it2.close()


def test_inmem_partial_tail_sharding(scalar_dataset):
    """ADVICE r2: with sharding + last_batch='partial', the short tail batch is laid
    out per the sharding when its row count divides the batch axis, and yielded
    unsharded (no crash) when it does not."""
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from petastorm_tpu.loader import InMemDataLoader

    # 30 rows, batch 8 → tail 6. Over a 2-device batch axis 6 divides → sharded tail.
    mesh2 = Mesh(np.array(jax.devices()[:2]), ("dp",))
    reader = make_batch_reader(scalar_dataset.url, num_epochs=1)
    with InMemDataLoader(reader, batch_size=8, num_epochs=1, shuffle=False,
                         sharding=NamedSharding(mesh2, P("dp")),
                         last_batch="partial") as loader:
        batches = list(loader)
    assert len(batches[-1]["id"]) == 6
    assert len(batches[-1]["id"].sharding.device_set) == 2

    # Over an 8-device batch axis 6 does not divide → tail yielded unsharded.
    mesh8 = Mesh(np.array(jax.devices()[:8]), ("dp",))
    reader = make_batch_reader(scalar_dataset.url, num_epochs=1)
    with InMemDataLoader(reader, batch_size=8, num_epochs=1, shuffle=False,
                         sharding=NamedSharding(mesh8, P("dp")),
                         last_batch="partial") as loader:
        batches = list(loader)
    tail = batches[-1]["id"]
    assert len(tail) == 6
    assert len(batches[0]["id"].sharding.device_set) == 8  # full batches still sharded


def test_undecomposable_multiprocess_sharding_raises(scalar_dataset, monkeypatch):
    """VERDICT r2 #5: under multi-process JAX, a PositionalSharding/GSPMD sharding
    whose batch axis cannot be decomposed per process must raise — not silently feed
    every process the GLOBAL batch."""
    import jax
    from jax.sharding import SingleDeviceSharding
    from petastorm_tpu.loader import _resolve_local_batch

    # SingleDeviceSharding carries no mesh structure — the undecomposable class
    sharding = SingleDeviceSharding(jax.devices()[3])
    # single process: fine (no decomposition needed)
    assert _resolve_local_batch(16, sharding) == 16
    # simulate a 2-process topology where the sharding's device is remote
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setattr(jax, "process_index", lambda: 1)
    with pytest.raises(ValueError, match="cannot decompose the global batch"):
        _resolve_local_batch(16, sharding)
    with pytest.raises(ValueError, match="cannot decompose the global batch"):
        _resolve_local_batch(16, {"x": sharding})
    # a sharding entirely on THIS process's devices stays valid (local placement)
    monkeypatch.setattr(jax, "process_index", lambda: 0)
    assert _resolve_local_batch(16, sharding) == 16
    assert _resolve_local_batch(16, {"x": sharding}) == 16


def test_device_shuffle_capacity_exactly_once_and_shuffled(scalar_dataset):
    """VERDICT r2 #4: the HBM exchange shuffle is wired into the loader with
    epoch-honest semantics — every row delivered exactly once per epoch, order
    decorrelated, both when capacity >= dataset and when capacity < dataset."""
    def run(capacity, seed=11):
        reader = make_batch_reader(scalar_dataset.url, shuffle_row_groups=False,
                                   schema_fields=["id", "float_col"],
                                   reader_pool_type="dummy")
        loader = DataLoader(reader, batch_size=5, last_batch="partial",
                            device_shuffle_capacity=capacity, seed=seed)
        with loader:
            batches = list(loader)
        ids = np.concatenate([np.asarray(b["id"]) for b in batches])
        floats = np.concatenate([np.asarray(b["float_col"]) for b in batches])
        return ids, floats

    expected = {r["id"]: r["float_col"] for r in scalar_dataset.data}
    for capacity in (64, 10):  # >= dataset (drain-only) and < dataset (steady exchange)
        ids, floats = run(capacity)
        assert sorted(ids.tolist()) == sorted(expected)
        assert ids.tolist() != sorted(expected), "capacity=%d did not shuffle" % capacity
        for i, f in zip(ids, floats):  # columns stay row-aligned through the ring
            # float32 tolerance: device_put truncates float64 with jax x64 off,
            # exactly as the non-shuffled device path does
            assert abs(expected[int(i)] - float(f)) < 1e-5

    a, _ = run(10, seed=11)
    b, _ = run(10, seed=11)
    assert a.tolist() == b.tolist()  # deterministic in the seed
    c, _ = run(10, seed=12)
    assert a.tolist() != c.tolist()


def test_device_shuffle_rejects_host_columns(scalar_dataset):
    reader = make_batch_reader(scalar_dataset.url)  # string_col is host-only
    loader = DataLoader(reader, batch_size=5, device_shuffle_capacity=32)
    with loader, pytest.raises(ValueError, match="host-only"):
        for _ in loader:
            pass


def test_device_shuffle_requires_to_device(scalar_dataset):
    reader = make_batch_reader(scalar_dataset.url)
    with pytest.raises(ValueError, match="to_device"):
        DataLoader(reader, batch_size=5, device_shuffle_capacity=32, to_device=False)
    reader.stop()
    reader.join()


def test_multiprocess_inmem_guards(scalar_dataset, monkeypatch):
    """Review r3: multi-process InMemDataLoader must reject a replicated batch axis
    (divergent per-process shards would silently assemble as 'replicas') and any
    missing sharding/last_batch misconfig — before touching the reader."""
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from petastorm_tpu.loader import InMemDataLoader

    monkeypatch.setattr(jax, "process_count", lambda: 2)
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(8), ("dp",))
    reader = make_batch_reader(scalar_dataset.url, num_epochs=1)
    try:
        with pytest.raises(ValueError, match="requires a sharding"):
            InMemDataLoader(reader, batch_size=8)  # no sharding at all
        with pytest.raises(ValueError, match="drop"):
            InMemDataLoader(reader, batch_size=8, last_batch="partial",
                            sharding=NamedSharding(mesh, P("dp")))
        with pytest.raises(ValueError, match="replicated batch axis|spans processes"):
            InMemDataLoader(reader, batch_size=8,
                            sharding=NamedSharding(mesh, P(None)))
    finally:
        reader.stop()
        reader.join()
