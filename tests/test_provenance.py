"""Causal batch provenance & critical-path attribution (ISSUE 10).

Unit contracts for the span fold and the recorder, end-to-end item/batch
attribution on every pool type (process pools prove the cross-pid merge),
the tiered-remote and quarantine-heavy acceptance scenarios (verdict stable,
ids exactly-once, zero leaked leases), Perfetto flow events, and the
Reporter rotation satellite."""
import json
import os
import time

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from petastorm_tpu.loader import DataLoader
from petastorm_tpu.obs import provenance as prov
from petastorm_tpu.obs.critical_path import analyze_batches, fold_self_times
from petastorm_tpu.obs.provenance import ItemProvenance, ProvenanceRecorder
from petastorm_tpu.reader import make_batch_reader


@pytest.fixture(autouse=True)
def _clean_plane():
    """Every test starts and ends with the process-global plane disarmed."""
    prov.ACTIVE = None
    prov._tls.item = None
    yield
    prov.ACTIVE = None
    prov._tls.item = None


@pytest.fixture
def store(tmp_path):
    root = str(tmp_path / "data")
    os.makedirs(root)
    for i in range(3):
        pq.write_table(
            pa.table({"id": np.arange(64, dtype=np.int64) + i * 64,
                      "x": np.random.default_rng(i).random(64)}),
            os.path.join(root, "p%d.parquet" % i))
    return root


def _leaked_total():
    from petastorm_tpu.obs.metrics import default_registry

    return default_registry().counter("ptpu_lease_leaked_total").value


def _assert_exactly_once(loader, expected_rows):
    per_item = {}
    for b in loader.provenance.batches():
        for e, o, r in (b["items"] or ()):
            per_item[(e, o)] = per_item.get((e, o), 0) + r
    assert sum(per_item.values()) == expected_rows, per_item
    quarantined = {(e, o) for e, o, _a, _k in loader.provenance.quarantined()}
    assert not (quarantined & set(per_item))
    assert loader.provenance.duplicate_absorbs == 0
    return per_item


# -- critical-path fold -----------------------------------------------------------------


def test_fold_charges_nested_spans_to_the_child():
    spans = [("outer", 0.0, 10.0, 1),
             ("inner", 2.0, 8.0, 1),
             ("leaf", 3.0, 4.0, 2)]
    out = fold_self_times(spans)
    assert out["leaf"] == pytest.approx(1.0)
    assert out["inner"] == pytest.approx(5.0)   # 6 - 1 nested
    assert out["outer"] == pytest.approx(4.0)   # 10 - 6 nested


def test_fold_partial_overlap_is_siblings():
    out = fold_self_times([("a", 0.0, 5.0, 1), ("b", 3.0, 9.0, 1)])
    assert out["a"] == pytest.approx(5.0)
    assert out["b"] == pytest.approx(6.0)


def test_fold_same_site_accumulates():
    out = fold_self_times([("a", 0.0, 1.0, 1), ("a", 2.0, 3.5, 1)])
    assert out["a"] == pytest.approx(2.5)


def test_analyze_batches_names_the_culprit_and_splits_by_tier():
    views = []
    for i in range(10):
        slow = i == 9
        views.append({
            "seq": i, "rows": 8, "step_gap_s": 1.0 if slow else 0.01,
            "spans": [{"site": "loader.collate", "t0": 0.0, "t1": 0.002,
                       "pid": 1}],
            "items": [(0, i, 8)],
            "item_records": [{
                "annotations": {"cache_tier": "remote" if slow else "mem"},
                "attempts": 1,
                "spans": [{"site": "io.remote", "t0": 0.0,
                           "t1": 0.9 if slow else 0.004, "pid": 1}],
            }],
        })
    report = analyze_batches(views)
    assert report.batches == 10
    assert report.top_stage == "io.remote"
    assert report.slow_top == "io.remote"
    assert "io.remote" in report.verdict
    assert report.by_tier["remote"]["p99_s"] >= report.by_tier["mem"]["p99_s"]
    d = report.to_dict()
    assert d["slow_top"] == "io.remote"
    assert "io.remote" in report.render()


# -- recorder units ---------------------------------------------------------------------


def test_item_key_is_the_chaos_stable_key():
    class Piece:
        path = "/d/p.parquet"
        row_group = 3

    tagged = (1, 7, (Piece(), 0))
    assert prov.item_key(tagged) == "epoch=1 ordinal=7 /d/p.parquet:3"
    rec_a = ItemProvenance(*prov.item_identity(tagged))
    rec_b = ItemProvenance(*prov.item_identity(tagged))
    assert rec_a.trace_id == rec_b.trace_id  # stable across processes


def test_hooks_are_noops_when_disarmed():
    assert prov.begin_item((0, 0, "x")) is None  # graftlint: disable=GL-O003 (disarmed no-op)
    prov.add_span("site", 0.0, 1.0)
    prov.annotate("k", "v")
    with prov.span("site"):
        pass
    assert prov.end_item() is None


def test_recorder_spans_annotations_and_retry_attempts():
    rec = ProvenanceRecorder().arm()
    try:
        tagged = (0, 1, "item")
        prov.begin_item(tagged)  # graftlint: disable=GL-O003 (unit test drives the raw API)
        with prov.span("reader.read"):
            time.sleep(0.002)
        prov.annotate("cache_tier", "mem")
        prov.annotate_add("io_retries", 2)
        prov.end_item()
        # a retry of the same (epoch, ordinal) reuses the record
        prov.begin_item(tagged)  # graftlint: disable=GL-O003 (unit test drives the raw API)
        prov.end_item()
        items = rec.items()
        assert len(items) == 1
        record = next(iter(items.values()))
        assert record["attempts"] == 2
        assert record["annotations"] == {"cache_tier": "mem", "io_retries": 2}
        assert record["spans"][0]["site"] == "reader.read"
        assert record["spans"][0]["t1"] > record["spans"][0]["t0"]
    finally:
        rec.disarm()


def test_second_recorder_arm_raises_but_rearm_is_idempotent():
    rec = ProvenanceRecorder().arm()
    try:
        rec.arm()  # same recorder: fine
        with pytest.raises(RuntimeError):
            ProvenanceRecorder().arm()
    finally:
        rec.disarm()
    other = ProvenanceRecorder().arm()  # after disarm: fine
    other.disarm()


def test_absorb_child_aligns_clocks_and_learns_the_key():
    rec = ProvenanceRecorder()
    # the delivery note arrives first, with only (epoch, ordinal)
    rec.note_delivery(0, 4, 64)
    wall = time.time() + 100.0      # a "child" whose anchors are shifted
    perf = 5000.0
    blob = (0, 4, "epoch=0 ordinal=4 /d/p.parquet:1",
            [("child.work", 5000.0, 5000.5, 4242)], {"hedges": 1})
    rec.absorb_child(blob, 4242, wall, perf)
    items = rec.items()
    key = "epoch=0 ordinal=4 /d/p.parquet:1"
    assert key in items
    span = items[key]["spans"][0]
    assert span["pid"] == 4242
    assert span["t1"] - span["t0"] == pytest.approx(0.5)
    # aligned onto the parent timeline: ~100s ahead of the recorder origin
    assert span["t0"] - rec._origin == pytest.approx(100.0, abs=5.0)
    assert items[key]["annotations"]["hedges"] == 1


def test_item_registry_is_bounded():
    rec = ProvenanceRecorder(max_items=4)
    for i in range(10):
        rec.note_delivery(0, i, 1)
    assert len(rec.items()) == 4


def test_batch_cut_consumes_the_delivery_fifo_in_order():
    rec = ProvenanceRecorder()
    rec.note_delivery(0, 0, 10)
    rec.note_delivery(0, 1, 6)
    bp1 = rec.producer_cut(8)
    bp2 = rec.producer_cut(8)
    assert bp1.items == [(0, 0, 8)]
    assert bp2.items == [(0, 0, 2), (0, 1, 6)]
    rec.transfer_next()
    rec.transfer_span("loader.h2d", 0.0, 0.001)
    assert rec.batch_delivered() is not None
    assert rec.batch_delivered() is not None
    batches = rec.batches()
    assert [b["seq"] for b in batches] == [1, 2]
    assert batches[0]["spans"][0]["site"] == "loader.h2d"
    assert batches[1]["step_gap_s"] is not None


def test_dropped_batches_keep_pointers_aligned():
    rec = ProvenanceRecorder()
    rec.note_delivery(0, 0, 16)
    bp1 = rec.producer_cut(8)
    bp2 = rec.producer_cut(8)
    rec.batch_dropped(bp1)
    delivered = rec.batch_delivered()
    assert delivered is bp2


# -- loader end-to-end ------------------------------------------------------------------


@pytest.mark.parametrize("pool", ["dummy", "thread"])
def test_loader_attribution_end_to_end(store, pool):
    leaked0 = _leaked_total()
    reader = make_batch_reader("file://" + store, num_epochs=2,
                               workers_count=2, reader_pool_type=pool,
                               provenance=True)
    with DataLoader(reader, 32, to_device=False) as loader:
        rows = sum(len(b["id"]) for b in loader)
    assert rows == 384
    assert _leaked_total() - leaked0 == 0
    per_item = _assert_exactly_once(loader, rows)
    assert len(per_item) == 6  # 3 files x 2 epochs
    items = loader.provenance.items()
    assert all(".parquet:" in k for k in items)
    assert all(rec["spans"] for rec in items.values())
    bp = loader.batch_provenance()
    assert bp["item_records"] and bp["rows"] == 32
    report = loader.attribution_report()
    assert report.batches == 12
    assert report.stage_self_s
    assert "critical path" in report.render() or report.verdict
    # module plane disarmed at __exit__
    assert prov.ACTIVE is None


def test_loader_without_provenance_refuses():
    loader = DataLoader.__new__(DataLoader)
    loader._prov_rec = None
    with pytest.raises(ValueError, match="provenance"):
        loader._require_provenance()


def test_process_pool_merges_child_spans_and_keys(store):
    reader = make_batch_reader("file://" + store, num_epochs=1,
                               workers_count=2, reader_pool_type="process",
                               wire_serializer="shm-view", provenance=True)
    with DataLoader(reader, 32, to_device=False) as loader:
        rows = sum(len(b["id"]) for b in loader)
    assert rows == 192
    _assert_exactly_once(loader, rows)
    items = loader.provenance.items()
    assert all(".parquet:" in k for k in items)
    local = os.getpid()
    pids = {sp["pid"] for rec in items.values() for sp in rec["spans"]}
    assert any(p != local for p in pids), "child spans did not merge"
    sites = {sp["site"] for rec in items.values() for sp in rec["spans"]}
    assert {"wire.roundtrip", "wire.decode", "child.work"} <= sites
    report = loader.attribution_report()
    assert report.batches == 6


def test_perfetto_flow_events_link_item_spans_across_pids(store, tmp_path):
    from petastorm_tpu.trace import TraceRecorder

    tracer = TraceRecorder()
    reader = make_batch_reader("file://" + store, num_epochs=1,
                               workers_count=2, reader_pool_type="process",
                               wire_serializer="shm-view", provenance=True)
    with DataLoader(reader, 64, to_device=False, trace=tracer) as loader:
        rows = sum(len(b["id"]) for b in loader)
    assert rows == 192
    path = str(tmp_path / "trace.json")
    tracer.dump(path)
    with open(path) as f:
        events = json.load(f)["traceEvents"]
    flows = [e for e in events if e["ph"] in ("s", "t", "f")]
    assert flows, "no flow events in the dump"
    by_id = {}
    for e in flows:
        by_id.setdefault(e["id"], []).append(e)
    # one flow per delivered item, each spanning >= 2 pid lanes and properly
    # terminated
    assert len(by_id) == 3
    for chain in by_id.values():
        phases = [e["ph"] for e in sorted(chain, key=lambda e: e["ts"])]
        assert phases[0] == "s" and phases[-1] == "f"
        assert len({e["pid"] for e in chain}) >= 2


def test_shuffling_disables_batch_membership_but_items_still_collect(store):
    reader = make_batch_reader("file://" + store, num_epochs=1,
                               workers_count=2, provenance=True)
    with DataLoader(reader, 32, to_device=False,
                    shuffling_queue_capacity=128, seed=1) as loader:
        rows = sum(len(b["id"]) for b in loader)
    assert rows == 192
    rec = loader.provenance
    assert len(rec._delivery_fifo) == 0  # never grows while disabled
    for b in rec.batches():
        assert b["items"] is None
    assert len(rec.items()) == 3  # item records still collected


# -- acceptance scenarios (satellite) ---------------------------------------------------


def test_attribution_under_tiered_remote_path(store):
    """CloudLatencyFS + mem tier: verdict stable across runs, tier
    annotations present, zero leaked leases, ids exactly-once — and
    bottleneck_report() keeps working beside it."""
    import pyarrow.fs as pafs

    from petastorm_tpu.io.latencyfs import CloudLatencyFS

    def run():
        fs = CloudLatencyFS(pafs.LocalFileSystem(), seed=3,
                            base_latency_s=0.01, tail_fraction=0.2,
                            tail_multiplier=5.0)
        leaked0 = _leaked_total()
        reader = make_batch_reader(
            "file://" + store, filesystem=fs, num_epochs=2, workers_count=2,
            provenance=True,
            io_options=dict(readahead=False, memcache_bytes=64 << 20,
                            remote=dict(enabled=True, hedge=False)))
        with DataLoader(reader, 32, to_device=False) as loader:
            rows = sum(len(b["id"]) for b in loader)
        assert rows == 384
        assert _leaked_total() - leaked0 == 0
        _assert_exactly_once(loader, rows)
        tiers = {rec["annotations"].get("cache_tier")
                 for rec in loader.provenance.items().values()}
        report = loader.attribution_report()
        assert loader.bottleneck_report().verdict  # coexists
        return report, tiers, loader.provenance

    # COLD run: epoch 1 pays the injected remote latency, epoch 2 serves
    # from the (process-wide) mem tier — the totals blame the remote plane
    first, tiers, recorder = run()
    assert "remote" in tiers and "mem" in tiers
    assert first.top_stage == "io.remote"
    # the verdict is STABLE: re-folding the same recorded window gives the
    # same attribution, byte for byte
    assert recorder.report().to_dict() == first.to_dict()
    # WARM run: the process-wide mem tier now serves everything — the
    # attribution must NOT keep blaming a remote plane that never ran
    second, tiers2, _rec2 = run()
    assert tiers2 == {"mem"}
    assert second.top_stage != "io.remote"
    assert second.stage_self_s.get("io.remote", 0.0) == 0.0


def test_attribution_under_quarantine_heavy_chaos(store):
    """A poison-heavy chaos plan: quarantined ids land in the provenance
    ledger exactly once, disjoint from deliveries; attempts are recorded;
    zero leaked leases; the report stays computable."""
    from petastorm_tpu import chaos
    from petastorm_tpu.chaos.plan import FaultPlan, FaultRule

    leaked0 = _leaked_total()
    plan = FaultPlan([FaultRule("worker.item", "raise_transient",
                                item_key="p1.parquet")], seed=9)
    with chaos.armed(plan):
        reader = make_batch_reader(
            "file://" + store, num_epochs=1, workers_count=2,
            provenance=True,
            recovery=dict(on_poison="quarantine", poison_attempts=2))
        with DataLoader(reader, 32, to_device=False) as loader:
            rows = sum(len(b["id"]) for b in loader)
    assert rows == 128  # p1's 64 rows quarantined away
    assert _leaked_total() - leaked0 == 0
    per_item = _assert_exactly_once(loader, rows)
    quarantined = loader.provenance.quarantined()
    assert len(quarantined) == 1
    epoch, ordinal, attempts, kind = quarantined[0]
    assert attempts == 2
    assert (epoch, ordinal) not in per_item
    items = loader.provenance.items()
    poisoned = [r for r in items.values()
                if r["annotations"].get("quarantined")]
    assert len(poisoned) == 1
    report = loader.attribution_report()
    assert report.batches == 4
    assert "quarantined" not in report.by_cause or \
        report.by_cause["quarantined"]["batches"] >= 0


# -- reader-level (loader-less) ---------------------------------------------------------


def test_loader_less_reader_records_items(store):
    reader = make_batch_reader("file://" + store, num_epochs=1,
                               workers_count=1, provenance=True)
    rec = reader._prov
    try:
        rows = 0
        for batch in reader:
            rows += len(batch.id)
        assert rows == 192
        items = rec.items()
        assert len(items) == 3
        assert all(r["rows"] == 64 for r in items.values())
    finally:
        reader.stop()
        reader.join()
        rec.disarm()


# -- Reporter rotation (satellite) ------------------------------------------------------


def test_reporter_jsonl_rotation_caps_growth(tmp_path):
    from petastorm_tpu.obs.export import Reporter
    from petastorm_tpu.obs.metrics import MetricsRegistry

    registry = MetricsRegistry()
    registry.counter("ptpu_test_total").inc()
    path = str(tmp_path / "stats.jsonl")
    reporter = Reporter(registry=registry, interval_s=600.0, jsonl_path=path,
                        max_bytes=200, keep=2)
    for _ in range(12):
        reporter._write_once()
    size = os.path.getsize(path)
    assert size <= 200 + 120  # cap + at most one line of slack
    rotated = sorted(p.name for p in tmp_path.iterdir())
    assert "stats.jsonl.1" in rotated and "stats.jsonl.2" in rotated
    assert "stats.jsonl.3" not in rotated  # keep=2 bounds the chain
    # every surviving file holds well-formed snapshot lines
    for name in ("stats.jsonl", "stats.jsonl.1", "stats.jsonl.2"):
        with open(str(tmp_path / name)) as f:
            for line in f:
                assert "metrics" in json.loads(line)


def test_reporter_rotation_preserves_stop_flush(tmp_path):
    from petastorm_tpu.obs.export import Reporter
    from petastorm_tpu.obs.metrics import MetricsRegistry

    registry = MetricsRegistry()
    path = str(tmp_path / "stats.jsonl")
    with Reporter(registry=registry, interval_s=600.0, jsonl_path=path,
                  max_bytes=10_000, keep=1):
        pass  # stop() writes the final snapshot through the rotation path
    with open(path) as f:
        assert "metrics" in json.loads(f.readline())


def test_reporter_without_cap_never_rotates(tmp_path):
    from petastorm_tpu.obs.export import Reporter
    from petastorm_tpu.obs.metrics import MetricsRegistry

    path = str(tmp_path / "stats.jsonl")
    reporter = Reporter(registry=MetricsRegistry(), interval_s=600.0,
                        jsonl_path=path)
    for _ in range(5):
        reporter._write_once()
    assert [p.name for p in tmp_path.iterdir()] == ["stats.jsonl"]


# -- stats dashboard panels (satellite) -------------------------------------------------


def test_dashboard_renders_remote_tier_transform_and_prov_panels():
    from petastorm_tpu.obs.stats_cli import render_dashboard

    metrics = {
        "ptpu_io_tier_hits_total{tier=\"mem\"}": 5,
        "ptpu_io_tier_hits_total{tier=\"remote\"}": 2,
        "ptpu_io_tier_bytes_total{tier=\"mem\"}": 1e6,
        "ptpu_io_tier_bytes_total{tier=\"remote\"}": 2e6,
        "ptpu_io_remote_gets_total": 12,
        "ptpu_io_remote_bytes_total": 3.2e7,
        "ptpu_io_hedges_total": 4,
        "ptpu_io_hedge_wins_total": 3,
        "ptpu_io_remote_sparse_fallbacks_total": 0,
        "ptpu_io_footer_cache_hits_total": 9,
        "ptpu_io_footer_cache_misses_total": 1,
        "ptpu_io_remote_get_seconds{size_class=\"20\",store=\"s\"}":
            {"count": 12, "sum": 0.6, "mean": 0.05, "p50": 0.04, "p90": 0.09,
             "p99": 0.2},
        "ptpu_transform_seconds{op=\"normalize(x)\"}":
            {"count": 6, "sum": 0.3, "mean": 0.05, "p50": 0.04, "p90": 0.08,
             "p99": 0.1},
        "ptpu_transform_rows_total": 384,
        "ptpu_prov_items": 6,
        "ptpu_prov_batches": 12,
        "ptpu_prov_quarantined": 1,
        "ptpu_prov_self_s_io_remote": 1.25,
        "ptpu_prov_self_s_transform": 0.25,
    }
    out = render_dashboard(metrics, title="t")
    assert "cache tiers:" in out and "remote hits=2" in out
    assert "remote io:" in out and "hedges=4 (wins=3)" in out
    assert "footer cache: hits=9" in out
    assert "transform ops" in out and "normalize(x)" in out
    assert "attribution" in out and "io_remote" in out
    assert "quarantined items: 1" in out
    # the new families no longer spill into the catch-all section
    assert "other metrics:" not in out


# -- post-review regressions ------------------------------------------------------------


def test_fold_sibling_pop_preserves_grandparent():
    """A partial-overlap sibling pops only the top of the stack — enclosing
    ancestors that still contain the new span keep their parenthood."""
    out = fold_self_times([("gp", 0.0, 10.0, 1), ("a", 1.0, 4.0, 1),
                           ("b", 3.0, 9.0, 1)])
    assert out["a"] == pytest.approx(3.0)
    assert out["b"] == pytest.approx(6.0)
    assert out["gp"] == pytest.approx(1.0)  # 10 - 3 - 6: both nested


def test_concurrent_items_fold_per_record_not_merged():
    """Two items' interleaved timelines must not double-charge outer spans
    (the review repro): each record folds alone, nesting intact."""
    views = [{"seq": 1, "rows": 8, "step_gap_s": 0.1, "spans": [],
              "items": [(0, 0, 4), (0, 1, 4)],
              "item_records": [
                  {"annotations": {}, "attempts": 1, "spans": [
                      {"site": "reader.read", "t0": 0, "t1": 10, "pid": 1},
                      {"site": "io.remote", "t0": 1, "t1": 9, "pid": 1}]},
                  {"annotations": {}, "attempts": 1, "spans": [
                      {"site": "reader.read", "t0": 0.5, "t1": 10.5,
                       "pid": 2}]}]}]
    rep = analyze_batches(views)
    # chain A: read self 10-8=2s + remote 8s; chain B: read self 10s —
    # summing to each chain's own wall, never the merged-timeline 20s
    assert rep.stage_self_s["io.remote"] == pytest.approx(8.0)
    assert rep.stage_self_s["reader.read"] == pytest.approx(12.0)


def test_factory_recorder_released_at_reader_teardown(store):
    """A factory-built recorder must release the process-global slot at
    reader join (the review lifecycle leak): a SECOND provenance reader in
    the same process works after the first is torn down — and stays refused
    while the first is live."""
    r1 = make_batch_reader("file://" + store, num_epochs=1, provenance=True)
    try:
        with pytest.raises(RuntimeError, match="armed"):
            make_batch_reader("file://" + store, num_epochs=1,
                              provenance=True)
    finally:
        r1.stop()
        r1.join()
    assert prov.ACTIVE is None  # join released the slot
    r2 = make_batch_reader("file://" + store, num_epochs=1, provenance=True)
    try:
        rows = sum(len(b.id) for b in r2)
        assert rows == 192
        assert len(r2._prov.items()) == 3
    finally:
        r2.stop()
        r2.join()
    assert prov.ACTIVE is None


def test_reset_rearms_the_recorder(store):
    """reset() goes through join() (disarm) then _start (re-arm): the second
    pass must keep recording."""
    reader = make_batch_reader("file://" + store, num_epochs=1,
                               provenance=True)
    try:
        assert sum(len(b.id) for b in reader) == 192
        reader.reset()
        assert prov.ACTIVE is reader._prov
        assert sum(len(b.id) for b in reader) == 192
        items = reader._prov.items()
        assert all(r["rows"] == 128 for r in items.values())  # both passes
    finally:
        reader.stop()
        reader.join()


def test_caller_supplied_recorder_stays_armed_past_teardown(store):
    """A recorder the CALLER passed in is the caller's to disarm — loader
    __exit__ / reader join must not release it."""
    rec = ProvenanceRecorder()
    reader = make_batch_reader("file://" + store, num_epochs=1,
                               provenance=rec)
    with DataLoader(reader, 32, to_device=False, provenance=rec) as loader:
        assert sum(len(b["id"]) for b in loader) == 192
    assert prov.ACTIVE is rec  # still armed: caller-owned
    rec.disarm()


def test_summary_is_cached_until_the_window_moves():
    rec = ProvenanceRecorder()
    rec.note_delivery(0, 0, 8)
    rec.producer_cut(8)
    rec.batch_delivered()
    first = rec.summary()
    assert rec._summary_cache is not None
    assert rec.summary() == first  # served from cache, equal content
    rec.note_delivery(0, 1, 8)
    rec.producer_cut(8)
    rec.batch_delivered()
    second = rec.summary()
    assert second["batches"] == 2  # cache invalidated by the new batch
