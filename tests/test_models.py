"""Model-zoo architecture pins.

ResNet18/34 must be the basic-block variants (He et al. 2015 table 1) — VERDICT r3 #8
flagged that earlier rounds aliased them onto bottleneck stacks. The param counts below
are the canonical torchvision numbers (trainable params; BN running stats are flax
batch_stats collections, excluded like torch buffers).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from petastorm_tpu.models.resnet import BasicBlock, BottleneckBlock, ResNet18, ResNet34, ResNet50


def _param_count(model):
    variables = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)), train=False))
    return sum(int(np.prod(leaf.shape)) for leaf in jax.tree_util.tree_leaves(variables["params"]))


@pytest.mark.parametrize("model_fn,expected", [
    (ResNet18, 11_689_512),   # torchvision resnet18
    (ResNet34, 21_797_672),   # torchvision resnet34
    (ResNet50, 25_557_032),   # torchvision resnet50
])
def test_param_counts_canonical(model_fn, expected):
    assert _param_count(model_fn(num_classes=1000)) == expected


def test_block_classes():
    assert ResNet18().block_cls is BasicBlock
    assert ResNet34().block_cls is BasicBlock
    assert ResNet50().block_cls is BottleneckBlock


def test_basic_block_forward_shapes():
    model = ResNet18(num_classes=10)
    x = jnp.zeros((2, 64, 64, 3))
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    out = model.apply(variables, x, train=False)
    assert out.shape == (2, 10)
    assert out.dtype == jnp.float32
