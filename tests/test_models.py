"""Model-zoo architecture pins.

ResNet18/34 must be the basic-block variants (He et al. 2015 table 1) — VERDICT r3 #8
flagged that earlier rounds aliased them onto bottleneck stacks. The param counts below
are the canonical torchvision numbers (trainable params; BN running stats are flax
batch_stats collections, excluded like torch buffers).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from petastorm_tpu.models.resnet import BasicBlock, BottleneckBlock, ResNet18, ResNet34, ResNet50


def _param_count(model):
    variables = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)), train=False))
    return sum(int(np.prod(leaf.shape)) for leaf in jax.tree_util.tree_leaves(variables["params"]))


@pytest.mark.parametrize("model_fn,expected", [
    (ResNet18, 11_689_512),   # torchvision resnet18
    (ResNet34, 21_797_672),   # torchvision resnet34
    (ResNet50, 25_557_032),   # torchvision resnet50
])
def test_param_counts_canonical(model_fn, expected):
    assert _param_count(model_fn(num_classes=1000)) == expected


def test_block_classes():
    assert ResNet18().block_cls is BasicBlock
    assert ResNet34().block_cls is BasicBlock
    assert ResNet50().block_cls is BottleneckBlock


def _vit_param_count(model, image=224):
    variables = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, image, image, 3)), train=False))
    return sum(int(np.prod(leaf.shape)) for leaf in jax.tree_util.tree_leaves(variables["params"]))


def test_vit_param_counts_canonical():
    """ViT-B/16 at 224^2/1000cls is 86.6M params (Dosovitskiy et al. table 1 /
    timm vit_base_patch16_224: 86,567,656); S/16 is 22.1M."""
    from petastorm_tpu.models.vit import ViT_B16, ViT_S16

    assert _vit_param_count(ViT_B16(num_classes=1000)) == 86_567_656
    assert _vit_param_count(ViT_S16(num_classes=1000)) == 22_050_664


def test_vit_forward_and_dropout():
    """A uint8 image batch (the loader's delivery dtype) runs straight through
    (patchify handles the cast, logits float32), and the train flag has a real
    effect: with dropout_rate > 0, train=True needs a dropout rng and perturbs
    outputs, train=False is deterministic."""
    from petastorm_tpu.models.vit import ViT

    model = ViT(num_classes=10, patch_size=8, hidden_size=64, num_layers=2,
                num_heads=4, mlp_dim=128, dropout_rate=0.5)
    x = np.random.RandomState(0).randint(0, 255, (2, 32, 32, 3)).astype(np.uint8)
    x = jnp.asarray(x)
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    eval1 = model.apply(variables, x, train=False)
    eval2 = model.apply(variables, x, train=False)
    np.testing.assert_array_equal(np.asarray(eval1), np.asarray(eval2))
    assert eval1.shape == (2, 10) and eval1.dtype == jnp.float32
    tr1 = model.apply(variables, x, train=True,
                      rngs={"dropout": jax.random.PRNGKey(1)})
    tr2 = model.apply(variables, x, train=True,
                      rngs={"dropout": jax.random.PRNGKey(2)})
    assert not np.array_equal(np.asarray(tr1), np.asarray(tr2))


def test_basic_block_forward_shapes():
    model = ResNet18(num_classes=10)
    x = jnp.zeros((2, 64, 64, 3))
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    out = model.apply(variables, x, train=False)
    assert out.shape == (2, 10)
    assert out.dtype == jnp.float32
