# Sphinx configuration (reference parity: petastorm ships docs/ + readthedocs; this
# image has no sphinx installed, so the docs build runs on RTD/CI, not locally).
import os
import sys

sys.path.insert(0, os.path.abspath(".."))

project = "petastorm_tpu"
author = "petastorm_tpu developers"
release = "0.2.0"

extensions = [
    "sphinx.ext.autodoc",
    "sphinx.ext.napoleon",
    "sphinx.ext.viewcode",
    "sphinx.ext.intersphinx",
]
# static_analysis.md is markdown; render it when myst is available (RTD/CI
# installs it), degrade to a toctree warning when not
try:
    import myst_parser  # noqa: F401

    extensions.append("myst_parser")
except ImportError:
    pass
autodoc_mock_imports = ["jax", "jaxlib", "flax", "optax", "cv2", "torch",
                        "tensorflow", "pyspark"]
intersphinx_mapping = {
    "python": ("https://docs.python.org/3", None),
    "numpy": ("https://numpy.org/doc/stable/", None),
}
html_theme = "alabaster"
master_doc = "index"
