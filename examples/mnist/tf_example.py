"""MNIST with the TensorFlow adapter (reference examples/mnist/tf_example.py):
Parquet → make_batch_reader → petastorm_tpu.adapters.tf.make_petastorm_dataset →
tf.data pipeline → a small Keras CNN.

Run: python examples/mnist/tf_example.py [--epochs 1]
"""
import argparse
import tempfile

from train_mnist_jax import generate_mnist_parquet


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--path", default=None)
    parser.add_argument("--epochs", type=int, default=1)
    parser.add_argument("--batch-size", type=int, default=128)
    args = parser.parse_args()

    import tensorflow as tf

    from petastorm_tpu import make_batch_reader
    from petastorm_tpu.adapters.tf import make_petastorm_dataset

    path = args.path or tempfile.mkdtemp(prefix="mnist_pq")
    generate_mnist_parquet(path)
    url = "file://" + path

    model = tf.keras.Sequential([
        tf.keras.layers.Input((28, 28, 1)),
        tf.keras.layers.Conv2D(16, 3, activation="relu", padding="same"),
        tf.keras.layers.MaxPool2D(),
        tf.keras.layers.Conv2D(32, 3, activation="relu", padding="same"),
        tf.keras.layers.MaxPool2D(),
        tf.keras.layers.Flatten(),
        tf.keras.layers.Dense(10),
    ])
    model.compile(
        optimizer="adam",
        loss=tf.keras.losses.SparseCategoricalCrossentropy(from_logits=True),
        metrics=["accuracy"],
    )

    def prep(batch):
        image = tf.cast(tf.reshape(batch["image"], (-1, 28, 28, 1)), tf.float32) / 255.0
        return image, batch["digit"]

    for epoch in range(args.epochs):
        with make_batch_reader(url, num_epochs=1, shuffle_row_groups=True,
                               seed=epoch) as reader:
            ds = make_petastorm_dataset(reader).map(prep)
            model.fit(ds, epochs=1, verbose=2)


if __name__ == "__main__":
    main()
