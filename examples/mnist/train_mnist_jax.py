"""MNIST end-to-end (reference examples/mnist): synthetic-or-real MNIST → Parquet →
make_batch_reader → JAX DataLoader → jitted train step on MnistCNN.

The acceptance slice from SURVEY.md §8: schema inference, row-group planning, async
device_put prefetch, sharded jax.Array batch, epoch semantics — all in ~100 lines.
"""
import argparse
import tempfile
import time

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq


def generate_mnist_parquet(path, rows=2048):
    """Writes an MNIST-shaped Parquet dataset (random pixels unless real data is at hand)."""
    rng = np.random.RandomState(0)
    images = rng.randint(0, 256, (rows, 28 * 28), dtype=np.uint8)
    labels = rng.randint(0, 10, rows).astype(np.int32)
    table = pa.table({
        "image": pa.FixedSizeListArray.from_arrays(pa.array(images.reshape(-1)), 28 * 28),
        "digit": labels,
    })
    pq.write_table(table, path + "/mnist.parquet", row_group_size=256)


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--path", default=None)
    parser.add_argument("--epochs", type=int, default=2)
    parser.add_argument("--batch-size", type=int, default=128)
    parser.add_argument("--in-memory", action="store_true",
                        help="load the dataset to device memory once and serve epochs "
                             "as on-device permutation gathers (InMemDataLoader) — "
                             "zero host work per step, ideal at MNIST scale")
    args = parser.parse_args()

    import jax
    import jax.numpy as jnp
    import optax

    from petastorm_tpu import make_batch_reader
    from petastorm_tpu.loader import DataLoader
    from petastorm_tpu.models.mnist import MnistCNN
    from petastorm_tpu.transform import TransformSpec

    path = args.path or tempfile.mkdtemp(prefix="mnist_pq")
    generate_mnist_parquet(path)
    url = "file://" + path

    model = MnistCNN()
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 28, 28, 1)))
    tx = optax.adam(1e-3)
    opt_state = tx.init(params)

    prep = TransformSpec(
        func=lambda b: {"image": b["image"].reshape(-1, 28, 28, 1).astype(jnp.float32) / 255.0,
                        "digit": b["digit"]},
        device=True)

    @jax.jit
    def train_step(params, opt_state, batch):
        def loss_fn(p):
            logits = model.apply(p, batch["image"])
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, batch["digit"]).mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state)
        return optax.apply_updates(params, updates), opt_state, loss

    t0 = time.perf_counter()
    steps = 0
    if args.in_memory:
        from petastorm_tpu.loader import InMemDataLoader

        reader = make_batch_reader(url, num_epochs=1, transform_spec=prep,
                                   shuffle_row_groups=False)
        loader_cm = InMemDataLoader(reader, args.batch_size, num_epochs=args.epochs,
                                    seed=0)
    else:
        reader = make_batch_reader(url, num_epochs=args.epochs, transform_spec=prep,
                                   shuffle_row_groups=True, seed=0)
        loader_cm = DataLoader(reader, args.batch_size, shuffling_queue_capacity=1024)
    with loader_cm as loader:
        for batch in loader:
            params, opt_state, loss = train_step(params, opt_state, batch)
            steps += 1
    print("trained %d steps in %.1fs, final loss %.4f" % (steps,
                                                          time.perf_counter() - t0,
                                                          float(loss)))


if __name__ == "__main__":
    main()
