"""MNIST with the PyTorch adapter (reference examples/mnist/pytorch_example.py):
Parquet → make_batch_reader → petastorm_tpu.adapters.pytorch.BatchedDataLoader →
a small torch CNN train loop (CPU torch is fine).

Run: python examples/mnist/pytorch_example.py [--epochs 1]
"""
import argparse
import tempfile

from train_mnist_jax import generate_mnist_parquet


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--path", default=None)
    parser.add_argument("--epochs", type=int, default=1)
    parser.add_argument("--batch-size", type=int, default=128)
    args = parser.parse_args()

    import torch
    import torch.nn as nn
    import torch.nn.functional as F

    from petastorm_tpu import make_batch_reader
    from petastorm_tpu.adapters.pytorch import BatchedDataLoader

    path = args.path or tempfile.mkdtemp(prefix="mnist_pq")
    generate_mnist_parquet(path)
    url = "file://" + path

    class Net(nn.Module):
        def __init__(self):
            super().__init__()
            self.conv1 = nn.Conv2d(1, 16, 3, padding=1)
            self.conv2 = nn.Conv2d(16, 32, 3, padding=1)
            self.fc = nn.Linear(32 * 7 * 7, 10)

        def forward(self, x):
            x = F.max_pool2d(F.relu(self.conv1(x)), 2)
            x = F.max_pool2d(F.relu(self.conv2(x)), 2)
            return self.fc(x.flatten(1))

    model = Net()
    opt = torch.optim.Adam(model.parameters(), lr=1e-3)

    for epoch in range(args.epochs):
        reader = make_batch_reader(url, num_epochs=1, shuffle_row_groups=True, seed=epoch)
        loader = BatchedDataLoader(reader, batch_size=args.batch_size,
                                   shuffling_queue_capacity=4096)
        total, correct, steps = 0, 0, 0
        with loader:
            for batch in loader:
                images = batch["image"].float().reshape(-1, 1, 28, 28) / 255.0
                labels = batch["digit"].long()
                opt.zero_grad()
                logits = model(images)
                loss = F.cross_entropy(logits, labels)
                loss.backward()
                opt.step()
                correct += (logits.argmax(1) == labels).sum().item()
                total += len(labels)
                steps += 1
        print("epoch %d: %d steps, train acc %.3f" % (epoch, steps, correct / max(1, total)))


if __name__ == "__main__":
    main()
