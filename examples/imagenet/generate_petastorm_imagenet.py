"""ImageNet → petastorm-format Parquet (reference examples/imagenet): JPEG-encoded images
stored via CompressedImageCodec, read back with on-device decode-friendly layout.

Pass a directory tree of JPEGs (class-per-subdir) or omit it for a synthetic smoke run.
"""
import argparse
import os
import tempfile

import numpy as np

from petastorm_tpu.codecs import CompressedImageCodec, ScalarCodec
from petastorm_tpu.metadata import RowWriter
from petastorm_tpu.types import StringType
from petastorm_tpu.unischema import Unischema, UnischemaField

ImagenetSchema = Unischema("ImagenetSchema", [
    UnischemaField("noun_id", np.str_, (), ScalarCodec(StringType()), False),
    UnischemaField("text", np.str_, (), ScalarCodec(StringType()), False),
    UnischemaField("image", np.uint8, (None, None, 3), CompressedImageCodec("jpeg", 90),
                   False),
])


def training_schema(size):
    """Fixed-shape training layout: resized square images + integer labels — the shape
    the on-device decode path and train_imagenet_jax.py consume (uniform image size per
    batch is the device-decode contract)."""
    from petastorm_tpu.types import IntegerType

    return Unischema("ImagenetTrainSchema", [
        UnischemaField("noun_id", np.str_, (), ScalarCodec(StringType()), False),
        UnischemaField("label", np.int32, (), ScalarCodec(IntegerType()), False),
        UnischemaField("image", np.uint8, (size, size, 3),
                       CompressedImageCodec("jpeg", 90), False),
    ])


def _iter_images(src):
    if src is None:
        rng = np.random.RandomState(0)
        for i in range(32):
            yield ("n%08d" % i, "synthetic_%d" % i,
                   rng.randint(0, 256, (64, 64, 3), dtype=np.uint8))
        return
    import cv2

    for noun_id in sorted(os.listdir(src)):
        cls_dir = os.path.join(src, noun_id)
        if not os.path.isdir(cls_dir):
            continue
        for fname in sorted(os.listdir(cls_dir)):
            img = cv2.imread(os.path.join(cls_dir, fname))
            if img is None:
                continue
            yield noun_id, fname, cv2.cvtColor(img, cv2.COLOR_BGR2RGB)


def _resize_square(img, size):
    """Shorter-side resize + center crop to (size, size, 3) — standard train layout."""
    import cv2

    h, w = img.shape[:2]
    scale = size / min(h, w)
    img = cv2.resize(img, (max(size, int(round(w * scale))),
                           max(size, int(round(h * scale)))),
                     interpolation=cv2.INTER_AREA)
    h, w = img.shape[:2]
    y, x = (h - size) // 2, (w - size) // 2
    return np.ascontiguousarray(img[y:y + size, x:x + size])


def generate(url, src=None, size=None):
    if size is None:
        with RowWriter(url, ImagenetSchema, row_group_size_mb=64) as writer:
            for noun_id, text, img in _iter_images(src):
                writer.write({"noun_id": noun_id, "text": text, "image": img})
        return
    schema = training_schema(size)
    labels = {}
    with RowWriter(url, schema, row_group_size_mb=64) as writer:
        for noun_id, _text, img in _iter_images(src):
            label = labels.setdefault(noun_id, len(labels))
            writer.write({"noun_id": noun_id, "label": np.int32(label),
                          "image": _resize_square(img, size)})


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--src", default=None, help="ImageNet root (class dirs of JPEGs)")
    parser.add_argument("--url", default=None)
    parser.add_argument("--size", type=int, default=None,
                        help="write the fixed-shape training layout (resize + center "
                             "crop to SIZE, add integer labels) instead of raw shapes")
    args = parser.parse_args()
    url = args.url or "file://" + tempfile.mkdtemp(prefix="imagenet_pq")
    generate(url, args.src, args.size)
    from petastorm_tpu import make_reader

    with make_reader(url, schema_fields=["noun_id", "image"]) as reader:
        row = next(iter(reader))
        print(row.noun_id, row.image.shape)


if __name__ == "__main__":
    main()
