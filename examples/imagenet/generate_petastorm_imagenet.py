"""ImageNet → petastorm-format Parquet (reference examples/imagenet): JPEG-encoded images
stored via CompressedImageCodec, read back with on-device decode-friendly layout.

Pass a directory tree of JPEGs (class-per-subdir) or omit it for a synthetic smoke run.
"""
import argparse
import os
import tempfile

import numpy as np

from petastorm_tpu.codecs import CompressedImageCodec, ScalarCodec
from petastorm_tpu.metadata import RowWriter
from petastorm_tpu.types import StringType
from petastorm_tpu.unischema import Unischema, UnischemaField

ImagenetSchema = Unischema("ImagenetSchema", [
    UnischemaField("noun_id", np.str_, (), ScalarCodec(StringType()), False),
    UnischemaField("text", np.str_, (), ScalarCodec(StringType()), False),
    UnischemaField("image", np.uint8, (None, None, 3), CompressedImageCodec("jpeg", 90),
                   False),
])


def _iter_images(src):
    if src is None:
        rng = np.random.RandomState(0)
        for i in range(32):
            yield ("n%08d" % i, "synthetic_%d" % i,
                   rng.randint(0, 256, (64, 64, 3), dtype=np.uint8))
        return
    import cv2

    for noun_id in sorted(os.listdir(src)):
        cls_dir = os.path.join(src, noun_id)
        if not os.path.isdir(cls_dir):
            continue
        for fname in sorted(os.listdir(cls_dir)):
            img = cv2.imread(os.path.join(cls_dir, fname))
            if img is None:
                continue
            yield noun_id, fname, cv2.cvtColor(img, cv2.COLOR_BGR2RGB)


def generate(url, src=None):
    with RowWriter(url, ImagenetSchema, row_group_size_mb=64) as writer:
        for noun_id, text, img in _iter_images(src):
            writer.write({"noun_id": noun_id, "text": text, "image": img})


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--src", default=None, help="ImageNet root (class dirs of JPEGs)")
    parser.add_argument("--url", default=None)
    args = parser.parse_args()
    url = args.url or "file://" + tempfile.mkdtemp(prefix="imagenet_pq")
    generate(url, args.src)
    from petastorm_tpu import make_reader

    with make_reader(url, schema_fields=["noun_id", "image"]) as reader:
        row = next(iter(reader))
        print(row.noun_id, row.image.shape)


if __name__ == "__main__":
    main()
