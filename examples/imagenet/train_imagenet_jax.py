"""ImageNet training on TPU: the north-star pipeline end to end.

Parquet (JPEG via CompressedImageCodec) → ``make_batch_reader(decode_on_device=True)``
(native C++ entropy decode in the reader pool) → ``DataLoader`` (batched Pallas/XLA
dequant+IDCT+color on device, async transfer thread, data-parallel sharding over every
local device) → ResNet-50 train step under ``jit``.

Reference analog: examples/imagenet + the pytorch/tf mnist training loops; this is the
acceptance config BASELINE.json names (ImageNet-1k JPEG, on-device decode). Run
``generate_petastorm_imagenet.py`` first (or point --dataset-url at any dataset written
with a fixed-shape jpeg image field), e.g.::

    python generate_petastorm_imagenet.py --url file:///tmp/imagenet_pq --size 224
    python train_imagenet_jax.py --dataset-url file:///tmp/imagenet_pq --steps 100
"""
import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

from petastorm_tpu.loader import DataLoader
from petastorm_tpu.models.resnet import ResNet50
from petastorm_tpu.parallel import batch_sharding, make_mesh
from petastorm_tpu.reader import make_batch_reader


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--dataset-url", required=True)
    parser.add_argument("--batch-size", type=int, default=256)
    parser.add_argument("--steps", type=int, default=100)
    parser.add_argument("--learning-rate", type=float, default=0.1)
    parser.add_argument("--workers", type=int, default=4)
    parser.add_argument("--num-classes", type=int, default=1000)
    parser.add_argument("--model", choices=["resnet50", "vit_b16", "vit_s16"],
                        default="resnet50",
                        help="consumer model: ResNet-50 (conv, batch-norm state) or "
                             "a ViT (patchify + attention; same data plane)")
    parser.add_argument("--host-decode", action="store_true",
                        help="disable the two-stage on-device JPEG decode (baseline)")
    parser.add_argument("--augment", action="store_true",
                        help="on-device random crop (stored size must exceed 224) + "
                             "horizontal flip, keyed per batch by the loader")
    parser.add_argument("--decode-resize", type=int, default=0,
                        help="on-device resize target (pixels, square) for stores "
                             "with MIXED image sizes; 0 = require a uniform store")
    parser.add_argument("--trace", metavar="PATH", default=None,
                        help="write a chrome://tracing span trace of the pipeline + "
                             "train steps to PATH at exit")
    args = parser.parse_args()
    if args.decode_resize and args.host_decode:
        parser.error("--decode-resize requires the on-device decode path "
                     "(drop --host-decode, or resize the store on write)")

    mesh = make_mesh()  # all local devices on a 'dp' axis
    sharding = batch_sharding(mesh)

    reader = make_batch_reader(
        args.dataset_url, workers_count=args.workers, num_epochs=None,
        shuffle_row_groups=True, decode_on_device=not args.host_decode,
        schema_fields=["image", "label"],
    )

    # init at the shape batches will actually have: ViT's position embedding is
    # resolution-dependent (ResNet is agnostic via global pooling, but one init
    # path keeps the example honest for both)
    if args.decode_resize:
        init_hw = (args.decode_resize, args.decode_resize)
    else:
        field_shape = reader.schema.fields["image"].shape
        init_hw = tuple(field_shape[:2]) if field_shape and None not in field_shape \
            else (224, 224)
    if args.augment and init_hw[0] > 224 and init_hw[1] > 224:
        init_hw = (224, 224)  # the device_transform random-crops to 224 below
    if args.model == "resnet50":
        model = ResNet50(num_classes=args.num_classes)
    else:
        from petastorm_tpu.models.vit import ViT_B16, ViT_S16

        model = (ViT_B16 if args.model == "vit_b16" else ViT_S16)(
            num_classes=args.num_classes)
    rng = jax.random.PRNGKey(0)
    dummy = jnp.zeros((2,) + init_hw + (3,), jnp.float32)
    variables = model.init(rng, dummy, train=False)
    params, batch_stats = variables["params"], variables.get("batch_stats", {})
    tx = optax.sgd(args.learning_rate, momentum=0.9, nesterov=True)
    opt_state = tx.init(params)

    @jax.jit
    def train_step(params, batch_stats, opt_state, image, label, dropout_rng):
        def loss_fn(p):
            x = image.astype(jnp.float32) / 255.0
            variables = {"params": p}
            # `batch_stats` is a pytree dict: its truthiness (empty vs not) is
            # fixed at trace time, so this branch is static, not a tracer leak
            if batch_stats:  # graftlint: disable=GL-J002
                variables["batch_stats"] = batch_stats
                out, updates = model.apply(variables, x, train=True,
                                           mutable=["batch_stats"])
                new_stats = updates["batch_stats"]
            else:  # ViT: no mutable state; dropout stays LIVE in training
                out = model.apply(variables, x, train=True,
                                  rngs={"dropout": dropout_rng})
                new_stats = batch_stats
            loss = optax.softmax_cross_entropy_with_integer_labels(out, label).mean()
            return loss, new_stats

        (loss, new_stats), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), new_stats, opt_state, loss

    device_transform = None
    if args.augment:
        from petastorm_tpu.ops.image import random_crop

        def device_transform(batch, key):
            img = batch["image"]
            kc, kf = jax.random.split(key)
            if img.shape[1] > 224 and img.shape[2] > 224:
                img = random_crop(img, kc, 224, 224)
            flips = jax.random.bernoulli(kf, 0.5, (img.shape[0],))
            img = jnp.where(flips[:, None, None, None], img[:, :, ::-1, :], img)
            return {**batch, "image": img}

    # Stores with mixed image sizes (raw, un-resized corpora) batch at one static
    # shape via the on-device resize; uniform pre-resized stores skip it (no-op).
    resize = None
    if args.decode_resize:
        resize = (args.decode_resize, args.decode_resize)
    tracer = None
    if args.trace:
        from petastorm_tpu.trace import TraceRecorder

        tracer = TraceRecorder()
    step = 0
    t0 = time.perf_counter()
    try:
        with DataLoader(reader, args.batch_size, sharding=sharding,
                        device_transform=device_transform,
                        device_decode_resize=resize, trace=tracer) as loader:
            import contextlib

            dropout_base = jax.random.PRNGKey(0)
            for batch in loader:
                with tracer.span("train.step") if tracer is not None \
                        else contextlib.nullcontext():
                    params, batch_stats, opt_state, loss = train_step(
                        params, batch_stats, opt_state, batch["image"],
                        jnp.asarray(batch["label"]),
                        jax.random.fold_in(dropout_base, step))
                step += 1
                if step % 20 == 0:
                    jax.block_until_ready(loss)
                    dt = time.perf_counter() - t0
                    print("step %d loss %.4f  %.1f img/s  stages=%s"
                          % (step, float(loss), step * args.batch_size / dt,
                             loader.stats.snapshot()))
                if step >= args.steps:
                    jax.block_until_ready(loss)
                    break
    finally:
        # a crash or Ctrl-C mid-run is exactly when the trace matters
        if tracer is not None:
            print("trace written to", tracer.dump(args.trace))
    print("done: %d steps, %.1f img/s overall"
          % (step, step * args.batch_size / (time.perf_counter() - t0)))


if __name__ == "__main__":
    main()
