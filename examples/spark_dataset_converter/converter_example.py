"""Spark DataFrame → JAX/torch loaders via SparkDatasetConverter (reference
examples/spark_dataset_converter). Requires pyspark; the Spark-free equivalent workflow
(pyarrow write + make_batch_reader) is shown as the fallback."""
import tempfile


def spark_path():
    from pyspark.sql import SparkSession

    from petastorm_tpu.spark import SparkDatasetConverter, make_spark_converter

    spark = SparkSession.builder.master("local[2]").getOrCreate()
    spark.conf.set(SparkDatasetConverter.PARENT_CACHE_DIR_URL_CONF,
                   "file://" + tempfile.mkdtemp(prefix="converter_cache"))
    df = spark.range(1000).selectExpr("id", "rand() as feature")
    converter = make_spark_converter(df)
    print("materialized %d rows" % len(converter))
    with converter.make_torch_dataloader(batch_size=64) as loader:
        for batch in loader:
            print("torch batch:", {k: tuple(v.shape) for k, v in batch.items()})
            break
    loader = converter.make_jax_dataloader(batch_size=64)
    with loader:
        for batch in loader:
            print("jax batch:", {k: v.shape for k, v in batch.items()})
            break
    converter.delete()


def arrow_fallback():
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    from petastorm_tpu.loader import make_dataloader

    path = tempfile.mkdtemp(prefix="converter_fallback")
    rng = np.random.RandomState(0)
    pq.write_table(pa.table({"id": np.arange(1000), "feature": rng.rand(1000)}),
                   path + "/data.parquet")
    loader = make_dataloader("file://" + path, batch_size=64)
    with loader:
        for batch in loader:
            print("jax batch (no spark):", {k: v.shape for k, v in batch.items()})
            break


if __name__ == "__main__":
    try:
        import pyspark  # noqa: F401

        spark_path()
    except ImportError:
        print("pyspark not installed; running the pyarrow-native equivalent")
        arrow_fallback()
