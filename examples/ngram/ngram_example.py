"""NGram windowed reading (reference NGram usage): consecutive timestamped rows assembled
into {offset: row} windows for sequence models."""
import argparse
import tempfile

import numpy as np

from petastorm_tpu import make_reader
from petastorm_tpu.codecs import NdarrayCodec, ScalarCodec
from petastorm_tpu.metadata import write_dataset
from petastorm_tpu.ngram import NGram
from petastorm_tpu.types import LongType
from petastorm_tpu.unischema import Unischema, UnischemaField

SeqSchema = Unischema("SeqSchema", [
    UnischemaField("timestamp", np.int64, (), ScalarCodec(LongType()), False),
    UnischemaField("sensor", np.float32, (8,), NdarrayCodec(), False),
])


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--url", default=None)
    args = parser.parse_args()
    url = args.url or "file://" + tempfile.mkdtemp(prefix="ngram_ds")

    rng = np.random.RandomState(0)
    write_dataset(url, SeqSchema, (
        {"timestamp": t, "sensor": rng.standard_normal(8).astype(np.float32)}
        for t in range(100)
    ))

    def mk():
        return NGram(fields={-1: ["timestamp", "sensor"],
                             0: ["timestamp", "sensor"],
                             1: ["timestamp"]},
                     delta_threshold=2, timestamp_field="timestamp")

    # reference-style per-row windows: {offset: row namedtuple} dicts
    with make_reader(url, schema_fields=mk(), shuffle_row_groups=False) as reader:
        for i, window in enumerate(reader):
            if i < 3:
                print({k: (v.timestamp, getattr(v, "sensor", None) is not None)
                       for k, v in window.items()})
        print("per-row windows:", i + 1)

    # COLUMNAR windows (TPU-first, ~7x faster): whole row groups windowed
    # in-worker, delivered as flat 'offset/field' columns — feed these straight
    # to the JAX DataLoader for device batches
    from petastorm_tpu.reader import make_batch_reader

    total = 0
    with make_batch_reader(url, schema_fields=mk(),
                           shuffle_row_groups=False) as reader:
        for batch in reader:
            if not total:
                print("columnar batch columns:", sorted(batch))
            total += len(batch["0/timestamp"])
    print("columnar windows:", total)


if __name__ == "__main__":
    main()
