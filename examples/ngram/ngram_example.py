"""NGram windowed reading (reference NGram usage): consecutive timestamped rows assembled
into {offset: row} windows for sequence models."""
import argparse
import tempfile

import numpy as np

from petastorm_tpu import make_reader
from petastorm_tpu.codecs import NdarrayCodec, ScalarCodec
from petastorm_tpu.metadata import write_dataset
from petastorm_tpu.ngram import NGram
from petastorm_tpu.types import LongType
from petastorm_tpu.unischema import Unischema, UnischemaField

SeqSchema = Unischema("SeqSchema", [
    UnischemaField("timestamp", np.int64, (), ScalarCodec(LongType()), False),
    UnischemaField("sensor", np.float32, (8,), NdarrayCodec(), False),
])


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--url", default=None)
    args = parser.parse_args()
    url = args.url or "file://" + tempfile.mkdtemp(prefix="ngram_ds")

    rng = np.random.RandomState(0)
    write_dataset(url, SeqSchema, (
        {"timestamp": t, "sensor": rng.standard_normal(8).astype(np.float32)}
        for t in range(100)
    ))

    ngram = NGram(fields={-1: ["timestamp", "sensor"],
                          0: ["timestamp", "sensor"],
                          1: ["timestamp"]},
                  delta_threshold=2, timestamp_field="timestamp")
    with make_reader(url, schema_fields=ngram, shuffle_row_groups=False) as reader:
        for i, window in enumerate(reader):
            if i < 3:
                print({k: (v.timestamp, getattr(v, "sensor", None) is not None)
                       for k, v in window.items()})
        print("windows:", i + 1)


if __name__ == "__main__":
    main()
