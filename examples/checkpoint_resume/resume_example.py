"""Exact mid-epoch resume after preemption — a capability the reference lacks
(SURVEY.md §6: "no sample-level resumable cursor"; pod preemption is routine on TPU).

Simulates a preempted training job: read part of an epoch, checkpoint the reader
cursor (alongside model state — the dict is orbax/pickle-friendly), "crash", rebuild
the reader from the checkpoint, and finish. Verifies the union of rows seen before
and after the preemption covers the epoch exactly, with duplicates only at row-group
granularity (the documented at-least-once contract for in-flight work).

Run: ``python resume_example.py`` (CPU jax is fine).
"""
import json
import tempfile

import numpy as np

from petastorm_tpu import types as ptypes
from petastorm_tpu.codecs import ScalarCodec
from petastorm_tpu.metadata import write_dataset
from petastorm_tpu.reader import make_batch_reader
from petastorm_tpu.unischema import Unischema, UnischemaField

ROWS = 96


def build_dataset():
    schema = Unischema("S", [
        UnischemaField("id", np.int64, (), ScalarCodec(ptypes.LongType()), False),
        UnischemaField("x", np.float32, (4,), None, False),
    ])
    root = tempfile.mkdtemp(prefix="resume_ds")
    rng = np.random.RandomState(0)
    write_dataset("file://" + root, schema,
                  ({"id": i, "x": rng.standard_normal(4).astype(np.float32)}
                   for i in range(ROWS)),
                  rows_per_file=48, row_group_size_mb=1)
    return "file://" + root


def main():
    url = build_dataset()
    kwargs = dict(shuffle_row_groups=True, seed=7, num_epochs=1, workers_count=2)

    # ---- phase 1: consume part of the epoch, checkpoint, "crash" ----
    seen_before = []
    with make_batch_reader(url, **kwargs) as reader:
        for batch in reader:
            seen_before.extend(np.asarray(batch.id).tolist())
            if len(seen_before) >= ROWS // 3:
                break
        ckpt = reader.state_dict()      # goes into the same tree as model params
    blob = json.dumps(ckpt)             # JSON/orbax/pickle friendly
    print("preempted after %d rows; checkpoint: %s..." % (len(seen_before), blob[:70]))

    # ---- phase 2: new process, restore, finish the epoch ----
    seen_after = []
    with make_batch_reader(url, **kwargs) as reader:
        reader.load_state_dict(json.loads(blob))
        for batch in reader:
            seen_after.extend(np.asarray(batch.id).tolist())

    union = set(seen_before) | set(seen_after)
    assert union == set(range(ROWS)), "resume missed rows!"
    overlap = set(seen_before) & set(seen_after)
    print("resumed: %d rows after restore; %d replayed (at-least-once, row-group "
          "granularity); epoch coverage exact." % (len(seen_after), len(overlap)))

    # ---- the production shape: one orbax step holds params AND the data cursor ----
    orbax_roundtrip(url, kwargs)

    # ---- training through a DataLoader? checkpoint the LOADER (consumer
    # watermark): rows prefetched into its buffers replay instead of vanishing ----
    loader_watermark(url, kwargs)


def loader_watermark(url, kwargs):
    from petastorm_tpu import checkpoint as ptck
    from petastorm_tpu.loader import DataLoader

    import os

    # orbax refuses an existing destination: point at a fresh subpath
    ckpt_dir = os.path.join(tempfile.mkdtemp(prefix="loader_ckpt"), "step0")
    loader = DataLoader(make_batch_reader(url, **kwargs), batch_size=8,
                        prefetch=3, to_device=False)
    pre = []
    with loader:
        it = iter(loader)
        for _ in range(4):
            pre.extend(int(x) for x in next(it)["id"])
        # the producer thread has read AHEAD of these 4 batches; saving the
        # READER here would skip the buffered rows — the loader's state saves at
        # what the training loop actually received
        ptck.save(ckpt_dir, loader)

    resumed = DataLoader(make_batch_reader(url, **kwargs), batch_size=8,
                         to_device=False)
    ptck.restore(ckpt_dir, resumed)
    post = []
    with resumed:
        for b in resumed:
            post.extend(int(x) for x in b["id"])
    assert set(pre) | set(post) == set(range(ROWS))
    print("loader watermark: %d rows pre-save + %d post-restore; prefetched rows "
          "replayed, none lost." % (len(pre), len(post)))


def orbax_roundtrip(url, kwargs):
    import jax.numpy as jnp
    import orbax.checkpoint as ocp

    from petastorm_tpu import checkpoint as ptck

    ckpt_dir = tempfile.mkdtemp(prefix="orbax_ckpt")
    params = {"w": jnp.ones((4, 2))}
    mngr = ocp.CheckpointManager(ckpt_dir)
    reader = make_batch_reader(url, **kwargs)
    first = np.asarray(next(iter(reader)).id).tolist()
    mngr.save(step=1, args=ocp.args.Composite(
        params=ocp.args.StandardSave(params),
        reader=ptck.save_args(reader),
    ))
    mngr.wait_until_finished()
    reader.stop()
    reader.join()

    restored = mngr.restore(1, args=ocp.args.Composite(
        params=ocp.args.StandardRestore({"w": jnp.zeros((4, 2))}),
        reader=ptck.restore_args(),
    ))
    resumed = make_batch_reader(url, **kwargs)
    ptck.apply(resumed, restored["reader"])
    rest = [int(x) for b in resumed for x in np.asarray(b.id)]
    resumed.stop()
    resumed.join()
    mngr.close()
    assert set(first) | set(rest) == set(range(ROWS))
    print("orbax composite step: params + data cursor saved/restored together; "
          "epoch coverage exact after restore.")


if __name__ == "__main__":
    main()
