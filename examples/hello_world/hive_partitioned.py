"""hello_world, hive-partitioned Parquet store (the most common vanilla-Parquet layout
in the wild — reference: ``pq.ParquetDataset`` partition handling): partition-directory
columns materialize as row values, and ``filters=`` prunes whole directories before any
file is opened."""
import argparse
import os
import tempfile

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

from petastorm_tpu import make_batch_reader
from petastorm_tpu.loader import DataLoader


def generate_dataset(path, rows_per_part=40):
    rng = np.random.RandomState(0)
    rid = 0
    for date in ("2024-06-01", "2024-06-02", "2024-06-03"):
        for region in ("us", "eu"):
            d = os.path.join(path, "date=%s" % date, "region=%s" % region)
            os.makedirs(d, exist_ok=True)
            table = pa.table({
                "id": np.arange(rid, rid + rows_per_part, dtype=np.int64),
                "value": rng.standard_normal(rows_per_part),
            })
            pq.write_table(table, os.path.join(d, "part-0.parquet"),
                           row_group_size=10)
            rid += rows_per_part


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--path", default=None)
    args = parser.parse_args()
    path = args.path or tempfile.mkdtemp(prefix="hive_ds")
    generate_dataset(path)
    url = "file://" + path

    # partition columns (date: string, region: string) arrive as ordinary columns
    with make_batch_reader(url, shuffle_row_groups=False) as reader:
        batch = next(iter(reader))
        print("columns:", list(batch._fields))
        print("first rows:", list(zip(batch.id[:3].tolist(),
                                      list(batch.date[:3]), list(batch.region[:3]))))

    # directory pruning: only date=2024-06-02 files are ever opened
    with make_batch_reader(url, filters=[("date", "=", "2024-06-02")]) as reader:
        total = sum(len(b.id) for b in reader)
        print("rows for 2024-06-02:", total)  # 80 of 240

    # mixed DNF: directory pruning + row-level mask, straight into the JAX loader
    reader = make_batch_reader(
        url, filters=[("region", "=", "eu"), ("value", ">", 0.0)],
        shuffle_row_groups=False)
    with DataLoader(reader, batch_size=16, last_batch="partial") as loader:
        n = sum(len(b["id"]) for b in loader)
        print("eu rows with positive value:", n)


if __name__ == "__main__":
    main()
