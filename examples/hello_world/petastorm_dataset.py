"""hello_world, petastorm-format dataset (reference examples/hello_world/petastorm_dataset):
write a tensor-columned dataset with RowWriter (no Spark needed), read with make_reader."""
import argparse
import tempfile

import numpy as np

from petastorm_tpu import make_reader
from petastorm_tpu.codecs import CompressedImageCodec, NdarrayCodec, ScalarCodec
from petastorm_tpu.metadata import write_dataset
from petastorm_tpu.types import IntegerType
from petastorm_tpu.unischema import Unischema, UnischemaField

HelloWorldSchema = Unischema("HelloWorldSchema", [
    UnischemaField("id", np.int32, (), ScalarCodec(IntegerType()), False),
    UnischemaField("image1", np.uint8, (128, 256, 3), CompressedImageCodec("png"), False),
    UnischemaField("array_4d", np.uint8, (None, 128, 30, None), NdarrayCodec(), False),
])


def row_generator(x):
    return {
        "id": x,
        "image1": np.random.randint(0, 255, (128, 256, 3), dtype=np.uint8),
        "array_4d": np.random.randint(0, 255, (4, 128, 30, 3), dtype=np.uint8),
    }


def generate_dataset(url, rows=10):
    write_dataset(url, HelloWorldSchema, (row_generator(i) for i in range(rows)),
                  row_group_size_mb=8)


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--url", default=None)
    args = parser.parse_args()
    url = args.url or "file://" + tempfile.mkdtemp(prefix="hello_world_ds")
    generate_dataset(url)
    with make_reader(url) as reader:
        for row in reader:
            print(row.id, row.image1.shape, row.array_4d.shape)


if __name__ == "__main__":
    main()
