"""hello_world, vanilla-Parquet dataset (reference examples/hello_world/external_dataset):
any Parquet store read with make_batch_reader / the JAX DataLoader."""
import argparse
import tempfile

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

from petastorm_tpu import make_batch_reader
from petastorm_tpu.loader import DataLoader


def generate_dataset(path, rows=100):
    rng = np.random.RandomState(0)
    table = pa.table({
        "id": np.arange(rows, dtype=np.int64),
        "value1": rng.standard_normal(rows),
        "value2": rng.randint(0, 10, rows).astype(np.int32),
    })
    pq.write_table(table, path + "/data.parquet", row_group_size=20)


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--path", default=None)
    args = parser.parse_args()
    path = args.path or tempfile.mkdtemp(prefix="external_ds")
    generate_dataset(path)
    url = "file://" + path

    # plain iteration
    with make_batch_reader(url) as reader:
        total = sum(len(b.id) for b in reader)
        print("rows:", total)

    # JAX loader: batches on device
    reader = make_batch_reader(url, shuffle_row_groups=False)
    with DataLoader(reader, batch_size=16) as loader:
        for batch in loader:
            print("batch:", {k: (v.shape, str(v.dtype)) for k, v in batch.items()})
            break


if __name__ == "__main__":
    main()
