"""Packaging for petastorm_tpu (reference setup.py parity: extras + console scripts).

Console scripts mirror the reference's CLIs:
  petastorm-tpu-generate-metadata  (reference: petastorm-generate-metadata)
  petastorm-tpu-copy-dataset       (reference: petastorm-copy-dataset)
  petastorm-tpu-throughput         (reference: petastorm-throughput)
  petastorm-tpu-lint               (no reference analog: graftlint static analysis)
  petastorm-tpu-stats              (no reference analog: metrics snapshot viewer)
"""
from setuptools import find_packages, setup

setup(
    name="petastorm-tpu",
    version="0.2.0",
    description="TPU-native Parquet data-loading framework (Petastorm-class capabilities)",
    packages=find_packages(include=["petastorm_tpu", "petastorm_tpu.*"]),
    # the native C++ sources ship with the wheel: kernels compile at first use via g++
    package_data={"petastorm_tpu.ops.native": ["*.cpp"]},
    python_requires=">=3.10",
    install_requires=[
        "numpy",
        "pyarrow>=10",
        "fsspec",
    ],
    extras_require={
        "jax": ["jax", "flax", "optax"],
        "tf": ["tensorflow"],
        "torch": ["torch"],
        "opencv": ["opencv-python-headless"],
        "spark": ["pyspark>=3.0"],
        "gcs": ["gcsfs"],
        # everything the suite exercises (CI installs .[test])
        "test": ["pytest", "pytest-timeout", "jax", "flax", "optax", "pandas",
                 "opencv-python-headless", "torch", "tensorflow"],
    },
    entry_points={
        "console_scripts": [
            "petastorm-tpu-generate-metadata=petastorm_tpu.tools.generate_metadata:main",
            "petastorm-tpu-copy-dataset=petastorm_tpu.tools.copy_dataset:main",
            "petastorm-tpu-throughput=petastorm_tpu.benchmark.cli:main",
            "petastorm-tpu-bench=petastorm_tpu.benchmark.cli:main",
            "petastorm-tpu-lint=petastorm_tpu.analysis.cli:main",
            "petastorm-tpu-stats=petastorm_tpu.obs.stats_cli:main",
        ],
    },
)
