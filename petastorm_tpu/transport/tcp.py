"""Framed tcp transport (ISSUE 15): the pool wire over loopback/LAN sockets.

Topology: the parent (``ProcessExecutor``) owns one :class:`TcpHub` — a
listening socket plus an acceptor thread that routes incoming connections to
per-child *sessions* by the hello frame they open with. Each driver talks to
its child through a :class:`TcpTransport`; the child dials back with a
:class:`TcpChildTransport`. Every frame is length-prefixed and
crc32-trailered (:mod:`~petastorm_tpu.transport.framing`); every socket
carries a bounded timeout (reads tick at :data:`TICK` and resume a partial
frame from the endpoint's buffer, so a timeout never loses stream sync).

The reconnect state machine (see docs/robustness.md for the full table)::

    CONNECTED --error/EOF/corrupt-frame/half-open--> DOWN
        parent: warn-once transport_link_down, raise TransportLinkDown,
                driver calls reconnect() == bounded wait for re-adoption
        child:  redial the hub with jittered exponential backoff
                (base io_retry_backoff_s, ceiling link_reconnect_s)
    DOWN --child hello accepted--> CONNECTED (generation += 1)
        parent: transport_reconnected degradation + ptpu_net_reconnects_total;
                buffers from the dead generation are DISCARDED — a result
                conversation is only valid on the link generation its item
                was dispatched on (the in-flight ledger pins it), so a
                half-delivered result can never be stitched to a fresh link
    DOWN --no redial within link_reconnect_s--> DEAD
        parent: the driver falls through to the child-death path (respawn
                budget / poison quarantine); child: exits (parent gone)

Half-open detection: both sides run a heartbeat sender thread (one frame per
``link_heartbeat_s``) and police inbound traffic age while they are *waiting*
on the link; ``link_miss_threshold`` quiet intervals tear the link down. A
peer that is merely busy (a child mid-decode, a parent blocked on a full
results queue) keeps transmitting through its sender thread, so silence
really means the link — not the workload — is gone.

Chaos: ``transport.send`` / ``transport.recv`` hook sites fire on every frame
of a *ready* link (bootstrap is the spawn-failure path's job) with the raw
frame bytes as payload. ``net.slow`` delays a frame, ``net.reset`` turns into
a real socket teardown, ``net.corrupt_frame`` flips a byte the receiver's crc
trailer catches. ``net.partition`` honors reliable-transport semantics:
heartbeat frames are DROPPED (starving the peer's half-open detector — the
partition's observable signal) while app frames STALL at the send site until
the window closes or the link is torn down under them (real TCP retransmits
through a partition; data is delayed or the connection dies, never silently
lost — a sender that believed "sent" about a lost frame would deadlock its
conversation).
"""
from __future__ import annotations

import json
import os
import pickle
import select
import socket
import struct
import threading
import time
import zlib
from collections import deque

from petastorm_tpu import chaos as _chaos
from petastorm_tpu.errors import TransportFrameCorrupt, TransportLinkDown
from petastorm_tpu.transport import Transport, net_metrics
from petastorm_tpu.transport.framing import (
    K_HB,
    K_HB_ACK,
    K_HELLO,
    K_HELLO_ACK,
    K_OBJ,
    K_RAW,
    pack_frame,
    split_tenant,
    take_frame,
)

#: socket read/accept tick — every blocking socket op is bounded by this and
#: re-checks deadlines/stop conditions between ticks (GL-R003's contract)
TICK = 0.05

_HB_STAMP = struct.Struct(">d")


def _jitter(attempt):
    """Deterministic backoff jitter factor in [0.5, 1.0): crc32 of
    (pid, attempt) — no ``random`` state, replayable like the chaos coins."""
    h = zlib.crc32(("%d|%d" % (os.getpid(), attempt)).encode("ascii"))
    return 0.5 + (h & 0xFFFF) / 131072.0


def _degradation(*args, **kwargs):
    from petastorm_tpu.obs.log import degradation

    degradation(*args, **kwargs)


class _FramedLink(Transport):
    """Shared framed-socket machinery: buffered frame reads over bounded
    socket timeouts, heartbeat accounting, chaos hook sites, and the
    ``Connection``-surface API. Subclasses define what a link death means
    (:meth:`_link_down`) — the parent waits for re-adoption, the child
    redials."""

    #: chaos item key for this link's hook-site hits
    _site_key = None
    #: does this endpoint echo inbound HB frames as HB_ACK (the child does;
    #: the parent is the rtt observer)
    _ack_hb = False

    def __init__(self, recovery):
        self._rec = recovery
        self._cv = threading.Condition()
        self._sock = None
        self._gen = 0           # bumps on every (re)established socket
        self._closed = False
        self._rbuf = bytearray()
        self._app = deque()     # decoded (kind, payload) app frames
        self._send_lock = threading.Lock()
        self._last_rx = 0.0
        self._missed = 0
        self._warned_down = False
        #: half-open policing is armed per LINK GENERATION by the first
        #: inbound frame after this side is ready: the peer may mark ready
        #: later than we do (the pool registers children sequentially), and
        #: policing a link whose peer has not yet reached steady state reads
        #: its bootstrap pause as a half-open connection
        self._ready_rx = False
        self._hb_stop = threading.Event()
        self._hb_thread = None
        self._inflight = None
        self._inflight_gen = -1
        #: per-tenant frame tagging (ISSUE 18): armed only after the hello
        #: exchange proved the peer understands K_TENANT_FLAG — an old peer
        #: must never receive a flagged kind byte it would read as garbage
        self._tenant_frames = False
        self._tx_tenant = None
        self._warned_downgrade = False
        self.peer_tenant = None
        #: lazy self-pipe for wakeable polls (created on first
        #: ``poll(wakeable=True)``): lets another thread return a poller
        #: control without a byte on the wire (``wake()``)
        self._wake_rx = None
        self._wake_tx = None

    # -- tenant tagging (ISSUE 18) ------------------------------------------------------

    def set_tenant(self, label):
        """Pin the tenant slug outbound app frames are tagged with (the
        executor calls this with the reader's resolved tenant; None falls
        back to the thread/process tenant context at send time)."""
        self._tx_tenant = label

    def _frame_tenant(self):
        """The slug to tag the next outbound app frame with, or None. A
        tenant that WANTS tagging on an un-negotiated link degrades once
        (``tenant_frame_downgrade``) and ships untagged — old peers keep
        working, attribution loses the wire dimension only."""
        label = self._tx_tenant
        if label is None:
            from petastorm_tpu.obs import tenant as _tenant_ctx

            label = _tenant_ctx.current_label()
        if label is None:
            return None
        if not self._tenant_frames:
            if not self._warned_downgrade:
                self._warned_downgrade = True
                _degradation(
                    "tenant_frame_downgrade",
                    "transport link %s peer did not negotiate tenant frame "
                    "headers — sending untagged (per-tenant wire accounting "
                    "is lost on this link)", self._site_key)
            return None
        return label

    # -- in-flight ledger ---------------------------------------------------------------

    def track(self, key):
        with self._cv:
            self._inflight = key
            self._inflight_gen = self._gen

    def settle(self):
        with self._cv:
            self._inflight = None

    def inflight(self):
        with self._cv:
            return self._inflight

    # -- lifecycle ----------------------------------------------------------------------

    def _install(self, sock, first, leftover=b""):
        """Adopt ``sock`` as the live link (caller-side naming differs:
        parent adoption vs child redial). Buffers from the dead generation
        are discarded — partial frames, un-consumed results, everything.
        ``leftover`` carries bytes the hello/ack exchange read PAST its own
        frame (the peer's first frames can coalesce with it into one recv)
        — they belong to the fresh generation and seed its buffer."""
        sock.settimeout(TICK)
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass
        with self._cv:
            if self._closed:
                try:
                    sock.close()
                except OSError:
                    pass
                return False
            old, self._sock = self._sock, sock
            self._gen += 1
            self._rbuf.clear()
            self._app.clear()
            if leftover:
                self._rbuf += leftover
            self._last_rx = time.monotonic()
            self._missed = 0
            self._warned_down = False
            self._ready_rx = False  # re-armed by the fresh link's first frame
            self._cv.notify_all()
        if old is not None:
            try:
                old.close()
            except OSError:
                pass
        m = net_metrics()
        m.connects.inc()
        if not first:
            m.reconnects.inc()
        return True

    def mark_ready(self):
        super().mark_ready()
        if self._hb_thread is None:
            self._hb_thread = threading.Thread(
                target=self._hb_loop, daemon=True,
                name="ptpu-net-hb-%s" % (self._site_key or "link"))
            self._hb_thread.start()

    def close(self):
        with self._cv:
            self._closed = True
            sock, self._sock = self._sock, None
            wake_rx, self._wake_rx = self._wake_rx, None
            wake_tx, self._wake_tx = self._wake_tx, None
            self._cv.notify_all()
        self._hb_stop.set()
        for s in (sock, wake_rx, wake_tx):
            if s is not None:
                try:
                    s.close()
                except OSError:
                    pass

    # -- heartbeats ---------------------------------------------------------------------

    def _hb_loop(self):
        """Transport heartbeat sender: proves link liveness to the peer even
        while this side's main thread is busy (a child mid-decode, a parent
        blocked on a full results queue). Quiet on failure — it closes the
        socket so the main thread's next op fails fast, never redials or
        raises from this thread."""
        while not self._hb_stop.wait(self._rec.link_heartbeat_s):
            if not self.ready:
                continue
            with self._cv:
                sock = self._sock
            if sock is None:
                continue
            self._send_quiet(pack_frame(
                K_HB, _HB_STAMP.pack(time.monotonic())), sock)

    def _send_quiet(self, frame, sock):
        """Best-effort frame send for the heartbeat thread: chaos applies
        (a partition must starve the peer's half-open detector for real),
        errors close the socket and return."""
        try:
            frame = self._chaos_frame("transport.send", frame)
            if frame is None:
                return
            with self._send_lock:  # frames must never interleave mid-wire
                self._sendall(sock, frame)
            m = net_metrics()
            m.frames_tx.inc()
            m.bytes_tx.inc(len(frame))
        except (OSError, TransportLinkDown):
            self._quiet_close(sock)

    def _quiet_close(self, sock):
        with self._cv:
            if self._sock is sock:
                self._sock = None
        try:
            sock.close()
        except OSError:
            pass

    # -- chaos --------------------------------------------------------------------------

    def _chaos_frame(self, site, frame):
        """Run one frame through the chaos hook site; returns the (possibly
        corrupted) frame, None when a partition dropped it, and converts an
        injected reset into a real link teardown."""
        if _chaos.ACTIVE is None or not self.ready:
            return frame
        from petastorm_tpu.chaos.plan import DROPPED

        out = _chaos.ACTIVE.hit(site, key=self._site_key, payload=frame)
        if out is DROPPED:
            return None
        return out

    # -- send path ----------------------------------------------------------------------

    def send(self, obj):
        self._send_wire(pack_frame(K_OBJ, pickle.dumps(obj, protocol=4),
                                   tenant=self._frame_tenant()))

    def send_bytes(self, data):
        self._send_wire(pack_frame(K_RAW, data, tenant=self._frame_tenant()))

    def _send_wire(self, frame):
        with self._cv:
            sock = self._sock
            gen = self._gen
        while True:
            try:
                out = self._chaos_frame("transport.send", frame)
            except ConnectionResetError as e:  # chaos net.reset: REAL teardown
                self._link_down(e, sock=sock)
            if out is not None:
                frame = out
                break
            # net.partition: the frame is stalled IN the network, never
            # silently lost — reliable-transport semantics (real TCP
            # retransmits through a partition, so data is delayed or the
            # connection dies; a sender that believes "sent" about a lost
            # frame would deadlock the conversation). The peer's half-open
            # detector may tear the link down mid-stall: this conversation
            # then aborts and the in-flight ledger re-dispatches it.
            time.sleep(TICK)
            with self._cv:
                replaced = self._sock is not sock
            if replaced or self._closed:
                self._link_down(TransportLinkDown(
                    "transport link %s torn down during a partition stall"
                    % self._site_key), sock=sock)
        if sock is None:
            self._link_down(TransportLinkDown(
                "transport link %s is down" % self._site_key))
        try:
            with self._send_lock:  # frames must never interleave mid-wire
                self._sendall(sock, frame)
        except OSError as e:
            self._link_down(e, sock=sock)
        with self._cv:
            if self._inflight is not None and self._sock is sock:
                # re-pin the conversation to the generation the frame really
                # went out on: track() may have pinned an older generation if
                # an adoption slipped in between track and send — leaving the
                # stale pin would make poll() declare this (successfully
                # dispatched) conversation replaced and re-dispatch a
                # DUPLICATE onto the same live link
                self._inflight_gen = gen
        m = net_metrics()
        m.frames_tx.inc()
        m.bytes_tx.inc(len(frame))

    def _sendall(self, sock, data):
        """sendall over a tick-bounded socket: short ticks keep the shared
        socket timeout uniform; the overall send is bounded by the reconnect
        ceiling (a peer that cannot drain a frame for that long is a dead
        link, not backpressure — app backpressure lives in the results
        queue, not in TCP buffers)."""
        deadline = time.monotonic() + max(5.0, self._rec.link_reconnect_s)
        view = memoryview(data)
        while view:
            try:
                n = sock.send(view)
            except socket.timeout:
                if time.monotonic() > deadline:
                    raise OSError(
                        "transport send stalled past the %.0fs link ceiling"
                        % max(5.0, self._rec.link_reconnect_s)) from None
                continue
            view = view[n:]

    # -- receive path -------------------------------------------------------------------

    def poll(self, timeout=0.0, wakeable=False):
        """True when a complete app frame is buffered; reads/demultiplexes
        inbound traffic (heartbeats, acks) meanwhile. Raises
        :class:`TransportLinkDown` on any link fault, including a link that
        was replaced mid-conversation (the in-flight ledger pins the dispatch
        generation) and a heartbeat-detected half-open link.

        The wait honors ``timeout`` precisely (readability is select-gated,
        so an idle link never rounds the wait up to the socket tick). With
        ``wakeable=True`` another thread's :meth:`wake` interrupts the wait
        and poll returns False early — the service's serve loop uses this to
        flush a freshly decoded item the moment it lands instead of riding
        out the poll tick (delivery latency would otherwise quantize to it,
        and the trainer-side provenance fold would charge the slack to
        ``svc.lease_wait``)."""
        deadline = time.monotonic() + max(0.0, timeout)
        if wakeable:
            self._ensure_wake()
        while True:
            with self._cv:
                if self._inflight is not None \
                        and self._inflight_gen != self._gen:
                    # the peer reconnected while a result was owed on the OLD
                    # socket: that conversation is unfinishable. Raise WITHOUT
                    # tearing the fresh link down — the driver's reconnect()
                    # sees it live and re-dispatches immediately.
                    self._inflight_gen = self._gen
                    raise TransportLinkDown(
                        "link %s replaced mid-conversation (peer reconnected);"
                        " re-dispatching its un-acked item" % self._site_key)
                if self._app:
                    return True
                sock = self._sock
                wake_rx = self._wake_rx if wakeable else None
            if sock is None:
                self._link_down(TransportLinkDown(
                    "transport link %s is down" % self._site_key))
            if self._rbuf:
                # leftover bytes from the hello/ack exchange or a previous
                # partial parse may already complete a frame without a read
                self._drain_frames(sock)
                with self._cv:
                    if self._app:
                        return True
            remaining = deadline - time.monotonic()
            rlist = [sock] if wake_rx is None else [sock, wake_rx]
            try:
                ready = select.select(rlist, (), (),
                                      max(0.0, min(remaining, TICK)))[0]
            except (OSError, ValueError):
                # fd died under us (close/generation swap): the recv path
                # owns the canonical link-death handling
                self._read_once(sock)
                ready = ()
            if wake_rx is not None and wake_rx in ready:
                self._drain_wake(wake_rx)
                return False  # woken: the caller's queue check is the point
            if sock in ready:
                self._read_once(sock)
                with self._cv:
                    if self._app:
                        return True
            else:
                self._police_staleness(sock)
            if time.monotonic() >= deadline:
                return False

    def _ensure_wake(self):
        with self._cv:
            if self._wake_rx is None and not self._closed:
                rx, tx = socket.socketpair()
                rx.setblocking(False)
                tx.setblocking(False)
                self._wake_rx, self._wake_tx = rx, tx

    @staticmethod
    def _drain_wake(wake_rx):
        try:
            while wake_rx.recv(64):
                pass
        except (BlockingIOError, OSError):
            pass

    def wake(self):
        """Nudge a thread blocked in ``poll(wakeable=True)`` so it re-checks
        caller state now. No-op until the first wakeable poll armed the
        self-pipe; never blocks (a pending nudge already buffered is
        enough)."""
        with self._cv:
            tx = self._wake_tx
        if tx is None:
            return
        try:
            tx.send(b"\0")
        except (BlockingIOError, OSError):
            pass

    def _read_once(self, sock):
        if self._rbuf:
            # leftover bytes seeded by the hello/ack exchange (or left by a
            # previous partial parse): frames may already be complete
            self._drain_frames(sock)
            with self._cv:
                if self._app:
                    return
        try:
            data = sock.recv(1 << 16)
        except socket.timeout:
            self._police_staleness(sock)
            return
        except OSError as e:
            self._link_down(e, sock=sock)
        if not data:
            self._link_down(TransportLinkDown(
                "peer closed transport link %s" % self._site_key), sock=sock)
        with self._cv:
            if self._sock is not sock:
                return  # replaced mid-read: these bytes died with their link
            self._last_rx = time.monotonic()
            self._missed = 0
            if self.ready:
                self._ready_rx = True  # peer reached steady state: police on
            self._rbuf += data  # under the lock: adoption clears this buffer
        net_metrics().bytes_rx.inc(len(data))
        self._drain_frames(sock)

    def _drain_frames(self, sock):
        from petastorm_tpu.transport.framing import frame_size

        while True:
            try:
                total = frame_size(self._rbuf)
            except TransportFrameCorrupt as e:
                self._frame_corrupt(e, sock)
            if total is None:
                return
            raw = bytes(self._rbuf[:total])
            del self._rbuf[:total]
            try:
                out = self._chaos_frame("transport.recv", raw)
            except ConnectionResetError as e:
                self._link_down(e, sock=sock)
            if out is None:
                # net.partition at the recv site: only heartbeat frames are
                # droppable (starving the local staleness detector — the
                # observable inbound effect of a partition); app frames are
                # reliable-transport data a real partition would have
                # retransmitted, so they pass through
                if len(raw) > 2 and raw[2] in (K_HB, K_HB_ACK):
                    continue
            else:
                raw = out
            try:
                kind, payload = take_frame(bytearray(raw))
                kind, payload, frame_tenant = split_tenant(kind, payload)
            except TransportFrameCorrupt as e:
                self._frame_corrupt(e, sock)
            net_metrics().frames_rx.inc()
            if frame_tenant is not None:
                # rx-side only: both endpoints of an in-process test share the
                # default registry, so a tx-side twin would double-count
                self.peer_tenant = frame_tenant
                from petastorm_tpu.obs import tenant as _tenant_ctx

                _tenant_ctx.charge("wire_bytes", len(raw), label=frame_tenant)
            self._handle_frame(kind, payload, sock)

    def _handle_frame(self, kind, payload, sock):
        if kind == K_HB:
            if self._ack_hb:
                self._send_quiet(pack_frame(K_HB_ACK, payload), sock)
            return
        if kind == K_HB_ACK:
            try:
                (stamp,) = _HB_STAMP.unpack(payload)
            except struct.error:
                return
            net_metrics().rtt.observe(max(0.0, time.monotonic() - stamp))
            return
        with self._cv:
            if self._sock is sock:  # frames die with a replaced generation
                self._app.append((kind, payload))

    def _frame_corrupt(self, exc, sock):
        net_metrics().frames_corrupt.inc()
        _degradation(
            "transport_frame_corrupt",
            "transport link %s received a corrupt frame (%s); tearing the "
            "link down — the in-flight item re-dispatches, the corrupt "
            "payload is never delivered", self._site_key, exc, once=False)
        self._link_down(exc, sock=sock)

    def _police_staleness(self, sock):
        """Half-open detection: count quiet heartbeat intervals while this
        side is WAITING on the link; at the miss threshold the link dies.
        Armed only once the peer has demonstrably reached steady state on
        THIS link generation (``_ready_rx``) — a peer still bootstrapping
        its other links is quiet, not gone."""
        if not self.ready or not self._ready_rx:
            return
        hb = self._rec.link_heartbeat_s
        with self._cv:
            if self._sock is not sock:
                return
            age = time.monotonic() - self._last_rx
            missed = int(age / hb)
            if missed > self._missed:
                net_metrics().hb_missed.inc(missed - self._missed)
                self._missed = missed
            tripped = missed >= self._rec.link_miss_threshold
        if tripped:
            self._link_down(TransportLinkDown(
                "half-open link %s: no traffic for %.1fs (%d heartbeat "
                "intervals)" % (self._site_key, age, missed)), sock=sock)

    def _next_app_frame(self):
        while True:
            with self._cv:
                if self._app:
                    return self._app.popleft()
            self.poll(TICK)

    def recv(self):
        kind, payload = self._next_app_frame()
        if kind != K_OBJ:
            self._link_down(TransportFrameCorrupt(
                "expected an object frame on link %s, got kind %d"
                % (self._site_key, kind)))
        return pickle.loads(payload)

    def recv_bytes(self):
        kind, payload = self._next_app_frame()
        if kind != K_RAW:
            self._link_down(TransportFrameCorrupt(
                "expected a raw frame on link %s, got kind %d"
                % (self._site_key, kind)))
        return payload

    # -- link death ---------------------------------------------------------------------

    def _tear_down(self, exc, sock=None):
        """Common half of :meth:`_link_down`: close the dead socket, warn
        once per connection. ``sock`` pins the failure to the generation it
        happened on — an error from an already-replaced socket must never
        tear down the fresh link that superseded it. Returns the exception
        to (re-)raise."""
        with self._cv:
            if sock is not None and self._sock is not None \
                    and self._sock is not sock:
                stale = True  # the failure belongs to a dead generation
            else:
                stale = False
                sock, self._sock = self._sock, None
            warned, self._warned_down = self._warned_down, True
        if sock is not None and not stale:
            try:
                sock.close()
            except OSError:
                pass
        if not warned and not stale:
            _degradation(
                "transport_link_down",
                "transport link %s died (%s); un-acked items re-dispatch "
                "through the poison/quarantine path", self._site_key, exc,
                once=False)
        if isinstance(exc, TransportLinkDown):
            return exc
        err = TransportLinkDown(
            "transport link %s died: %s" % (self._site_key, exc))
        err.__cause__ = exc
        return err

    def _link_down(self, exc, sock=None):
        raise NotImplementedError


class TcpTransport(_FramedLink):
    """Parent (driver) side of one child's link. The hub adopts reconnected
    sockets into it; the driver recovers from a :class:`TransportLinkDown`
    by calling :meth:`reconnect` — a bounded wait for that adoption — and
    re-dispatching the ledgered in-flight item."""

    def __init__(self, session, recovery):
        super().__init__(recovery)
        self.session = session
        self._site_key = "session=%d" % session
        self._adopted = 0

    def adopt(self, sock, leftover=b""):
        """Called by the hub's acceptor thread with a hello-verified socket
        (initial connect or a redial)."""
        first = self._adopted == 0
        if self._install(sock, first, leftover=leftover):
            self._adopted += 1
            if not first:
                _degradation(
                    "transport_reconnected",
                    "transport link %s re-established (adoption %d); "
                    "re-dispatching its un-acked items", self._site_key,
                    self._adopted, once=False)

    def wait_connected(self, timeout):
        """Bounded wait for the first adoption (pool start / respawn)."""
        with self._cv:
            return self._cv.wait_for(
                lambda: self._sock is not None or self._closed, timeout) \
                and self._sock is not None

    def reconnect(self, timeout=None):
        """Bounded wait for the child to redial after a link death; True when
        a fresh generation is live (the caller re-dispatches on it)."""
        if timeout is None:
            timeout = self._rec.link_reconnect_s
        with self._cv:
            ok = self._cv.wait_for(
                lambda: self._sock is not None or self._closed, timeout)
            return bool(ok and self._sock is not None and not self._closed)

    def _link_down(self, exc, sock=None):
        raise self._tear_down(exc, sock)


class TcpChildTransport(_FramedLink):
    """Child side: dials the hub, redials with jittered exponential backoff
    on any link death (base ``io_retry_backoff_s``, per-sleep cap
    ``io_retry_max_backoff_s``, overall ceiling ``link_reconnect_s``). A
    successful redial surfaces as :class:`TransportLinkDown` — the child's
    work loop discards the broken conversation and waits for the parent's
    re-dispatch; an exhausted ceiling surfaces as ``EOFError`` (the parent is
    gone; the child exits)."""

    _ack_hb = True  # the child echoes heartbeats; the parent observes rtt

    def __init__(self, host, port, session, token, recovery):
        super().__init__(recovery)
        self._host = host
        self._port = port
        self.session = session
        self._token = token
        self._site_key = "session=%d" % session
        self._dialed = 0

    def dial(self):
        """One connect + hello/ack exchange, bounded by
        ``link_connect_timeout_s`` end to end. Raises ``OSError`` on failure
        (the caller owns retry policy)."""
        timeout = self._rec.link_connect_timeout_s
        deadline = time.monotonic() + timeout
        sock = socket.create_connection((self._host, self._port),
                                        timeout=timeout)
        try:
            sock.settimeout(TICK)
            from petastorm_tpu.obs import tenant as _tenant_ctx

            hello = json.dumps({"token": self._token, "session": self.session,
                                "attempt": self._dialed,
                                "features": ["tenant"],
                                "tenant": _tenant_ctx.current_label(),
                                }).encode("utf-8")
            self._sendall(sock, pack_frame(K_HELLO, hello))
            buf = bytearray()
            while True:
                frame = take_frame(buf)
                if frame is not None:
                    break
                try:
                    data = sock.recv(1 << 12)
                except socket.timeout:
                    data = b""
                if data:
                    buf += data
                elif time.monotonic() > deadline:
                    raise OSError("transport hello ack did not arrive within "
                                  "%.0fs" % timeout)
            kind, ack_payload = frame
            if kind != K_HELLO_ACK:
                raise OSError("unexpected transport hello response kind %d"
                              % kind)
            # version negotiation (ISSUE 18): a new hub answers with a JSON
            # feature list; an old hub's empty ack simply negotiates nothing
            # (pre-ISSUE-18 children never parse the ack payload, so the
            # asymmetric upgrade is safe in both directions)
            features = ()
            if ack_payload:
                try:
                    features = json.loads(
                        ack_payload.decode("utf-8")).get("features") or ()
                except (ValueError, AttributeError):
                    features = ()
            self._tenant_frames = "tenant" in features
        except BaseException:
            try:
                sock.close()
            except OSError:
                pass
            raise
        self._install(sock, self._dialed == 0, leftover=bytes(buf))
        self._dialed += 1

    def _redial(self):
        """Jittered-backoff redial under the reconnect ceiling. The first
        attempt is immediate — the common case is a blipped link with a
        healthy hub."""
        rec = self._rec
        deadline = time.monotonic() + rec.link_reconnect_s
        attempt = 0
        while not self._closed:
            try:
                self.dial()
                return True
            except OSError:
                pass
            delay = min(rec.io_retry_max_backoff_s,
                        rec.io_retry_backoff_s * (2 ** attempt)) \
                * _jitter(attempt)
            attempt += 1
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return False
            time.sleep(min(delay, remaining))
        return False

    def _link_down(self, exc, sock=None):
        err = self._tear_down(exc, sock)
        if self._closed:
            raise EOFError("transport closed") from err
        with self._cv:
            live = self._sock is not None  # a stale-generation failure
        if live or self._redial():
            # the conversation is lost but the LINK is back: the work loop
            # discards its in-flight state and awaits the re-dispatch
            raise err
        raise EOFError(
            "transport link %s could not be re-established within %.0fs — "
            "parent gone" % (self._site_key, self._rec.link_reconnect_s)) \
            from err


class TcpHub:
    """The parent's listener: one loopback/LAN socket, an acceptor thread
    that hello-verifies each inbound connection (shared-secret token) and
    routes it to its session's :class:`TcpTransport` — initial connects and
    redials alike. Sessions are registered by the pool before it spawns the
    child that will dial them."""

    def __init__(self, recovery, token=None, host="127.0.0.1"):
        self._rec = recovery
        self.token = token if token is not None else os.urandom(16).hex()
        self._sessions = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        try:
            srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            srv.bind((host, 0))
            srv.listen(128)
            srv.settimeout(TICK)
        except BaseException:
            srv.close()
            raise
        self._srv = srv
        self.host, self.port = srv.getsockname()[:2]
        self._thread = threading.Thread(target=self._accept_loop, daemon=True,
                                        name="ptpu-tcp-hub")
        self._thread.start()

    def address_for(self, session):
        """The child argv address: ``tcp:<host>:<port>:<session>``."""
        return "tcp:%s:%d:%d" % (self.host, self.port, session)

    def create_session(self, session):
        transport = TcpTransport(session, self._rec)
        with self._lock:
            self._sessions[session] = transport
        return transport

    def drop_session(self, session):
        with self._lock:
            self._sessions.pop(session, None)

    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                sock, _addr = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return  # listener closed
            # hello on its OWN short-lived thread: a dialer that connects but
            # stalls before its hello (a wedged child, a stray scanner) must
            # not park the acceptor for link_connect_timeout_s — during a
            # reconnect storm that would hold every OTHER child's redial past
            # its parent's reconnect ceiling, turning one slow dialer into
            # cascading spurious child deaths
            threading.Thread(target=self._bootstrap_safe, args=(sock,),
                             daemon=True, name="ptpu-tcp-hello").start()

    def _bootstrap_safe(self, sock):
        try:
            self._bootstrap(sock)
        except Exception:  # noqa: BLE001 — one bad dial must not kill accepts
            try:
                sock.close()
            except OSError:
                pass  # graftlint: disable=GL-O002 (unauthenticated/garbled dial: drop silently)

    def _bootstrap(self, sock):
        """Read + verify the hello frame (bounded), ack, route to its
        session. Unknown sessions and bad tokens are dropped silently —
        the dialer's own connect timeout reports the failure."""
        sock.settimeout(TICK)
        deadline = time.monotonic() + self._rec.link_connect_timeout_s
        buf = bytearray()
        while True:
            frame = take_frame(buf)
            if frame is not None:
                break
            try:
                data = sock.recv(1 << 12)
            except socket.timeout:
                data = b""
            if data:
                buf += data
            elif time.monotonic() > deadline:
                raise OSError("transport hello did not arrive in time")
        kind, payload = frame
        if kind != K_HELLO:
            raise OSError("expected a hello frame, got kind %d" % kind)
        hello = json.loads(payload.decode("utf-8"))
        if hello.get("token") != self.token:
            raise OSError("transport hello token mismatch")
        with self._lock:
            transport = self._sessions.get(hello.get("session"))
        if transport is None:
            raise OSError("transport hello for unknown session %r"
                          % hello.get("session"))
        # feature negotiation (ISSUE 18): only a child that advertised the
        # tenant feature gets a feature-list ack (and tagged frames); an old
        # child gets the historical empty ack and an untagged link
        features = hello.get("features") or ()
        tenant_ok = "tenant" in features
        transport._tenant_frames = tenant_ok
        if hello.get("tenant"):
            transport.peer_tenant = hello["tenant"]
        ack = json.dumps({"features": ["tenant"]}).encode("utf-8") \
            if tenant_ok else b""
        sock.sendall(pack_frame(K_HELLO_ACK, ack))
        transport.adopt(sock, leftover=bytes(buf))

    def close(self):
        self._stop.set()
        try:
            self._srv.close()
        except OSError:
            pass
        self._thread.join(timeout=5.0)


def parse_address(address):
    """``tcp:<host>:<port>:<session>`` -> (host, port, session)."""
    parts = address.split(":")
    if len(parts) != 4 or parts[0] != "tcp":
        raise ValueError("malformed tcp transport address %r" % address)
    return parts[1], int(parts[2]), int(parts[3])


def connect_child_tcp(address, authkey):
    """Child-side bootstrap (``_child_worker``): dial the hub named by the
    argv ``address``, authenticating with the authkey the parent piped to
    stdin. Link policy comes from the ``PTPU_LINK_*`` / retry env vars the
    parent exported into the child environment."""
    from petastorm_tpu.recovery import RecoveryOptions

    host, port, session = parse_address(address)
    transport = TcpChildTransport(host, port, session,
                                  token=bytes(authkey).hex(),
                                  recovery=RecoveryOptions())
    transport.dial()
    return transport
