"""Wire framing for the tcp transport (ISSUE 15): length-prefixed,
crc32-trailered frames.

A frame is::

    >H   magic   (0xF7A5 — stream-desync canary)
    >B   kind    (K_* below)
    >I   length  (payload bytes; bounded by MAX_FRAME)
    ...  payload
    >I   crc32 over (kind byte + payload)

The crc covers the kind so a flipped kind byte cannot re-type a payload; the
magic makes a desynchronized stream (a partial frame left behind by a link
death) fail loudly instead of parsing garbage lengths. Verification failures
raise :class:`petastorm_tpu.errors.TransportFrameCorrupt` — the link is torn
down and the in-flight item re-dispatches; a corrupt payload is never
delivered (the chaos ``net.corrupt_frame`` action is caught exactly here).

Parsing is buffer-based (:func:`take_frame` over a ``bytearray`` the endpoint
appends socket reads into), so a read timeout mid-frame keeps the partial
bytes and resumes — bounded-socket-timeout reads never lose sync.
"""
from __future__ import annotations

import struct
import zlib

from petastorm_tpu.errors import TransportFrameCorrupt

MAGIC = 0xF7A5
_HEADER = struct.Struct(">HBI")
_TRAILER = struct.Struct(">I")
HEADER_LEN = _HEADER.size
TRAILER_LEN = _TRAILER.size

#: frame kinds
K_OBJ = 1      #: a pickled python object (the Connection.send/recv parity)
K_RAW = 2      #: raw serializer bytes (the Connection.send_bytes parity)
K_HB = 3       #: transport heartbeat; payload = ">d" sender-monotonic stamp
K_HB_ACK = 4   #: heartbeat echo (same payload) — the sender's rtt sample
K_HELLO = 5    #: connection bootstrap: token + session id + dial attempt
K_HELLO_ACK = 6

#: kind-byte flag (ISSUE 18): the payload is prefixed with an optional tenant
#: header — one length byte + that many ascii slug bytes — before the real
#: payload. Version-negotiated in the hello exchange: a sender only sets the
#: flag after the peer advertised the ``tenant`` feature, so old peers never
#: see a flagged kind (and :func:`split_tenant` makes a new receiver treat an
#: unflagged frame as untagged — old senders keep working unchanged). The crc
#: covers the flagged kind byte plus the prefixed payload, so the tenant
#: header enjoys the same corruption detection as the body.
K_TENANT_FLAG = 0x80

#: hard bound on one frame's payload — a desynced length field must fail fast,
#: not allocate gigabytes (result payloads are row-group batches, well under)
MAX_FRAME = 1 << 31


def pack_frame(kind, payload, tenant=None):
    """One wire frame for ``payload`` (bytes-like).

    ``tenant`` (a validated bounded slug, or None) rides an optional header
    in front of the payload, marked by :data:`K_TENANT_FLAG` on the kind
    byte. Callers must only pass a tenant after hello negotiation confirmed
    the peer understands the flag.
    """
    payload = bytes(payload)
    if tenant is not None:
        slug = tenant.encode("ascii")
        if not 0 < len(slug) < 256:
            raise ValueError("tenant frame header slug %r out of bounds"
                             % (tenant,))
        kind |= K_TENANT_FLAG
        payload = bytes((len(slug),)) + slug + payload
    crc = zlib.crc32(bytes((kind,)) + payload) & 0xFFFFFFFF
    return _HEADER.pack(MAGIC, kind, len(payload)) + payload \
        + _TRAILER.pack(crc)


def split_tenant(kind, payload):
    """``(kind, payload, tenant-or-None)`` with the tenant header stripped.

    Receivers call this on every frame :func:`take_frame` yields: unflagged
    frames (every frame an old peer sends) pass through untouched with
    ``tenant=None``; flagged frames lose the flag bit and the slug prefix. A
    flagged frame whose header is truncated or non-ascii is corrupt — same
    teardown path as a crc mismatch.
    """
    if not kind & K_TENANT_FLAG:
        return kind, payload, None
    kind &= ~K_TENANT_FLAG
    if not payload:
        raise TransportFrameCorrupt(
            "tenant-flagged frame (kind=%d) carries no tenant header" % kind)
    n = payload[0]
    if len(payload) < 1 + n:
        raise TransportFrameCorrupt(
            "tenant frame header truncated (kind=%d want %d slug bytes, "
            "frame has %d)" % (kind, n, len(payload) - 1))
    try:
        tenant = payload[1:1 + n].decode("ascii")
    except UnicodeDecodeError:
        raise TransportFrameCorrupt(
            "tenant frame header is not ascii (kind=%d)" % kind)
    return kind, payload[1 + n:], tenant


def frame_size(buf):
    """Total byte length of the frame at the head of ``buf``, or None while
    the header (or body) is still incomplete. Raises on a bad magic/length —
    the stream is desynchronized and the link must die."""
    if len(buf) < HEADER_LEN:
        return None
    magic, _kind, length = _HEADER.unpack_from(buf)
    if magic != MAGIC:
        raise TransportFrameCorrupt(
            "transport stream desynchronized (bad frame magic 0x%04X)" % magic)
    if length > MAX_FRAME:
        raise TransportFrameCorrupt(
            "transport frame length %d exceeds the %d-byte bound (desynced "
            "stream?)" % (length, MAX_FRAME))
    total = HEADER_LEN + length + TRAILER_LEN
    return total if len(buf) >= total else None


def take_frame(buf):
    """Pop one complete frame off the head of ``buf`` (a ``bytearray``):
    ``(kind, payload-bytes)``, or ``None`` when the buffer holds only a
    partial frame. Raises :class:`TransportFrameCorrupt` on a crc/magic
    mismatch (the corrupt bytes are consumed first so the caller can count
    before tearing the link down)."""
    total = frame_size(buf)
    if total is None:
        return None
    _magic, kind, length = _HEADER.unpack_from(buf)
    payload = bytes(buf[HEADER_LEN:HEADER_LEN + length])
    (crc,) = _TRAILER.unpack_from(buf, HEADER_LEN + length)
    del buf[:total]
    if crc != (zlib.crc32(bytes((kind,)) + payload) & 0xFFFFFFFF):
        raise TransportFrameCorrupt(
            "transport frame crc mismatch (kind=%d len=%d)" % (kind, length))
    return kind, payload
