"""Partition-tolerant transport plane for the worker-pool wire (ISSUE 15).

The process pool's (serializer, descriptor, lease) result protocol was welded
to ``multiprocessing.connection`` pipes — none of the robustness machinery
(PR 7 exactly-once-or-quarantined accounting, PR 11 generation tokens, PR 14
control frames) could cross a host boundary, which blocked ROADMAP item 1's
disaggregated data service. This package lifts the wire into a ``Transport``
interface with two implementations:

- :class:`PipeTransport` — today's unix-socket ``Connection``, byte-identical
  and zero new cost (methods are bound straight to the connection's in
  ``__init__``; the only additions are no-op ledger hooks).
- :class:`~petastorm_tpu.transport.tcp.TcpTransport` — length-prefixed
  crc32-trailered frames (:mod:`~petastorm_tpu.transport.framing`) over
  loopback/LAN sockets with bounded connect/read timeouts, transport-level
  heartbeats with half-open link detection, jittered-backoff reconnect driven
  by :class:`~petastorm_tpu.recovery.RecoveryOptions`, and a per-connection
  in-flight ledger so a link death re-dispatches un-acked items through the
  PR 7 poison/quarantine path — never delivering twice, never losing a
  watermark row.

The interface is deliberately the ``multiprocessing.connection.Connection``
surface the pool already speaks (``send``/``recv``/``poll``/``send_bytes``/
``recv_bytes``/``close``) plus the robustness extensions (``reconnect``,
``track``/``settle``/``inflight``, ``mark_ready``), so ``ProcessExecutor``'s
driver protocol — result blobs, control frames, pid/handshake acks, heartbeat
pings — rides either implementation unchanged. Link faults surface as
:class:`petastorm_tpu.errors.TransportLinkDown` (a ``ConnectionResetError``
subclass, so the existing dead-child except clauses classify it).

Metrics (``ptpu_net_*``, resolved once per process): connects, reconnects,
heartbeats missed, frames/bytes by direction, corrupt frames, and an rtt
histogram over the PR 5 log-bucket primitive. See docs/robustness.md for the
fault model and docs/observability.md for the family rows.
"""
from __future__ import annotations

import threading

from petastorm_tpu.errors import (  # noqa: F401  (re-export: the plane's API)
    TransportFrameCorrupt,
    TransportLinkDown,
)

#: transport selector values accepted by the pool factories / PTPU_TRANSPORT
TRANSPORTS = ("pipe", "tcp")


class Transport:
    """The pool-wire interface: framed send/recv of result blobs, control
    frames, and pid/handshake acks, plus the robustness extensions. Concrete
    transports implement the ``Connection`` surface; the base supplies the
    no-op robustness hooks so the pipe path stays byte-identical."""

    #: True once the app-level handshake (pid ack) completed — chaos hook
    #: sites and heartbeat policing only engage on the steady-state link
    #: (bootstrap failures are the spawn-failure path's job)
    ready = False

    def mark_ready(self):
        self.ready = True

    # -- per-connection in-flight ledger ------------------------------------------------
    # The driver tracks the item it dispatched and settles it when the result
    # (or exc header) is fully consumed; whatever is still tracked at link
    # death is exactly what must re-dispatch. Pipe links have no partial-
    # delivery mode (a dead pipe IS a dead child), so the base is a no-op.

    def track(self, key):
        pass

    def settle(self):
        pass

    def inflight(self):
        """The un-acked dispatched item key, or None."""
        return None


class PipeTransport(Transport):
    """Today's pool wire: a ``multiprocessing.connection.Connection`` behind
    the :class:`Transport` interface. Methods are bound directly to the
    connection in ``__init__`` — the pipe path costs nothing new (no
    per-message indirection), and there is no ``reconnect``: a dead pipe is a
    dead child, handled by the pool's respawn machinery."""

    def __init__(self, conn):
        self._conn = conn
        self.send = conn.send
        self.recv = conn.recv
        self.send_bytes = conn.send_bytes
        self.recv_bytes = conn.recv_bytes
        self.poll = conn.poll
        self.close = conn.close

    def fileno(self):
        return self._conn.fileno()


_net_metrics = None
_net_lock = threading.Lock()


class _NetMetrics:
    """The ``ptpu_net_*`` families, resolved once per process (same contract
    as the steal counter in workers.py — hot paths pay one ``inc()``)."""

    __slots__ = ("connects", "reconnects", "hb_missed", "frames_tx",
                 "frames_rx", "bytes_tx", "bytes_rx", "frames_corrupt", "rtt")

    def __init__(self, registry):
        self.connects = registry.counter(
            "ptpu_net_connects_total",
            help="tcp transport links established (first connects + redials)")
        self.reconnects = registry.counter(
            "ptpu_net_reconnects_total",
            help="tcp transport links re-established after a link death")
        self.hb_missed = registry.counter(
            "ptpu_net_heartbeats_missed_total",
            help="heartbeat intervals that passed with no traffic from the "
                 "peer (link_miss_threshold of these = half-open, torn down)")
        self.frames_tx = registry.counter(
            "ptpu_net_frames_total", direction="tx",
            help="transport frames by direction")
        self.frames_rx = registry.counter(
            "ptpu_net_frames_total", direction="rx")
        self.bytes_tx = registry.counter(
            "ptpu_net_bytes_total", direction="tx",
            help="transport wire bytes by direction (headers + trailers "
                 "included)")
        self.bytes_rx = registry.counter(
            "ptpu_net_bytes_total", direction="rx")
        self.frames_corrupt = registry.counter(
            "ptpu_net_frames_corrupt_total",
            help="frames rejected by the crc32 trailer / magic check — each "
                 "one also tears its link down")
        self.rtt = registry.histogram(
            "ptpu_net_rtt_seconds",
            help="transport heartbeat round-trip time (HB -> HB_ACK)")


def net_metrics():
    """The process-wide net-metric struct (created on first use)."""
    global _net_metrics
    m = _net_metrics
    if m is None:
        with _net_lock:
            if _net_metrics is None:
                from petastorm_tpu.obs.metrics import default_registry

                _net_metrics = _NetMetrics(default_registry())
            m = _net_metrics
    return m


def normalize_transport(value):
    """``None``/env -> 'pipe'; validates the selector."""
    import os

    if value is None:
        value = os.environ.get("PTPU_TRANSPORT") or "pipe"
    if value not in TRANSPORTS:
        raise ValueError("transport must be one of %s, got %r"
                         % (TRANSPORTS, value))
    return value
