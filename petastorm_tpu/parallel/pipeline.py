"""Pipeline parallelism: GPipe-schedule microbatching over a ``pp`` mesh axis.

Not present in the reference (SURVEY.md §3.7 — its closest analog is the host-side
ventilator→worker→collate pipeline). Here stages live on different devices along the ``pp``
axis; activations hop stage-to-stage with ``lax.ppermute`` (neighbour ICI transfers), and the
schedule runs ``n_micro + n_stages - 1`` ticks with the classic bubble. Everything is
static-shape ``lax.scan`` — jittable, differentiable, XLA-schedulable.

Layout contract: stage parameters are stacked on a leading ``n_stages`` axis and sharded over
``pp`` (one stage per device row); inputs are microbatched (n_micro, micro_b, ...) and fully
replicated along ``pp`` (only stage 0 consumes them, only stage n-1 emits outputs).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax


def spmd_pipeline(stage_fn, stage_params, microbatches, axis_name):
    """Run inside shard_map over ``axis_name``; returns (n_micro, micro_b, ...) outputs.

    ``stage_fn(params, x) -> y`` is the per-stage computation; ``stage_params`` here is the
    LOCAL slice (leading dim 1) of the stacked stage parameters; ``microbatches`` has shape
    (n_micro, micro_b, ...), identical on every stage.
    """
    n_stages = lax.psum(1, axis_name)
    stage = lax.axis_index(axis_name)
    params = jax.tree.map(lambda p: p[0], stage_params)  # local (1, ...) -> (...)
    n_micro = microbatches.shape[0]

    def tick(carry, t):
        state, outputs = carry
        # stage 0 ingests microbatch t (clipped; predication handles the tail bubble)
        inp = microbatches[jnp.clip(t, 0, n_micro - 1)]
        x = jnp.where(stage == 0, inp, state)
        y = stage_fn(params, x)
        # last stage finishes microbatch t-(n_stages-1) at tick t
        mb = t - (n_stages - 1)
        write_ok = (stage == n_stages - 1) & (mb >= 0)
        mbc = jnp.clip(mb, 0, n_micro - 1)
        outputs = outputs.at[mbc].set(jnp.where(write_ok, y, outputs[mbc]))
        shifted = lax.ppermute(
            y, axis_name, [(j, (j + 1) % n_stages) for j in range(n_stages)]
        )
        return (shifted, outputs), None

    # the carries become pp-varying after the ppermute/one-hot write, so the inits must
    # carry that varying-axes type too; deriving from microbatches (* 0) also inherits any
    # dp/sp varying axes the data brings in
    # lax.pcast only exists on jax versions with explicit varying-axes types;
    # older shard_map treats everything as varying already, so identity is the
    # correct degenerate form there (ISSUE 12 satellite: version compat)
    if hasattr(lax, "pcast"):
        state0 = lax.pcast(microbatches[0] * 0, (axis_name,), to="varying")
        outputs0 = lax.pcast(microbatches * 0, (axis_name,), to="varying")
    else:
        state0 = microbatches[0] * 0
        outputs0 = microbatches * 0
    (_, outputs), _ = lax.scan(tick, (state0, outputs0), jnp.arange(n_micro + n_stages - 1))
    # every stage's `outputs` buffer is only filled on the last stage; broadcast it back so
    # the result is replicated along pp (psum over one-hot keeps it a collective, not a gather)
    return lax.psum(jnp.where(stage == n_stages - 1, outputs, jnp.zeros_like(outputs)),
                    axis_name)


def pipelined_apply(stage_fn, stacked_params, x, mesh, n_micro, pp_axis="pp"):
    """Mesh-level entry: apply an ``n_stages``-deep pipeline to a global batch.

    ``stacked_params``: pytree with leading axis n_stages (shard over ``pp`` with
    ``stage_sharding``); ``x``: (batch, ...) global batch; ``n_micro`` microbatches must
    divide batch. Returns (batch, ...) outputs.
    """
    from jax.sharding import PartitionSpec as P

    from petastorm_tpu.compat import shard_map

    if x.shape[0] % n_micro:
        raise ValueError("batch %d not divisible into %d microbatches" % (x.shape[0], n_micro))
    micro_b = x.shape[0] // n_micro
    xm = x.reshape((n_micro, micro_b) + x.shape[1:])

    fn = functools.partial(spmd_pipeline, stage_fn, axis_name=pp_axis)
    param_specs = jax.tree.map(lambda _: P(pp_axis), stacked_params)
    out = shard_map()(
        fn, mesh=mesh,
        in_specs=(param_specs, P()),
        out_specs=P(),
    )(stacked_params, xm)
    return out.reshape((x.shape[0],) + out.shape[2:])


def stage_sharding(mesh, pp_axis="pp"):
    """NamedSharding for stage-stacked parameters (leading axis over ``pp``)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P(pp_axis))
