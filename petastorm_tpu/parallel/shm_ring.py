"""Shared-memory slab ring for the process pool's zero-copy wire.

The ProcessExecutor wire used to be the last multi-copy hop on the decode path:
children pushed every payload frame through a ``multiprocessing.connection`` unix
socket (one kernel copy out, one allocation+copy in), after which the writable-batch
contract forced another full copy of every read-only reconstruction. This module
provides the slab transport that removes the socket hop (Zerrow's "true zero-copy
Arrow pipelines" observation, PAPERS.md): the parent owns a ring of
``multiprocessing.shared_memory`` segments ("slabs") with a thread-safe free list;
per item, a driver thread acquires a slab and grants it to the child alongside the
work item; the child writes its serialized frames straight into the slab and answers
with a tiny descriptor; the parent reconstructs buffer views into the slab with no
copy at all. See :class:`petastorm_tpu.serializers.ShmSerializer` for the framing
and :class:`petastorm_tpu.workers.ProcessExecutor` for the grant protocol.

Lifecycle rules (the leak-proof part):

- The PARENT is the only creator and the only unlinker. ``SlabRing.close()`` —
  called from ``ProcessExecutor.join()`` — unlinks every segment, so nothing
  survives in ``/dev/shm`` whatever the children did (including SIGKILL mid-write).
- Children attach by name and explicitly deregister from their process's
  ``resource_tracker`` (gh-82300: an attaching process otherwise unlinks the
  parent's segments when it exits — exactly the respawn path).
- A slab granted to a child that dies mid-item is released back to the ring by the
  driver thread before the replacement child is spawned.
- Consumer-held leases (:class:`SlabLease`) release idempotently, and release after
  ``close()`` is a no-op — teardown order cannot double-free or resurrect a slab.

The ring also keeps the wire gauges (slabs in flight, bytes through shm, socket
fallbacks, cumulative acquire wait) surfaced via ``PipelineStats`` / ``Reader.
wire_stats()``, and records ``shm.acquire_wait`` spans into an attached
:class:`petastorm_tpu.trace.TraceRecorder`.
"""
from __future__ import annotations

import logging
import os
import queue
import threading
import time

logger = logging.getLogger(__name__)

#: /dev/shm segment name prefix — the test suite's leak fixture and operators
#: debugging a wedged pool both grep for it.
SEGMENT_PREFIX = "ptpu_shm_"

_supported_cache = None


def _noop():
    pass


def shm_supported():
    """True when ``multiprocessing.shared_memory`` works on this platform (probed
    once): a missing ``/dev/shm`` mount, a SELinux denial, or a python built
    without ``_posixshmem`` all degrade the wire to the socket path."""
    global _supported_cache
    if _supported_cache is None:
        try:
            from multiprocessing import shared_memory

            probe = shared_memory.SharedMemory(create=True, size=16)
            try:
                probe.buf[0] = 1
            finally:
                probe.close()
                probe.unlink()
            _supported_cache = True
        except Exception as e:  # noqa: BLE001 — any failure means "not here"
            from petastorm_tpu.obs.log import degradation

            degradation("shm_unsupported",
                        "shared-memory wire unavailable (%s); the process pool "
                        "will use the socket wire", e)
            _supported_cache = False
    return _supported_cache


def _untrack(segment):
    """Deregister an ATTACHED segment from this process's resource_tracker.

    gh-82300: on POSIX, ``SharedMemory(name=...)`` registers the segment with the
    tracker even when it did not create it, and the tracker unlinks everything it
    knows at process exit — so a pool child exiting cleanly would tear the
    parent's ring out from under the other children."""
    try:
        from multiprocessing import resource_tracker

        resource_tracker.unregister(segment._name, "shared_memory")
    except Exception:  # noqa: BLE001 — tracker internals vary; worst case is a
        pass           # spurious unlink warning at child exit, not a leak  # graftlint: disable=GL-O002


def untrack_attachment(segment):
    """Public gh-82300 seam: the cache arena (``io/arena.py``) attaches
    segments by name exactly like :class:`SlabClient` and needs the same
    tracker deregistration — one fix, one place."""
    _untrack(segment)


class SlabLease:
    """One consumer-held reference to an acquired slab.

    ``release()`` returns the slab to the ring exactly once — atomically, so the
    cross-thread teardown pattern the pools support (a consumer thread iterating
    while another thread calls ``stop()``, both reaching the same lease) cannot
    double-insert the slab id into the free list and hand one slab to two
    children. Dropping the last reference releases too (refcount ``__del__``),
    so a consumer that simply discards a batch cannot wedge the ring — the
    explicit hook (``Reader.release_batch()``) just makes the return prompt and
    deterministic.
    """

    __slots__ = ("_ring", "slab_id", "_released", "_lock")

    def __init__(self, ring, slab_id):
        self._ring = ring
        self.slab_id = slab_id
        self._released = False
        self._lock = threading.Lock()

    def release(self):
        with self._lock:  # exactly-once even under concurrent release/__del__
            if self._released:
                return
            self._released = True
        self._ring.release(self.slab_id)

    def __del__(self):
        try:
            self.release()
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass  # graftlint: disable=GL-O002 (GC/exit path: logging may itself fail)


class SlabRing:
    """Parent-side slab owner: fixed-size segments + a thread-safe free list."""

    def __init__(self, slab_bytes, num_slabs, trace=None):
        from multiprocessing import shared_memory

        if slab_bytes <= 0 or num_slabs <= 0:
            raise ValueError("slab_bytes and num_slabs must be positive")
        self.slab_bytes = int(slab_bytes)
        self._lock = threading.Lock()
        self._closed = False
        self._segs = []
        token = "%d_%s" % (os.getpid(), os.urandom(4).hex())
        try:
            for i in range(num_slabs):
                seg = shared_memory.SharedMemory(
                    create=True, size=self.slab_bytes,
                    name="%s%s_%d" % (SEGMENT_PREFIX, token, i))
                self._segs.append(seg)
        except BaseException:
            self.close()  # a half-built ring must not leak its earlier segments
            raise
        self.names = [seg.name for seg in self._segs]
        self._free = queue.Queue()
        for i in range(num_slabs):
            self._free.put(i)
        #: ids currently OUT of the free list — release() only accepts these, so
        #: a double release (two owners each "returning" the same slab) can
        #: never insert one id twice and grant one slab to two children
        self._granted = set()
        #: slab id -> weakref to the outstanding view-mode Lease issued over it
        #: (registered by ShmSerializer.deserialize; entry dropped at release).
        #: reclaim() consults this so a dead-child reclaim can never hand out a
        #: slab a consumer-retained batch still views — it revokes instead.
        self._leases = {}
        self._trace = trace
        # wire gauges (read via stats(); exported through PipelineStats.shm_*)
        self._grants = 0
        self._bytes_through = 0
        self._fallbacks = 0
        self._acquire_wait_s = 0.0

    def __len__(self):
        return len(self._segs)

    # -- free-list protocol -------------------------------------------------------------

    def acquire(self, timeout=2.0):
        """A free slab id, or None after ``timeout`` (the caller then degrades to
        the socket wire for that item — graceful, never blocking the pool)."""
        if self._closed:
            return None
        t0 = time.perf_counter()
        try:
            slab_id = self._free.get(timeout=timeout)
        except queue.Empty:
            slab_id = None
        waited = time.perf_counter() - t0
        with self._lock:
            self._acquire_wait_s += waited
            if slab_id is not None:
                self._grants += 1
                self._granted.add(slab_id)
        if self._trace is not None and waited > 1e-4:
            self._trace.add("shm.acquire_wait", t0, waited)
        return slab_id

    def release(self, slab_id):
        """Return a slab to the free list (no-op after close()). Releasing an
        id that is not currently granted is ignored with a logged degradation —
        a double release must never insert one slab twice (two children would
        be granted the same memory, corrupting a consumer-retained view)."""
        if self._closed:
            return
        with self._lock:
            if slab_id not in self._granted:
                from petastorm_tpu.obs.log import degradation

                degradation(
                    "shm_double_release",
                    "slab %s released while not granted (double release "
                    "suppressed — see docs/robustness.md)", slab_id, once=False)
                return
            self._granted.discard(slab_id)
            self._leases.pop(slab_id, None)
        self._free.put(slab_id)

    def register_lease(self, slab_id, lease):
        """Record the outstanding consumer lease over a granted slab (view-mode
        deliveries). The entry drops automatically when the lease's release
        returns the slab; :meth:`reclaim` consults it."""
        import weakref

        with self._lock:
            if slab_id in self._granted:
                self._leases[slab_id] = weakref.ref(lease)

    def reclaim(self, slab_id):
        """Lease-aware slab reclaim — the dead-child path (ISSUE 7).

        PR-2's reclaim blind-released the dead child's in-flight slab; since
        the PR-6 lease contract a slab can be consumer-leased (a loader batch
        retaining zero-copy views), and re-inserting such a slab would hand it
        to a respawned child to overwrite under the consumer. If an outstanding
        lease exists it is REVOKED instead — the retained batch raises
        :class:`~petastorm_tpu.errors.LeaseRevoked` on next access, and the
        slab returns to the free list through the holder's own release."""
        if self._closed:
            return
        with self._lock:
            ref = self._leases.pop(slab_id, None)
        lease = ref() if ref is not None else None
        if lease is not None:
            revoke = getattr(lease, "revoke", None)
            if revoke is not None:
                revoke()
                from petastorm_tpu.obs.log import degradation

                degradation(
                    "lease_revoked_on_reclaim",
                    "slab %s reclaimed (dead child) while a consumer lease was "
                    "outstanding; the lease was revoked — retained views raise "
                    "LeaseRevoked instead of reading reused memory", slab_id,
                    once=False)
                return
        self.release(slab_id)

    def buffer(self, slab_id):
        """Writable memoryview over one slab's full extent."""
        return self._segs[slab_id].buf

    def set_trace(self, trace):
        self._trace = trace

    # -- accounting ---------------------------------------------------------------------

    def add_bytes(self, n):
        with self._lock:
            self._bytes_through += int(n)

    def count_fallback(self):
        with self._lock:
            self._fallbacks += 1

    def stats(self):
        """Wire gauges: slab occupancy, shm byte volume, socket fallbacks,
        cumulative acquire wait."""
        with self._lock:
            in_flight = len(self._segs) - self._free.qsize() if not self._closed else 0
            return {
                "shm_slabs_total": len(self._segs),
                "shm_slabs_in_flight": in_flight,
                "shm_grants": self._grants,
                "shm_bytes": self._bytes_through,
                "shm_fallbacks": self._fallbacks,
                "shm_acquire_wait_s": round(self._acquire_wait_s, 4),
            }

    # -- teardown -----------------------------------------------------------------------

    def close(self):
        """Unlink + unmap every segment (idempotent). Runs from
        ``ProcessExecutor.join()`` AFTER children are reaped, so no writer is
        live; consumer views may still exist (view-mode batches a consumer kept
        past join), in which case the unmap is deferred to interpreter exit but
        the ``/dev/shm`` entry is removed HERE either way — segments never
        outlive the pool on disk."""
        with self._lock:  # stats() reads occupancy from these concurrently
            self._closed = True
            segs, self._segs = self._segs, []
        for seg in segs:
            try:
                seg.unlink()
            except FileNotFoundError:
                pass
            except Exception:  # noqa: BLE001 — unlink is best-effort per segment
                pass  # graftlint: disable=GL-O002 (exit path; FileNotFoundError handled above)
            try:
                seg.close()
            except BufferError:
                # exported views still alive (a consumer kept a view-mode batch):
                # the name is already unlinked above, the mapping frees with the
                # last view / at process exit. Shadow close() so the segment's
                # __del__ does not retry and spam "Exception ignored" at GC.
                seg.close = _noop
            except Exception:  # noqa: BLE001
                pass  # graftlint: disable=GL-O002 (exit path: mapping frees at process exit)


class SlabClient:
    """Child-side attach-by-name view of the parent's ring (write-only use).

    Segments attach lazily on first grant and are detached — never unlinked —
    by ``close()``; every attachment is deregistered from the child's
    resource_tracker (see :func:`_untrack`).
    """

    def __init__(self, names, slab_bytes):
        self._names = list(names)
        self.slab_bytes = int(slab_bytes)
        self._segs = {}

    def buffer(self, slab_id):
        seg = self._segs.get(slab_id)
        if seg is None:
            from multiprocessing import shared_memory

            seg = shared_memory.SharedMemory(name=self._names[slab_id])
            _untrack(seg)
            self._segs[slab_id] = seg
        return seg.buf

    def close(self):
        segs, self._segs = self._segs, {}
        for seg in segs.values():
            try:
                seg.close()
            except Exception:  # noqa: BLE001 — exit path
                pass  # graftlint: disable=GL-O002 (exit path: mapping frees at process exit)
