"""Parallelism toolkit: device meshes, batch shardings, sequence/context parallelism
(ring attention, Ulysses), and pipeline microbatching. See SURVEY.md §3.7 for how this
generalizes the reference's static shard arithmetic."""

from petastorm_tpu.parallel.mesh import (  # noqa: F401
    AXIS_ORDER,
    batch_sharding,
    local_batch_size,
    make_mesh,
    sequence_sharding,
)


def __getattr__(name):
    if name in ("ring_attention", "ulysses_attention", "reference_attention",
                "ring_self_attention", "ulysses_self_attention"):
        from petastorm_tpu.parallel import attention

        return getattr(attention, name)
    if name in ("spmd_pipeline", "pipelined_apply", "stage_sharding"):
        from petastorm_tpu.parallel import pipeline

        return getattr(pipeline, name)
    raise AttributeError("module 'petastorm_tpu.parallel' has no attribute %r" % name)
