"""parallel subpackage."""
