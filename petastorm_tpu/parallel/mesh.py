"""Device-mesh construction and batch-sharding helpers.

The reference's distribution story is static shard arithmetic (``cur_shard``/``shard_count``,
petastorm/reader.py ~L470) with zero runtime communication. The TPU-native generalization is a
``jax.sharding.Mesh`` over the pod slice: the data plane delivers batches already laid out for
whatever (dp, pp, tp, sp, ep) the training step uses, and collectives ride ICI via XLA.
"""
from __future__ import annotations

import math

import numpy as np

#: Canonical mesh-axis vocabulary used across petastorm_tpu:
#: dp = data (batch), pp = pipeline stages, sp = sequence/context, tp = tensor (model),
#: ep = expert (MoE; commonly aliased onto dp or its own axis).
AXIS_ORDER = ("dp", "pp", "ep", "sp", "tp")


def make_mesh(axis_sizes=None, devices=None):
    """Build a ``Mesh`` from ``{axis: size}``; unlisted devices fold into ``dp``.

    ``axis_sizes=None`` → pure data-parallel mesh over all devices. Sizes of -1 (at most one)
    are inferred from the device count. Axis order follows :data:`AXIS_ORDER` so the
    fastest-varying (innermost, highest-bandwidth ICI neighbours) axis is ``tp`` — the axis
    whose collectives are latency-critical.
    """
    import jax
    from jax.sharding import Mesh

    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    axis_sizes = dict(axis_sizes or {})
    for ax in axis_sizes:
        if ax not in AXIS_ORDER:
            raise ValueError("Unknown mesh axis %r (expected one of %s)" % (ax, AXIS_ORDER))
    known = [s for s in axis_sizes.values() if s != -1]
    n_unknown = sum(1 for s in axis_sizes.values() if s == -1)
    if n_unknown > 1:
        raise ValueError("At most one axis size may be -1")
    prod = math.prod(known) if known else 1
    if n_unknown:
        if n % prod:
            raise ValueError("Cannot infer -1 axis: %d devices not divisible by %d" % (n, prod))
        inferred = n // prod
        axis_sizes = {k: (inferred if v == -1 else v) for k, v in axis_sizes.items()}
        prod = n
    if "dp" not in axis_sizes:
        if n % prod:
            raise ValueError(
                "Axis sizes %r do not divide device count %d" % (axis_sizes, n)
            )
        axis_sizes["dp"] = n // prod
    sizes = [(ax, axis_sizes[ax]) for ax in AXIS_ORDER if ax in axis_sizes]
    total = math.prod(s for _, s in sizes)
    if total != n:
        raise ValueError("Mesh %r needs %d devices, have %d" % (dict(sizes), total, n))
    shape = tuple(s for _, s in sizes)
    names = tuple(ax for ax, _ in sizes)
    return Mesh(np.array(devices).reshape(shape), names)


def batch_sharding(mesh, batch_axes=("dp",), extra_dims=0):
    """``NamedSharding`` splitting the leading (batch) dim over ``batch_axes``.

    This is what a DataLoader consumer passes as ``sharding=``: data parallelism over ``dp``
    (optionally ``('dp', 'fsdp'-style combos)``); trailing dims replicated.
    """
    from jax.sharding import NamedSharding, PartitionSpec

    axes = tuple(a for a in batch_axes if a in mesh.axis_names)
    spec = PartitionSpec(axes if len(axes) > 1 else (axes[0] if axes else None),
                         *([None] * extra_dims))
    return NamedSharding(mesh, spec)


def sequence_sharding(mesh, batch_axis="dp", seq_axis="sp"):
    """Sharding for (batch, seq, ...) token batches: batch over dp, sequence over sp.

    A long-context consumer (ring attention / Ulysses) hands this to the DataLoader so
    sequences arrive already split along the context axis — the loader's only CP obligation
    (SURVEY.md §6)."""
    from jax.sharding import NamedSharding, PartitionSpec

    b = batch_axis if batch_axis in mesh.axis_names else None
    s = seq_axis if seq_axis in mesh.axis_names else None
    return NamedSharding(mesh, PartitionSpec(b, s))


def batch_axis_shard_count(sharding):
    """How many distinct slices a sharding cuts its batch (leading) axis into.

    1 = replicated/unsharded batch axis or not a ``NamedSharding`` (single-device
    placements lay out any row count). Shared by the loader's layout checks and the
    decode op's SPMD input staging — one definition, so they always agree on
    whether a batch is shardable."""
    import jax.sharding as jsh
    import numpy as np

    if isinstance(sharding, jsh.NamedSharding):
        spec0 = sharding.spec[0] if len(sharding.spec) else None
        if spec0 is None:
            return 1
        axes = (spec0,) if isinstance(spec0, str) else tuple(spec0)
        return int(np.prod([sharding.mesh.shape[a] for a in axes]))
    return 1


def local_batch_size(global_batch_size, mesh, batch_axes=("dp",)):
    """Rows this process must feed for a given global batch (multi-host loaders).

    The batch dim splits into prod(batch-axis sizes) chunks laid out along those mesh axes;
    a process must supply rows for every batch-chunk coordinate that any of its local
    devices occupies (other axes replicate and don't reduce the obligation).
    """
    axes = [a for a in batch_axes if a in mesh.axis_names]
    shards = math.prod(mesh.shape[a] for a in axes) if axes else 1
    if global_batch_size % shards:
        raise ValueError("global batch %d not divisible by %d-way batch sharding"
                         % (global_batch_size, shards))
    dev_grid = mesh.devices
    local_ids = {d.id for d in mesh.local_devices}
    axis_idx = [mesh.axis_names.index(a) for a in axes]
    owned = set()
    for pos in np.ndindex(*dev_grid.shape):
        if dev_grid[pos].id in local_ids:
            owned.add(tuple(pos[i] for i in axis_idx))
    return global_batch_size * len(owned) // shards
