"""Sequence/context parallelism: ring attention and Ulysses (all-to-all) attention.

The reference has no sequence parallelism (SURVEY.md §3.7 — NGram is windowing, not
parallelism); these are the TPU-native long-context primitives this framework adds so
consumers of sequence-sharded batches (``parallel.mesh.sequence_sharding``) can attend over
contexts longer than one chip's HBM:

- **Ring attention**: K/V blocks rotate around the ``sp`` ring via ``lax.ppermute`` (ICI
  neighbour hops) while each device keeps its Q block; softmax is accumulated online
  (flash-attention style log-sum-exp carry) so nothing materializes the full score matrix.
- **Ulysses**: ``lax.all_to_all`` reshards (seq-sharded → head-sharded), runs plain local
  attention over the full sequence per head group, then reshards back. Cheaper at moderate
  context when heads ≥ ring size; ring wins at extreme context.

All functions are shard_map-style collectives over an axis name, jittable and
differentiable; use :func:`ring_self_attention` / :func:`ulysses_self_attention` for the
mesh-wrapped form.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax


def reference_attention(q, k, v, causal=False):
    """Dense softmax attention (b, s, h, d) — the correctness oracle for the parallel forms."""
    scale = q.shape[-1] ** -0.5
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        s_q, s_k = scores.shape[-2], scores.shape[-1]
        mask = jnp.tril(jnp.ones((s_q, s_k), dtype=bool), k=s_k - s_q)
        scores = jnp.where(mask, scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def ring_attention(q, k, v, axis_name, causal=False):
    """Ring attention over a sharded sequence axis (inside shard_map over ``axis_name``).

    Args are local blocks (b, s_local, h, d); the global sequence is the concatenation of
    blocks in axis order. Returns the local output block. Accumulation is float32.
    """
    ring_size = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    b, s_loc, h, d = q.shape
    scale = d ** -0.5
    q32 = q.astype(jnp.float32)

    # derive accumulators from q so they inherit its varying-manual-axes type — fresh
    # zeros would be unvarying and the fori_loop carry types would disagree under shard_map
    o = q32 * 0.0
    zero_bhs = jnp.moveaxis(q32[..., 0] * 0.0, 1, 2)  # (b, h, s_loc)
    m = zero_bhs - jnp.inf
    l = zero_bhs

    def body(i, carry):
        o, m, l, k_blk, v_blk = carry
        kv_idx = (my_idx - i) % ring_size  # whose block we hold at step i
        scores = jnp.einsum("bqhd,bkhd->bhqk", q32, k_blk.astype(jnp.float32)) * scale
        if causal:
            q_pos = my_idx * s_loc + jnp.arange(s_loc)[:, None]
            k_pos = kv_idx * s_loc + jnp.arange(s_loc)[None, :]
            scores = jnp.where(q_pos >= k_pos, scores, -jnp.inf)
        blk_max = jnp.max(scores, axis=-1)
        m_new = jnp.maximum(m, blk_max)
        # fully-masked rows keep m=-inf; guard the exp against -inf - -inf
        safe_m = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(scores - safe_m[..., None])
        p = jnp.where(jnp.isfinite(scores), p, 0.0)
        corr = jnp.where(jnp.isfinite(m), jnp.exp(m - safe_m), 0.0)
        l = l * corr + jnp.sum(p, axis=-1)
        o = o * corr.transpose(0, 2, 1)[..., None] + jnp.einsum(
            "bhqk,bkhd->bqhd", p, v_blk.astype(jnp.float32)
        )
        perm = [(j, (j + 1) % ring_size) for j in range(ring_size)]
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        return o, m_new, l, k_blk, v_blk

    o, m, l, _, _ = lax.fori_loop(0, ring_size, body, (o, m, l, k, v))
    l = jnp.where(l == 0.0, 1.0, l)  # fully-masked rows -> zero output, not NaN
    out = o / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ulysses_attention(q, k, v, axis_name, causal=False):
    """Ulysses sequence parallelism (inside shard_map over ``axis_name``).

    all_to_all: (b, s/N, h, d) → (b, s, h/N, d), dense attention per local head group over
    the FULL sequence, then the inverse all_to_all. Requires heads % axis_size == 0.
    """
    n = lax.psum(1, axis_name)
    h = q.shape[2]
    if h % n:
        raise ValueError("Ulysses needs heads (%d) divisible by axis size (%d)" % (h, n))
    def seq_to_heads(x):
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=True)

    def heads_to_seq(x):
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2, tiled=True)

    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    oh = reference_attention(qh, kh, vh, causal=causal)
    return heads_to_seq(oh)


def _mesh_wrap(fn, mesh, seq_axis, batch_axis):
    from jax.sharding import PartitionSpec as P

    from petastorm_tpu.compat import shard_map

    spec = P(batch_axis if batch_axis in mesh.axis_names else None, seq_axis)
    return shard_map()(fn, mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec)


def ring_self_attention(q, k, v, mesh, seq_axis="sp", batch_axis="dp", causal=False):
    """Mesh-level ring attention: q/k/v are global (b, s, h, d) arrays sequence-sharded over
    ``seq_axis`` (e.g. via ``parallel.mesh.sequence_sharding``)."""
    fn = functools.partial(ring_attention, axis_name=seq_axis, causal=causal)
    return _mesh_wrap(fn, mesh, seq_axis, batch_axis)(q, k, v)


def ulysses_self_attention(q, k, v, mesh, seq_axis="sp", batch_axis="dp", causal=False):
    """Mesh-level Ulysses attention over a sequence-sharded batch."""
    fn = functools.partial(ulysses_attention, axis_name=seq_axis, causal=causal)
    return _mesh_wrap(fn, mesh, seq_axis, batch_axis)(q, k, v)
