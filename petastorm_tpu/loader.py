"""JAX ``DataLoader``: reader batches → globally-sharded ``jax.Array`` batches.

This is the TPU-native replacement for the reference's framework adapters
(petastorm/pytorch.py ``DataLoader``/``BatchedDataLoader`` ~L120/~L260 and
petastorm/tf_utils.py ``make_petastorm_dataset`` ~L350). Where the reference pays a
Python-callback + host-copy per training step (``tf.py_func`` / per-row torch collate), this
loader runs an async producer pipeline:

    reader (columnar numpy) → host re-batch [+ shuffling buffer] → background queue
        → ``jax.device_put`` with the consumer's ``Sharding`` (double/triple buffered)
        → optional jitted on-device transform (fused by XLA)

so the only per-step work on the critical path is a queue pop. Batches are *fixed-size*
(static shapes — XLA requirement); the remainder is dropped or padded per ``last_batch``.

Sharding contract (SURVEY.md §3.7): the loader accepts an arbitrary ``jax.sharding.Sharding``
for the batch. Data parallelism is the common case (batch axis over a mesh ``dp`` axis), but a
consumer running TP/SP can hand a sharding that splits feature/sequence axes and the loader
will lay batches out accordingly — this is the TPU-idiomatic superset of the reference's
``cur_shard``/``shard_count``. Under multi-process JAX each process's reader must already be
sharded (``cur_shard=jax.process_index()``); the loader assembles the global array with
``jax.make_array_from_process_local_data``.
"""
from __future__ import annotations

import collections
import functools
import itertools
import logging
import os
import queue
import threading
import time

import numpy as np

from petastorm_tpu.io.lease import (LeasedBatch, attach_leases, count_copy,
                                    take_leases)
from petastorm_tpu.shuffle import BatchedRandomShufflingBuffer
from petastorm_tpu.utils import stack_as_column

logger = logging.getLogger(__name__)

_SENTINEL = object()

#: per-process pipeline ids for health-scope namespacing: loaders SHARING one
#: HealthMonitor must not share heartbeat slots (itertools.count is GIL-atomic)
_pipeline_seq = itertools.count()


class PipelineStats:
    """Cheap per-stage counters for the loader pipeline (SURVEY.md §6: the reference
    exports nothing; the north-star metric is device idle, which needs a stage split).

    All times are cumulative seconds since the last ``reset()``:

    - ``read_s``: producer time blocked on the reader (parquet IO + worker decode)
    - ``batch_s``: producer time re-batching/shuffling host rows
    - ``put_wait_s``: producer time blocked putting into a FULL host-batch queue
      (downstream backpressure — the producer outruns decode/transfer/step; the
      bottleneck analyzer's consumer-bound signal)
    - ``decode_s``: transfer-thread time in batched on-device codec decode dispatch
    - ``h2d_s``: transfer-thread time in ``device_put`` / global-array assembly
    - ``queue_wait_s``: transfer-thread time starved waiting on the host-batch queue
    - ``device_queue_wait_s``: consumer time starved waiting on the device-batch queue
      (the end-user-visible starvation — nonzero means the pipeline cannot keep the
      accelerator fed)

    ``decode_unsharded_batches`` counts staged-decode dispatches that ran on a
    SINGLE device although the configured sharding cuts the batch axis across
    several — the undivisible-batch / local-mesh-derivation-failure / pre-``sharding``-
    kwarg-codec fallbacks (VERDICT r4 #6). Nonzero on a pod means one chip is
    decoding for many; fix the batch size / sharding / codec signature.

    The ``shm_*`` fields mirror the process pool's shared-memory wire gauges
    (``Reader.wire_stats()``, refreshed per reader delivery; all zero on thread/
    dummy pools and socket wires): ``shm_slabs_in_flight`` (slabs currently out
    of the ring), ``shm_bytes`` (payload bytes that traveled through shared
    memory), ``shm_fallbacks`` (items that degraded to the socket wire —
    oversized payload or a starved ring), ``shm_acquire_wait_s`` (cumulative
    driver-thread wait for a free slab — sustained growth means the ring is
    undersized for the consumer's release cadence).

    The same totals are exported as the ``ptpu_pipeline_*`` metric families
    when the loader is built with ``metrics=`` (see
    :mod:`petastorm_tpu.obs.metrics`), and
    ``petastorm_tpu.obs.analyze.analyze_snapshot`` reads this snapshot shape
    directly (``DataLoader.bottleneck_report()``).
    """

    __slots__ = ("rows", "batches", "read_s", "batch_s", "put_wait_s",
                 "decode_s", "h2d_s",
                 "queue_wait_s", "device_queue_wait_s", "decode_unsharded_batches",
                 "shm_slabs_in_flight", "shm_bytes", "shm_fallbacks",
                 "shm_acquire_wait_s")

    def __init__(self):
        self.reset()

    def reset(self):
        self.rows = 0
        self.batches = 0
        self.read_s = 0.0
        self.batch_s = 0.0
        self.put_wait_s = 0.0
        self.decode_s = 0.0
        self.h2d_s = 0.0
        self.queue_wait_s = 0.0
        self.device_queue_wait_s = 0.0
        self.decode_unsharded_batches = 0
        self.shm_slabs_in_flight = 0
        self.shm_bytes = 0
        self.shm_fallbacks = 0
        self.shm_acquire_wait_s = 0.0

    def snapshot(self):
        return {
            "rows": self.rows,
            "batches": self.batches,
            "read_s": round(self.read_s, 4),
            "batch_s": round(self.batch_s, 4),
            "put_wait_s": round(self.put_wait_s, 4),
            "decode_s": round(self.decode_s, 4),
            "h2d_s": round(self.h2d_s, 4),
            "queue_wait_s": round(self.queue_wait_s, 4),
            "device_queue_wait_s": round(self.device_queue_wait_s, 4),
            "decode_unsharded_batches": self.decode_unsharded_batches,
            "shm_slabs_in_flight": self.shm_slabs_in_flight,
            "shm_bytes": self.shm_bytes,
            "shm_fallbacks": self.shm_fallbacks,
            "shm_acquire_wait_s": round(self.shm_acquire_wait_s, 4),
        }

    def update_wire(self, wire_stats):
        """Fold the pool's shm gauges (``Reader.wire_stats()`` dict) in."""
        if not wire_stats:
            return
        self.shm_slabs_in_flight = wire_stats.get("shm_slabs_in_flight", 0)
        self.shm_bytes = wire_stats.get("shm_bytes", 0)
        self.shm_fallbacks = wire_stats.get("shm_fallbacks", 0)
        self.shm_acquire_wait_s = wire_stats.get("shm_acquire_wait_s", 0.0)


#: per-span stage keys for the loader's latency histograms (the trace span
#: names map 1:1: reader.next -> read, batch.form -> batch, ...)
_OBS_STAGES = ("read", "batch", "host_queue_put", "host_queue_wait", "decode",
               "device_inflate", "h2d", "device_queue_wait")


class _LoaderObs:
    """Pre-resolved metric handles for one loader's hot path (ISSUE 3).

    Built only when ``DataLoader(metrics=...)`` was requested, so the disabled
    path stays one ``is None`` check per site (the ``trace.py`` contract). Holds
    one log-bucketed latency histogram per pipeline stage
    (``ptpu_pipeline_stage_seconds{stage=...}``) and registers two pull
    collectors: the ``PipelineStats`` totals + live queue depths as
    ``ptpu_pipeline_*``, and ``Reader.wire_stats()`` (slab-ring gauges) as
    ``ptpu_wire_*`` — the migration of the pre-existing ad-hoc gauges onto
    named metric families, with their hot paths unchanged.

    One metrics-enabled loader per registry at a time: the family names carry
    no per-loader label, so two live pipelines on ONE registry would merge
    their stage histograms and clobber each other's collector keys. Give each
    concurrent loader its own ``MetricsRegistry`` (an exporter can serve
    several registries to distinct files).

    The loader is held through a WEAK reference: collectors survive in the
    registry when a caller skips the context-manager teardown, but a
    garbage-collected pipeline stops exporting (and is not kept alive by the
    registry) instead of freezing its last gauges into every future snapshot.
    """

    def __init__(self, registry, loader):
        import weakref

        self.registry = registry
        self._hists = {
            stage: registry.histogram(
                "ptpu_pipeline_stage_seconds",
                help="per-occurrence pipeline stage latency (seconds)",
                stage=stage)
            for stage in _OBS_STAGES
        }
        self._handles = [registry.register_collector(
            "pipeline", self._collect_pipeline)]
        health = getattr(loader, "_health", None)
        if health is not None:
            # health layer (ISSUE 5): heartbeat ages + stalled flags + stall
            # total export as ptpu_health_* (the monitor's lifetime is tied to
            # the loader's, and close() unregisters the collector with the rest)
            self._handles.append(registry.register_collector(
                "health", health.collect))
        self._loader_ref = weakref.ref(loader)
        wire_stats_fn = getattr(loader.reader, "wire_stats", None)
        if wire_stats_fn is not None:
            # weak like the loader: the registry must not pin a dead reader
            wire_ref = weakref.WeakMethod(wire_stats_fn)
            self._handles.append(registry.register_collector(
                "wire", lambda: (wire_ref() or dict)()))
        io_stats_fn = getattr(loader.reader, "io_stats", None)
        if io_stats_fn is not None:
            # async read path (ISSUE 4): readahead hit/miss/pending/bytes,
            # memcache, dispatch steals — live gauges as ptpu_io_* families
            io_ref = weakref.WeakMethod(io_stats_fn)
            self._handles.append(registry.register_collector(
                "io", lambda: (io_ref() or dict)()))
        prov = getattr(loader, "_prov_rec", None)
        if prov is not None:
            # provenance plane (ISSUE 10): item/batch counts + per-site
            # critical-path self seconds as ptpu_prov_* (rendered by the
            # petastorm-tpu-stats attribution panel)
            prov_ref = weakref.ref(prov)
            self._handles.append(registry.register_collector(
                "prov", lambda: (lambda r: r.summary() if r is not None
                                 else {})(prov_ref())))

    def add_collector(self, prefix, fn):
        """Register one more pull collector whose lifetime follows this
        loader's (unregistered with the rest at ``close()``)."""
        self._handles.append(self.registry.register_collector(prefix, fn))

    def observe(self, stage, dur):
        self._hists[stage].observe(dur)

    def stage_histograms(self):
        return dict(self._hists)

    def reset_stage_histograms(self):
        """Re-anchor the stage percentiles to a fresh window (benchmarks call
        this beside ``PipelineStats.reset()`` so the bottleneck report's p50/
        p90/p99 cover the measured window, not warmup/compile)."""
        for hist in self._hists.values():
            hist.reset()

    def _collect_pipeline(self):
        loader = self._loader_ref()
        if loader is None:
            return {}
        out = dict(loader.stats.snapshot())
        q = loader._queue
        dq = loader._dev_queue
        out["host_queue_depth"] = q.qsize() if q is not None else 0
        out["device_queue_depth"] = dq.qsize() if dq is not None else 0
        return out

    def close(self):
        """Unregister the pull collectors (loader ``__exit__``): a torn-down
        pipeline must stop contributing stale families to exports."""
        handles, self._handles = self._handles, []
        for handle in handles:
            self.registry.unregister_collector(handle)


def _is_device_dtype(arr):
    """Only numeric/bool fixed-shape columns can live on device; strings/objects stay host."""
    return isinstance(arr, np.ndarray) and arr.dtype.kind in "biufc" and arr.dtype.hasobject is False


def _validate_decode_resize(resize, device_fields):
    """Normalize/validate ``device_decode_resize`` at construction: a misspelled dict
    key or a malformed target must fail HERE, not silently no-op and resurface later
    as a mixed-size error telling the user to pass the option they already passed."""
    if resize is None:
        return None
    if not device_fields:
        raise ValueError(
            "device_decode_resize was given but the reader has no device-decoded "
            "fields — open it with decode_on_device=True (and an image-codec "
            "column) for the on-device resize to apply")

    def check_target(t, label):
        try:
            h, w = int(t[0]), int(t[1])
        except (TypeError, ValueError, IndexError):
            raise ValueError(
                "device_decode_resize%s must be an (h, w) pair, got %r" % (label, t))
        if h <= 0 or w <= 0 or len(tuple(t)) != 2:
            raise ValueError(
                "device_decode_resize%s must be two positive ints, got %r" % (label, t))
        return (h, w)

    if isinstance(resize, dict):
        known = set(device_fields or ())
        unknown = set(resize) - known
        if unknown:
            raise ValueError(
                "device_decode_resize names %s, but the reader's device-decoded "
                "fields are %s (is decode_on_device=True set, and are the names "
                "spelled right?)" % (sorted(unknown), sorted(known)))
        return {k: check_target(v, "[%r]" % k) for k, v in resize.items()}
    return check_target(resize, "")


class _HostBatcher:
    """Accumulates columnar chunks and cuts exact fixed-size batches (static shapes)."""

    def __init__(self, batch_size, shuffling_queue_capacity=0, seed=None):
        self.batch_size = batch_size
        if shuffling_queue_capacity and shuffling_queue_capacity > 0:
            self._buffer = BatchedRandomShufflingBuffer(
                shuffling_queue_capacity,
                min_after_retrieve=min(shuffling_queue_capacity // 2, shuffling_queue_capacity - 1),
                batch_size=batch_size,
                seed=seed,
            )
            self._shuffling = True
        else:
            self._buffer = None
            self._shuffling = False
            self._pending = {}  # {name: deque of (array, offset)} — remainder stays put
            self._pending_rows = 0

    # -- non-shuffling path: chunk deque, O(batch) per cut ------------------------------
    #
    # Batches are assembled from whole/partial chunk VIEWS; the remainder is tracked as
    # an offset into the head chunk instead of re-sliced into a fresh array every cut
    # (the previous whole[batch_size:] copy was O(rowgroup^2/batch) bytes per row group).
    #
    # Lease retention (ISSUE 6): a chunk delivered with a lease (zero-copy slab views
    # from a view-mode wire) records that lease on every per-column entry; each batch
    # cut from leased chunks RETAINS the contributing leases (a LeasedBatch rides
    # them downstream), and the batcher's own hold drops as chunks drain — this is
    # what replaced the per-delivery _detach_slab_views copy-out.

    def _plain_add(self, columns, lease=None):
        n = None
        for name, arr in columns.items():
            entry = [arr, 0, lease]
            if lease is not None:
                lease.retain()  # one hold per column entry
            self._pending.setdefault(name, []).append(entry)
            n = len(arr)
        if lease is not None:
            lease.release()  # the ownership ref handed in: now held per entry
        if n is not None:
            self._pending_rows += n

    def _cut_one(self, take):
        merged = {}
        batch_leases = {}
        drained = []
        for name, chunks in self._pending.items():
            parts = []
            need = take
            while need > 0:
                entry = chunks[0]
                arr, off, lease = entry
                if lease is not None:
                    batch_leases[id(lease)] = lease
                avail = len(arr) - off
                if avail > need:
                    parts.append(arr[off:off + need])
                    entry[1] = off + need
                    need = 0
                else:
                    parts.append(arr[off:] if off else arr)
                    chunks.pop(0)
                    if lease is not None:
                        drained.append(lease)
                    need -= avail
            merged[name] = parts[0] if len(parts) == 1 else _concat(parts)
        self._pending_rows -= take
        if batch_leases:
            # retain for the batch BEFORE dropping the drained entries' holds:
            # a drained entry may hold the last reference, and releasing it
            # first would return the slab under the batch's feet
            merged = attach_leases(
                merged, [lease.retain() for lease in batch_leases.values()])
        for lease in drained:
            lease.release()
        return merged

    def _plain_cut(self, final=False):
        out = []
        while self._pending_rows >= self.batch_size:
            out.append(self._cut_one(self.batch_size))
        if final and self._pending_rows > 0:
            out.append(self._cut_one(self._pending_rows))
        return out

    # -- public -----------------------------------------------------------------------

    def add(self, columns, lease=None):
        """Feed one columnar chunk; returns list of ready full-size batches.
        ``lease`` (ownership transferred in) marks the chunk's arrays as views
        into lease-backed buffers — only supported on the non-shuffling path
        (the shuffling buffer holds rows indefinitely, so its feed is detached
        by the producer instead)."""
        if not self._shuffling:
            self._plain_add(columns, lease)
            return self._plain_cut()
        if lease is not None:  # defensive: the producer never does this
            lease.release()
        ready = []
        self._buffer.add_many(columns)
        while self._buffer.can_retrieve:
            ready.append(self._buffer.retrieve())
        return ready

    def finish(self):
        """Flush remaining rows as (possibly short) final batches."""
        if not self._shuffling:
            return self._plain_cut(final=True)
        self._buffer.finish()
        ready = []
        while self._buffer.can_retrieve:
            ready.append(self._buffer.retrieve())
        return ready

    def close(self):
        """Drop the batcher's holds on any still-pending leased chunks (producer
        teardown mid-epoch: rows that never formed a batch)."""
        if self._shuffling:
            return
        for chunks in self._pending.values():
            for _arr, _off, lease in chunks:
                if lease is not None:
                    lease.release()
        self._pending.clear()
        self._pending_rows = 0


def _batch_row_count(batch):
    """Rows in a yielded batch (leading dim of the first column; 0 when empty)."""
    if not batch:
        return 0
    return int(len(next(iter(batch.values()))))


def _detach_slab_views(columns):
    """Copy every zero-copy slab view out of a view-mode reader delivery before it
    enters a buffering stage: top-level read-only ndarrays, read-only ELEMENTS of
    object (ragged) columns, and staged payload objects exposing ``detach()`` —
    all go stale when the Reader releases the batch's lease at its next fetch.

    Since ISSUE 6 this is the FALLBACK path (shuffling buffers and per-row
    readers, whose buffering the lease cannot ride); the plain batched path
    retains the delivery's lease instead of copying. Bytes copied here are
    charged to the ``loader_detach`` census site."""
    out = {}
    copied = 0
    for name, v in columns.items():
        if isinstance(v, np.ndarray):
            if v.dtype.hasobject:
                fresh = np.empty(v.shape, dtype=object)
                for idx, e in np.ndenumerate(v):
                    if isinstance(e, np.ndarray) and not e.flags.writeable:
                        copied += e.nbytes
                        e = e.copy()
                    elif hasattr(e, "detach"):
                        e = e.detach()
                    fresh[idx] = e
                v = fresh
            elif not v.flags.writeable:
                copied += v.nbytes
                v = v.copy()
        out[name] = v
    count_copy("loader_detach", copied)
    return out


def _materialize_passthrough(batch, cause=None):
    """Inflate any compressed-page pass-through columns IN PLACE via the host
    reference decode (ISSUE 14). In-place keeps a ``LeasedBatch``'s identity
    and leases intact (pass-through buffers are owned bytes, never slab
    views). ``cause`` names the degradation to count when this seam is a
    FALLBACK (shuffling buffers, pad tails) rather than the designed host
    path (host-only delivery, loader-less readers pass ``None``)."""
    names = [name for name, v in batch.items()
             if getattr(v, "is_passthrough", False)]
    if not names:
        return batch
    if cause is not None:
        from petastorm_tpu.obs.log import degradation

        degradation(cause, "pass-through column(s) %s inflated on host at a "
                    "buffering seam; the device inflate stage was bypassed",
                    names)
    for name in names:
        batch[name] = batch[name].materialize()
    return batch


def _batch_valid_rows(batch):
    """Rows the READER actually delivered in this batch: under ``last_batch='pad'``
    the tail batch repeats its final row up to ``batch_size`` with a ``__valid__``
    mask, and counting the padding would advance the consumer checkpoint watermark
    past the producer's delivered-row log (ADVICE r5 loader.py:846 — harmless at
    the tail today, wrong the moment padding ever happens mid-stream)."""
    if not batch:
        return 0
    valid = batch.get("__valid__")
    if isinstance(valid, np.ndarray) and valid.dtype == np.bool_:
        return int(valid.sum())
    return _batch_row_count(batch)


def _concat(chunks):
    chunks = [c for c in chunks if len(c)]
    if not chunks:
        return np.empty((0,))
    if len(chunks) == 1:
        return chunks[0]
    if any(getattr(c, "is_passthrough", False) for c in chunks):
        from petastorm_tpu.io.pagedec import PassthroughColumn

        if all(getattr(c, "is_passthrough", False) for c in chunks):
            # window chaining, not a copy: the batch keeps riding raw pages
            return PassthroughColumn.concat(chunks)
        # mixed chunk types for one column (a per-chunk fallback mid-epoch):
        # the decoded form is the common denominator
        chunks = [c.materialize() if getattr(c, "is_passthrough", False)
                  else c for c in chunks]
    if any(c.dtype == object for c in chunks):
        out = np.empty(sum(len(c) for c in chunks), dtype=object)
        pos = 0
        for c in chunks:
            out[pos:pos + len(c)] = c
            pos += len(c)
    else:
        out = np.concatenate(chunks, axis=0)
    count_copy("loader_concat", out.nbytes)
    return out


def _release_leases(batch):
    """Release every lease a batch carries (no-op for plain dicts): the tidy
    path for batches that die inside the pipeline — dropped tails, stopped
    deliveries, queue drains — so teardown never strands a slab hold until GC
    (which would count as ``ptpu_lease_leaked_total``)."""
    for lease in take_leases(batch):
        lease.release()


def _flatten_ngram_window(window):
    """{offset: row} NGram window → one flat {'offset/field': value} row.

    The reference's torch collate nests tensors per offset; for the device path a
    FLAT naming keeps every loader feature working unchanged — per-field shardings,
    ``pad_shapes``, masks, and device transforms all key by ``'0/image'``-style
    names. Consumers regroup with one dict comprehension."""
    flat = {}
    for off, row in window.items():
        row = row._asdict() if hasattr(row, "_asdict") else row
        for name, value in row.items():
            flat["%s/%s" % (off, name)] = value
    return flat


def _rows_to_columns(rows, object_fields=()):
    """Row dicts/namedtuples → columnar numpy dict (per-row ``make_reader`` path).

    ``object_fields`` are forced to object dtype: device-decode staging columns may mix
    JpegPlanes payloads with host-fallback ndarrays across rows, and letting np.asarray
    pick a per-batch layout would break downstream concatenation."""
    if not rows:
        return {}
    first = rows[0]
    if hasattr(first, "_asdict"):
        rows = [r._asdict() for r in rows]
    names = rows[0].keys()
    return {
        name: stack_as_column([r[name] for r in rows], force_object=name in object_fields)
        for name in names
    }


class DataLoader:
    """Iterable of batches: ``{field: jax.Array}`` (device fields) laid out per ``sharding``.

    Parameters
    ----------
    reader : petastorm_tpu.reader.Reader
        Batch reader (columnar) or per-row reader (rows are stacked host-side).
        A :class:`petastorm_tpu.service.client.ServiceReader` (ISSUE 19 —
        :func:`petastorm_tpu.reader.make_service_reader`) plugs in here
        unchanged: batches then come from a shared decode fleet instead of a
        local pool, with the same batch/checkpoint semantics.
    batch_size : int
        GLOBAL batch size: rows per yielded batch across all processes. Under
        multi-process JAX with a ``NamedSharding`` whose batch axis spans processes,
        each process cuts only its local share (``batch_size / batch-shards ×
        locally-owned shard positions``) and the global array is assembled from the
        process-local parts; with one process (or a replicated batch axis) local ==
        global.
    sharding : jax.sharding.Sharding, optional
        Layout for yielded arrays. Default: single-device placement on the default device.
    shuffling_queue_capacity : int
        >0 enables a host-side row shuffling buffer (reference ``shuffling_queue_capacity``).
    last_batch : {"drop", "pad", "partial"}
        Remainder policy. ``drop`` (default) keeps shapes static; ``pad`` repeats final rows
        up to ``batch_size`` and adds a boolean ``__valid__`` mask column; ``partial`` yields
        the short batch (host numpy only fields keep working; device arrays get a new shape —
        triggers one extra XLA compile).
    device_transform : callable, optional
        Jittable ``fn(batch) -> batch`` applied on device after transfer (augment/normalize —
        XLA fuses it into the step). Defaults to ``reader.transform_spec`` when that was
        declared ``device=True``. A two-argument ``fn(batch, key) -> batch`` receives a
        fresh ``jax.random`` key per batch (folded from ``seed`` and a batch counter) —
        the hook for random augmentation (crop/flip) on device.
    prefetch : int
        Device batches kept in flight (double/triple buffering). 0 disables (debug).
    to_device : bool
        False yields host numpy dicts (CPU-only consumers, tests, torch adapter).
    device_shuffle_capacity : int
        >0 enables the HBM-resident exchange shuffle
        (:class:`petastorm_tpu.ops.device_shuffle.DeviceShuffleBuffer`): after
        transfer, each batch swaps into a device ring of ~this many rows and the
        displaced rows are delivered (exactly-once, ~capacity decorrelation window,
        one fused gather+scatter per batch — zero host work). Requires every
        delivered column to be device-resident (no strings); composes with
        ``shuffling_queue_capacity`` (host pre-shuffle) and ``device_transform``
        (applied to the shuffled output). Capacity is rounded up to a batch multiple.
    pad_shapes : dict, optional
        Ragged-field policy (SURVEY.md §8 hard part #2): ``{field: max_shape}`` pads
        every row of a ragged tensor field up to ``max_shape`` (zeros) and adds a
        boolean ``<field>__mask`` column marking the valid region, so the column
        reaches the device with a static shape. Rows exceeding the declared max raise.
        Ragged tensor fields WITHOUT a declared max raise at transfer time.
    trace : petastorm_tpu.trace.TraceRecorder, optional
        Records every pipeline stage (reader fetch, batch formation, decode
        dispatch, H2D, queue waits) as chrome-trace spans — the per-span view of
        the totals in ``stats``; ``tracer.dump(path)`` loads in ``chrome://tracing``
        / Perfetto. Default None = zero overhead.
    metrics : petastorm_tpu.obs.MetricsRegistry or True, optional
        Export the pipeline onto the metrics registry (``True`` = the
        process-wide default registry): per-stage latency histograms
        (``ptpu_pipeline_stage_seconds{stage=...}``, log-bucketed p50/p90/p99),
        the ``PipelineStats`` totals + live queue depths as ``ptpu_pipeline_*``,
        and the pool wire gauges as ``ptpu_wire_*`` — what
        ``petastorm_tpu.obs.export`` exporters and ``petastorm-tpu-stats``
        read. The families carry no per-loader label, so run at most ONE
        metrics-enabled loader per registry at a time (concurrent train + eval
        loaders: one private ``MetricsRegistry`` each). Default None =
        disabled, one ``is None`` check per stage site.
    health : True, petastorm_tpu.obs.health.HealthOptions or HealthMonitor, optional
        Active stall monitoring (ISSUE 5): every pipeline actor (this loader's
        producer and transfer thread, the reader's executor workers and
        readahead IO threads, process-pool children) stamps a heartbeat, and a
        watchdog daemon writes a structured **flight record** (driver + child
        stacks, queue depths, recent events) when a busy actor misses its
        threshold — backpressure waits never count as stalls. ``True`` =
        defaults; a :class:`~petastorm_tpu.obs.health.HealthOptions` tunes
        thresholds/escalation (escalation ``"raise"`` delivers
        :class:`petastorm_tpu.errors.StallError` to the consumer so training
        fails fast instead of hanging a TPU slice); an existing
        :class:`~petastorm_tpu.obs.health.HealthMonitor` is shared (the caller
        owns its lifecycle). ``PTPU_HEALTH=1`` enables the defaults without
        code changes. Default None = disabled, one ``is None`` check per
        site. ``DataLoader.health_report()`` works whenever it is on; with
        ``metrics=`` heartbeat ages also export as ``ptpu_health_*`` families.
    staging : None, bool or int, optional
        Pinned-host H2D staging (ISSUE 6): the transfer thread copies each
        batch's device-bound columns into a page-locked slab ring
        (:class:`petastorm_tpu.io.staging.PinnedStagingPool`) and launches
        ``device_put`` from there, so the DMA engine reads page-locked memory
        instead of pageable numpy (no runtime-side pinning/bounce per batch).
        Default ``None`` = auto: enabled on accelerator backends (TPU/GPU),
        off on the CPU backend where ``device_put`` may alias host memory and
        the extra staging copy buys nothing. ``True`` forces it on (still
        refused, with a ``staging_aliasing`` degradation, on a backend whose
        ``device_put`` aliases — recycled slabs would corrupt delivered
        arrays); ``False`` disables; an ``int`` forces it on with that slab
        size in bytes (otherwise sized from the first staged batch).
    provenance : True or petastorm_tpu.obs.provenance.ProvenanceRecorder, optional
        Causal per-item provenance (ISSUE 10): every dispatched row group
        accumulates ``(site, t_start, t_end, pid)`` spans and annotations
        (cache tier served from, hedges fired/won, retries, quarantine)
        through the whole pipeline — pool children included, via the
        result-header piggyback — and each delivered batch knows its
        contributing items. ``DataLoader.batch_provenance()`` returns the
        latest batch's record; ``DataLoader.attribution_report()`` folds the
        window into a critical-path step-time attribution (which SITE owns
        the p99 batch). ``True`` builds a recorder; pass an existing
        :class:`~petastorm_tpu.obs.provenance.ProvenanceRecorder` to share
        one. One provenance-enabled loader per process at a time (the item
        hooks are a process-global plane, like the chaos plan).
        ``PTPU_PROVENANCE=1`` enables it without code changes. Default None =
        disabled, one module-global ``is None`` check per site. Batch↔item
        attribution is unavailable under shuffling (rows decorrelate from row
        groups); per-item records still collect.
    slos : sequence of petastorm_tpu.obs.slo.SloSpec, or an SloEngine, optional
        Temporal SLO watching (ISSUE 12; requires ``metrics=``): the specs
        are evaluated against the registry's windowed time-series on the
        sampling cadence (a :class:`petastorm_tpu.obs.export.Reporter`
        flushing this registry, or explicit ``registry.sample_timelines()``
        calls). Debounced breaches fire ``cause=slo_breach`` degradation
        events mirrored into live flight recorders, and — when the loader
        also has ``provenance=`` — each alert carries an
        ``attribution_report()`` snapshot naming the culprit site. Read
        alerts from ``loader.slo_alerts()`` / ``loader.slo_engine``; pass a
        pre-built :class:`petastorm_tpu.obs.slo.SloEngine` to add anomaly
        watches or share an engine. Zero hot-path cost — evaluation happens
        on the sampler thread only.
    controller : True, petastorm_tpu.control.ControlOptions or Controller, optional
        Closed-loop self-tuning (ISSUE 13; requires ``metrics=``): a
        :class:`~petastorm_tpu.control.Controller` rides the same window
        cadence as the SLO engine and retunes the reader's LIVE knobs
        through the sanctioned :class:`~petastorm_tpu.control.KnobSet`
        seam — readahead depth/bytes, ranged-GET pool width, hedge
        quantile, mem-tier budget, disk admission, worker-fleet size
        (shrink drains, never kills mid-item). Declarative rules with
        hysteresis, debounce, per-knob cooldowns, step limits and a global
        revert-and-freeze no-gain guard (the anti-oscillation contract —
        docs/performance.md). With ``provenance=`` the rules read the
        attribution snapshot, so actuations are triggered by (and logged
        with) the culprit SITE. Decisions are ``cause=ctl_actuate``/
        ``ctl_revert``/``ctl_freeze`` degradation events plus
        ``ptpu_ctl_*`` families; read them from ``loader.ctl_decisions()``
        / ``loader.controller``. Zero hot-path cost.
    """

    def __init__(self, reader, batch_size, sharding=None, shuffling_queue_capacity=0,
                 seed=None, last_batch="drop", device_transform=None, prefetch=2,
                 to_device=True, host_queue_size=8, pad_shapes=None,
                 device_shuffle_capacity=0, device_decode_resize=None, trace=None,
                 metrics=None, health=None, staging=None, provenance=None,
                 slos=None, controller=None, tenant=None):
        if batch_size <= 0:
            raise ValueError("batch_size must be positive")
        #: per-tenant accounting (ISSUE 18): explicit tenant= wins, else the
        #: reader's resolved context, else ambient/PTPU_TENANT; None ⇒ untagged
        from petastorm_tpu.obs import tenant as _tenant_mod

        self.tenant_context = _tenant_mod.resolve(tenant, env_default=False) \
            if tenant is not None else None
        if self.tenant_context is None:
            self.tenant_context = getattr(reader, "tenant_context", None)
        if self.tenant_context is None:
            self.tenant_context = _tenant_mod.current()
        if last_batch not in ("drop", "pad", "partial"):
            raise ValueError("last_batch must be drop|pad|partial, got %r" % last_batch)
        if device_shuffle_capacity and not to_device:
            raise ValueError("device_shuffle_capacity requires to_device=True "
                             "(the ring lives in device memory)")
        self.reader = reader
        self.batch_size = int(batch_size)
        #: rows THIS process cuts per batch (== batch_size unless the sharding's batch
        #: axis spans multiple processes — ADVICE r1: batch_size is documented global)
        self.local_batch_size = _resolve_local_batch(self.batch_size, sharding)
        self.sharding = sharding
        self.last_batch = last_batch
        self.prefetch = int(prefetch)
        self.to_device = to_device
        self._seed = seed
        self._shuffling_queue_capacity = shuffling_queue_capacity
        self._host_queue_size = host_queue_size
        self._pad_shapes = dict(pad_shapes) if pad_shapes else {}
        #: (h, w) — or {field: (h, w)} — on-device resize target for device-decoded
        #: image columns; lets mixed-size stores (raw ImageNet-style) batch with one
        #: static shape (petastorm_tpu.ops.jpeg.resize_image_batch)
        self._device_decode_resize = _validate_decode_resize(
            device_decode_resize, getattr(reader, "device_decode_fields", None))
        self._device_shuffle_capacity = int(device_shuffle_capacity or 0)
        #: compressed-page pass-through adoption (ISSUE 14): this loader
        #: finishes the inflate itself (device kernels when a non-CPU backend
        #: is live, the numpy reference otherwise), so the reader must stop
        #: materializing PassthroughColumn values at delivery. Un-adopted at
        #: __exit__ — a reader outliving its loader serves decoded batches
        #: again.
        self._adopted_passthrough = False
        if getattr(reader, "is_batched_reader", False) \
                and hasattr(reader, "keep_passthrough"):
            reader.keep_passthrough = True
            self._adopted_passthrough = True
        #: optional petastorm_tpu.trace.TraceRecorder — per-span chrome-trace view of
        #: the same stages PipelineStats totals (None = zero overhead). The pool
        #: wire joins in: an shm-wire reader records shm.acquire_wait spans too.
        self._trace = trace
        if trace is not None and hasattr(reader, "set_trace"):
            reader.set_trace(trace)
        if getattr(device_transform, "declarative", False):
            if getattr(reader, "ngram", None) is not None:
                # same mismatch the auto-wiring branch below guards: the
                # pipeline's ops name schema fields, but NGram batches are
                # keyed 'offset/field' — it would KeyError inside the jit
                raise ValueError(
                    "a declarative FeaturePipeline cannot be the "
                    "device_transform of an NGram reader: batches are keyed "
                    "'offset/field', not by schema field names. Pass a "
                    "function written against the flat columns instead.")
            # a FeaturePipeline passed directly: compile it against the
            # reader's delivered schema and ride its jittable device function
            # (statistics-dependent ops must have been resolved — device_fn
            # raises with the fix otherwise)
            device_transform = device_transform.device_fn(reader.schema)
        self._device_transform = device_transform
        if device_transform is None:
            spec = getattr(reader, "transform_spec", None)
            if spec is not None and getattr(spec, "device", False) and spec.func is not None:
                if getattr(reader, "ngram", None) is not None:
                    # the spec's func is written against schema field names, but
                    # NGram batches arrive flattened to 'offset/field' columns —
                    # auto-wiring it would KeyError (or silently touch the wrong
                    # columns) on the first batch
                    raise ValueError(
                        "a device TransformSpec cannot be auto-applied to an NGram "
                        "reader: batches are keyed 'offset/field', not by schema "
                        "field names. Pass DataLoader(device_transform=...) written "
                        "against the flat 'offset/field' columns instead.")
                self._device_transform = spec.func
        self._jitted_transform = None
        self._transform_takes_key = False
        self._transform_step = 0
        #: (n) -> frozen (gather index, validity mask) for last_batch='pad'
        self._pad_cache = {}
        #: pinned-host staging ring (io/staging.py), built lazily on the
        #: transfer thread from the first staged batch's size; None until then
        #: (and forever when disabled/refused — see the `staging` parameter)
        self._staging_arg = staging
        self._staging = None
        self._staging_decided = False
        self._producer = None
        self._queue = None
        self._dev_queue = None
        self._transfer_thread = None
        self._stop = threading.Event()
        self._producer_error = None
        #: False while a watchdog fail-fast StallError is pending but has not
        #: reached any consumer yet — _start_producer must surface it, not
        #: silently clear it into an empty epoch
        self._producer_error_delivered = True
        #: bumped by every _start_producer(); a superseded iterator's finalizer
        #: compares its captured generation before calling stop() so closing/GC-ing
        #: an old iterator cannot kill the pipeline a newer __iter__ armed
        self._generation = 0
        self.stats = PipelineStats()
        self._warned_unsharded_decode = False
        # consumer-watermark checkpointing (see state_dict): producer logs
        # (cumulative-delivered-rows, reader state) per delivery; the consumer
        # counts rows actually yielded; state_dict() returns the newest logged
        # state the consumer has fully caught up to. Disabled under shuffling —
        # state_dict() refuses there anyway, and with the device shuffle the
        # consumer count never advances, so the log would grow unpruned forever.
        # Not armed lazily: a state at a past delivery point cannot be
        # reconstructed retroactively, and the throttled snapshots are µs-scale
        # next to each delivery's parquet IO.
        self._ckpt_enabled = (hasattr(reader, "state_dict")
                              and not shuffling_queue_capacity
                              and not self._device_shuffle_capacity)
        self._ckpt_lock = threading.Lock()
        self._ckpt_log = collections.deque()
        self._ckpt_base = None
        self._rows_consumed = 0
        #: optional petastorm_tpu.obs.health wiring (None = disabled, the
        #: default): heartbeats on every pipeline actor + the stall watchdog +
        #: the flight recorder. Built BEFORE _obs so the metrics wiring can
        #: export the monitor's collector alongside the stage histograms.
        self._health = None
        self._health_owned = False
        self._health_handles = ()
        self._hb_producer = None   # set by the producer thread while it lives
        self._hb_transfer = None   # set by the transfer thread while it lives
        # normalized unconditionally: PTPU_HEALTH=1 must enable monitoring
        # even when health= was not passed (normalize_health handles every
        # shape — None + env, True, HealthOptions, a shared HealthMonitor)
        from petastorm_tpu.obs.health import normalize_health

        self._health, self._health_owned = normalize_health(health)
        self._health_scope = None
        if self._health is not None:
            import weakref

            monitor = self._health
            if self._health_owned:
                # exclusive monitor: bare actor names (loader.producer, ...)
                self._health_scope = monitor
                scope_prefix = None
            else:
                # SHARED monitor: namespace this pipeline's actors so another
                # loader's healthy stamps cannot mask this one's stall (and
                # per-worker latency keys stay per-executor)
                scope_prefix = "pipe%d" % next(_pipeline_seq)
                self._health_scope = monitor.scoped(scope_prefix)
            ref = weakref.ref(self)
            # weak like _LoaderObs: a shared monitor must not pin a dead loader
            self._health_handles = (
                monitor.add_context(
                    "pipeline" if scope_prefix is None
                    else "pipeline/%s" % scope_prefix,
                    lambda: (lambda l: l._health_context() if l is not None
                             else {})(ref())),
                monitor.add_stall_callback(
                    lambda err: (lambda l: l._fail_fast(err) if l is not None
                                 else None)(ref()),
                    prefix=scope_prefix),
            )
            if self._health_owned and metrics:
                # route per-worker latency histograms onto the metrics=
                # registry BEFORE the live executor is rewired below: workers
                # observe latencies the moment set_health lands, and
                # set_registry no-ops once observations exist (re-homing a
                # live family would split it) — wiring it at the _obs block
                # further down raced those first observations onto the
                # default registry
                from petastorm_tpu.obs.metrics import MetricsRegistry, \
                    default_registry

                monitor.set_registry(
                    metrics if isinstance(metrics, MetricsRegistry)
                    else default_registry())
            if hasattr(reader, "set_health"):
                reader.set_health(self._health_scope)
            monitor.start()
        #: optional causal provenance plane (ISSUE 10; None = disabled): one
        #: ProvenanceRecorder collecting per-item spans across every seam —
        #: armed process-globally (worker threads + IO hooks), attached to the
        #: reader (delivery/quarantine notes, pool-child span merge), and fed
        #: batch-plane spans by the producer/transfer/consumer hooks below.
        self._prov_rec = None
        self._prov_owned = False
        #: a recorder the READER factory already attached (provenance= on
        #: make_reader/make_batch_reader, or PTPU_PROVENANCE) is adopted: it
        #: was armed BEFORE the executor started, so it saw every item — a
        #: loader-built recorder attached now can miss items a small plan
        #: already drained through the pool (still fine for long streams)
        from petastorm_tpu.obs import provenance as _prov_mod

        existing = getattr(reader, "_prov", None)
        if isinstance(provenance, _prov_mod.ProvenanceRecorder):
            rec = provenance.arm()  # caller-owned: stays armed past __exit__
        elif existing is not None:
            rec = existing.arm()  # reader-owned: reader.join() disarms
        else:
            # None/True + the PTPU_PROVENANCE env switch, one copy of the
            # policy; a recorder built HERE is this loader's to disarm
            rec = _prov_mod.resolve(provenance)
            self._prov_owned = rec is not None
        if rec is not None:
            if trace is not None:
                rec.set_trace(trace)  # Perfetto flow events into the dump
            rec.set_batch_tracking(not shuffling_queue_capacity
                                   and not self._device_shuffle_capacity)
            if hasattr(reader, "set_provenance") and existing is not rec:
                reader.set_provenance(rec)
            self._prov_rec = rec
        #: optional petastorm_tpu.obs wiring (None = disabled, the default):
        #: stage latency histograms + pull collectors for the stats/wire gauges
        self._obs = None
        if metrics:
            from petastorm_tpu.obs.metrics import MetricsRegistry, default_registry

            registry = metrics if isinstance(metrics, MetricsRegistry) \
                else default_registry()
            if self._health is not None and self._health_owned:
                # a loader-owned monitor exports its per-worker latency
                # histograms beside the stage histograms (a SHARED monitor
                # keeps whatever registry its owner configured)
                self._health.set_registry(registry)
            self._obs = _LoaderObs(registry, self)
        #: optional SLO/anomaly engine (ISSUE 12) over the registry's windowed
        #: time-series: attached to the timeline store's sampling cadence, so
        #: the loader hot paths never see it. Breach alerts carry an
        #: attribution snapshot when provenance is on.
        self._slo_engine = None
        self._slo_owned = False
        if slos:
            if self._obs is None:
                raise ValueError(
                    "DataLoader(slos=...) requires metrics= — the SLO engine "
                    "evaluates the metrics registry's windowed time-series")
            from petastorm_tpu.obs.slo import SloEngine

            registry = self._obs.registry
            if isinstance(slos, SloEngine):
                # caller-supplied (shared) engine: like a shared
                # HealthMonitor/ProvenanceRecorder, its lifecycle stays the
                # caller's — never detached at __exit__, and never re-homed
                # off a store the caller already attached it to
                engine = slos
                if engine._registry is None:
                    engine._registry = registry
                if engine._store is None:
                    engine.attach(registry.timeline_store())
            else:
                engine = SloEngine(specs=list(slos), registry=registry)
                engine.attach(registry.timeline_store())
                self._slo_owned = True
            if engine._attribution is None and self._prov_rec is not None:
                engine.set_attribution(self.attribution_report)
            self._slo_engine = engine
        #: optional closed-loop controller (ISSUE 13; requires ``metrics=``):
        #: rides the same window cadence as the SLO engine and actuates the
        #: reader's live knobs (readahead depth/bytes, GET pool width, hedge
        #: quantile, mem-tier budget, worker fleet) through the sanctioned
        #: KnobSet seam. ``True`` = default rules over the standard knobs; a
        #: ControlOptions tunes warmup/cooldown/no-gain policy; a pre-built
        #: Controller is shared (caller-owned lifecycle). With provenance on,
        #: rules read the attribution snapshot (culprit-site triggers).
        self._controller = None
        self._ctl_owned = False
        if controller:
            if self._obs is None:
                raise ValueError(
                    "DataLoader(controller=...) requires metrics= — the "
                    "controller reads the registry's windowed time-series")
            from petastorm_tpu.control import (ControlOptions, Controller,
                                               build_knobset)

            registry = self._obs.registry
            if isinstance(controller, Controller):
                # caller-supplied (shared): lifecycle stays the caller's —
                # never detached at __exit__ (same convention as slos=)
                ctl = controller
                if ctl._registry is None:
                    ctl._registry = registry
                if ctl._store is None:
                    ctl.attach(registry.timeline_store())
            else:
                ctl_opts = controller \
                    if isinstance(controller, ControlOptions) else None
                ctl = Controller(build_knobset(reader), registry=registry,
                                 options=ctl_opts)
                ctl.attach(registry.timeline_store())
                self._ctl_owned = True
            if ctl._attribution is None and self._prov_rec is not None:
                ctl.set_attribution(self.attribution_report)
            self._controller = ctl
            self._obs.add_collector("ctl", ctl.collect)

    # -- producer (background thread: reader → host batches) ---------------------------
    #
    # The host-batch queue is passed IN (not read off self) so a thread from a
    # superseded iteration that outlives join()'s timeout keeps draining/feeding its
    # OWN queue and can never steal batches from the queue a newer __iter__ installed.

    def _ckpt_record(self, cum_rows):
        """Producer side of consumer-watermark checkpointing: log the reader's state
        as of ``cum_rows`` delivered rows, pruning entries the consumer already
        passed (keeps the log ~in-flight-sized even over infinite epochs)."""
        state = self.reader.state_dict()
        with self._ckpt_lock:
            log = self._ckpt_log
            c = self._rows_consumed
            while len(log) >= 2 and log[1][0] <= c:
                log.popleft()
            if log and log[0][0] <= c:
                self._ckpt_base = log.popleft()[1]
            log.append((cum_rows, state))

    def _produce(self, q):
        batcher = _HostBatcher(self.local_batch_size, self._shuffling_queue_capacity,
                               self._seed)
        stats = self.stats
        # health wiring (ISSUE 5): one heartbeat for this producer thread,
        # stamped at the existing trace/obs sites (disabled = hb is None, one
        # check per site); the flight ring gets per-delivery span edges.
        # Registration goes through the SCOPE (namespaced on shared monitors).
        scope = self._health_scope
        hb = None
        flight = None
        if scope is not None:
            hb = scope.register("loader.producer", "producer")
            flight = scope.flight
        self._hb_producer = hb
        ckpt_cum = 0  # cumulative rows delivered by the reader this generation
        ckpt_deliveries = 0
        ckpt_next_snap = 1
        # shm wire integration: gauges refresh per delivery
        wire_stats_fn = getattr(self.reader, "wire_stats", None)
        if wire_stats_fn is not None and not wire_stats_fn():
            wire_stats_fn = None  # thread/dummy pool or socket wire: nothing to poll
        wire_views = bool(getattr(self.reader, "wire_views", False))
        # Lease retention (ISSUE 6): on the plain batched path the view-mode
        # delivery's lease is TAKEN from the reader and rides the batcher's
        # chunk deque and every batch cut from it — the old per-delivery
        # copy-out disappears. Shuffling buffers (rows linger indefinitely),
        # per-row readers (rows are restacked anyway), and staged device-decode
        # payloads (opaque objects the batcher cannot track) still detach,
        # charged to the ``loader_detach`` census site.
        take_lease_fn = getattr(self.reader, "take_lease", None)
        lease_mode = (wire_views and take_lease_fn is not None
                      and not self._shuffling_queue_capacity
                      and bool(getattr(self.reader, "is_batched_reader", False))
                      and not getattr(self.reader, "device_decode_fields", None))
        detach_views = wire_views and not lease_mode
        try:
            it = iter(self.reader)
            while True:
                if hb is not None:
                    hb.beat("read")
                t0 = time.perf_counter()
                item = next(it, _SENTINEL)
                dt = time.perf_counter() - t0
                stats.read_s += dt
                if self._trace is not None:
                    self._trace.add("reader.next", t0, dt)
                if self._obs is not None:
                    self._obs.observe("read", dt)
                if flight is not None:
                    flight.record("span", name="read", dur_s=round(dt, 6))
                if item is _SENTINEL:
                    # final snapshot: the all-delivered state must be reachable
                    # even when the throttle skipped the tail deliveries
                    if self._ckpt_enabled and ckpt_deliveries:
                        self._ckpt_record(ckpt_cum)
                    if wire_stats_fn is not None:
                        stats.update_wire(wire_stats_fn())
                    break
                if self._stop.is_set():
                    return
                # batched readers yield columnar dicts; per-row readers yield one row per
                # item (branching on the reader contract, not a shape heuristic — a row
                # whose fields are all equal-length ndarrays must NOT be read as a batch)
                if getattr(self.reader, "is_batched_reader", False):
                    columns = item._asdict() if hasattr(item, "_asdict") else item
                    if not isinstance(columns, dict):
                        raise TypeError("unexpected reader item %r" % type(item))
                    columns = {k: v for k, v in columns.items() if v is not None}
                else:
                    if getattr(self.reader, "ngram", None) is not None:
                        # NGram windows arrive as {offset: row}: flatten to
                        # 'offset/field' columns so every timestep's tensors reach
                        # the device as ordinary static-shape arrays (shardings,
                        # pad_shapes, and transforms key by the flat name)
                        item = _flatten_ngram_window(item)
                    columns = _rows_to_columns(
                        [item],
                        object_fields=getattr(self.reader, "device_decode_fields", ()),
                    )
                lease = take_lease_fn() if lease_mode else None
                if detach_views:
                    columns = _detach_slab_views(columns)
                if wire_stats_fn is not None:
                    stats.update_wire(wire_stats_fn())
                if hb is not None:
                    hb.beat("batch")
                t0 = time.perf_counter()
                if self._pad_shapes:
                    columns = _pad_ragged_columns(columns, self._pad_shapes)
                if self._shuffling_queue_capacity:
                    # the shuffling buffer permutes ROWS — compressed pages
                    # cannot be row-permuted without decoding, so this seam
                    # inflates on host (counted; pagedec=auto never pairs
                    # with a host shuffle on purpose — the HBM ring shuffle
                    # is the pass-through-compatible one)
                    columns = _materialize_passthrough(
                        columns, cause="pagedec_host_inflate")
                    # rows linger in the shuffling buffer across row groups: staged
                    # payloads that are views into a row group's stacked buffers must be
                    # detached or one straggler row pins its whole group's memory
                    for name in getattr(self.reader, "device_decode_fields", ()):
                        col = columns.get(name)
                        if col is not None and col.dtype == object:
                            for i, v in enumerate(col):
                                if hasattr(v, "detach"):
                                    col[i] = v.detach()
                ready = batcher.add(columns, lease)
                dt = time.perf_counter() - t0
                stats.batch_s += dt
                collate_span = (t0, dt)
                if self._trace is not None:
                    self._trace.add("batch.form", t0, dt)
                if self._obs is not None:
                    self._obs.observe("batch", dt)
                if flight is not None:
                    flight.record("span", name="batch", dur_s=round(dt, 6),
                                  ready=len(ready))
                if self._ckpt_enabled:
                    ckpt_cum += _batch_row_count(columns)
                    # Snapshot at delivery boundaries (batched reader items ≈ row
                    # groups; per-row readers at batch cuts), geometrically
                    # throttled: Reader.state_dict() rebuilds the consumed map
                    # (O(groups log groups)), so per-delivery snapshots would make
                    # the producer O(n²) over a long epoch (review r5). After 512
                    # unthrottled snapshots the stride grows with the delivery
                    # count — ~512 more per epoch, bounding restore replay to
                    # ~deliveries/512 extra row groups while keeping small
                    # datasets exact.
                    ckpt_deliveries += 1
                    if (ready or getattr(self.reader, "is_batched_reader", False)) \
                            and ckpt_deliveries >= ckpt_next_snap:
                        self._ckpt_record(ckpt_cum)
                        ckpt_next_snap = ckpt_deliveries \
                            + max(1, ckpt_deliveries // 512)
                if not self._deliver_batches(q, ready, hb,
                                             collate_span=collate_span):
                    return
            # tail flush: the same per-batch stop check as the main loop — a stop()
            # during the flush must not leave the producer blocked on an untimed put
            # after the consumer already exited on the re-injected sentinel. Under
            # last_batch='drop' the shuffling buffer can still hold whole batches at
            # reader exhaustion — only the short tail is dropped.
            if not self._deliver_batches(q, batcher.finish(), hb,
                                         drop_short=self.last_batch == "drop"):
                return
        except Exception as e:  # noqa: BLE001 — surfaced to consumer thread
            self._producer_error = e
            if flight is not None:
                flight.record("producer_error", error=repr(e))
        finally:
            # drop the batcher's holds on chunks that never formed a batch
            # (teardown mid-epoch): their slabs go back to the ring now
            batcher.close()
            if flight is not None:
                flight.record("queue", event="producer_end_of_stream")
            if hb is not None:
                hb.done()
            self._hb_producer = None
            _put_sentinel(q, self._stop)

    def _put_batch(self, q, batch, hb=None, bp=None):
        """Producer put into the host queue, timed: blocking here is DOWNSTREAM
        backpressure (decode/transfer/step slower than the producer) — the
        bottleneck analyzer's consumer-bound signal (``put_wait_s``) and, for
        the stall watchdog, a ``wait:`` state that must NEVER read as a stall
        (a full queue means the consumer is the slow one, not this thread)."""
        if hb is not None:
            hb.wait("host_queue_put")
        t0 = time.perf_counter()
        ok = _put_with_stop(q, batch, self._stop)
        dt = time.perf_counter() - t0
        self.stats.put_wait_s += dt
        if self._trace is not None:
            self._trace.add("wait.host_queue_put", t0, dt)
        if self._obs is not None:
            self._obs.observe("host_queue_put", dt)
        if bp is not None:
            self._prov_rec.batch_span(bp, "loader.host_queue_put", t0, dt)
        if hb is not None:
            hb.beat("batch")
        return ok

    def _deliver_batches(self, q, batches, hb, drop_short=False,
                         collate_span=None):
        """Push cut batches into the host queue, padding per ``last_batch``.
        Returns False once the loader is stopped (or the put gives up); on any
        early exit — and for a ``drop_short`` tail — the undelivered batches'
        leases are released so teardown never strands a slab hold until GC.

        Provenance (ISSUE 10): each batch opens its BatchProvenance here —
        membership attributed from the delivery FIFO, the collate span split
        across the cut set — and a batch that dies on this path is retired so
        the transfer/delivery pointers stay aligned."""
        rec = self._prov_rec
        collate_t0 = collate_share = None
        if rec is not None and collate_span is not None and batches:
            collate_t0 = collate_span[0]
            collate_share = collate_span[1] / len(batches)
        for i, batch in enumerate(batches):
            bp = None
            if rec is not None:
                bp = rec.producer_cut(_batch_row_count(batch), collate_t0,
                                      collate_share)
            if self._stop.is_set():
                if bp is not None:
                    rec.batch_dropped(bp)
                for b in batches[i:]:
                    _release_leases(b)
                return False
            if drop_short and _batch_row_count(batch) < self.local_batch_size:
                if bp is not None:
                    rec.batch_dropped(bp)
                _release_leases(batch)
                continue
            if self.last_batch == "pad":
                batch = self._pad(batch)
            if not self._put_batch(q, batch, hb, bp):
                if bp is not None:
                    rec.batch_dropped(bp)
                _release_leases(batch)
                for b in batches[i + 1:]:
                    _release_leases(b)
                return False
        return True

    def _pad(self, batch):
        n = len(next(iter(batch.values()))) if batch else 0
        if n == 0 or n == self.local_batch_size:
            if batch and "__valid__" not in batch:
                batch["__valid__"] = np.ones(n, dtype=bool)
            return batch
        pad = self.local_batch_size - n
        # pass-through columns inflate on host before the gather below (a
        # short TAIL batch only — full batches never reach this line)
        batch = _materialize_passthrough(batch, cause="pagedec_host_inflate")
        # the gather index and validity mask depend only on (n, batch_size):
        # built once per row count and frozen, instead of the old
        # np.concatenate([arange, full]) rebuild on every partial batch
        cached = self._pad_cache.get(n)
        if cached is None:
            idx = np.concatenate([np.arange(n), np.full(pad, n - 1)])
            idx.flags.writeable = False
            valid = np.concatenate([np.ones(n, dtype=bool),
                                    np.zeros(pad, dtype=bool)])
            valid.flags.writeable = False
            cached = self._pad_cache[n] = (idx, valid)
        idx, valid = cached
        leases = take_leases(batch)
        out = {}
        copied = 0
        for name, arr in batch.items():
            if isinstance(arr, np.ndarray):
                gathered = arr[idx]  # fancy indexing: an owned copy...
                if arr.dtype == object:
                    # ...of the OUTER pointers only: ragged ELEMENTS may still
                    # be read-only views into a leased slab the release below
                    # recycles — copy them owned (what _detach_slab_views does
                    # on the non-lease path)
                    for i, e in np.ndenumerate(gathered):
                        if isinstance(e, np.ndarray) and not e.flags.writeable:
                            gathered[i] = e.copy()
                            copied += e.nbytes
                else:
                    copied += gathered.nbytes
                out[name] = gathered
            else:  # non-ndarray sequence: repeat the last element so every column is
                out[name] = list(arr) + [arr[-1]] * pad  # batch_size long (ADVICE r1)
        count_copy("loader_pad", copied)
        for lease in leases:
            lease.release()  # every column was gathered out of the leased views
        out["__valid__"] = valid.copy()  # consumers own (and may mutate) the mask
        return out

    # -- consumer side ------------------------------------------------------------------

    def _advance_consumed(self, n):
        """Bump the consumer watermark under the checkpoint lock: the producer
        prunes the delivery log against ``_rows_consumed`` concurrently
        (``_ckpt_record``), so an unlocked ``+=`` could tear against that read."""
        if n:
            with self._ckpt_lock:
                self._rows_consumed += n

    def _start_producer(self):
        """Arm the pipeline for a fresh iteration. MUST run on the consumer thread
        (ADVICE r2: ``_stop.clear()`` used to run on the transfer thread at first
        advance, so a ``stop()`` issued around iteration start could be silently
        undone, and a second ``__iter__`` could race a still-live previous set of
        threads). A new ``__iter__`` supersedes any previous one: the old pipeline
        is stopped and joined before state is reset."""
        if (self._producer is not None and self._producer.is_alive()) or (
                self._transfer_thread is not None and self._transfer_thread.is_alive()):
            self.stop()
            self.join()
            if (self._producer is not None and self._producer.is_alive()) or (
                    self._transfer_thread is not None
                    and self._transfer_thread.is_alive()):
                # join() timed out: resetting _stop under a live thread would let a
                # zombie keep running into the new iteration — refuse instead
                raise RuntimeError(
                    "previous DataLoader iteration did not shut down within the join "
                    "timeout (a pipeline thread is still alive — likely stuck in a "
                    "long device dispatch); cannot safely start a new iteration")
        self._generation += 1
        self._stop.clear()
        pending = self._producer_error
        if pending is not None and not self._producer_error_delivered:
            from petastorm_tpu.errors import StallError

            if isinstance(pending, StallError):
                # the watchdog fail-fasted while no consumer was iterating
                # (pre-iteration or between epochs): the reader is already
                # stopped/truncated, and the debounced watchdog will not
                # re-report the same hang — clearing here would turn a
                # detected stall into a silently empty epoch
                self._producer_error_delivered = True
                raise pending
        self._producer_error = None
        self._producer_error_delivered = True
        self.stats.reset()
        if self._obs is not None:
            # percentiles re-anchor with the totals: bottleneck_report() must
            # describe ONE window, never fresh totals + stale histograms
            self._obs.reset_stage_histograms()
        if self._ckpt_enabled:
            with self._ckpt_lock:
                # fresh watermark per iteration: base = reader state BEFORE any of
                # this generation's deliveries (a restore target of "nothing from
                # this iteration consumed yet")
                self._ckpt_log.clear()
                self._ckpt_base = self.reader.state_dict()
                self._rows_consumed = 0
        self._queue = queue.Queue(maxsize=max(2, self._host_queue_size))
        self._dev_queue = None
        self._producer = threading.Thread(target=self._produce, args=(self._queue,),
                                          name="ptpu-loader", daemon=True)
        self._producer.start()

    def _host_batches(self, q):
        stats = self.stats
        while True:
            hb = self._hb_transfer  # live only while the transfer thread runs
            if hb is not None:
                hb.wait("host_queue")  # starvation = upstream's problem
            t0 = time.perf_counter()
            item = q.get()
            dt = time.perf_counter() - t0
            stats.queue_wait_s += dt
            if self._trace is not None:
                self._trace.add("wait.host_queue", t0, dt)
            if self._obs is not None:
                self._obs.observe("host_queue_wait", dt)
            if item is _SENTINEL:
                if self._producer_error is not None:
                    self._producer_error_delivered = True
                    raise self._producer_error
                return
            stats.batches += 1
            stats.rows += len(next(iter(item.values()))) if item else 0
            yield item

    def _decode_staged(self, batch):
        """Finish two-stage codec decode on device: staging-payload columns (e.g. JPEG
        DCT coefficient planes produced by ``decode_on_device=True`` readers) become
        device arrays via one batched codec dispatch per column."""
        fields = getattr(self.reader, "device_decode_fields", None)
        if not fields:
            return batch, {}
        import jax

        batch = dict(batch)
        decoded = {}
        unsharded_fallback = False  # per-BATCH: any staged field fell back
        for name in fields:
            arr = batch.pop(name, None)
            if arr is None:
                continue
            field = self.reader.schema.fields[name]
            staged = list(arr)
            if any(s is None for s in staged):
                raise ValueError(
                    "Field %r has null rows; nullable columns are not supported with "
                    "decode_on_device (pad or filter nulls upstream)" % name
                )
            base_s = None
            if self.sharding is not None:
                base_s = self.sharding.get(name) \
                    if isinstance(self.sharding, dict) else self.sharding
            decode_s = _decode_sharding(base_s, len(staged)) \
                if base_s is not None else None
            rt = self._device_decode_resize
            if isinstance(rt, dict):
                rt = rt.get(name)
            # sharding passed only when resolved AND the codec takes it:
            # third-party codec subclasses predating the kwarg keep decoding
            # single-device (their output is resharded below — the old behavior)
            kwargs = {} if decode_s is None else {"sharding": decode_s}
            probe = False
            if "sharding" in kwargs:
                support = _accepts_kwarg(field.codec.device_decode_batch,
                                         "sharding")
                if support is False:
                    kwargs.pop("sharding")
                elif support is None:
                    # uninspectable callable (C-implemented / exotic wrapper):
                    # the old behavior ASSUMED the legacy signature and
                    # silently degraded to unsharded decode. Probe instead —
                    # one try-call with the kwarg; its outcome is cached per
                    # underlying callable so the probe runs once per process
                    # (ISSUE 8 satellite, ADVICE round-5 loader.py:1145).
                    probe = True
            if rt is not None:
                kwargs["resize_to"] = tuple(rt)
            try:
                out = field.codec.device_decode_batch(field, staged, **kwargs)
                if probe:
                    _record_probed_kwarg(field.codec.device_decode_batch,
                                         "sharding", True)
            except TypeError as e:
                # only the probed kwarg's rejection is absorbable; the message
                # check keeps a TypeError raised INSIDE a sharding-aware decode
                # from being eaten (worst case the retry below re-raises it)
                if not (probe and "sharding" in kwargs and "sharding" in str(e)):
                    raise
                _record_probed_kwarg(field.codec.device_decode_batch,
                                     "sharding", False)
                kwargs.pop("sharding")
                out = field.codec.device_decode_batch(field, staged, **kwargs)
            # Surface the single-device fallback (VERDICT r4 #6): the configured
            # sharding cuts the batch axis across >1 device, but this decode ran
            # on one (axis undivisible, local-mesh derivation failed, or the
            # codec rejected the kwarg). Correct output either way — but on a pod
            # host it silently makes one chip decode for all of them, so count it
            # and warn once. Computed from the FINAL call shape, after the probe
            # resolved. (Mixed-layout sub-groups smaller than the batch can
            # still fall back inside the codec without being counted here; the
            # whole-batch divisibility check mirrors the codec's own.)
            want_shards = _batch_shard_count(base_s) if base_s is not None else 1
            got_shards = _batch_shard_count(kwargs["sharding"]) \
                if "sharding" in kwargs else 1
            if want_shards > 1 and (
                    got_shards <= 1 or len(staged) % got_shards != 0):
                if not unsharded_fallback:  # once per batch, however many fields
                    unsharded_fallback = True
                    self.stats.decode_unsharded_batches += 1
                if not self._warned_unsharded_decode:
                    self._warned_unsharded_decode = True
                    from petastorm_tpu.obs.log import degradation

                    # once=False: the per-LOADER flag above already gates the
                    # log (obs.log's own warn-once is per process, and two
                    # loaders each deserve their one warning)
                    degradation(
                        "unsharded_decode",
                        "Staged decode of field %r is running on a SINGLE device "
                        "although its sharding splits the batch axis %d ways "
                        "(batch rows=%d). Decode output is correct but unscaled; "
                        "make the per-process batch divisible by the batch-axis "
                        "shard count and use a codec whose device_decode_batch "
                        "accepts the `sharding` kwarg. (Warned once; see "
                        "PipelineStats.decode_unsharded_batches.)",
                        name, want_shards, len(staged), once=False)
            if self.sharding is not None:
                s = self.sharding.get(name) if isinstance(self.sharding, dict) \
                    else _matching_sharding(self.sharding, out)
                if s is not None:
                    if jax.process_count() > 1:
                        # `out` is already device-resident (the decode just ran on
                        # device). jax's process-local assembly slices it lazily and
                        # places shards device-to-device, so passing the jax.Array
                        # straight through keeps the decoded pixels on device —
                        # np.asarray here would re-pay the full decoded-bytes D2H+H2D
                        # the two-stage split exists to avoid (VERDICT r2 #3).
                        out = jax.make_array_from_process_local_data(s, out)
                    else:
                        out = jax.device_put(out, s)
            decoded[name] = out
        return batch, decoded

    def _to_device(self, batch):
        arrays, host = self._transfer_batch(batch)
        arrays = self._apply_device_transform(arrays)
        arrays.update(host)
        return arrays

    def _inflate_passthrough(self, batch):
        """The device inflate stage of the compressed-page pass-through
        (ISSUE 14): PassthroughColumn values → device arrays via the Pallas
        kernels (:mod:`petastorm_tpu.ops.pagedec_kernels`) when a non-CPU
        backend is live, the numpy reference twin otherwise (the decoded
        array then rides the normal staging + ``device_put`` path). Returns
        ``(batch_without_passthrough, {name: device array})``.

        Accounting: pages and compressed/saved bytes land in the
        ``ptpu_pagedec_*`` family — the compressed payload (plus the small
        page tables) is what the pipeline carried in place of decoded
        arrays: the pool-wire volume on every path, and the PCIe volume when
        the DEVICE inflate runs. Columns that take the host fallback here
        (CPU backend, sharded delivery, kernel bail) additionally count
        ``ptpu_pagedec_host_inflate_columns_total`` — their H2D leg shipped
        the decoded array, so the saved-bytes number covered the wire only.
        The stage records a ``decode.device_inflate`` span (provenance +
        trace + kernel-time histogram) so ``attribution_report()`` can blame
        or exonerate it, and carries a chaos hook site of the same name for
        synthetic kernel-slow injection."""
        names = [name for name, v in batch.items()
                 if getattr(v, "is_passthrough", False)]
        if not names:
            return batch, {}
        import jax

        from petastorm_tpu import chaos as _chaos
        from petastorm_tpu.io.pagedec import pagedec_counters
        from petastorm_tpu.ops import pagedec_kernels as pk

        counters = pagedec_counters()
        rec = self._prov_rec
        t0 = time.perf_counter()
        if _chaos.ACTIVE is not None:
            _chaos.ACTIVE.hit("decode.device_inflate")
        # sharded delivery keeps the host path for now: the decoded array
        # goes through the same sharded device_put as any other column
        # (per-shard device inflate is the ROADMAP item-2 follow-up)
        use_device = self.sharding is None and (
            jax.default_backend() != "cpu"
            or os.environ.get("PTPU_PAGEDEC_DEVICE", "") not in ("", "0"))
        decoded = {}
        for name in names:
            col = batch.pop(name)
            counters["pages"].inc(sum(
                (p1 - p0) + (1 if c.dict_page is not None else 0)
                for c, s, t in col.parts
                for p0, p1, _base in (c.covering_pages(s, t),)))
            shipped = col.shipped_nbytes
            counters["bytes_compressed"].inc(shipped)
            counters["bytes_saved"].inc(max(0, col.raw_nbytes - shipped))
            arr = None
            if use_device:
                try:
                    arr = pk.inflate_column(col)
                except pk.DeviceInflateError:
                    arr = None  # host twin below validates + raises if corrupt
            if arr is None:
                # CPU fallback / kernel bail: reference decode, normal H2D
                counters["host_inflate_columns"].inc()
                batch[name] = col.materialize()
            else:
                decoded[name] = arr
        dt = time.perf_counter() - t0
        counters["inflate_seconds"].observe(dt)
        if self._trace is not None:
            self._trace.add("decode.device_inflate", t0, dt)
        if self._obs is not None:
            self._obs.observe("device_inflate", dt)
        if rec is not None:
            rec.transfer_span("decode.device_inflate", t0, dt)
        return batch, decoded

    def _ensure_staging(self, device):
        """Resolve (once) and return the pinned H2D staging pool, or None.

        Decided lazily on the transfer thread from the first device-bound
        batch: ``staging=None`` auto-enables on accelerator backends only;
        ``True``/an int force it — but ANY mode is refused when this backend's
        ``device_put`` aliases host memory (recycled slabs would corrupt
        delivered arrays), with a ``staging_aliasing`` degradation."""
        if self._staging_decided:
            return self._staging
        sizes = [v.nbytes for v in device.values() if isinstance(v, np.ndarray)]
        if not sizes:
            return None  # nothing stageable yet: decide on a later batch
        self._staging_decided = True
        arg = self._staging_arg
        if arg is False:
            return None
        from petastorm_tpu.io.staging import (PinnedStagingPool, _STAGE_ALIGN,
                                              device_put_aliases_host)

        if arg is None:
            import jax

            if jax.default_backend() == "cpu" or device_put_aliases_host():
                return None  # auto mode: pageable→pinned buys nothing on CPU
        elif device_put_aliases_host():
            from petastorm_tpu.obs.log import degradation

            degradation(
                "staging_aliasing",
                "DataLoader(staging=%r) refused: this backend's device_put "
                "ALIASES host numpy memory, so staging-slab reuse would "
                "corrupt delivered batches; transferring from pageable "
                "memory", arg)
            return None
        need = 0
        for nbytes in sizes:
            need = -(-need // _STAGE_ALIGN) * _STAGE_ALIGN + nbytes
        slab_bytes = int(arg) if not isinstance(arg, bool) and arg is not None \
            else need
        self._staging = PinnedStagingPool(max(slab_bytes, need), num_slabs=2)
        return self._staging

    def _transfer_batch(self, batch):
        """Staged decode + device_put with the configured sharding. Returns the device
        arrays and the host-only (string/object) columns separately."""
        import jax

        hb = self._hb_transfer
        rec = self._prov_rec
        if rec is not None:
            # host batches flow to this thread strictly FIFO: advance the
            # recorder's transfer pointer to this batch's provenance
            rec.transfer_next()
        batch, inflated = self._inflate_passthrough(batch)
        if hb is not None:
            hb.beat("decode")
        t0 = time.perf_counter()
        batch, staged = self._decode_staged(batch)
        dt = time.perf_counter() - t0
        self.stats.decode_s += dt
        if self.tenant_context is not None:
            from petastorm_tpu.obs import tenant as _tenant_mod

            _tenant_mod.charge("decode_s", dt,
                               label=self.tenant_context.tenant)
        if self._trace is not None:
            self._trace.add("decode.dispatch", t0, dt)
        if self._obs is not None:
            self._obs.observe("decode", dt)
        if rec is not None:
            rec.transfer_span("loader.decode", t0, dt)
        if hb is not None:
            hb.beat("h2d")
        t0 = time.perf_counter()
        leases = take_leases(batch)
        device = {k: v for k, v in batch.items() if _is_device_dtype(v)}
        host = {k: v for k, v in batch.items() if k not in device}
        for name, arr in host.items():
            if isinstance(arr, np.ndarray) and arr.dtype == object and len(arr) \
                    and isinstance(arr[0], (np.ndarray, list, tuple)):
                raise ValueError(
                    "Field %r holds ragged tensors and cannot reach the device with a "
                    "static shape. Declare DataLoader(pad_shapes={%r: (max_dims...)}) "
                    "to zero-pad it (a %s__mask column marks the valid region)."
                    % (name, name, name)
                )
        if host:
            logger.debug("Fields kept host-side (non-device dtypes): %s", sorted(host))
            if leases:
                # host columns outlive this thread (they ride to the consumer
                # past the lease release below) — copy them out of the slabs
                host = _detach_slab_views(host)
        staging_lease = None
        pool = self._ensure_staging(device) if device else None
        if pool is not None:
            # one copy into a page-locked slab; device_put below DMAs straight
            # from it (and the original — possibly leased — buffers are done)
            device, staging_lease = pool.stage(device)
        elif leases:
            from petastorm_tpu.io.staging import device_put_aliases_host

            if device_put_aliases_host():
                # this backend's device_put ALIASES host numpy: transferring the
                # leased slab views directly would hand the consumer arrays into
                # memory the release below recycles — copy them owned first
                copied = 0
                for name, arr in list(device.items()):
                    if isinstance(arr, np.ndarray) and not arr.flags.writeable:
                        device[name] = arr.copy()
                        copied += arr.nbytes
                count_copy("h2d_owned_copy", copied)
        if self.sharding is None:
            arrays = jax.device_put(device)
        else:
            import jax.sharding as jsh

            arrays = {}
            for name, arr in device.items():
                s = self.sharding.get(name) if isinstance(self.sharding, dict) \
                    else _matching_sharding(self.sharding, arr)
                if s is None:  # field without an explicit sharding (e.g. __valid__)
                    arrays[name] = jax.device_put(arr)
                    continue
                if jax.process_count() > 1:
                    arrays[name] = jax.make_array_from_process_local_data(s, arr)
                else:
                    arrays[name] = jax.device_put(arr, s)
        arrays.update(staged)
        arrays.update(inflated)
        if staging_lease is not None or leases:
            # the H2D copy may still be reading the source buffers (device_put
            # is async): wait for it before the slabs go back to their rings
            jax.block_until_ready(arrays)
            if staging_lease is not None:
                staging_lease.release()
            for lease in leases:
                lease.release()
        dt = time.perf_counter() - t0
        self.stats.h2d_s += dt
        if self._trace is not None:
            self._trace.add("h2d.transfer", t0, dt)
        if self._obs is not None:
            self._obs.observe("h2d", dt)
        if rec is not None:
            rec.transfer_span("loader.h2d", t0, dt)
        return arrays, host

    def _apply_device_transform(self, arrays):
        if self._device_transform is None:
            return arrays
        import jax

        if self._jitted_transform is None:
            import inspect

            try:
                n_params = len(inspect.signature(
                    self._device_transform).parameters)
            except (TypeError, ValueError):
                n_params = 1
            self._transform_takes_key = n_params >= 2
            self._jitted_transform = jax.jit(self._device_transform)
        if self._transform_takes_key:
            key = jax.random.fold_in(
                jax.random.PRNGKey(self._seed or 0), self._transform_step)
            self._transform_step += 1
            return self._jitted_transform(arrays, key)
        return self._jitted_transform(arrays)

    def _device_batches(self, host_q):
        """host batches → ``(device batch, local_rows)``, with the optional HBM
        exchange shuffle between transfer and transform (rows are decorrelated over
        a ~capacity window by one fused gather+scatter per batch — zero host work).

        ``local_rows`` is the HOST batch's row count — the unit the checkpoint
        watermark needs: under multi-process JAX the assembled device batch has the
        GLOBAL leading dim, but the producer's delivery log counts this process's
        reader rows, and mixing the two would advance the watermark process_count×
        too fast (skipping buffered rows on restore)."""
        if not self._device_shuffle_capacity:
            for batch in self._host_batches(host_q):
                if self._stop.is_set():
                    _release_leases(batch)
                    return
                n = _batch_valid_rows(batch)
                yield self._to_device(batch), n
            return
        from petastorm_tpu.ops.device_shuffle import DeviceShuffleBuffer

        def _ring_sharding(name, arr):
            # lay the ring out like the batches (capacity axis where the batch axis
            # is), so the resident rows split across devices instead of replicating
            if self.sharding is None:
                return None
            s = self.sharding.get(name) if isinstance(self.sharding, dict) \
                else _matching_sharding(self.sharding, arr)
            return s

        shuffler = DeviceShuffleBuffer(self._device_shuffle_capacity,
                                       seed=self._seed or 0,
                                       shardings=_ring_sharding)
        for batch in self._host_batches(host_q):
            if self._stop.is_set():
                _release_leases(batch)
                return
            arrays, host = self._transfer_batch(batch)
            if host:
                raise ValueError(
                    "device_shuffle_capacity requires every delivered column to be "
                    "device-resident, but %s are host-only (strings/objects cannot "
                    "live in the HBM ring). Narrow schema_fields or drop the device "
                    "shuffle." % sorted(host)
                )
            out = shuffler.push(arrays)
            if out is not None:
                # local_rows 0: shuffled rows have no watermark (state_dict refuses
                # under device shuffle), so the count is never consulted
                yield self._apply_device_transform(out), 0
        for out in shuffler.drain():
            if self._stop.is_set():
                return
            yield self._apply_device_transform(out), 0

    def __iter__(self):
        self._start_producer()
        gen = self._generation  # superseded iterators must not stop a newer pipeline
        host_q = self._queue
        if not self.to_device:
            # staged decode still has to finish (decode runs on device, delivery is
            # host numpy) so CPU-only consumers see images, not coefficient payloads
            if getattr(self.reader, "device_decode_fields", None):
                for batch in self._host_batches(host_q):
                    # host delivery IS the designed host-decode seam for
                    # pass-through columns (no degradation counted)
                    batch = _materialize_passthrough(batch)
                    rest, staged = self._decode_staged(batch)
                    rest.update({k: np.asarray(v) for k, v in staged.items()})
                    self._advance_consumed(_batch_valid_rows(rest))
                    if self._prov_rec is not None:
                        self._prov_rec.batch_delivered()
                    yield rest
            else:
                # lease-backed batches stay valid until the consumer asks for
                # the NEXT one (same cadence as Reader.release_batch): the
                # previous batch's slabs return to the ring here, and the last
                # one's at generator close
                prev = None
                try:
                    for batch in self._host_batches(host_q):
                        if prev is not None:
                            prev.release()
                        prev = batch if isinstance(batch, LeasedBatch) else None
                        batch = _materialize_passthrough(batch)
                        self._advance_consumed(_batch_valid_rows(batch))
                        if self._prov_rec is not None:
                            self._prov_rec.batch_delivered()
                        yield batch
                finally:
                    if prev is not None:
                        prev.release()
            return
        if self.prefetch <= 0:  # synchronous transfer (debug)
            for batch, local_rows in self._device_batches(host_q):
                self._advance_consumed(local_rows)
                if self._prov_rec is not None:
                    self._prov_rec.batch_delivered()
                yield batch
            return
        # Async transfer thread: host batches → decode dispatch + device_put → a small
        # device-batch queue. Keeping dispatch OFF the consumer thread both overlaps
        # H2D/decode with the training step and absorbs device-service latency spikes
        # (a slow dispatch drains the queue instead of stalling the step).
        dev_q = queue.Queue(maxsize=max(1, self.prefetch))
        self._dev_queue = dev_q
        transfer_error = []

        def _transfer():
            scope = self._health_scope
            hb = None
            if scope is not None:
                hb = scope.register("loader.transfer", "transfer")
                self._hb_transfer = hb
            try:
                for batch_rows in self._device_batches(host_q):
                    if self._stop.is_set():
                        return
                    if hb is not None:
                        # a full device queue means the TRAINING STEP is the
                        # slow one — a wait, never a stall
                        hb.wait("device_queue_put")
                    if not _put_with_stop(dev_q, batch_rows, self._stop):
                        return
            except Exception as e:  # noqa: BLE001 — surfaced to consumer thread
                transfer_error.append(e)
            finally:
                if hb is not None:
                    hb.done()
                self._hb_transfer = None
                _put_sentinel(dev_q, self._stop)

        self._transfer_thread = threading.Thread(
            target=_transfer, name="ptpu-transfer", daemon=True)
        self._transfer_thread.start()
        stats = self.stats
        finished = False
        try:
            while True:
                t0 = time.perf_counter()
                # bounded wait (GL-R001): the transfer thread's finally puts a
                # sentinel on every exit path, but a thread that died without
                # one (killed hard mid-put, interpreter teardown race) used to
                # hang this consumer forever — re-check liveness each second
                # and surface the stored error / end the epoch instead
                while True:
                    try:
                        item = dev_q.get(timeout=1.0)
                        break
                    except queue.Empty:
                        t_thread = self._transfer_thread
                        if t_thread is None or not t_thread.is_alive():
                            item = _SENTINEL
                            break
                dt = time.perf_counter() - t0
                stats.device_queue_wait_s += dt
                if self._trace is not None:
                    self._trace.add("wait.device_queue", t0, dt)
                if self._obs is not None:
                    self._obs.observe("device_queue_wait", dt)
                if item is _SENTINEL:
                    finished = True
                    if transfer_error:
                        raise transfer_error[0]
                    if self._producer_error is not None:
                        # normally the transfer thread re-raises the producer's
                        # error through _host_batches, but a watchdog fail-fast
                        # (StallError) injects the sentinel DIRECTLY into this
                        # queue — the error must still surface, not silently
                        # end the epoch
                        self._producer_error_delivered = True
                        raise self._producer_error
                    return
                batch, local_rows = item
                self._advance_consumed(local_rows)
                if self._prov_rec is not None:
                    self._prov_rec.batch_delivered()
                yield batch
        finally:
            if not finished and gen == self._generation:
                # iterator abandoned mid-epoch (break / del): stop the pipeline so the
                # transfer thread does not keep pinning prefetched device batches.
                # Guarded by generation: closing a SUPERSEDED iterator (a newer
                # __iter__ already re-armed the loader) must not kill the new one.
                self.stop()

    # -- lifecycle ----------------------------------------------------------------------

    def _fail_fast(self, err):
        """Stall-watchdog escalation (``escalation="raise"``): surface ``err``
        to the consumer and unwedge every queue — the training loop gets a
        :class:`~petastorm_tpu.errors.StallError` instead of hanging. The
        reader is stopped too (truncation semantics, same as a user ``stop()``)
        so a producer blocked inside ``reader.next`` wakes promptly; a worker
        thread wedged in native code stays behind as a daemon and is reported
        by the executor's ``thread_join_timeout`` degradation at join."""
        self._producer_error = err
        self._producer_error_delivered = False
        try:
            self.reader.stop()
        except Exception:  # noqa: BLE001 — fail-fast must not die on teardown
            pass  # graftlint: disable=GL-O002 (the StallError itself is the signal)
        self.stop()

    def _health_context(self):
        """Queue depths + stats + io gauges, snapshotted into flight records
        (the watchdog's evidence of WHERE the pipeline was backed up)."""
        q = self._queue
        dq = self._dev_queue
        out = {
            "host_queue_depth": q.qsize() if q is not None else 0,
            "host_queue_size": max(2, self._host_queue_size),
            "device_queue_depth": dq.qsize() if dq is not None else 0,
            "device_queue_size": max(1, self.prefetch),
            "stats": self.stats.snapshot(),
        }
        for name in ("io_stats", "wire_stats"):
            fn = getattr(self.reader, name, None)
            if fn is not None:
                try:
                    polled = fn()
                except Exception:  # noqa: BLE001 — evidence is best-effort
                    polled = None
                if polled:
                    out[name.replace("_stats", "")] = polled
        rec = self._prov_rec
        if rec is not None:
            # attribution summary rides into the flight record on stall: the
            # operator sees WHICH site owned the critical path when it hung
            try:
                out["attribution"] = rec.summary()
            except Exception:  # noqa: BLE001 — evidence is best-effort
                out["attribution"] = None
        if self._slo_engine is not None:
            # temporal plane (ISSUE 12): recent SLO alerts into the flight
            # context — a stall that followed a burn shows the burn
            alerts = self._slo_engine.alerts()
            out["slo"] = {
                "alerts": len(alerts),
                "breaching": self._slo_engine.breaching(),
                "last_alert": alerts[-1].message if alerts else None,
            }
        return out

    def health_report(self, dump_path=None):
        """On-demand health snapshot (requires the loader to have been built
        with ``health=``): the full flight-record dict — heartbeat ages and
        states, driver (and pool-child) stacks, queue depths, degradation
        counts, per-worker latency, the recent-event ring — plus the
        bottleneck analyzer's verdict under ``"bottleneck"``. Pass
        ``dump_path`` to also write it as a JSON flight record."""
        if self._health is None:
            raise ValueError(
                "DataLoader was built without health monitoring — pass "
                "health=True (or a HealthOptions/HealthMonitor, or set "
                "PTPU_HEALTH=1) to enable health_report()")
        report = self._health.capture("on_demand")
        report["bottleneck"] = self.bottleneck_report().to_dict()
        if dump_path is not None:
            from petastorm_tpu.obs.flight import write_flight_record

            write_flight_record(dump_path, report)
        return report

    def stop(self):
        self._stop.set()
        for q in (self._queue, self._dev_queue):
            if q is not None:
                # unblock a producer/transfer thread stuck on a full queue. Catches
                # Exception rather than queue.Empty: stop() can run from a generator
                # finalizer during interpreter shutdown, when the queue module's
                # globals (incl. Empty) may already be torn down to None.
                try:
                    while True:
                        item = q.get_nowait()
                        # a drained batch may still carry slab/staging leases —
                        # return them to their rings now instead of stranding
                        # them until GC (counted as ptpu_lease_leaked_total)
                        try:
                            _release_leases(item)
                        except Exception:  # noqa: BLE001
                            pass  # graftlint: disable=GL-O002 (teardown: lease module may be torn down)
                except Exception:  # noqa: BLE001
                    pass  # graftlint: disable=GL-O002 (interpreter teardown: queue globals may be None)
                # the drain may have consumed the producer's end-of-stream sentinel
                # while the downstream thread is blocked in an untimed get() with the
                # producer already exited (ADVICE r2 teardown race) — re-put it so the
                # blocked get always wakes. The queue was just drained, so put_nowait
                # cannot be full except under a concurrent producer put, in which case
                # that put itself unblocks the get.
                try:
                    q.put_nowait(_SENTINEL)
                except Exception:  # noqa: BLE001
                    pass  # graftlint: disable=GL-O002 (interpreter teardown: queue globals may be None)
        # host-wide cache arena (ISSUE 17): the consumer is going away — sweep
        # holder refcounts left by processes that died without releasing (a
        # SIGKILLed pool child mid-read), so their pinned entries become
        # evictable again. Live peers' views are untouched; same exit-drain
        # discipline as the lease release above.
        try:
            from petastorm_tpu.io import arena as _arena_mod

            arena_obj = _arena_mod.process_arena()
            if arena_obj is not None:
                arena_obj.reclaim()
        except Exception:  # noqa: BLE001
            pass  # graftlint: disable=GL-O002 (interpreter teardown: arena module may be torn down)

    def join(self):
        if self._producer is not None:
            self._producer.join(timeout=60)
        if self._transfer_thread is not None:
            self._transfer_thread.join(timeout=60)

    # -- consumer-watermark checkpointing ----------------------------------------------

    @property
    def cur_shard(self):
        """This process's shard id (the reader's), so a ``DataLoader`` duck-types as
        a checkpointable reader for :mod:`petastorm_tpu.checkpoint` routing."""
        return getattr(self.reader, "cur_shard", None)

    def state_dict(self):
        """Exact-resume state at the CONSUMER watermark — checkpoint through the
        loader, not the reader, when batches flow through a ``DataLoader``.

        ``Reader.state_dict()`` marks a row group consumed when the reader hands it
        to whoever calls ``next()`` — here the loader's background producer, which
        runs ahead of the training loop by the prefetch/host-queue depth. Saving
        the READER's state mid-stream would therefore skip every row sitting in
        the loader's buffers on restore (delivered, never trained on). This method
        instead returns the newest reader state whose deliveries the consumer has
        FULLY received — rows in flight inside the loader replay after restore
        (the same at-least-once row-group granularity ``Reader.state_dict``
        documents), and nothing is lost.

        Works with any :mod:`petastorm_tpu.checkpoint` entry point (the loader
        duck-types as a reader): ``ptck.save(path, loader)``,
        ``ocp.args.Composite(reader=ptck.save_args(loader))``, pod-exact included.

        Raises for shuffling loaders (host ``shuffling_queue_capacity`` or
        ``device_shuffle_capacity``): a row can linger in a random-exchange buffer
        arbitrarily long, so no row-group watermark short of the epoch boundary is
        correct — checkpoint those at epoch ends via ``Reader.state_dict()``.
        """
        if self._shuffling_queue_capacity or self._device_shuffle_capacity:
            raise ValueError(
                "DataLoader.state_dict() is not available with shuffling enabled "
                "(shuffling_queue_capacity/device_shuffle_capacity): shuffled rows "
                "linger in the buffer indefinitely, so a mid-epoch row-group "
                "watermark would lose them. Checkpoint at an epoch boundary with "
                "Reader.state_dict() instead.")
        if not self._ckpt_enabled:
            raise AttributeError(
                "underlying reader %r has no state_dict" % type(self.reader).__name__)
        with self._ckpt_lock:
            state = self._ckpt_base
            for cum, st in self._ckpt_log:
                if cum <= self._rows_consumed:
                    state = st
                else:
                    break
        if state is None:  # never iterated: the reader's current state IS the truth
            state = self.reader.state_dict()
        return state

    def load_state_dict(self, state):
        """Restore into the underlying reader (before iterating)."""
        self.reader.load_state_dict(state)

    @property
    def quarantine_report(self):
        """The underlying reader's poison-item
        :class:`~petastorm_tpu.recovery.QuarantineReport` (ISSUE 7): every plan
        item skipped under ``RecoveryOptions(on_poison="quarantine")`` with its
        plan ordinals, file/row-group identity, and exception chain. Falsy when
        nothing was quarantined; ``None`` for readers without the recovery
        machinery (e.g. an ``InMemDataset`` source)."""
        return getattr(self.reader, "quarantine_report", None)

    def bottleneck_report(self):
        """Name the limiting pipeline stage from the stage counters: a
        :class:`petastorm_tpu.obs.analyze.BottleneckReport` with verdict
        ``producer-bound`` / ``wire-bound`` / ``consumer-bound`` / ``balanced``
        and per-side utilization fractions (``print(report)`` for the
        human-readable rendering; p50/p90/p99 stage detail attached when the
        loader was built with ``metrics=``). Reads the CURRENT ``stats``
        window — call after (or during) iteration. With ``provenance=``,
        :meth:`attribution_report` refines this down to a concrete SITE."""
        from petastorm_tpu.obs.analyze import analyze_loader

        return analyze_loader(self)

    @property
    def provenance(self):
        """The attached :class:`~petastorm_tpu.obs.provenance
        .ProvenanceRecorder`, or None when ``provenance=`` was not passed."""
        return self._prov_rec

    @property
    def slo_engine(self):
        """The attached :class:`~petastorm_tpu.obs.slo.SloEngine`, or None
        when ``slos=`` was not passed."""
        return self._slo_engine

    @property
    def controller(self):
        """The attached :class:`~petastorm_tpu.control.Controller`, or None
        when ``controller=`` was not passed."""
        return self._controller

    def ctl_decisions(self):
        """The controller's decisions so far (ISSUE 13) — each a
        :class:`~petastorm_tpu.control.Decision` carrying the cause
        (``ctl_actuate``/``ctl_revert``/``ctl_freeze``), the knob's
        before/after values and the triggering window. Empty without
        ``controller=``."""
        return self._controller.decisions() if self._controller is not None \
            else []

    def slo_alerts(self):
        """Debounced SLO-breach/anomaly alerts so far (ISSUE 12) — each an
        :class:`~petastorm_tpu.obs.slo.SloAlert` carrying an attribution
        snapshot when provenance is on. Empty without ``slos=``."""
        return self._slo_engine.alerts() if self._slo_engine is not None \
            else []

    def _require_provenance(self):
        if self._prov_rec is None:
            raise ValueError(
                "DataLoader was built without provenance — pass "
                "provenance=True (or a ProvenanceRecorder, or set "
                "PTPU_PROVENANCE=1) to enable batch_provenance()/"
                "attribution_report()")
        return self._prov_rec

    def batch_provenance(self):
        """The most recently delivered batch's provenance (ISSUE 10): its
        contributing item records — spans across every pipeline seam and
        process, annotations (cache tier, hedges, retries, quarantine) — plus
        the batch-plane spans and the step gap. ``None`` before the first
        delivery. Requires ``provenance=``."""
        return self._require_provenance().last_batch()

    def attribution_report(self, tenant=None):
        """Fold the recorded batch window into a critical-path step-time
        attribution (:class:`~petastorm_tpu.obs.critical_path
        .AttributionReport`): per-site self seconds and shares on the
        critical path, step-gap p50/p99 split by cache tier and degradation
        cause, and the "your p99 batch spent N% in <site>" verdict — the
        refinement of :meth:`bottleneck_report` down to a concrete site.
        ``tenant=`` (ISSUE 18) narrows the batch window to batches whose
        items that tenant delivered. Requires ``provenance=``."""
        return self._require_provenance().report(tenant=tenant)

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.stop()
        self.join()
        self.reader.stop()
        self.reader.join()
        if self._adopted_passthrough:
            # hand delivery materialization back to the reader: a reader
            # outliving this loader serves decoded batches again
            self.reader.keep_passthrough = False
            self._adopted_passthrough = False
        if self._staging is not None:
            self._staging.close()
            self._staging = None
        if self._slo_engine is not None and self._slo_owned:
            # a loader-built engine stops evaluating on the sampler cadence
            # (alerts stay readable); a caller-supplied SHARED engine keeps
            # watching — a sibling pipeline may still be burning
            self._slo_engine.detach()
        if self._controller is not None and self._ctl_owned:
            # same ownership convention: a loader-built controller stops
            # actuating (decisions stay readable); a shared one is the
            # caller's to detach
            self._controller.detach()
        if self._obs is not None:
            self._obs.close()
        if self._prov_rec is not None and self._prov_owned:
            # a loader-built recorder releases the process-global slot here
            # (records stay readable — a post-exit attribution_report() still
            # works over the window); reader-owned recorders were disarmed by
            # reader.join() above, caller-supplied ones stay armed (theirs)
            self._prov_rec.disarm()
        if self._health is not None:
            monitor = self._health
            context_handle, stall_handle = self._health_handles or (None, None)
            if context_handle is not None:
                monitor.remove_context(context_handle)
            if stall_handle is not None:
                monitor.remove_stall_callback(stall_handle)
            self._health_handles = ()
            if self._health_owned:
                # a SHARED monitor (health=HealthMonitor(...)) stays running —
                # its owner tears it down; one the loader built is retired here
                monitor.stop()
            elif self._health_scope is not None:
                # shared monitor: retire this pipeline's scoped actors so
                # closed loader generations don't accumulate on it forever
                self._health_scope.close()


def _put_with_stop(q, item, stop_event):
    """Bounded-queue put that gives up once the loader is stopped: an untimed put can
    block forever when stop() wins the race for the slot freed by its own drain (the
    consumer is gone, nothing ever drains again). Returns False when stopped."""
    full = queue.Full  # bound early: may run during interpreter teardown
    while True:
        try:
            q.put(item, timeout=0.1)
            return True
        except full:
            if stop_event.is_set():
                return False


def _put_sentinel(q, stop_event):
    """Deliver the end-of-stream sentinel even when the consumer is slow: keep retrying
    until the put lands or the loader is stopped (a timed-out put must NOT drop the
    sentinel — the consumer would block forever on an empty queue)."""
    full = queue.Full  # bound early: may run during interpreter teardown
    while True:
        try:
            q.put(_SENTINEL, timeout=1)
            return
        except full:
            if stop_event.is_set():
                return


def _pad_ragged_columns(columns, pad_shapes):
    """Zero-pad ragged tensor columns to their declared max shape + a validity mask.

    Runs in the producer (before shuffling/batching) so downstream stages only ever
    see static shapes."""
    columns = dict(columns)
    for name, target in pad_shapes.items():
        col = columns.get(name)
        if col is None:
            continue
        if isinstance(col, np.ndarray) and col.dtype != object:
            if col.shape[1:] == tuple(target):  # already uniform at the max: mask-only
                columns[name + "__mask"] = np.ones(col.shape, dtype=bool)
                continue
            col = list(col)  # uniform but below max: pad like the ragged case
        rows = [np.asarray(r) for r in col]
        target = tuple(target)
        out = np.zeros((len(rows),) + target, dtype=rows[0].dtype if rows else np.float64)
        mask = np.zeros((len(rows),) + target, dtype=bool)
        for i, r in enumerate(rows):
            if r.ndim != len(target):
                raise ValueError(
                    "pad_shapes[%r]=%r has rank %d but row %d has rank %d"
                    % (name, target, len(target), i, r.ndim)
                )
            if any(a > t for a, t in zip(r.shape, target)):
                raise ValueError(
                    "Row %d of field %r has shape %r exceeding declared pad max %r"
                    % (i, name, r.shape, target)
                )
            region = tuple(slice(0, s) for s in r.shape)
            out[i][region] = r
            mask[i][region] = True
        columns[name] = out
        columns[name + "__mask"] = mask
    return columns


def _resolve_local_batch(batch_size, sharding):
    """Rows this process feeds per global batch of ``batch_size`` (1 process → all).

    A global batch that does not divide over the sharding's batch axis raises
    (misconfiguration must not silently feed P×-larger batches). Under multi-process
    JAX, only a ``NamedSharding`` (or a sharding whose devices are all local) can be
    decomposed into per-process shares — a ``PositionalSharding``/GSPMD sharding
    spanning processes raises instead of silently feeding every process the GLOBAL
    batch and assembling wrong data (VERDICT r2 #5)."""
    try:
        import jax
        import jax.sharding as jsh
    except ImportError:  # jax optional for host-only use
        return batch_size
    if sharding is None or jax.process_count() == 1:
        return batch_size

    def _all_local(s):
        try:
            pi = jax.process_index()
            return all(d.process_index == pi for d in s.device_set)
        except Exception:  # noqa: BLE001 — unknown sharding type: treat as non-local
            return False

    def _reject(s):
        raise ValueError(
            "DataLoader cannot decompose the global batch across processes for %s: "
            "only NamedSharding exposes the mesh/axis structure needed to compute "
            "each process's local share. Use a NamedSharding over a Mesh (batch axis "
            "in PartitionSpec position 0), or shard the reader per process and pass "
            "a process-local sharding." % type(s).__name__
        )

    if isinstance(sharding, dict):  # per-field dict: use the first named sharding
        # EVERY non-named entry must be process-local — one decomposable field must
        # not grandfather in an undecomposable one beside it
        for s in sharding.values():
            if s is not None and not isinstance(s, jsh.NamedSharding) \
                    and not _all_local(s):
                _reject(s)
        named = [s for s in sharding.values() if isinstance(s, jsh.NamedSharding)]
        if not named:
            return batch_size  # every field placement is process-local
        sharding = named[0]
    if not isinstance(sharding, jsh.NamedSharding):
        if _all_local(sharding):
            return batch_size  # single-device/local placement: no decomposition needed
        _reject(sharding)
    from petastorm_tpu.parallel.mesh import local_batch_size

    spec0 = sharding.spec[0] if len(sharding.spec) else None
    if spec0 is None:
        return batch_size  # batch axis replicated: every process feeds all rows
    axes = (spec0,) if isinstance(spec0, str) else tuple(spec0)
    return local_batch_size(batch_size, sharding.mesh, batch_axes=axes)


def _batch_shard_count(sharding):
    """See :func:`petastorm_tpu.parallel.mesh.batch_axis_shard_count` (shared with
    the decode op's SPMD input staging)."""
    from petastorm_tpu.parallel.mesh import batch_axis_shard_count

    return batch_axis_shard_count(sharding)


#: try-call probe outcomes for uninspectable callables, keyed by the
#: underlying function — codecs live for the process, so strong refs are fine
_probed_kwargs = {}


def _record_probed_kwarg(fn, name, supported):
    """Cache a try-call probe's verdict so it runs once per process."""
    _probed_kwargs[(getattr(fn, "__func__", fn), name)] = bool(supported)


def _accepts_kwarg(fn, name):
    """``True``/``False`` when ``fn``'s signature answers whether keyword
    ``name`` is accepted (or ``**kwargs`` taken); ``None`` when the callable
    is uninspectable — the caller then probes by calling once and records the
    outcome via :func:`_record_probed_kwarg`. Cached on the underlying
    function — this runs on the transfer thread per batch, and a signature
    cannot change between batches."""
    fn = getattr(fn, "__func__", fn)
    probed = _probed_kwargs.get((fn, name))
    if probed is not None:
        return probed
    return _accepts_kwarg_cached(fn, name)


@functools.lru_cache(maxsize=None)
def _accepts_kwarg_cached(fn, name):
    import inspect

    try:
        params = inspect.signature(fn).parameters
    except (TypeError, ValueError):
        # Uninspectable (C-implemented / exotic wrappers): unknown — the old
        # behavior assumed the legacy signature and silently dropped the
        # kwarg; callers now try-call once instead (ISSUE 8 satellite)
        return None
    return name in params or any(
        p.kind is inspect.Parameter.VAR_KEYWORD for p in params.values())


def _decode_sharding(s, local_rows):
    """Batch-axis sharding to SPMD-decode staged payloads under (VERDICT r3 #2).

    Single-process: the loader's sharding itself (stage 2 consumes its mesh + batch
    axis; trailing axes are replicated per slab inside the decode). Multi-process: a
    global ``NamedSharding`` cannot place host data, so derive a process-LOCAL 1-D
    mesh whose device order mirrors ``s``'s local batch-slice order — decode output
    shards then already sit where ``make_array_from_process_local_data`` wants them.
    Returns None when the batch axis is unsharded or does not divide — decode then
    runs on the default device exactly as before (correct, just unscaled)."""
    import jax
    import jax.sharding as jsh

    if not isinstance(s, jsh.NamedSharding) or not len(s.spec) or s.spec[0] is None:
        return None
    if jax.process_count() == 1:
        return s
    axis = s.spec[0]
    s1 = jsh.NamedSharding(s.mesh, jsh.PartitionSpec(axis))
    global_rows = local_rows * jax.process_count()
    try:
        imap = s1.addressable_devices_indices_map((global_rows,))
    except ValueError:
        return None
    by_start = {}
    for dev, idx in imap.items():
        sl = idx[0]
        start = 0 if sl.start is None else int(sl.start)
        by_start.setdefault(start, dev)  # one device per distinct slice (replicas skip)
    devs = [by_start[k] for k in sorted(by_start)]
    if len(devs) <= 1 or local_rows % len(devs) != 0:
        return None
    mesh = jsh.Mesh(np.asarray(devs), ("_decode_batch",))
    return jsh.NamedSharding(mesh, jsh.PartitionSpec("_decode_batch"))


def _matching_sharding(sharding, arr):
    """Adapt a batch-axis sharding to an array's rank (replicate the trailing axes)."""
    import jax.sharding as jsh

    if isinstance(sharding, jsh.NamedSharding):
        spec = list(sharding.spec)
        if len(spec) < arr.ndim:
            spec = spec + [None] * (arr.ndim - len(spec))
        elif len(spec) > arr.ndim:
            spec = spec[: arr.ndim]
        return jsh.NamedSharding(sharding.mesh, jsh.PartitionSpec(*spec))
    return sharding


class InMemDataLoader:
    """Epochs served entirely from device memory: load the dataset (or its shard) to
    HBM ONCE, then every batch is a single on-device permutation gather — zero host
    work, zero H2D after the fill.

    TPU-native analog of the reference's ``InMemBatchedDataLoader``
    (petastorm/pytorch.py ~L380), which re-collates host tensors per epoch; here the
    shuffle itself runs on device (one fused ``take`` per column), so epoch iteration
    costs no host CPU and no transfer — the right shape for small/medium datasets
    (MNIST-scale fine-tuning, eval sets) on big accelerators.

    Under multi-process JAX each process fills its own shard (pass a sharded reader,
    ``cur_shard=jax.process_index()``) and keeps it resident on ITS devices; every
    batch gathers each process's local share and assembles the global ``jax.Array``
    from the device-resident parts. ``batch_size`` stays GLOBAL; requires a
    decomposable ``NamedSharding`` and ``last_batch='drop'``; the per-epoch batch
    count is agreed once at fill time (allgather of local row counts — the only
    collective).

    Parameters
    ----------
    reader : Reader
        Source reader; consumed ONCE during construction (its ``num_epochs`` should be
        1). Device-decode staging columns are finished on device during the fill.
    batch_size : int
        Rows per yielded batch.
    num_epochs : int or None
        Epochs to serve; ``None`` = infinite.
    shuffle : bool
        Reshuffle every epoch with a fresh fold of ``seed`` (deterministic).
    sharding : jax.sharding.Sharding, optional
        Layout for the resident store AND the yielded batches (e.g. batch axis over a
        ``dp`` mesh axis).
    last_batch : {"drop", "partial"}
        Remainder policy per epoch (``pad`` is pointless here — resize the store).
        With ``sharding``, a ``partial`` tail batch is laid out per the sharding when
        its row count divides the batch axis, and yielded unsharded (default layout)
        otherwise — a pjit'd step with fixed ``in_shardings`` should use ``drop``.
    """

    def __init__(self, reader, batch_size, num_epochs=1, shuffle=True, seed=0,
                 sharding=None, last_batch="drop", device_transform=None,
                 device_decode_resize=None, trace=None):
        if last_batch not in ("drop", "partial"):
            raise ValueError("last_batch must be drop|partial, got %r" % last_batch)
        import jax
        import jax.numpy as jnp

        self.batch_size = int(batch_size)
        self.num_epochs = num_epochs
        self.shuffle = shuffle
        self.last_batch = last_batch
        self._seed = int(seed)
        self._device_transform = device_transform
        self._jitted_transform = None
        # fill: reuse the streaming DataLoader (handles staged on-device decode and the
        # sharding layout), then concatenate the chunks on device
        plan = getattr(reader, "_plan", None)
        if plan is not None and getattr(plan, "_num_epochs", 1) is None:
            raise ValueError(
                "InMemDataLoader consumes the reader ONCE to fill device memory; an "
                "infinite reader (num_epochs=None) would never finish the fill. Build "
                "the reader with num_epochs=1 and set epochs here."
            )
        #: multi-process: each process keeps ITS shard HBM-resident and every batch
        #: assembles a global jax.Array from the per-process device-resident gathers
        #: (same contract as per-rank InMemBatchedDataLoader under DDP, but the
        #: delivered batch is global). Requires a decomposable NamedSharding and
        #: last_batch='drop'; per-epoch batch count is agreed once at fill time via
        #: an allgather of local row counts.
        self._multiprocess = jax.process_count() > 1
        if self._multiprocess:
            if sharding is None:
                raise ValueError(
                    "multi-process InMemDataLoader requires a sharding (a "
                    "NamedSharding whose batch axis decomposes per process)")
            if last_batch != "drop":
                raise ValueError(
                    "multi-process InMemDataLoader supports last_batch='drop' only "
                    "(a ragged tail cannot assemble into a uniform global array)")
            self.local_batch_size = _resolve_local_batch(self.batch_size, sharding)
            if self.local_batch_size >= self.batch_size:
                # a replicated batch axis would assemble each process's DIFFERENT
                # shard rows as 'replicas' of one global array — silent corruption
                # (jax requires replica data to be identical and does not verify it)
                raise ValueError(
                    "multi-process InMemDataLoader requires a sharding whose batch "
                    "axis spans processes (each process contributes its shard); a "
                    "replicated batch axis would label divergent per-process shards "
                    "as replicas of the same array")
        else:
            self.local_batch_size = self.batch_size
        self._sharding = sharding
        self._trace = trace  # fill spans recorded via the inner DataLoader; gather
        # dispatch spans recorded per batch below
        chunks = []
        dropped = set()
        # fill UNSHARDED: chunk/partial-batch row counts rarely divide the batch axis;
        # the resident store and gathered batches are laid out below instead
        with DataLoader(reader, self.batch_size, sharding=None,
                        last_batch="partial", prefetch=2,
                        device_decode_resize=device_decode_resize,
                        trace=trace) as fill:
            for batch in fill:
                kept = {}
                for k, v in batch.items():
                    # host-only columns (strings/objects) cannot live in HBM — dropped
                    if isinstance(v, np.ndarray) and not _is_device_dtype(v):
                        dropped.add(k)
                    else:
                        kept[k] = v
                chunks.append(kept)
        if dropped:
            logger.warning("InMemDataLoader dropped host-only fields: %s",
                           sorted(dropped))
        if not chunks:
            raise ValueError("reader yielded no rows to load in memory")
        self._store = {
            k: jnp.concatenate([jnp.asarray(c[k]) for c in chunks], axis=0)
            for k in chunks[0]
        }
        self.rows = int(next(iter(self._store.values())).shape[0])
        if self._multiprocess:
            from jax.experimental import multihost_utils

            self._local_rows = self.rows
            all_rows = np.asarray(multihost_utils.process_allgather(
                np.array([self._local_rows], dtype=np.int64))).ravel()
            self._batches_per_epoch = int(all_rows.min()) // self.local_batch_size
            if self._batches_per_epoch == 0:
                raise ValueError(
                    "multi-process InMemDataLoader: some process holds only %d rows "
                    "— fewer than its local batch share %d; no full global batch "
                    "can be formed" % (int(all_rows.min()), self.local_batch_size))
            served = self._batches_per_epoch * self.local_batch_size
            if int(all_rows.max()) > served:
                logger.warning(
                    "InMemDataLoader shards are uneven (%d..%d rows/process): each "
                    "epoch serves %d rows/process; with shuffle=True the excluded "
                    "rows differ per epoch, with shuffle=False the SAME surplus "
                    "rows are never served — rebalance shards (shard_seed) or keep "
                    "shuffle on", int(all_rows.min()), int(all_rows.max()), served)
            self.rows = int(all_rows.sum())
            # the store stays PROCESS-LOCAL (addressable devices); the global layout
            # happens per batch from the already-device-resident gathers
        elif sharding is not None:
            # shard the resident store along the batch axis when the row count
            # divides; otherwise it stays on the default device and only the
            # gathered batches are laid out per the sharding
            try:
                self._store = {
                    k: jax.device_put(v, _matching_sharding(sharding, v))
                    for k, v in self._store.items()
                }
            except ValueError:
                logger.warning(
                    "InMemDataLoader store (%d rows) does not divide over the "
                    "sharding's batch axis; store kept unsharded", self.rows)

        def _gather(store, idx):
            return {k: v[idx] for k, v in store.items()}

        self._gather = jax.jit(_gather)
        #: (epoch, next batch within epoch) the NEXT yield will serve — the
        #: exact-resume cursor (epochs are deterministic by seed/epoch fold)
        self._pos = (0, 0)
        self._resume = None

    # -- exact resume (epochs are deterministic, so the cursor IS the state) -----------

    def state_dict(self):
        """Exact-resume cursor: ``(epoch, batch)`` of the next batch to serve, plus
        the stream-identity config. Epoch order is a pure function of
        ``seed``/``epoch`` (per process under multi-process JAX), so restoring the
        cursor into a same-config loader reproduces the stream EXACTLY-once — no
        replay at all, stronger than the streaming loader's row-group watermark.
        Duck-types for :mod:`petastorm_tpu.checkpoint` like the other loaders.

        A pending restored cursor (``load_state_dict`` before the first batch) is
        returned as-is — saving immediately after restoring must not forget the
        restore point. After a pass completes, the cursor points past its last
        epoch (an exhausted stream restores to an empty one — correct); a
        RE-iteration is a new pass and resets the cursor when it starts."""
        epoch, batch = self._resume if self._resume is not None else self._pos
        return {"inmem": True, "epoch": int(epoch), "batch": int(batch),
                "seed": self._seed, "shuffle": bool(self.shuffle),
                "rows": int(self.rows), "batch_size": int(self.batch_size),
                "last_batch": self.last_batch,
                "num_epochs": None if self.num_epochs is None
                else int(self.num_epochs)}

    def load_state_dict(self, state):
        """Resume a same-config loader at a saved cursor (before iterating)."""
        if not state.get("inmem"):
            raise ValueError(
                "not an InMemDataLoader state (checkpoint from a streaming loader/"
                "reader? restore it into the matching object)")
        mismatches = {
            k: (state.get(k), have) for k, have in (
                ("seed", self._seed), ("shuffle", bool(self.shuffle)),
                ("rows", int(self.rows)), ("batch_size", int(self.batch_size)),
                ("last_batch", self.last_batch),
                # a shorter num_epochs would silently serve NOTHING when the
                # cursor's epoch is past it — a different finite stream entirely
                ("num_epochs", self.num_epochs),
            ) if state.get(k) != have
        }
        if mismatches:
            raise ValueError(
                "InMemDataLoader state does not match this loader's stream config "
                "(saved vs built): %s — a different config is a different epoch "
                "stream, and resuming would serve wrong rows" % (mismatches,))
        self._resume = (int(state["epoch"]), int(state["batch"]))
        return self

    @property
    def cur_shard(self):
        """Per-process routing key for pod checkpoints (process index: each process
        serves its own resident shard)."""
        import jax

        return jax.process_index() if self._multiprocess else None

    def __len__(self):
        if self._multiprocess:
            return self._batches_per_epoch
        full, rem = divmod(self.rows, self.batch_size)
        return full + (1 if rem and self.last_batch == "partial" else 0)

    def __iter__(self):
        import jax
        import jax.numpy as jnp

        resume, self._resume = self._resume, None
        epoch = resume[0] if resume else 0
        skip = resume[1] if resume else 0  # batches to skip in the FIRST epoch only
        # a fresh pass resets the cursor: without this, a checkpoint taken early in
        # a RE-iteration would carry the previous pass's end-of-stream position
        self._pos = (epoch, skip)
        takes_key = False
        if self._device_transform is not None:
            import inspect

            try:
                takes_key = len(inspect.signature(
                    self._device_transform).parameters) >= 2
            except (TypeError, ValueError):
                takes_key = False
        while self.num_epochs is None or epoch < self.num_epochs:
            # absolute step (for the transform's rng fold) is position-derived so a
            # resumed stream folds the SAME keys an uninterrupted run would
            per_epoch = len(self)
            if self._multiprocess:
                yield from self._multiprocess_epoch(epoch, takes_key,
                                                    epoch * per_epoch, skip)
                epoch += 1
                skip = 0
                continue
            if self.shuffle:
                key = jax.random.fold_in(jax.random.PRNGKey(self._seed), epoch)
                perm = jax.random.permutation(key, self.rows)
            else:
                perm = jnp.arange(self.rows)
            for bidx, start in enumerate(range(0, self.rows, self.batch_size)):
                if bidx < skip:
                    continue
                idx = perm[start:start + self.batch_size]
                if len(idx) < self.batch_size and self.last_batch == "drop":
                    break
                t_g = time.perf_counter()
                batch = self._gather(self._store, idx)
                if self._sharding is not None:
                    # shard the short final batch too when its row count divides the
                    # sharding's batch axis; otherwise it stays on the gather's layout
                    # (documented: a pjit'd step with fixed in_shardings will see one
                    # differently-laid-out tail batch — use last_batch='drop' there).
                    # Divisibility is checked explicitly — a blanket except would
                    # misreport genuine sharding bugs (rank/spec mismatch) as a
                    # tail-batch artifact and transfer columns only to discard them.
                    if len(idx) % _batch_shard_count(self._sharding) == 0:
                        batch = {k: jax.device_put(v, _matching_sharding(self._sharding, v))
                                 for k, v in batch.items()}
                    else:
                        logger.warning(
                            "InMemDataLoader: final partial batch (%d rows) does not "
                            "divide the sharding's batch axis; yielded unsharded",
                            len(idx))
                if self._trace is not None:
                    # span covers gather + layout dispatch — the same serving work
                    # the multi-process path's span covers (gather + assembly)
                    self._trace.add("inmem.gather", t_g, time.perf_counter() - t_g)
                batch = self._apply_transform(batch, epoch * per_epoch + bidx,
                                              takes_key)
                self._pos = (epoch, bidx + 1)
                yield batch
            epoch += 1
            skip = 0

    def _multiprocess_epoch(self, epoch, takes_key, step0, skip=0):
        """One epoch under multi-process JAX: per-process local permutation gathers,
        each assembled into a global jax.Array from the device-resident local share
        (no host round trip — same path the streaming loader's decode assembly uses)."""
        import jax
        import jax.numpy as jnp

        if self.shuffle:
            # fold the process index so shard orders decorrelate across processes
            key = jax.random.fold_in(
                jax.random.fold_in(jax.random.PRNGKey(self._seed), epoch),
                jax.process_index() + 1)
            perm = jax.random.permutation(key, self._local_rows)
        else:
            perm = jnp.arange(self._local_rows)
        for b in range(skip, self._batches_per_epoch):
            idx = perm[b * self.local_batch_size:(b + 1) * self.local_batch_size]
            t_g = time.perf_counter()
            local = self._gather(self._store, idx)
            batch = {}
            for k, v in local.items():
                s = self._sharding.get(k) if isinstance(self._sharding, dict) \
                    else _matching_sharding(self._sharding, v)
                if s is None:
                    batch[k] = v  # field without a declared layout stays local
                else:
                    batch[k] = jax.make_array_from_process_local_data(s, v)
            if self._trace is not None:
                # gather + global assembly dispatch: the per-batch serving cost
                self._trace.add("inmem.gather", t_g, time.perf_counter() - t_g)
            batch = self._apply_transform(batch, step0 + b, takes_key)
            self._pos = (epoch, b + 1)
            yield batch

    def _apply_transform(self, batch, step, takes_key):
        if self._device_transform is None:
            return batch
        import jax

        if self._jitted_transform is None:
            self._jitted_transform = jax.jit(self._device_transform)
        if takes_key:
            tkey = jax.random.fold_in(jax.random.PRNGKey(self._seed + 1), step)
            return self._jitted_transform(batch, tkey)
        return self._jitted_transform(batch)

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self._store = None  # release HBM


_UNSET = object()

#: DataLoader keyword parameters make_dataloader forwards when explicitly given —
#: defaults stay defined ONCE, on DataLoader.__init__ (they'd silently drift if
#: re-stated here).
_LOADER_OPTS = ("last_batch", "device_transform", "prefetch", "pad_shapes",
                "device_shuffle_capacity", "to_device", "host_queue_size",
                "device_decode_resize", "trace", "metrics", "health", "staging",
                "provenance", "slos", "controller")


def make_dataloader(dataset_url_or_urls, batch_size, sharding=None, num_epochs=1,
                    shuffling_queue_capacity=0, reader_factory=None,
                    last_batch=_UNSET, device_transform=_UNSET, prefetch=_UNSET,
                    pad_shapes=_UNSET, device_shuffle_capacity=_UNSET,
                    to_device=_UNSET, host_queue_size=_UNSET,
                    device_decode_resize=_UNSET, trace=_UNSET, metrics=_UNSET,
                    health=_UNSET, staging=_UNSET, provenance=_UNSET,
                    slos=_UNSET, controller=_UNSET, **reader_kwargs):
    """One-call convenience: ``make_batch_reader`` + :class:`DataLoader`.

    ``reader_kwargs`` pass through to :func:`petastorm_tpu.reader.make_batch_reader`
    (or ``reader_factory`` when given); the explicit loader parameters mirror
    :class:`DataLoader` (defaults are DataLoader's — only explicitly-passed values
    are forwarded). Under multi-process JAX, ``cur_shard``/``shard_count`` default
    to ``jax.process_index()``/``jax.process_count()``.
    """
    from petastorm_tpu.reader import make_batch_reader

    factory = reader_factory or make_batch_reader
    if "cur_shard" not in reader_kwargs:
        try:
            import jax

            if jax.process_count() > 1:
                reader_kwargs["cur_shard"] = jax.process_index()
                reader_kwargs["shard_count"] = jax.process_count()
        except Exception as e:  # noqa: BLE001 — jax optional for host-only use
            logger.debug("jax process topology unavailable (%s); reader "
                         "sharding left to explicit kwargs", e)
    reader = factory(dataset_url_or_urls, num_epochs=num_epochs, **reader_kwargs)
    seed = reader_kwargs.get("seed")
    if seed is None:
        seed = reader_kwargs.get("shard_seed")
    passed = locals()
    loader_kwargs = {name: passed[name] for name in _LOADER_OPTS
                     if passed[name] is not _UNSET}
    return DataLoader(reader, batch_size, sharding=sharding,
                      shuffling_queue_capacity=shuffling_queue_capacity, seed=seed,
                      **loader_kwargs)
