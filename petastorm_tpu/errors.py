"""Framework exceptions (reference: petastorm/errors.py, petastorm/workers_pool/__init__.py)."""

#: OSError subclasses that are REAL answers, not connection trouble — IO-retry and
#: HDFS-failover machinery must never retry these (a missing file or bad permissions
#: will not heal; an InterruptedError that escapes PEP-475 auto-retry is deliberate).
PERMANENT_IO_ERRORS = (FileNotFoundError, PermissionError, IsADirectoryError,
                       NotADirectoryError, FileExistsError, InterruptedError)


class PetastormTpuError(Exception):
    """Base class for framework errors."""


class DecodeFieldError(PetastormTpuError):
    """Raised when a codec fails to decode a stored field value."""


class NoDataAvailableError(PetastormTpuError):
    """Raised when a reader has no row groups to read after filtering/sharding."""


class EmptyResultError(PetastormTpuError):
    """Results queue empty and epochs exhausted (reference: workers_pool/__init__.py)."""


class TimeoutWaitingForResultError(PetastormTpuError):
    """No worker produced a result within the configured timeout."""


class MetadataError(PetastormTpuError):
    """Dataset metadata missing or malformed (reference: PetastormMetadataError)."""


class LeaseError(PetastormTpuError):
    """Broken lease discipline on a :class:`petastorm_tpu.io.lease.Lease` —
    releasing past a zero refcount (double release) or retaining a lease whose
    buffers were already returned to their owner. Always a caller bug: the
    lease contract is exactly-once release per retain (graftlint GL-L001
    checks the straight-line cases statically)."""


class LeaseRevoked(PetastormTpuError):
    """The buffers behind a lease were invalidated by their owner — e.g. a
    ``Reader.reset()`` rebuilt the executor whose slab ring backed a still-
    retained batch. Raised by lease-aware accessors instead of returning
    views into recycled memory: a consumer holding a batch across a revocation
    gets this error, never garbage."""


class WorkerDiedError(PetastormTpuError, RuntimeError):
    """A pool worker process died and the elastic-recovery budget
    (``worker_respawns`` / ``RecoveryOptions.worker_respawns``) is exhausted.
    Carries the ORIGINAL child failure as ``__cause__`` (and ``original``), so
    the consumer sees what actually killed the children — not a generic pool
    error. With ``RecoveryOptions(on_poison="quarantine")`` a single item that
    repeatedly kills children is skipped (see
    :class:`petastorm_tpu.recovery.QuarantineReport`) before it can exhaust
    the budget."""

    def __init__(self, message, original=None):
        super().__init__(message)
        self.original = original
        if original is not None:
            self.__cause__ = original


class PieceRemovedError(FileNotFoundError):
    """A planned row-group's file disappeared between planning and read (the
    dataset mutated under a running reader — ISSUE 11). Subclasses
    ``FileNotFoundError`` so it is classified PERMANENT by the IO-retry
    machinery; under ``RecoveryOptions(on_poison="quarantine")`` the item is
    quarantined with ``cause="piece_removed"`` and charged to the checkpoint
    watermark like any other skip."""


class PieceRewrittenError(PetastormTpuError):
    """A planned row-group's file no longer matches the generation token
    stamped into its plan item (size/mtime/footer-crc mismatch — the file was
    rewritten under a running reader, ISSUE 11). Never retried as transient:
    the stamped generation is gone and re-reading would deliver rows from a
    DIFFERENT generation than the rest of the epoch. The read path invalidates
    the piece's footer/mem/disk cache entries before raising; under the
    quarantine policy the item surfaces as ``cause="piece_rewritten"``, and
    the dataset watcher re-plans the new generation into a later epoch."""


class PagedecCorruptError(PetastormTpuError):
    """A compressed-page pass-through decoder found a malformed page: a
    truncated/bit-flipped header, a payload running past its chunk, a codec
    stream that fails to inflate, or a dictionary index out of range
    (ISSUE 14). Classified PERMANENT — retrying would re-read the same bytes —
    and quarantine-eligible under the PR 7 poison policy
    (``cause="pagedec_corrupt"``). Every decoder bounds-checks before touching
    memory, so corrupt input degrades to this error, never to an
    out-of-bounds read."""


class TransportLinkDown(ConnectionResetError):
    """A framed transport link (ISSUE 15) died mid-conversation: socket error,
    clean EOF from the peer, a heartbeat-detected half-open connection, or a
    replaced socket after the peer reconnected. Subclasses
    ``ConnectionResetError`` so the process pool's existing dead-child
    machinery classifies it without new except clauses — the driver first
    offers the link a bounded ``reconnect()`` (the child redials with
    jittered backoff) and only then spends the respawn budget. The in-flight
    item re-dispatches through the PR 7 poison/quarantine path either way:
    delivered exactly once or quarantined, never twice, never lost."""


class TransportFrameCorrupt(TransportLinkDown):
    """A framed transport received a frame whose crc32 trailer (or magic/
    header) does not match its bytes — a flipped bit on the wire, or a stream
    desync. The link cannot be trusted past this point, so it is torn down
    and treated exactly like a link death (counted separately as
    ``ptpu_degradations_total{cause="transport_frame_corrupt"}`` and
    ``ptpu_net_frames_corrupt_total``): the corrupt payload is never
    delivered, the in-flight item re-dispatches on the reconnected link."""


class StallError(PetastormTpuError):
    """A pipeline actor missed its heartbeat threshold and the health monitor's
    escalation policy is ``raise`` — the training loop fails fast instead of
    silently hanging an accelerator slice. The flight record written at
    detection (``HealthOptions.flight_path``) carries the evidence: driver and
    child stacks, queue depths, recent pipeline events."""
