"""Dataset-mutation plane (ISSUE 11): generation tokens, the watch thread
that diffs the piece set of a LIVE dataset, and deterministic mutation
helpers for the chaos harness."""
from petastorm_tpu.dataset.watch import (  # noqa: F401
    DatasetWatcher,
    PlanDelta,
    WatchOptions,
    current_stat_token,
    generation_token,
    stamp_generation_tokens,
    tokens_match,
)

__all__ = [
    "DatasetWatcher",
    "PlanDelta",
    "WatchOptions",
    "current_stat_token",
    "generation_token",
    "stamp_generation_tokens",
    "tokens_match",
]
