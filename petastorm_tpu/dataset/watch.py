"""Dataset-watch plane: generation tokens + mutation detection (ISSUE 11).

Every layer built before this module assumed a frozen dataset: plans from a
one-shot footer scan, caches validated wholesale by file size, closed epochs.
The production workload ROADMAP item 5 names is append-heavy — new Parquet
files land (and old ones get rewritten or deleted) while training runs
("Optimizing High-Throughput Distributed Data Pipelines for Reproducible Deep
Learning at Scale", PAPERS.md). This module makes mutation a first-class,
*accounted* event instead of a stale-cache hazard or an unclassified crash:

- **Generation tokens**: every file gets an identity string
  ``"<size>.<mtime_ns>.<footer-crc>"`` stamped into its plan items
  (:func:`stamp_generation_tokens` → ``RowGroupPiece.generation``). The token
  rides into every read (validated per attempt), every cache key
  (mem/disk/readahead — a rewritten file maps to NEW keys, so stale decoded
  payloads are unreachable even on a size+mtime collision), the footer cache
  (``FooterEntry.stat_token``), and the stats-cache fingerprint.
- **:class:`DatasetWatcher`**: a per-reader thread that re-enumerates the
  dataset every ``interval_s``, diffs against its snapshot, and emits a
  :class:`PlanDelta` (added / removed / rewritten). The reader extends its
  :class:`~petastorm_tpu.plan.EpochPlan` with added pieces (current epoch),
  defers a rewritten file's new generation to the NEXT epoch (the
  no-mixed-generations invariant), and invalidates the removed/rewritten
  pieces' cache entries. Deltas are counted
  (``ptpu_dataset_{pieces_added,pieces_removed,pieces_rewritten,
  plan_extensions,generation_conflicts}_total``) and mirrored into any live
  flight recorder so a stall record shows the mutation timeline.
- **Chaos hook**: each watch tick evaluates the ``dataset.mutate`` site when a
  mutator is attached, so seeded ``FaultPlan`` actions
  (``remove_file``/``rewrite_file``/``append_piece`` — see
  :mod:`petastorm_tpu.dataset.mutate`) drive replayable mutation scenarios in
  CI (``petastorm-tpu-bench chaos``, the ``mutating-dataset`` scenario).

Read-time enforcement lives in :mod:`petastorm_tpu.reader`
(``_WorkerBase._verify_generation``): a deleted file raises
:class:`~petastorm_tpu.errors.PieceRemovedError`, a token mismatch raises
:class:`~petastorm_tpu.errors.PieceRewrittenError` after invalidating the
piece's footer/mem/disk entries — both quarantine under the PR-7 policy with
their own causes (``piece_removed`` / ``piece_rewritten``) charged to the
checkpoint watermark, preserving exactly-once-or-quarantined under churn.

See docs/robustness.md "Mutable datasets".
"""
from __future__ import annotations

import os
import threading

from petastorm_tpu import chaos as _chaos
from petastorm_tpu.io import _env_float

#: token part separator; a token is "<size>.<mtime_ns>.<crc8hex-or-->"
_SEP = "."


def _with_crc(stat, crc=None):
    """Full token from a stat half + optional crc — the ONE encoding point
    (the stamping path, the watcher's scan, and the read-time verifier all
    compare tokens built here)."""
    return "%s%s%s" % (stat, _SEP, ("%08x" % crc) if crc is not None else "-")


def _format_token(size, mtime_ns, crc=None):
    return _with_crc("%s%s%s" % (size, _SEP, mtime_ns), crc)


def _split(token):
    """(stat_part, crc_part) of a token string (crc_part may be '-')."""
    stat, _, crc = token.rpartition(_SEP)
    return stat, crc


def stat_token_of(token):
    """The "<size>.<mtime_ns>" half of a full generation token."""
    return _split(token)[0]


def current_stat_token(fs, path, info=None):
    """The file's CURRENT stat identity, or raises
    :class:`~petastorm_tpu.errors.PieceRemovedError` when it is gone."""
    import pyarrow.fs as pafs

    from petastorm_tpu.errors import PieceRemovedError

    if info is None:
        info = fs.get_file_info(path)
    if info.type == pafs.FileType.NotFound:
        raise PieceRemovedError(
            "dataset file removed under a running reader: %s" % path)
    mtime = getattr(info, "mtime_ns", None)
    if mtime is None:  # filesystems without ns stamps: the datetime second
        dt = getattr(info, "mtime", None)
        mtime = int(dt.timestamp() * 1e9) if dt is not None else 0
    return "%s%s%s" % (info.size, _SEP, mtime)


def generation_token(fs, path, footer_crc=True, info=None, fresh=False):
    """The file's full generation token: stat identity plus (optionally) the
    footer-metadata crc, resolved through the shared footer cache pinned to
    exactly this stat identity — a stale same-size parse can never leak in.

    ``fresh=True`` drops any cached footer first: the one hole stat-pinning
    cannot close is a rewrite that collides on size AND mtime while a parse
    of the predecessor is still resident — reader construction pays one
    footer re-read per file to stamp tokens that describe the bytes as they
    are NOW."""
    stat = current_stat_token(fs, path, info=info)
    if not footer_crc:
        return _format_token(*stat.split(_SEP))
    from petastorm_tpu.io.footercache import shared_footer_cache

    footers = shared_footer_cache()
    if fresh:
        footers.invalidate(path)
    entry = footers.get(fs, path, stat_token=stat)
    return _with_crc(stat, entry.crc)


def tokens_match(stamped, observed):
    """Do two generation tokens identify the same file generation?

    ``None`` on either side means "unknown" and matches (no enforcement
    possible); a ``'-'`` crc half matches any crc (stat-only tokens)."""
    if stamped is None or observed is None:
        return True
    if stamped == observed:
        return True
    a_stat, a_crc = _split(stamped)
    b_stat, b_crc = _split(observed)
    if a_stat != b_stat:
        return False
    return a_crc == "-" or b_crc == "-" or a_crc == b_crc


def stamp_generation_tokens(fs, pieces, footer_crc=True):
    """Return ``pieces`` with each one's ``generation`` field stamped (one
    stat + one FRESH footer parse per unique path — a resident parse of a
    stat-colliding predecessor must not vouch for the current bytes). A path
    that cannot be tokenized (vanished mid-stamp, unreadable footer) keeps
    ``generation=None`` — its reads proceed unvalidated and fail on their own
    terms."""
    tokens = {}
    out = []
    for piece in pieces:
        tok = tokens.get(piece.path)
        if tok is None and piece.path not in tokens:
            try:
                tok = generation_token(fs, piece.path, footer_crc=footer_crc,
                                       fresh=True)
            except Exception as e:  # noqa: BLE001 — stamping is best-effort
                from petastorm_tpu.obs.log import degradation

                degradation(
                    "watch_error",
                    "could not stamp a generation token for %s (%s); reads of "
                    "it proceed unvalidated", piece.path, e)
                tok = None
            tokens[piece.path] = tok
        out.append(piece._replace(generation=tok) if tok is not None
                   else piece)
    return out


class WatchOptions:
    """Knobs for the dataset-watch plane (``watch=`` on the reader factories:
    ``True``/dict/instance — same normalize contract as ``IoOptions``).

    ==============  ========================  ===============================
    field           env var                   meaning
    ==============  ========================  ===============================
    interval_s      PTPU_WATCH_INTERVAL_S     seconds between watch ticks
                                              (default 5.0)
    footer_crc      PTPU_WATCH_FOOTER_CRC     include the footer-metadata crc
                                              in generation tokens (default
                                              on; off = stat-only tokens, one
                                              less footer read per file)
    ==============  ========================  ===============================
    """

    __slots__ = ("interval_s", "footer_crc")

    def __init__(self, interval_s=None, footer_crc=None):
        self.interval_s = max(0.05, _env_float("PTPU_WATCH_INTERVAL_S", 5.0)
                              if interval_s is None else float(interval_s))
        if footer_crc is None:
            footer_crc = (os.environ.get("PTPU_WATCH_FOOTER_CRC", "1")
                          not in ("0", "false", "no"))
        self.footer_crc = bool(footer_crc)

    @classmethod
    def normalize(cls, value):
        """``None``/``False`` → None (watching off), ``True`` → defaults,
        dict → kwargs, instance → itself."""
        if value is None or value is False:
            return None
        if value is True:
            return cls()
        if isinstance(value, cls):
            return value
        if isinstance(value, dict):
            return cls(**value)
        raise TypeError("watch must be a WatchOptions, a dict of its fields, "
                        "True/False, or None; got %r" % type(value).__name__)


class PlanDelta:
    """One watch tick's observed mutations.

    ``added``: new files' pieces (stamped). ``removed``: ``(path,
    old_pieces)``. ``rewritten``: ``(path, old_pieces, new_pieces)`` — the old
    generation's pieces (for invalidation) and the new generation's stamped
    replacements (for deferred re-planning)."""

    __slots__ = ("added", "removed", "rewritten")

    def __init__(self, added=(), removed=(), rewritten=()):
        self.added = list(added)
        self.removed = list(removed)
        self.rewritten = list(rewritten)

    def __bool__(self):
        return bool(self.added or self.removed or self.rewritten)

    def __repr__(self):
        return "<PlanDelta +%d pieces, -%d files, ~%d files>" % (
            len(self.added), len(self.removed), len(self.rewritten))


_metrics_lock = threading.Lock()
_metrics = None


def watch_metrics():
    """The ``ptpu_dataset_*`` counter family (resolved once per process)."""
    global _metrics
    if _metrics is None:
        with _metrics_lock:
            if _metrics is None:
                from petastorm_tpu.obs.metrics import default_registry

                reg = default_registry()
                _metrics = {
                    "pieces_added": reg.counter(
                        "ptpu_dataset_pieces_added_total",
                        help="row-group pieces discovered by the dataset "
                             "watcher and appended to a live plan"),
                    "pieces_removed": reg.counter(
                        "ptpu_dataset_pieces_removed_total",
                        help="row-group pieces whose file disappeared under "
                             "a running reader"),
                    "pieces_rewritten": reg.counter(
                        "ptpu_dataset_pieces_rewritten_total",
                        help="row-group pieces whose file changed generation "
                             "under a running reader"),
                    "plan_extensions": reg.counter(
                        "ptpu_dataset_plan_extensions_total",
                        help="EpochPlan.extend calls applied by the watcher"),
                    "generation_conflicts": reg.counter(
                        "ptpu_dataset_generation_conflicts_total",
                        help="reads that found a generation-token mismatch "
                             "(file rewritten between plan and read)"),
                }
    return _metrics


class DatasetWatcher:
    """Polls a dataset for piece-set mutations and reports :class:`PlanDelta`\\ s.

    One per watching :class:`~petastorm_tpu.reader.Reader` (the reader primes
    it with the factory's stamped pieces and wires ``on_delta`` to its
    plan-extension seam). The scan enumerates REAL files — not the write-time
    KV row-group counts, which never learn about appended files — and reads
    footers only for new/changed paths (unchanged stat identities reuse the
    previous tick's pieces), so a quiet tick costs one listing plus one stat
    per file.

    The ``dataset.mutate`` chaos hook site is evaluated at the top of each
    tick **when a mutator is attached** (:meth:`set_mutator` — the chaos
    harness's seam; see :mod:`petastorm_tpu.dataset.mutate`), so seeded
    mutation scenarios count ticks deterministically from the moment the
    harness arms them.
    """

    def __init__(self, fs, path, options=None, on_delta=None):
        if isinstance(path, list):
            raise ValueError("dataset watching supports a single dataset "
                             "path, got a list of %d" % len(path))
        self._fs = fs
        self._path = path
        self._opts = options if options is not None else WatchOptions()
        self._on_delta = on_delta
        self._snapshot = None  # path -> (token, [pieces])
        self._mutator = None
        self._thread = None
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._ticks = 0
        self._deltas = 0
        self._errors = 0

    # -- lifecycle ----------------------------------------------------------------------

    def start(self):
        """Start (or restart after :meth:`stop`) the watch thread."""
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return
            self._stop.clear()
            self._thread = threading.Thread(target=self._run, daemon=True,
                                            name="ptpu-dataset-watch")
            self._thread.start()

    def stop(self):
        with self._lock:
            thread, self._thread = self._thread, None
        self._stop.set()
        if thread is not None:
            thread.join(timeout=30.0)

    def _run(self):
        while not self._stop.wait(self._opts.interval_s):
            self.poll_once()

    # -- wiring -------------------------------------------------------------------------

    def set_mutator(self, mutator):
        """Attach the chaos harness's dataset mutator: from the next tick on,
        the ``dataset.mutate`` hook site is evaluated with it as the payload
        (seeded ``remove_file``/``rewrite_file``/``append_piece`` actions)."""
        self._mutator = mutator

    def prime(self, pieces, known_paths=None):
        """Seed the snapshot from already-stamped plan pieces (the factory's
        initial scan) so the first tick diffs against the plan, not a rescan.

        ``known_paths``: every dataset file that existed at plan time —
        including ones plan-time pruning (filters / predicate / hive
        partitions / row-group selectors) kept OUT of the plan. Those enter
        the snapshot as inert sentinels, so the first tick does not
        misclassify them as appended and re-add what the user's selection
        excluded; they stay unwatched (a rewrite of an unplanned file is not
        this reader's business)."""
        snapshot = {}
        for piece in pieces:
            tok, existing = snapshot.get(piece.path, (piece.generation, []))
            existing.append(piece)
            snapshot[piece.path] = (tok, existing)
        for path in known_paths or ():
            snapshot.setdefault(path, (None, []))
        self._snapshot = snapshot

    # -- one tick -----------------------------------------------------------------------

    def poll_once(self):
        """One watch tick: chaos hook, rescan, diff, account, notify.
        Returns the :class:`PlanDelta` (empty on a quiet tick) or ``None``
        when the tick failed (counted + logged as ``watch_error``)."""
        self._ticks += 1
        if _chaos.ACTIVE is not None and self._mutator is not None:
            try:
                _chaos.ACTIVE.hit("dataset.mutate", key="tick=%d" % self._ticks,
                                  payload=self._mutator)
            except Exception as e:  # noqa: BLE001 — a bad mutate rule must not
                # kill the watch thread; the scenario sees it in the log
                self._errors += 1
                from petastorm_tpu.obs.log import degradation

                degradation("watch_error",
                            "dataset.mutate chaos action failed: %s", e,
                            once=False)
        try:
            current = self._scan()
        except Exception as e:  # noqa: BLE001 — a failed listing is a tick
            # lost, not a dead watcher: object-store listings flake
            self._errors += 1
            from petastorm_tpu.obs.log import degradation

            degradation("watch_error",
                        "dataset watch scan of %s failed (%s); retrying next "
                        "tick", self._path, e, once=False)
            return None
        previous, self._snapshot = self._snapshot, current
        if previous is None:
            return PlanDelta()
        delta = self._diff(previous, current)
        if delta:
            self._deltas += 1
            self._account(delta)
            if self._on_delta is not None:
                try:
                    self._on_delta(delta)
                except Exception as e:  # noqa: BLE001 — the reader seam must
                    # not kill the watch thread; surfaced like a scan failure
                    self._errors += 1
                    from petastorm_tpu.obs.log import degradation

                    degradation("watch_error",
                                "applying a dataset PlanDelta failed: %s", e,
                                once=False)
        return delta

    def _scan(self):
        """``{path: (token, [pieces])}`` of the dataset as it exists NOW."""
        import pyarrow.fs as pafs

        from petastorm_tpu.metadata import RowGroupPiece, _list_parquet_files
        from petastorm_tpu.partitions import partition_values_for_path

        out = {}
        snapshot = self._snapshot or {}
        for full in _list_parquet_files(self._fs, self._path):
            prev = snapshot.get(full)
            if prev is not None and prev[0] is None:
                # plan-time-pruned sentinel: the user's selection excluded
                # this file — stays inert (no stat, no footer, no deltas)
                out[full] = prev
                continue
            info = self._fs.get_file_info(full)
            if info.type == pafs.FileType.NotFound:
                continue  # raced a deletion between listing and stat
            stat = current_stat_token(self._fs, full, info=info)
            if prev is not None and stat_token_of(prev[0] or "") == stat:
                out[full] = prev  # unchanged: reuse last tick's pieces
                continue
            from petastorm_tpu.io.footercache import shared_footer_cache

            footers = shared_footer_cache()
            entry = footers.get(self._fs, full, stat_token=stat)
            tok = _with_crc(stat, entry.crc if self._opts.footer_crc else None)
            pv = partition_values_for_path(full, self._path) or None
            pieces = [RowGroupPiece(full, rg, entry.row_group_rows[rg], pv,
                                    None, tok)
                      for rg in range(entry.num_row_groups)]
            out[full] = (tok, pieces)
        return out

    @staticmethod
    def _diff(previous, current):
        added, removed, rewritten = [], [], []
        for path, (tok, pieces) in current.items():
            prev = previous.get(path)
            if prev is None:
                added.extend(pieces)
            elif not tokens_match(prev[0], tok):
                rewritten.append((path, prev[1], pieces))
        for path, (tok, pieces) in previous.items():
            if path not in current:
                removed.append((path, pieces))
        return PlanDelta(added, removed, rewritten)

    def _account(self, delta):
        metrics = watch_metrics()
        if delta.added:
            metrics["pieces_added"].inc(len(delta.added))
        removed = sum(len(pieces) for _p, pieces in delta.removed)
        if removed:
            metrics["pieces_removed"].inc(removed)
        rewritten = sum(len(old) for _p, old, _new in delta.rewritten)
        if rewritten:
            metrics["pieces_rewritten"].inc(rewritten)
        from petastorm_tpu.obs import flight as _flight

        for recorder in _flight.active_recorders():
            recorder.record(
                "dataset_watch", tick=self._ticks, added=len(delta.added),
                removed=[p for p, _ in delta.removed],
                rewritten=[p for p, _o, _n in delta.rewritten])
        from petastorm_tpu.obs.log import degradation

        if delta.removed or delta.rewritten:
            # informational but countable: the mutation itself is not a
            # failure — the per-piece consequences surface as their own
            # piece_removed/piece_rewritten causes at read time
            degradation(
                "dataset_mutated",
                "dataset watch observed +%d piece(s), -%d file(s), ~%d "
                "rewritten file(s) under a running reader", len(delta.added),
                len(delta.removed), len(delta.rewritten), once=False)

    def stats(self):
        """Live gauges for ``Reader.io_stats()`` / the bench harness."""
        return {
            "watch_ticks": self._ticks,
            "watch_deltas": self._deltas,
            "watch_errors": self._errors,
        }
