"""Deterministic local-dataset mutations for chaos scenarios (ISSUE 11).

:class:`LocalDatasetMutator` is the payload object the ``dataset.mutate``
chaos hook site hands to the ``remove_file`` / ``rewrite_file`` /
``append_piece`` :class:`~petastorm_tpu.chaos.FaultRule` actions: each action
calls the method of the same name with the rule's JSON ``target`` spec, so a
seeded :class:`~petastorm_tpu.chaos.FaultPlan` replays the exact same
mutation sequence at the exact same watch ticks every run.

Targets are plain dicts (they cross the FaultRule JSON round trip):

- ``remove_file``:  ``{"name": "part_003.parquet"}``
- ``rewrite_file``: ``{"name": "part_003.parquet", "start": 10**6,
  "rows": 64}`` — atomically replaces the file (write-temp + ``os.replace``)
  with a fresh generation whose ``id`` column covers ``[start, start+rows)``
- ``append_piece``: same spec, but the name must be new; by convention
  scenario files sort AFTER the initial ``part_*`` names (e.g.
  ``part_zz0.parquet``) so ordinal identity survives a plan rebuild on resume

The default table builder writes the chaos harness's ``{id: int64, x:
float64}`` schema with a seeded rng; pass ``table_fn(start, rows)`` for other
schemas. Local filesystems only — this is a test/bench utility, not a data
tool.
"""
from __future__ import annotations

import os


class LocalDatasetMutator:
    """Applies deterministic file mutations under a local dataset root."""

    def __init__(self, root, seed=0, table_fn=None):
        self._root = str(root)
        self._seed = int(seed)
        self._table_fn = table_fn
        self._applied = []  # (action, name) in application order

    def _build_table(self, start, rows):
        if self._table_fn is not None:
            return self._table_fn(start, rows)
        import numpy as np
        import pyarrow as pa

        rng = np.random.default_rng(self._seed + int(start))
        return pa.table({
            "id": np.arange(start, start + rows, dtype=np.int64),
            "x": rng.random(int(rows)),
        })

    def _write(self, name, start, rows):
        import pyarrow.parquet as pq

        table = self._build_table(int(start), int(rows))
        full = os.path.join(self._root, name)
        tmp = full + ".tmp-mutate"
        pq.write_table(table, tmp, row_group_size=table.num_rows)
        os.replace(tmp, full)  # atomic: readers see old bytes or new, never half

    # -- the chaos action surface -------------------------------------------------------

    def remove_file(self, target):
        name = target["name"] if isinstance(target, dict) else str(target)
        os.remove(os.path.join(self._root, name))
        self._applied.append(("remove_file", name))

    def rewrite_file(self, target):
        self._write(target["name"], target["start"], target["rows"])
        self._applied.append(("rewrite_file", target["name"]))

    def append_piece(self, target):
        full = os.path.join(self._root, target["name"])
        if os.path.exists(full):
            raise FileExistsError(
                "append_piece target already exists: %s" % full)
        self._write(target["name"], target["start"], target["rows"])
        self._applied.append(("append_piece", target["name"]))

    @property
    def applied(self):
        """``[(action, name), ...]`` in application order (scenario asserts)."""
        return list(self._applied)
