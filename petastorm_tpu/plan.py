"""Deterministic epoch planning: sharding, per-epoch shuffling, resumable cursor.

This replaces the reference's runtime scheduler state (petastorm/workers_pool/ventilator.py ~L60
``ConcurrentVentilator``: per-epoch reshuffle, ``iterations`` epochs, item feed) with a **pure
function of (seed, epoch, shard)** — the TPU-idiomatic design: every host computes the same global
plan and takes its slice by ``jax.process_index()``, so multi-host data parallelism needs zero
runtime communication (same property the reference gets from ``cur_shard``/``shard_count``,
petastorm/reader.py ~L470) and any position is checkpointable/resumable as a tiny state dict —
the checkpoint/resume upgrade SURVEY.md §6 calls for (the reference has none).
"""
from __future__ import annotations

import threading

import numpy as np


def shard_indices(num_items, cur_shard, shard_count, shard_seed=None):
    """Deterministic round-robin partition of ``range(num_items)`` for one shard.

    Matches reference semantics (petastorm/reader.py ~L470 ``_apply_row_drop_partition``
    neighborhood): optional seeded global permutation, then round-robin. Every shard computes
    the same permutation, so shards are disjoint and their union is exact.
    """
    if shard_count is None:
        return np.arange(num_items)
    if not (0 <= cur_shard < shard_count):
        raise ValueError(
            "cur_shard must be in [0, %d), got %r" % (shard_count, cur_shard)
        )
    order = np.arange(num_items)
    if shard_seed is not None:
        order = np.random.Generator(np.random.PCG64(shard_seed)).permutation(num_items)
    return order[cur_shard::shard_count]


def epoch_permutation(num_items, epoch, seed, shuffle):
    """Permutation of ``range(num_items)`` for one epoch; identity when not shuffling.

    Seeded by (seed, epoch) so every host derives the identical order with no communication.
    """
    if not shuffle:
        return np.arange(num_items)
    seq = np.random.SeedSequence([0 if seed is None else int(seed), int(epoch)])
    return np.random.Generator(np.random.PCG64(seq)).permutation(num_items)


class EpochPlan:
    """Resumable iterator over item indices across epochs.

    ``num_epochs=None`` means infinite (reference ``num_epochs=None`` contract). State is
    (epoch, position); :meth:`state_dict`/:meth:`load_state_dict` checkpoint it exactly.

    The plan is **extensible** (ISSUE 11): :meth:`extend` appends newly
    discovered items mid-run — either into the CURRENT epoch (appended files)
    or deferred to the NEXT epoch (``defer=True``, rewritten files whose new
    generation must not mix with the old one inside an epoch). Extension is
    thread-safe against iteration (the dataset watcher extends from its own
    thread), and :meth:`items_in_epoch` reports how many items belong to each
    epoch so the reader's consumed-ordinal watermark stays exact across
    extensions.
    """

    def __init__(self, items, num_epochs=1, shuffle=False, seed=None, with_epoch=False,
                 skip=None):
        """``with_epoch=True`` yields ``(epoch, ordinal, item)`` instead of ``item`` (lets a
        consumer tag in-flight work with its dispatch epoch for exact resume). ``skip``: optional
        ``{epoch: set(item_key)}`` of already-consumed work to omit, where item_key is
        ``items.index``-positional ordinal."""
        self._items = list(items)
        if num_epochs is not None and (not isinstance(num_epochs, int) or num_epochs < 1):
            raise ValueError("num_epochs must be a positive integer or None, got %r" % num_epochs)
        self._num_epochs = num_epochs
        self._shuffle = shuffle
        self._seed = seed
        self._with_epoch = with_epoch
        self._skip = {int(k): set(v) for k, v in (skip or {}).items()}
        self._epoch = 0
        self._pos = 0
        self._perm = epoch_permutation(len(self._items), 0, seed, shuffle)
        #: cumulative extension ledger: ``(birth_epoch, item_count)`` — the
        #: initial items are born at epoch 0; each extend() appends one entry.
        #: Drives items_in_epoch() (the reader's per-epoch watermark size).
        self._births = [(0, len(self._items))]
        self._lock = threading.Lock()

    def __getstate__(self):
        state = dict(self.__dict__)
        state["_lock"] = None  # not picklable; recreated on setstate
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._lock = threading.Lock()

    @property
    def items(self):
        return self._items

    @property
    def epoch(self):
        return self._epoch

    def __iter__(self):
        return self

    def _step(self, epoch, pos, perm):
        """Advance the cursor triple by ONE raw position (skip map not yet
        applied): returns ``(yield_epoch, ordinal, epoch, pos, perm)``. The
        single copy of the rollover/reshuffle algorithm — :meth:`__next__`
        mutates the instance cursor with it, :meth:`peek` walks a detached
        copy, so the two cannot drift."""
        yield_epoch = epoch
        ordinal = int(perm[pos])
        pos += 1
        # rollover checks the PERMUTATION length, not the item count: a
        # deferred extension (ISSUE 11) grows _items without touching the
        # current epoch's perm — those items first appear in the next epoch's
        # full permutation
        if pos >= len(perm):
            pos = 0
            epoch += 1
            if self._num_epochs is None or epoch < self._num_epochs:
                perm = epoch_permutation(
                    len(self._items), epoch, self._seed, self._shuffle
                )
        return yield_epoch, ordinal, epoch, pos, perm

    def __next__(self):
        with self._lock:
            while True:
                if not self._items:
                    raise StopIteration
                if self._num_epochs is not None and self._epoch >= self._num_epochs:
                    raise StopIteration
                epoch, ordinal, self._epoch, self._pos, self._perm = \
                    self._step(self._epoch, self._pos, self._perm)
                if self._skip and ordinal in self._skip.get(epoch, ()):
                    continue
                item = self._items[ordinal]
                if self._with_epoch:
                    return (epoch, ordinal, item)
                return item

    def peek(self, n):
        """The next ``n`` yields of :meth:`__next__` WITHOUT advancing the
        cursor — the readahead layer's lookahead window (ISSUE 4): a
        synchronous executor prefetches ``plan.peek(depth)`` while the current
        item decodes. Same ``_step`` advance as ``__next__`` (skip map, epoch
        roll-over, per-epoch reshuffle); returns fewer than ``n`` items when
        the plan is nearly exhausted."""
        out = []
        with self._lock:
            if not self._items:
                return out
            epoch, pos, perm = self._epoch, self._pos, self._perm
            while len(out) < n:
                if self._num_epochs is not None and epoch >= self._num_epochs:
                    break
                yield_epoch, ordinal, epoch, pos, perm = self._step(epoch, pos, perm)
                if self._skip and ordinal in self._skip.get(yield_epoch, ()):
                    continue
                item = self._items[ordinal]
                out.append((yield_epoch, ordinal, item) if self._with_epoch
                           else item)
        return out

    def extend(self, new_items, defer=False):
        """Append newly discovered ``new_items`` to a live plan (ISSUE 11).

        ``defer=False`` places them in the CURRENT epoch (appended to the tail
        of the running permutation — positions already consumed are
        untouched, so nothing replays); ``defer=True`` places them in the NEXT
        epoch (a rewritten file's new generation must never mix with the old
        generation inside one epoch). Returns the ordinals assigned to the new
        items. Existing ordinals keep their identity, so consumed-ordinal
        checkpoints taken before or after an extension stay exact."""
        new_items = list(new_items)
        if not new_items:
            return []
        with self._lock:
            start = len(self._items)
            self._items.extend(new_items)
            birth = self._epoch + (1 if defer else 0)
            self._births.append((birth, len(new_items)))
            new_ords = np.arange(start, len(self._items))
            if not defer:
                ords = new_ords
                if self._shuffle:
                    seq = np.random.SeedSequence(
                        [0 if self._seed is None else int(self._seed),
                         int(self._epoch), int(start)])
                    ords = ords[np.random.Generator(
                        np.random.PCG64(seq)).permutation(len(ords))]
                self._perm = np.concatenate([self._perm, ords])
            return [int(o) for o in new_ords]

    def items_in_epoch(self, epoch):
        """How many plan items belong to ``epoch`` (items born at or before
        it) — the per-epoch denominator the reader's consumed-ordinal
        watermark advances against (a fixed ``num_items`` would wedge the
        watermark the first time an extension landed mid-run)."""
        with self._lock:
            return sum(count for birth, count in self._births
                       if birth <= epoch)

    def remaining_in_epoch(self):
        return len(self._perm) - self._pos

    def exhausted(self):
        if not self._items:
            return True
        return self._num_epochs is not None and self._epoch >= self._num_epochs

    def reset(self):
        """Restart from epoch 0 (reference ``Reader.reset()``, petastorm/reader.py ~L700).

        Every item known so far — including extension-discovered ones — is
        part of the restarted epoch 0 (births collapse: the plan replays the
        dataset as currently known)."""
        with self._lock:
            self._epoch = 0
            self._pos = 0
            self._skip = {}
            self._perm = epoch_permutation(len(self._items), 0, self._seed,
                                           self._shuffle)
            self._births = [(0, len(self._items))]

    def seek_epoch(self, epoch):
        """Jump to the start of ``epoch`` (used by consumed-aware resume)."""
        with self._lock:
            self._epoch = int(epoch)
            self._pos = 0
            self._perm = epoch_permutation(len(self._items), self._epoch,
                                           self._seed, self._shuffle)

    def set_skip(self, skip):
        """Set the {epoch: set(ordinal)} map of work to omit (consumed-aware resume)."""
        with self._lock:
            self._skip = {int(k): set(v) for k, v in (skip or {}).items()}

    # -- checkpoint/resume ---------------------------------------------------------------

    def state_dict(self):
        return {
            "epoch": self._epoch,
            "pos": self._pos,
            "seed": self._seed,
            "shuffle": self._shuffle,
            "num_epochs": self._num_epochs,
            "num_items": len(self._items),
        }

    def load_state_dict(self, state):
        # fewer items than the checkpoint saw is a real mismatch (ordinals in
        # the consumed map would dangle); MORE is legal under mutable datasets
        # (ISSUE 11): files appended after the save are simply unconsumed
        if state["num_items"] > len(self._items):
            raise ValueError(
                "Checkpoint was taken over %d items; plan has %d"
                % (state["num_items"], len(self._items))
            )
        if state["num_items"] < len(self._items) and state["shuffle"] \
                and int(state["pos"]):
            # a mid-epoch POSITION is only meaningful against the exact
            # permutation it was taken over; a grown shuffled plan derives a
            # different one, so restoring pos would replay some consumed
            # ordinals and lose some unconsumed ones. The Reader's resume is
            # immune (pos=0 + consumed-ordinal skip map) — raw-plan users
            # must go the same way.
            raise ValueError(
                "cannot restore a mid-epoch shuffled checkpoint (pos=%d) into "
                "a grown plan (%d -> %d items): the permutation changed; "
                "resume via a consumed-ordinal skip map (pos=0 + set_skip), "
                "as Reader.load_state_dict does"
                % (state["pos"], state["num_items"], len(self._items)))
        with self._lock:
            self._epoch = int(state["epoch"])
            self._pos = int(state["pos"])
            self._seed = state["seed"]
            self._shuffle = state["shuffle"]
            self._num_epochs = state["num_epochs"]
            self._perm = epoch_permutation(
                len(self._items), self._epoch, self._seed, self._shuffle
            )
            self._births = [(0, len(self._items))]
