"""Dataset metadata: materialize datasets, persist/recover schemas, enumerate row groups.

Capability parity with the reference ETL/metadata layer (petastorm/etl/dataset_metadata.py:
``materialize_dataset`` ~L60, ``get_schema`` ~L250, ``get_schema_from_dataset_url`` ~L300,
``infer_or_load_unischema`` ~L340, ``load_row_groups`` ~L150), redesigned TPU-first:

- The native write path is **pyarrow**, not Spark (:func:`write_dataset` / :func:`RowWriter`);
  a Spark-compatible :func:`materialize_dataset` contextmanager is provided for Spark jobs.
- Native schema persistence is JSON (self-describing, no pickled classes) under
  ``PTPU_SCHEMA_KEY``; the reference's pickled ``dataset-toolkit.unischema.v1`` key is still
  READ (compat unpickler) so real petastorm datasets open unmodified.
- Row-group counts are persisted per file (``PTPU_ROW_GROUPS_KEY``; reference
  ``dataset-toolkit.num_row_groups_per_file.v1`` also read) so planning never scans every footer.
"""
from __future__ import annotations

import json
import os
import posixpath
from collections import namedtuple
from contextlib import contextmanager

from petastorm_tpu.errors import MetadataError
from petastorm_tpu.fs import get_filesystem_and_path_or_paths

# Native KV keys (JSON payloads)
PTPU_SCHEMA_KEY = b"petastorm_tpu.unischema.json.v1"
PTPU_ROW_GROUPS_KEY = b"petastorm_tpu.num_row_groups_per_file.json.v1"
# Reference KV keys (pickled payloads; read-only compat) — petastorm/etl/dataset_metadata.py
REFERENCE_SCHEMA_KEY = b"dataset-toolkit.unischema.v1"
REFERENCE_ROW_GROUPS_KEY = b"dataset-toolkit.num_row_groups_per_file.v1"

_METADATA_FILES = ("_common_metadata", "_metadata")

#: One unit of scheduled work: a single row group of a single file.
#: ``partition_values``: raw ``{key: str}`` parsed from hive ``key=value`` path segments
#: (None for flat layouts) — typed/pruned by :mod:`petastorm_tpu.partitions`.
#: ``stats``: ``{column: (min, max)}`` from the parquet row-group statistics when the
#: footer was read (None on the KV fast path) — lets ``filters`` skip whole row groups
#: before scheduling (reference: ``pq.ParquetDataset`` statistics filtering).
#: ``generation``: the file's generation token (size.mtime.footer-crc — see
#: :mod:`petastorm_tpu.dataset.watch`) stamped when dataset watching is on
#: (None otherwise): reads validate it, cache keys embed it, and a mismatch at
#: read time means the file was rewritten under the running reader.
RowGroupPiece = namedtuple("RowGroupPiece", ["path", "row_group", "num_rows",
                                             "partition_values", "stats",
                                             "generation"],
                           defaults=(None, None, None))


# --------------------------------------------------------------------------------------
# Write side
# --------------------------------------------------------------------------------------


class RowWriter:
    """pyarrow-native dataset writer: encode rows through codecs, write parquet files, then
    persist schema + row-group counts in ``_common_metadata``.

    TPU-first replacement for the reference's Spark-only write path: no cluster needed to
    create a dataset (examples, tests, single-host ETL). Spark jobs use
    :func:`materialize_dataset` instead and land on the same metadata format.
    """

    def __init__(self, dataset_url, schema, row_group_size_mb=32, rows_per_file=None,
                 filesystem=None, storage_options=None, compression="snappy"):
        self._url = str(dataset_url)
        self._schema = schema
        self._row_group_bytes = int(row_group_size_mb) << 20
        self._rows_per_file = rows_per_file
        self._compression = compression
        self._fs, self._path = get_filesystem_and_path_or_paths(
            self._url, storage_options=storage_options, filesystem=filesystem
        )
        self._arrow_schema = schema.as_arrow_schema()
        self._pending = []
        self._pending_bytes = 0
        self._file_index = 0
        self._files_written = []  # (filename, row_group_count)
        self._closed = False
        self._fs.create_dir(self._path, recursive=True)

    def write(self, row_dict):
        """Encode and stage one {field: value} row."""
        from petastorm_tpu.unischema import encode_row

        encoded = encode_row(self._schema, row_dict)
        clean = {
            k: (bytes(v) if isinstance(v, bytearray) else v) for k, v in encoded.items()
        }
        self._pending.append(clean)
        self._pending_bytes += sum(len(v) for v in clean.values() if isinstance(v, bytes)) + 64
        if self._rows_per_file and len(self._pending) >= self._rows_per_file:
            self._flush_file()
        elif self._pending_bytes >= self._row_group_bytes * 4:
            self._flush_file()

    def write_many(self, rows):
        for row in rows:
            self.write(row)

    def _flush_file(self):
        if not self._pending:
            return
        import pyarrow as pa
        import pyarrow.parquet as pq

        table = pa.Table.from_pylist(self._pending, schema=self._arrow_schema)
        fname = "part-%05d.parquet" % self._file_index
        full = posixpath.join(self._path, fname)
        rows_per_group = max(1, _rows_for_bytes(table, self._row_group_bytes))
        with self._fs.open_output_stream(full) as sink:
            pq.write_table(
                table,
                sink,
                row_group_size=rows_per_group,
                compression=self._compression,
            )
        num_row_groups = -(-table.num_rows // rows_per_group)  # ceil; avoids re-reading footer
        self._files_written.append((fname, num_row_groups))
        self._file_index += 1
        self._pending = []
        self._pending_bytes = 0

    def close(self):
        if self._closed:
            return
        self._flush_file()
        write_petastorm_tpu_metadata(
            self._fs, self._path, self._schema, dict(self._files_written)
        )
        self._closed = True

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is None:
            self.close()


def write_dataset(dataset_url, schema, rows, row_group_size_mb=32, rows_per_file=None,
                  filesystem=None, storage_options=None):
    """One-shot pyarrow-native dataset write (iterable of row dicts)."""
    with RowWriter(dataset_url, schema, row_group_size_mb, rows_per_file,
                   filesystem, storage_options) as w:
        w.write_many(rows)


def write_petastorm_tpu_metadata(fs, path, schema, row_groups_per_file):
    """Write ``_common_metadata`` carrying the JSON schema + row-group counts."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    arrow_schema = schema.as_arrow_schema()
    existing = dict(arrow_schema.metadata or {})
    existing[PTPU_SCHEMA_KEY] = schema.to_json().encode("utf-8")
    existing[PTPU_ROW_GROUPS_KEY] = json.dumps(row_groups_per_file).encode("utf-8")
    tagged = arrow_schema.with_metadata(existing)
    with fs.open_output_stream(posixpath.join(path, "_common_metadata")) as sink:
        pq.write_metadata(tagged, sink)


@contextmanager
def materialize_dataset(spark, dataset_url, schema, row_group_size_mb=32,
                        filesystem_factory=None):
    """Spark-compatible materialization contextmanager (reference API name and shape kept;
    petastorm/etl/dataset_metadata.py ~L60).

    Sets ``parquet.block.size`` for row-group sizing on entry; on exit counts row groups per
    written file and writes ``_common_metadata`` with the schema. Requires pyspark.
    """
    spark_config = {}
    hadoop_conf = spark.sparkContext._jsc.hadoopConfiguration()
    key = "parquet.block.size"
    spark_config[key] = hadoop_conf.get(key)
    hadoop_conf.setInt(key, int(row_group_size_mb) << 20)
    try:
        yield
    finally:
        if spark_config[key] is None:
            hadoop_conf.unset(key)
        else:
            hadoop_conf.set(key, spark_config[key])
    fs, path = get_filesystem_and_path_or_paths(dataset_url)
    row_groups = _count_row_groups_per_file(fs, path)
    write_petastorm_tpu_metadata(fs, path, schema, row_groups)


def _count_row_groups_per_file(fs, path):
    import pyarrow.parquet as pq

    counts = {}
    for full in _list_parquet_files(fs, path):
        with fs.open_input_file(full) as f:
            counts[posixpath.relpath(full, path)] = pq.ParquetFile(f).metadata.num_row_groups
    return counts


# --------------------------------------------------------------------------------------
# Read side
# --------------------------------------------------------------------------------------


def _list_parquet_files(fs, path):
    import pyarrow.fs as pafs

    info = fs.get_file_info(path)
    if info.type == pafs.FileType.File:
        return [path]
    if info.type == pafs.FileType.NotFound:
        # fsspec find() on a missing prefix returns [] without raising — a typo'd
        # path must surface as the missing directory, not as an empty dataset
        raise FileNotFoundError("Dataset path does not exist: %r" % path)
    names = _flat_object_listing(fs, path)
    if names is None:
        selector = pafs.FileSelector(path, recursive=True)
        names = [fi.path for fi in fs.get_file_info(selector)
                 if fi.type == pafs.FileType.File]
    files = []
    for full in names:
        base = posixpath.basename(full)
        if not base.startswith(("_", ".")):
            if base.endswith((".parquet", ".parq")) or "." not in base:
                files.append(full)
    return sorted(files)


def _flat_object_listing(fs, path):
    """One flat prefix listing for fsspec-bridged object stores, or None.

    Reference parity: petastorm/gcsfs_helpers/gcsfs_fast_listing.py ~L30 — gcsfs
    emulates directories, so a recursive ``FileSelector`` walk through the
    ``FSSpecHandler`` costs one API round trip per directory (O(dirs), brutal on
    hive-partitioned / many-file layouts), while object stores can enumerate any
    prefix in a single paginated call. ``fsspec``'s ``find()`` is that call."""
    handler = getattr(fs, "handler", None)
    inner = getattr(handler, "fs", None)
    if inner is None or not hasattr(inner, "find"):
        return None
    try:
        found = inner.find(path)
    except Exception as e:  # noqa: BLE001 — fall back to the selector walk
        import logging

        logging.getLogger(__name__).debug("flat listing failed (%s); selector walk", e)
        return None
    # fsspec returns keys in the inner fs's own convention; the FSSpecHandler maps
    # paths 1:1, so they are valid for the bridged pyarrow fs as-is
    return [str(p) for p in found]


def _read_kv_metadata(fs, path):
    """Merged KV metadata from ``_common_metadata`` and ``_metadata`` if present, else None.

    Both files are consulted (keys may live in either; _common_metadata wins on conflicts).
    """
    import pyarrow.parquet as pq

    merged = None
    for meta_name in reversed(_METADATA_FILES):  # _metadata first so _common_metadata overrides
        full = posixpath.join(path, meta_name)
        try:
            with fs.open_input_file(full) as f:
                md = pq.read_schema(f).metadata
        except (FileNotFoundError, OSError):
            continue
        if md:
            merged = {**(merged or {}), **dict(md)}
        elif merged is None:
            merged = {}
    return merged


def get_schema(fs, path):
    """Recover the Unischema stored with a dataset (native JSON or reference pickle).

    Reference: petastorm/etl/dataset_metadata.py ``get_schema`` ~L250.
    """
    kv = _read_kv_metadata(fs, path)
    if kv is None:
        raise MetadataError(
            "Dataset at %r has no _common_metadata/_metadata; was it written by "
            "materialize_dataset/write_dataset? Use make_batch_reader for vanilla "
            "Parquet stores." % path
        )
    if PTPU_SCHEMA_KEY in kv:
        from petastorm_tpu.unischema import Unischema

        return Unischema.from_json(kv[PTPU_SCHEMA_KEY].decode("utf-8"))
    if REFERENCE_SCHEMA_KEY in kv:
        from petastorm_tpu.compat.reference import loads_reference_pickle

        return loads_reference_pickle(kv[REFERENCE_SCHEMA_KEY])
    raise MetadataError(
        "Dataset at %r has parquet metadata but no unischema key; use make_batch_reader "
        "or regenerate metadata (petastorm-tpu-generate-metadata)." % path
    )


def get_schema_from_dataset_url(dataset_url, storage_options=None, filesystem=None):
    """Reference API: URL → stored Unischema (~L300)."""
    fs, path = get_filesystem_and_path_or_paths(
        dataset_url, storage_options=storage_options, filesystem=filesystem
    )
    return get_schema(fs, path)


def infer_or_load_unischema(fs, path):
    """Stored Unischema if present, else infer a codec-less one from the Arrow schema.

    Reference: ``infer_or_load_unischema`` ~L340.
    """
    try:
        return get_schema(fs, path)
    except MetadataError:
        import pyarrow.parquet as pq

        from petastorm_tpu.unischema import Unischema

        files = _list_parquet_files(fs, path)
        if not files:
            raise MetadataError("No parquet files found under %r" % path)
        with fs.open_input_file(files[0]) as f:
            arrow_schema = pq.read_schema(f)
        return Unischema.from_arrow_schema(arrow_schema)


def load_row_groups(fs, path, validate=False):
    """Enumerate :class:`RowGroupPiece` work units for a dataset.

    Fast path: per-file row-group counts from KV metadata (no footer scans — reference
    ``load_row_groups`` ~L150 semantics). Fallback: open each footer. ``num_rows`` is filled
    when footers are read, else -1 (planning does not need it).
    """
    kv = _read_kv_metadata(fs, path)
    counts = None
    if kv is not None:
        if PTPU_ROW_GROUPS_KEY in kv:
            counts = json.loads(kv[PTPU_ROW_GROUPS_KEY].decode("utf-8"))
        elif REFERENCE_ROW_GROUPS_KEY in kv:
            from petastorm_tpu.compat.reference import loads_reference_pickle

            counts = loads_reference_pickle(kv[REFERENCE_ROW_GROUPS_KEY])
    from petastorm_tpu.partitions import partition_values_for_path

    pieces = []
    if counts is not None and not validate:
        for fname in sorted(counts):
            full = fname if posixpath.isabs(fname) else posixpath.join(path, fname)
            pv = partition_values_for_path(full, path) or None
            for rg in range(int(counts[fname])):
                pieces.append(RowGroupPiece(full, rg, -1, pv))
        return pieces
    # footer scan fallback (vanilla parquet stores) — parses land in the
    # shared footer cache (ISSUE 8) so the predicate-pushdown statistics read
    # here and the workers' ParquetFile opens later share ONE footer read per
    # file per process instead of one per planning pass plus one per thread
    from petastorm_tpu.io.footercache import shared_footer_cache

    footers = shared_footer_cache()
    for full in _list_parquet_files(fs, path):
        md = footers.get(fs, full).metadata
        pv = partition_values_for_path(full, path) or None
        for rg in range(md.num_row_groups):
            rgmd = md.row_group(rg)
            pieces.append(RowGroupPiece(full, rg, rgmd.num_rows, pv,
                                        _rowgroup_stats(rgmd)))
    return pieces


def _rowgroup_stats(rgmd):
    """``{column: (min, max, null_count)}`` from a row group's parquet statistics, or
    None. ``null_count`` is None when the footer does not record it.

    Only simple (non-nested) columns with valid min/max are recorded — the plan-time
    statistics pruning in ``reader._prune_by_stats`` treats absent columns as
    unconstrained, so partial stats are safe. min/max EXCLUDE nulls (parquet
    semantics), which is why the null count must ride along: ``!=``-style pruning is
    only sound when the group provably has no nulls."""
    stats = {}
    for ci in range(rgmd.num_columns):
        col = rgmd.column(ci)
        st = col.statistics
        if st is None or not st.has_min_max:
            continue
        name = col.path_in_schema
        if "." in name:  # nested columns: path is not a plain field name
            continue
        try:
            nulls = st.null_count if st.has_null_count else None
            stats[name] = (st.min, st.max, nulls)
        except Exception:  # noqa: BLE001 — exotic logical types: skip, stay safe
            continue
    return stats or None


def aggregate_column_stats(fs, pieces, columns):
    """Dataset-level ``{column: (min, max)}`` aggregated from parquet
    row-group statistics over ``pieces`` — the resolution tier declarative
    pipelines try BEFORE any data pre-pass (ISSUE 9).

    Pieces that already carry ``stats`` (footer-scan planning) are consumed
    as-is; for KV-fast-path pieces (``stats=None``) the footers are read
    through the shared footer cache — one bounded metadata read per file,
    never a data read. A column is returned only when EVERY piece contributes
    valid min/max for it (a single silent gap would make the bound wrong);
    numeric coercion failures drop the column the same way. min/max exclude
    nulls (parquet semantics) — the right bound for normalization."""
    wanted = [c for c in columns]
    if not wanted or not pieces:
        return {}
    from petastorm_tpu.io.footercache import shared_footer_cache

    footers = shared_footer_cache()
    footer_stats = {}  # path -> [per-row-group stats dict] (lazy, cached)

    def piece_stats(piece):
        if piece.stats is not None:
            return piece.stats
        per_group = footer_stats.get(piece.path)
        if per_group is None:
            md = footers.get(fs, piece.path).metadata
            per_group = footer_stats[piece.path] = [
                _rowgroup_stats(md.row_group(rg)) or {}
                for rg in range(md.num_row_groups)
            ]
        if piece.row_group >= len(per_group):
            return {}
        return per_group[piece.row_group]

    out = {}
    for piece in pieces:
        try:
            stats = piece_stats(piece)
        except Exception:  # noqa: BLE001 — unreadable footer: no bounds at all
            return {}
        for name in list(wanted):
            entry = (stats or {}).get(name)
            if entry is None:
                wanted.remove(name)
                out.pop(name, None)
                continue
            try:
                mn, mx = float(entry[0]), float(entry[1])
            except (TypeError, ValueError):  # non-numeric stats (str/bytes)
                wanted.remove(name)
                out.pop(name, None)
                continue
            prev = out.get(name)
            if prev is None:
                out[name] = (mn, mx)
            else:
                out[name] = (min(prev[0], mn), max(prev[1], mx))
        if not wanted:
            break
    return out


def _rows_for_bytes(table, target_bytes):
    """Rows per row group so groups land near ``target_bytes`` (pre-compression estimate)."""
    if table.num_rows == 0:
        return 1
    per_row = max(1, table.nbytes // table.num_rows)
    return max(1, target_bytes // per_row)
