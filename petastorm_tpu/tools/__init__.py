"""tools subpackage."""
