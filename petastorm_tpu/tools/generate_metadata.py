"""Regenerate dataset metadata (reference petastorm/etl/petastorm_generate_metadata.py ~L40
``generate_petastorm_metadata`` + console script ``petastorm-generate-metadata``).

For datasets written without ``materialize_dataset``/``RowWriter`` — or written by real
petastorm (pickled unischema is read via the compat unpickler) — rewrites
``_common_metadata`` with our JSON schema + row-group counts so ``make_reader`` works.
"""
from __future__ import annotations

import argparse
import logging

logger = logging.getLogger(__name__)


def generate_metadata(dataset_url, use_summary_metadata=True, storage_options=None,
                      filesystem=None):
    """Infer-or-recover the schema and (re)write ``_common_metadata``."""
    from petastorm_tpu.fs import get_filesystem_and_path_or_paths
    from petastorm_tpu.metadata import (
        _count_row_groups_per_file,
        infer_or_load_unischema,
        write_petastorm_tpu_metadata,
    )

    fs, path = get_filesystem_and_path_or_paths(dataset_url, storage_options, filesystem)
    schema = infer_or_load_unischema(fs, path)
    row_groups = _count_row_groups_per_file(fs, path) if use_summary_metadata else {}
    write_petastorm_tpu_metadata(fs, path, schema, row_groups)
    logger.info("Wrote metadata for %s (%d files)", dataset_url, len(row_groups))
    return schema


# reference console-script name kept as an alias
generate_petastorm_metadata = generate_metadata


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("dataset_url")
    parser.add_argument("--no-summary-metadata", action="store_true",
                        help="skip row-group counting (footers read at open instead)")
    args = parser.parse_args(argv)
    generate_metadata(args.dataset_url,
                      use_summary_metadata=not args.no_summary_metadata)


if __name__ == "__main__":
    main()
