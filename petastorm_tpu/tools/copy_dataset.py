"""Copy/subset a dataset with metadata regeneration (reference petastorm/tools/copy_dataset.py
~L40 ``copy_dataset`` + console script ``petastorm-copy-dataset``).

The reference runs a Spark job; this is pyarrow-native (row-group streaming, no cluster),
with optional pyspark acceleration left to the caller. Supports column projection, row-count
partitioning, and predicate-less filtering via ``filters``.
"""
from __future__ import annotations

import argparse
import logging

logger = logging.getLogger(__name__)


def copy_dataset(source_url, target_url, field_regex=None, not_null_fields=None,
                 overwrite_output=False, partitions_count=None, row_group_size_mb=32,
                 storage_options=None, filesystem=None):
    """Copy ``source_url`` → ``target_url`` (optionally a subset of columns/rows).

    ``field_regex``: list of regex patterns selecting fields; ``not_null_fields``: rows with
    nulls in these fields are dropped; ``partitions_count``: number of output files.
    """
    import pyarrow.parquet as pq

    from petastorm_tpu.fs import get_filesystem_and_path_or_paths
    from petastorm_tpu.metadata import (
        _count_row_groups_per_file,
        _list_parquet_files,
        infer_or_load_unischema,
        write_petastorm_tpu_metadata,
    )
    from petastorm_tpu.unischema import match_unischema_fields

    src_fs, src_path = get_filesystem_and_path_or_paths(source_url, storage_options)
    dst_fs, dst_path = get_filesystem_and_path_or_paths(target_url, storage_options,
                                                        filesystem)
    schema = infer_or_load_unischema(src_fs, src_path)

    if field_regex:
        fields = match_unischema_fields(schema, field_regex)
        if not fields:
            raise ValueError("field_regex %r matched no fields" % (field_regex,))
        schema = schema.create_schema_view([f.name for f in fields])
    columns = list(schema.fields.keys())

    try:
        dst_fs.create_dir(dst_path, recursive=True)
    except Exception as e:  # noqa: BLE001 - exists
        logger.debug("create_dir(%s): %s (continuing — existing dir is fine)",
                     dst_path, e)
    existing = _list_parquet_files(dst_fs, dst_path)
    if existing and not overwrite_output:
        raise ValueError("Target %s is non-empty; pass overwrite_output=True" % target_url)
    for f in existing:
        dst_fs.delete_file(f)

    src_files = _list_parquet_files(src_fs, src_path)
    n_out = partitions_count or len(src_files)
    writers = {}
    total_rows = 0
    try:
        for i, src_file in enumerate(src_files):
            pf = pq.ParquetFile(src_fs.open_input_file(src_file))
            for rg in range(pf.num_row_groups):
                table = pf.read_row_group(rg, columns=columns)
                if not_null_fields:
                    import pyarrow.compute as pc

                    mask = None
                    for name in not_null_fields:
                        valid = pc.is_valid(table.column(name))
                        mask = valid if mask is None else pc.and_(mask, valid)
                    table = table.filter(mask)
                if table.num_rows == 0:
                    continue
                out_idx = i % n_out
                w = writers.get(out_idx)
                if w is None:
                    out = dst_fs.open_output_stream(
                        "%s/part-%05d.parquet" % (dst_path, out_idx))
                    w = writers[out_idx] = (
                        pq.ParquetWriter(out, table.schema), out)
                w[0].write_table(table,
                                 row_group_size=max(1, table.num_rows))
                total_rows += table.num_rows
    finally:
        for w, out in writers.values():
            w.close()
            out.close()

    row_groups = _count_row_groups_per_file(dst_fs, dst_path)
    write_petastorm_tpu_metadata(dst_fs, dst_path, schema, row_groups)
    logger.info("Copied %d rows, %d output files", total_rows, len(writers))
    return total_rows


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("source_url")
    parser.add_argument("target_url")
    parser.add_argument("--field-regex", nargs="*", default=None)
    parser.add_argument("--not-null-fields", nargs="*", default=None)
    parser.add_argument("--overwrite-output", action="store_true")
    parser.add_argument("--partitions-count", type=int, default=None)
    args = parser.parse_args(argv)
    copy_dataset(args.source_url, args.target_url, field_regex=args.field_regex,
                 not_null_fields=args.not_null_fields,
                 overwrite_output=args.overwrite_output,
                 partitions_count=args.partitions_count)


if __name__ == "__main__":
    main()
