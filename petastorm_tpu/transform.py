"""User transforms applied inside workers (reference: petastorm/transform.py ~L15).

``TransformSpec`` declares a function run on decoded rows (per-row path) or pandas DataFrames
(batch path) plus the schema edits it implies, so downstream consumers (JAX loader shapes, tf.data
signatures, torch collate) see the post-transform schema.

TPU delta: a transform may instead be *device-side* — a jittable ``fn(batch_dict) -> batch_dict``
applied after device transfer (fused by XLA into the input pipeline). Declare it with
``device=True``; the host pipeline then skips it and the JAX loader applies it under jit.

ISSUE-9 delta: :class:`petastorm_tpu.ops.tabular.FeaturePipeline` is the *declarative*
subclass — instead of an opaque callable it carries a plannable op list the reader
factories validate, fuse, and compile (``declarative = True`` below is the marker the
read path branches on: declarative transforms run columnar with no pandas round trip
and never request writable payloads).
"""
from __future__ import annotations

from petastorm_tpu.unischema import Unischema, UnischemaField


class TransformSpec:
    #: True on declarative subclasses (FeaturePipeline): the transform is a
    #: plannable op graph, not an opaque callable — workers apply it columnar
    #: and skip the writable-payload escalation (reader._spec_wants_writable)
    declarative = False

    def __init__(self, func=None, edit_fields=None, removed_fields=None, selected_fields=None,
                 device=False):
        self.func = func
        self.edit_fields = list(edit_fields or [])
        self.removed_fields = list(removed_fields or [])
        self.selected_fields = list(selected_fields) if selected_fields is not None else None
        self.device = bool(device)
        for f in self.edit_fields:
            if not isinstance(f, (tuple, UnischemaField)):
                raise ValueError("edit_fields entries must be tuples or UnischemaField; got %r" % (f,))


def transform_schema(schema, transform_spec):
    """Apply declared edits/removals/selection to a schema (reference: ~L40)."""
    fields = dict(schema.fields)
    for removed in transform_spec.removed_fields:
        fields.pop(removed, None)
    for edit in transform_spec.edit_fields:
        if isinstance(edit, UnischemaField):
            new_field = edit
        elif len(edit) == 4:
            # reference petastorm edit_fields contract: (name, numpy_dtype, shape, is_nullable)
            name, numpy_dtype, shape, nullable = edit
            new_field = UnischemaField(name, numpy_dtype, shape, None, nullable)
        else:
            new_field = UnischemaField(*edit)
        fields[new_field.name] = new_field
    ordered = [f for name, f in fields.items()]
    if transform_spec.selected_fields is not None:
        missing = set(transform_spec.selected_fields) - set(fields.keys())
        if missing:
            raise ValueError("selected_fields %r not present after transform" % sorted(missing))
        ordered = [fields[name] for name in transform_spec.selected_fields]
    return Unischema(schema.name + "_transformed", ordered)
