"""Recovery policy + poison-item quarantine for the read pipeline (ISSUE 7).

The repo grew its recovery primitives piecemeal: transient-IO retry lives on
the workers (``io_retries``/``io_retry_backoff_s``), elastic child respawn on
the process pool (``worker_respawns``), and the stall watchdog on the health
layer. This module unifies the *policy* into one picklable struct —
:class:`RecoveryOptions` — handed from the reader factories to every layer
(the same pattern as :class:`petastorm_tpu.io.IoOptions`), and adds the piece
that was missing entirely: **poison-item quarantine**.

A poison item is a plan item that repeatedly kills or wedges workers — a
corrupt row group that segfaults a decoder, an OOM-sized record. Before this
module each attempt burned the pool's respawn budget until the whole job died;
with ``on_poison="quarantine"`` the item is skipped after ``poison_attempts``
failures, surfaced in a :class:`QuarantineReport` on ``Reader``/``DataLoader``,
counted as ``ptpu_quarantined_{items,rows}_total``, and **charged against the
reader's consumed-ordinal bookkeeping** so checkpoint resume neither replays
nor loses it. The invariant the chaos harness asserts
(``petastorm-tpu-bench chaos``): every planned row is either delivered exactly
once or listed in the quarantine report — no hangs, no duplicates.

``on_poison="raise"`` (the default) keeps the historical contract: the first
worker exception propagates, and a dead child past the respawn budget raises
:class:`~petastorm_tpu.errors.WorkerDiedError` carrying the original failure.
"""
from __future__ import annotations

import os
import threading

from petastorm_tpu.io import _env_float, _env_int


class RecoveryOptions:
    """One policy struct for every recovery layer (picklable — it crosses the
    process-pool handshake inside the worker).

    ==================  ==========================  ===========================
    field               env var                     meaning
    ==================  ==========================  ===========================
    io_retries          PTPU_IO_RETRIES             extra attempts on transient
                                                    IO errors, identical budget
                                                    on the sync, readahead and
                                                    coalesced read paths
                                                    (default 2; 0 = fail fast)
    io_retry_backoff_s  PTPU_IO_RETRY_BACKOFF_S     base of the jittered
                                                    exponential backoff
                                                    (default 0.1)
    io_retry_max_       PTPU_IO_RETRY_MAX_          cap on a single backoff
    backoff_s           BACKOFF_S                   sleep (default 30.0)
    read_deadline_s     PTPU_READ_DEADLINE_S        wall-clock cap across ALL
                                                    attempts of one read; past
                                                    it the last error raises
                                                    without further retries
                                                    (default 0 = no deadline)
    worker_respawns     PTPU_WORKER_RESPAWNS        process-pool elastic-
                                                    recovery budget (default 2;
                                                    0 = fail fast)
    on_poison           PTPU_ON_POISON              'raise' (default) or
                                                    'quarantine': skip an item
                                                    that repeatedly kills or
                                                    wedges workers
    poison_attempts     PTPU_POISON_ATTEMPTS        failures of ONE plan item
                                                    (tracked per plan ordinal,
                                                    across respawns and heals)
                                                    before it is quarantined
                                                    (default 2)
    link_heartbeat_s    PTPU_LINK_HEARTBEAT_S       framed-transport (tcp)
                                                    heartbeat cadence per
                                                    direction (default 2.0)
    link_miss_threshold PTPU_LINK_MISS_THRESHOLD    consecutive missed
                                                    heartbeat intervals before
                                                    a quiet link is declared
                                                    half-open and torn down
                                                    (default 3)
    link_reconnect_s    PTPU_LINK_RECONNECT_S       ceiling on one reconnect
                                                    wait after a link death —
                                                    the child redials with
                                                    jittered exponential
                                                    backoff (base
                                                    io_retry_backoff_s) under
                                                    this cap; past it the link
                                                    is a dead child
                                                    (default 10.0)
    link_connect_       PTPU_LINK_CONNECT_          bound on a single tcp
    timeout_s           TIMEOUT_S                   connect/hello exchange
                                                    (default 10.0)
    ==================  ==========================  ===========================
    """

    __slots__ = ("io_retries", "io_retry_backoff_s", "io_retry_max_backoff_s",
                 "read_deadline_s", "worker_respawns", "on_poison",
                 "poison_attempts", "link_heartbeat_s", "link_miss_threshold",
                 "link_reconnect_s", "link_connect_timeout_s")

    def __init__(self, io_retries=None, io_retry_backoff_s=None,
                 io_retry_max_backoff_s=None, read_deadline_s=None,
                 worker_respawns=None, on_poison=None, poison_attempts=None,
                 link_heartbeat_s=None, link_miss_threshold=None,
                 link_reconnect_s=None, link_connect_timeout_s=None):
        self.io_retries = max(0, _env_int("PTPU_IO_RETRIES", 2)
                              if io_retries is None else int(io_retries))
        self.io_retry_backoff_s = max(
            0.0, _env_float("PTPU_IO_RETRY_BACKOFF_S", 0.1)
            if io_retry_backoff_s is None else float(io_retry_backoff_s))
        self.io_retry_max_backoff_s = max(
            0.0, _env_float("PTPU_IO_RETRY_MAX_BACKOFF_S", 30.0)
            if io_retry_max_backoff_s is None else float(io_retry_max_backoff_s))
        self.read_deadline_s = max(
            0.0, _env_float("PTPU_READ_DEADLINE_S", 0.0)
            if read_deadline_s is None else float(read_deadline_s))
        self.worker_respawns = max(0, _env_int("PTPU_WORKER_RESPAWNS", 2)
                                   if worker_respawns is None
                                   else int(worker_respawns))
        on_poison = (os.environ.get("PTPU_ON_POISON") or "raise") \
            if on_poison is None else on_poison
        if on_poison not in ("raise", "quarantine"):
            raise ValueError("on_poison must be 'raise' or 'quarantine', got %r"
                             % (on_poison,))
        self.on_poison = on_poison
        self.poison_attempts = max(1, _env_int("PTPU_POISON_ATTEMPTS", 2)
                                   if poison_attempts is None
                                   else int(poison_attempts))
        # framed-transport link policy (ISSUE 15): heartbeat cadence, half-open
        # detection threshold, and the reconnect/connect bounds the tcp
        # transport derives its jittered backoff ceiling from
        self.link_heartbeat_s = max(
            0.05, _env_float("PTPU_LINK_HEARTBEAT_S", 2.0)
            if link_heartbeat_s is None else float(link_heartbeat_s))
        self.link_miss_threshold = max(
            1, _env_int("PTPU_LINK_MISS_THRESHOLD", 3)
            if link_miss_threshold is None else int(link_miss_threshold))
        self.link_reconnect_s = max(
            0.1, _env_float("PTPU_LINK_RECONNECT_S", 10.0)
            if link_reconnect_s is None else float(link_reconnect_s))
        self.link_connect_timeout_s = max(
            0.1, _env_float("PTPU_LINK_CONNECT_TIMEOUT_S", 10.0)
            if link_connect_timeout_s is None
            else float(link_connect_timeout_s))

    @classmethod
    def normalize(cls, value):
        """``None`` → defaults (env-aware), dict → kwargs, RecoveryOptions →
        itself (same contract as ``IoOptions.normalize``)."""
        if value is None:
            return cls()
        if isinstance(value, cls):
            return value
        if isinstance(value, dict):
            return cls(**value)
        raise TypeError("recovery must be a RecoveryOptions, a dict of its "
                        "fields, or None; got %r" % type(value).__name__)

    @classmethod
    def resolve(cls, recovery, **legacy):
        """Factory-side merge: normalize ``recovery`` and overlay the legacy
        per-kwarg knobs (``io_retries=``/``io_retry_backoff_s=``/
        ``worker_respawns=`` on ``make_reader``) where the caller passed one
        explicitly (non-None) — explicit legacy kwargs win over the struct so
        existing call sites keep their exact behavior."""
        explicit = {k: v for k, v in legacy.items() if v is not None}
        if recovery is None and not explicit:
            return cls()
        base = cls.normalize(recovery)
        if not explicit:
            return base
        merged = {name: getattr(base, name) for name in cls.__slots__}
        merged.update(explicit)
        return cls(**merged)

    @property
    def quarantine(self):
        return self.on_poison == "quarantine"

    def __getstate__(self):
        return {name: getattr(self, name) for name in self.__slots__}

    def __setstate__(self, state):
        for name in self.__slots__:
            # .get: tolerate pickles from an older RecoveryOptions missing a
            # newer field (a child on a stale worker image keeps the default)
            setattr(self, name, state.get(name, getattr(type(self)(), name)))

    def __repr__(self):
        return "RecoveryOptions(%s)" % ", ".join(
            "%s=%r" % (name, getattr(self, name)) for name in self.__slots__)


class QuarantinedItem:
    """Executor→reader marker: this plan item was quarantined instead of
    delivered. Rides the results queue like a result; the ``Reader`` absorbs
    it (marks the ordinal consumed, records it in the report) and never yields
    it to the consumer."""

    __slots__ = ("item", "error", "attempts", "kind")

    def __init__(self, item, error, attempts, kind="exception"):
        self.item = item          # the dispatched (epoch, ordinal, work) tuple
        self.error = error        # the LAST failure (original exception chain)
        self.attempts = attempts  # how many times the item was tried
        self.kind = kind          # 'exception' | 'child_death' | 'link_death'
        #                           | 'wire_decode'

    def __repr__(self):
        return "<QuarantinedItem attempts=%d kind=%s error=%r>" % (
            self.attempts, self.kind, self.error)


class QuarantineEntry:
    """One quarantined plan item, with everything an operator needs to find
    the bad data: plan identity, file identity, and the failure chain."""

    __slots__ = ("epoch", "ordinal", "path", "row_group", "num_rows", "error",
                 "attempts", "kind")

    def __init__(self, epoch, ordinal, path, row_group, num_rows, error,
                 attempts, kind):
        self.epoch = epoch
        self.ordinal = ordinal
        self.path = path
        self.row_group = row_group
        self.num_rows = num_rows  # -1 when the footer was never readable
        self.error = error
        self.attempts = attempts
        self.kind = kind

    def as_dict(self):
        return {"epoch": self.epoch, "ordinal": self.ordinal,
                "path": self.path, "row_group": self.row_group,
                "num_rows": self.num_rows, "attempts": self.attempts,
                "kind": self.kind, "error": _format_error_chain(self.error)}

    def __repr__(self):
        return "<QuarantineEntry %s rg=%s ordinal=%s attempts=%d %s>" % (
            self.path, self.row_group, self.ordinal, self.attempts,
            self.kind)


def _format_error_chain(err):
    """``repr`` of an exception plus its ``__cause__``/``__context__`` chain —
    the quarantine report must show the ORIGINAL failure, not just the last
    wrapper."""
    parts = []
    seen = set()
    while err is not None and id(err) not in seen:
        seen.add(id(err))
        parts.append("%s: %s" % (type(err).__name__, err))
        err = err.__cause__ or err.__context__
    return " <- ".join(parts) if parts else ""


class QuarantineReport:
    """Every item this reader quarantined (thread-safe accumulation — markers
    arrive on the consumer thread but the report may be read from anywhere).
    Falsy when empty, so ``if reader.quarantine_report():`` reads naturally."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entries = []

    def add(self, entry):
        with self._lock:
            self._entries.append(entry)

    @property
    def entries(self):
        with self._lock:
            return list(self._entries)

    def __len__(self):
        with self._lock:
            return len(self._entries)

    def __bool__(self):
        return len(self) > 0

    def __iter__(self):
        return iter(self.entries)

    def ordinals(self):
        """``{(epoch, ordinal), ...}`` of quarantined plan items — what the
        chaos harness diffs against the delivered set."""
        with self._lock:
            return {(e.epoch, e.ordinal) for e in self._entries}

    def as_dict(self):
        return {"quarantined": [e.as_dict() for e in self.entries]}

    def render(self):
        entries = self.entries
        if not entries:
            return "quarantine report: empty (every planned item delivered)"
        lines = ["quarantine report: %d item(s) skipped" % len(entries)]
        for e in entries:
            lines.append(
                "  epoch=%s ordinal=%s %s row group %s (%s after %d attempts)"
                % (e.epoch, e.ordinal, e.path, e.row_group, e.kind, e.attempts))
            chain = _format_error_chain(e.error)
            if chain:
                lines.append("    %s" % chain)
        return "\n".join(lines)


_metrics_lock = threading.Lock()
_metrics = None


def _quarantine_metrics():
    global _metrics
    if _metrics is None:
        with _metrics_lock:
            if _metrics is None:
                from petastorm_tpu.obs.metrics import default_registry

                reg = default_registry()
                _metrics = (
                    reg.counter("ptpu_quarantined_items_total",
                                help="plan items skipped as poison "
                                     "(quarantined instead of delivered)"),
                    reg.counter("ptpu_quarantined_rows_total",
                                help="rows in quarantined row groups "
                                     "(by footer metadata)"),
                )
    return _metrics


def count_quarantined(rows):
    """Bump ``ptpu_quarantined_items_total`` (and rows, when the footer row
    count is known)."""
    items, row_counter = _quarantine_metrics()
    items.inc()
    if rows and rows > 0:
        row_counter.inc(int(rows))
