"""On-device data-plane ops: Pallas kernels and jitted transforms (image normalize/augment,
HBM shuffle buffer, JPEG device-stage decode). CPU topologies run kernels in interpret mode."""


def __getattr__(name):
    if name in ("normalize_images", "normalize_and_augment", "random_crop"):
        from petastorm_tpu.ops import image

        return getattr(image, name)
    if name == "DeviceShuffleBuffer":
        from petastorm_tpu.ops.device_shuffle import DeviceShuffleBuffer

        return DeviceShuffleBuffer
    if name in ("idct_blocks", "decode_jpeg_device_stage", "ycbcr_to_rgb",
                "entropy_decode_jpeg_fast", "decode_jpeg_batch", "decode_jpeg"):
        from petastorm_tpu.ops import jpeg

        return getattr(jpeg, name)
    if name in ("FeaturePipeline", "Normalize", "Standardize", "Clip", "Cast",
                "FillNull", "Bucketize", "HashField", "VocabLookup",
                "FeatureCross", "PipelineValidationError"):
        from petastorm_tpu.ops import tabular

        return getattr(tabular, name)
    raise AttributeError("module 'petastorm_tpu.ops' has no attribute %r" % name)
