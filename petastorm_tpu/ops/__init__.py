"""ops subpackage."""
