"""Native host kernels: built from C++ at first use with g++, loaded via ctypes.

The runtime tier around the JAX/Pallas compute path (SURVEY.md §3 "native tier"): the
entropy half of JPEG decode is sequential/branchy host work, so it runs as compiled C++
(jpeg_decoder.cpp) rather than the pure-Python oracle. ctypes calls release the GIL, so
the reader thread pool parallelizes stage-1 decode across cores.

Build model: the shared object is compiled once into a cache directory keyed by a hash of
the source (recompile-on-change), with an atomic rename so concurrent processes race
safely. No pybind11 (not in the image); the C ABI + ctypes keeps the binding dependency-free.
Set ``PETASTORM_TPU_DISABLE_NATIVE=1`` to force the Python fallbacks.
"""
from __future__ import annotations

import ctypes
import hashlib
import logging
import os
import subprocess
import tempfile
import threading

logger = logging.getLogger(__name__)

_SRC_DIR = os.path.dirname(os.path.abspath(__file__))
_LOCK = threading.Lock()
_LIB = None
_LIB_ERR = None


def _cache_dir():
    root = os.environ.get("PETASTORM_TPU_CACHE")
    if not root:
        root = os.path.join(
            os.environ.get("XDG_CACHE_HOME", os.path.expanduser("~/.cache")),
            "petastorm_tpu",
        )
    os.makedirs(root, exist_ok=True)
    return root


def _compile(sources, name):
    """Compile C++ sources into a cached .so; returns its path. Raises on failure."""
    hasher = hashlib.sha256()
    for src in sources:
        with open(src, "rb") as f:
            hasher.update(f.read())
    tag = hasher.hexdigest()[:16]
    out_path = os.path.join(_cache_dir(), "%s-%s.so" % (name, tag))
    if os.path.exists(out_path):
        return out_path
    fd, tmp = tempfile.mkstemp(suffix=".so", dir=_cache_dir())
    os.close(fd)
    cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-o", tmp] + list(sources)
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True, timeout=120)
        os.replace(tmp, out_path)  # atomic: concurrent builders converge on one file
    except subprocess.CalledProcessError as e:
        os.unlink(tmp)
        raise RuntimeError("native build failed: %s\n%s" % (" ".join(cmd), e.stderr))
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise
    return out_path


class _JpegCoeffs(ctypes.Structure):
    _fields_ = [
        ("height", ctypes.c_int32),
        ("width", ctypes.c_int32),
        ("ncomp", ctypes.c_int32),
        ("h_samp", ctypes.c_int32 * 4),
        ("v_samp", ctypes.c_int32 * 4),
        ("blocks_y", ctypes.c_int32 * 4),
        ("blocks_x", ctypes.c_int32 * 4),
        ("blocks", ctypes.POINTER(ctypes.c_int16) * 4),
        ("qtables", (ctypes.c_uint16 * 64) * 4),
    ]


class _JpegLayout(ctypes.Structure):
    _fields_ = [
        ("height", ctypes.c_int32),
        ("width", ctypes.c_int32),
        ("ncomp", ctypes.c_int32),
        ("h_samp", ctypes.c_int32 * 4),
        ("v_samp", ctypes.c_int32 * 4),
        ("blocks_y", ctypes.c_int32 * 4),
        ("blocks_x", ctypes.c_int32 * 4),
    ]


def _load():
    global _LIB, _LIB_ERR
    if _LIB is not None or _LIB_ERR is not None:
        return _LIB
    with _LOCK:
        if _LIB is not None or _LIB_ERR is not None:
            return _LIB
        if os.environ.get("PETASTORM_TPU_DISABLE_NATIVE"):
            _LIB_ERR = "disabled via PETASTORM_TPU_DISABLE_NATIVE"
            return None
        try:
            path = _compile([os.path.join(_SRC_DIR, "jpeg_decoder.cpp")], "ptpu_native")
            lib = ctypes.CDLL(path)
            lib.ptpu_jpeg_decode_coeffs.argtypes = [
                ctypes.c_char_p, ctypes.c_int64, ctypes.POINTER(_JpegCoeffs)]
            lib.ptpu_jpeg_decode_coeffs.restype = ctypes.c_int
            lib.ptpu_jpeg_free_coeffs.argtypes = [ctypes.POINTER(_JpegCoeffs)]
            lib.ptpu_jpeg_free_coeffs.restype = None
            lib.ptpu_jpeg_error_string.argtypes = [ctypes.c_int]
            lib.ptpu_jpeg_error_string.restype = ctypes.c_char_p
            lib.ptpu_jpeg_parse_layout.argtypes = [
                ctypes.c_char_p, ctypes.c_int64, ctypes.POINTER(_JpegLayout)]
            lib.ptpu_jpeg_parse_layout.restype = ctypes.c_int
            lib.ptpu_jpeg_decode_batch.argtypes = [
                ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(ctypes.c_int64),
                ctypes.c_int32, ctypes.POINTER(_JpegLayout),
                ctypes.POINTER(ctypes.POINTER(ctypes.c_int16)),
                ctypes.POINTER(ctypes.c_uint16), ctypes.POINTER(ctypes.c_int32),
                ctypes.POINTER(ctypes.c_int32)]
            lib.ptpu_jpeg_decode_batch.restype = ctypes.c_int32
            lib.ptpu_jpeg_zigzag_truncate.argtypes = [
                ctypes.POINTER(ctypes.c_int16), ctypes.POINTER(ctypes.c_int16),
                ctypes.c_int64, ctypes.c_int32]
            lib.ptpu_jpeg_zigzag_truncate.restype = None
            lib.ptpu_jpeg_pack12.argtypes = [
                ctypes.POINTER(ctypes.c_int16), ctypes.POINTER(ctypes.c_uint8),
                ctypes.c_int64]
            lib.ptpu_jpeg_pack12.restype = ctypes.c_int32
            lib.ptpu_jpeg_specmax.argtypes = [
                ctypes.POINTER(ctypes.c_int16), ctypes.c_int64, ctypes.c_int32,
                ctypes.c_int32, ctypes.POINTER(ctypes.c_int32)]
            lib.ptpu_jpeg_specmax.restype = None
            lib.ptpu_jpeg_pack_split.argtypes = [
                ctypes.POINTER(ctypes.c_int16), ctypes.c_int64, ctypes.c_int32,
                ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
                ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_int8),
                ctypes.POINTER(ctypes.c_uint8)]
            lib.ptpu_jpeg_pack_split.restype = ctypes.c_int32
            _LIB = lib
        except Exception as e:  # noqa: BLE001 — degrade to Python fallback
            _LIB_ERR = str(e)
            logger.warning("Native kernels unavailable (%s); using Python fallbacks", e)
        return _LIB


def native_available():
    """True when the compiled decoder loaded (builds it on first call)."""
    return _load() is not None


def native_error():
    """Why native is unavailable (None when it loaded fine)."""
    _load()
    return _LIB_ERR


#: Error codes the decoder maps to ValueError (bad input) vs RuntimeError (internal).
_VALUE_ERRORS = {-1, -2, -3, -4, -5, -6}


def jpeg_parse_layout_native(data):
    """JPEG bytes → layout tuple ``(height, width, ((h, v, by, bx), ...))`` from the
    frame header only (no entropy decode). ValueError on non-baseline/corrupt headers."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native jpeg decoder unavailable: %s" % _LIB_ERR)
    raw = bytes(data)
    out = _JpegLayout()
    rc = lib.ptpu_jpeg_parse_layout(raw, len(raw), ctypes.byref(out))
    if rc != 0:
        msg = lib.ptpu_jpeg_error_string(rc).decode()
        if rc in _VALUE_ERRORS:
            raise ValueError(msg)
        raise RuntimeError(msg)
    comps = tuple(
        (out.h_samp[c], out.v_samp[c], out.blocks_y[c], out.blocks_x[c])
        for c in range(out.ncomp)
    )
    return out.height, out.width, comps


def jpeg_decode_coeffs_batch_native(blobs):
    """Entropy-decode a whole row group of same-layout JPEGs in ONE native call.

    Decodes straight into stacked numpy buffers — no per-image ctypes round trip, no
    buffer copies, GIL released for the entire batch (the per-image path spends ~2/3 of
    its wall in Python wrapper overhead + ctypes→numpy copies on 1-core hosts).

    Returns ``(layout, coeffs, qtabs, kmax, status)``:

    - ``layout``: ``(height, width, ((h_samp, v_samp, blocks_y, blocks_x), ...))``
      parsed from the first stream
    - ``coeffs``: tuple of ``(n, blocks_y*blocks_x, 64)`` int16 arrays, one per component
    - ``qtabs``: ``(n, ncomp, 64)`` uint16 natural-order quantization tables
    - ``kmax``: per component, the max ZIGZAG index any stream wrote — every
      coefficient beyond it is zero, so transfers may ship only the prefix
      (:func:`jpeg_zigzag_truncate_native`)
    - ``status``: ``(n,)`` int32 — 0 decoded; nonzero = that stream failed
      (lossless/arithmetic mode / corrupt / different layout; its slice is zeroed) and
      the caller must re-decode it individually (e.g. cv2 host fallback). Baseline and
      progressive streams both decode natively.

    Raises ValueError when the FIRST stream has no parseable baseline-or-progressive
    layout (caller falls back to per-image decode for the whole batch)."""
    import numpy as np

    lib = _load()
    if lib is None:
        raise RuntimeError("native jpeg decoder unavailable: %s" % _LIB_ERR)
    blobs = [bytes(b) for b in blobs]
    n = len(blobs)
    if n == 0:
        raise ValueError("empty batch")
    layout = _JpegLayout()
    rc = lib.ptpu_jpeg_parse_layout(blobs[0], len(blobs[0]), ctypes.byref(layout))
    if rc != 0:
        msg = lib.ptpu_jpeg_error_string(rc).decode()
        if rc in _VALUE_ERRORS:
            raise ValueError(msg)
        raise RuntimeError(msg)
    ncomp = layout.ncomp

    datas = (ctypes.c_char_p * n)(*blobs)
    lens = (ctypes.c_int64 * n)(*[len(b) for b in blobs])
    coeffs = []
    block_ptrs = (ctypes.POINTER(ctypes.c_int16) * 4)()
    for c in range(ncomp):
        arr = np.empty((n, layout.blocks_y[c] * layout.blocks_x[c], 64), dtype=np.int16)
        coeffs.append(arr)
        block_ptrs[c] = arr.ctypes.data_as(ctypes.POINTER(ctypes.c_int16))
    qtabs = np.empty((n, ncomp, 64), dtype=np.uint16)
    kmax = np.zeros(4, dtype=np.int32)
    status = np.empty(n, dtype=np.int32)
    lib.ptpu_jpeg_decode_batch(
        datas, lens, n, ctypes.byref(layout), block_ptrs,
        qtabs.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16)),
        kmax.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        status.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
    )
    layout_key = (
        layout.height,
        layout.width,
        tuple((layout.h_samp[c], layout.v_samp[c], layout.blocks_y[c], layout.blocks_x[c])
              for c in range(ncomp)),
    )
    return layout_key, tuple(coeffs), qtabs, tuple(int(k) for k in kmax[:ncomp]), status


def jpeg_zigzag_truncate_native(src, k):
    """(n, nblocks, 64) int16 natural-order coefficients → (n, nblocks, k) int16
    zigzag-prefix pack (``dst[..., j] = src[..., zigzag_to_natural(j)]``). The caller
    guarantees all coefficients beyond zigzag index k-1 are zero (``kmax`` from the
    batch decode)."""
    import numpy as np

    lib = _load()
    if lib is None:
        raise RuntimeError("native jpeg decoder unavailable: %s" % _LIB_ERR)
    src = np.ascontiguousarray(src, dtype=np.int16)
    n, nb, last = src.shape
    if last != 64:
        raise ValueError("expected trailing dim 64, got %d" % last)
    if not 1 <= int(k) <= 64:  # k > 64 would read past the zigzag table in C
        raise ValueError("k must be in [1, 64], got %r" % (k,))
    dst = np.empty((n, nb, int(k)), dtype=np.int16)
    lib.ptpu_jpeg_zigzag_truncate(
        src.ctypes.data_as(ctypes.POINTER(ctypes.c_int16)),
        dst.ctypes.data_as(ctypes.POINTER(ctypes.c_int16)),
        n * nb, int(k),
    )
    return dst


def jpeg_pack12_native(src):
    """(n, nblocks, k) int16 coefficients → (n, nblocks, k*3//2) uint8 12-bit pack
    (two coefficients per 3 bytes), or None when any value exceeds the 12-bit range
    (the caller ships int16 unpacked). ``k`` must be even. The device side unpacks
    with fused integer ops (`ops.jpeg` stage 2) — H2D ships 75% of the bytes."""
    import numpy as np

    lib = _load()
    if lib is None:
        raise RuntimeError("native jpeg decoder unavailable: %s" % _LIB_ERR)
    src = np.ascontiguousarray(src, dtype=np.int16)
    n, nb, k = src.shape
    if k % 2:
        raise ValueError("pack12 needs an even trailing dim, got %d" % k)
    dst = np.empty((n, nb, k * 3 // 2), dtype=np.uint8)
    rc = lib.ptpu_jpeg_pack12(
        src.ctypes.data_as(ctypes.POINTER(ctypes.c_int16)),
        dst.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        n * nb * k,
    )
    return dst if rc == 0 else None


def jpeg_specmax_native(src, is_zigzag=False):
    """(n, nblocks, k) int16 coefficients → (k,) int32 per-zigzag-position max |value|.

    ``is_zigzag`` says rows are zigzag-prefix packs (:func:`jpeg_zigzag_truncate_native`
    output); otherwise rows are natural order and k must be 64. The spectral range
    profile drives the per-position bit-width split (:func:`jpeg_pack_split_native`)."""
    import numpy as np

    lib = _load()
    if lib is None:
        raise RuntimeError("native jpeg decoder unavailable: %s" % _LIB_ERR)
    src = np.ascontiguousarray(src, dtype=np.int16)
    n, nb, k = src.shape
    if not is_zigzag and k != 64:
        raise ValueError("natural-order specmax needs trailing dim 64, got %d" % k)
    out = np.zeros(k, dtype=np.int32)
    lib.ptpu_jpeg_specmax(
        src.ctypes.data_as(ctypes.POINTER(ctypes.c_int16)),
        n * nb, k, 1 if is_zigzag else 0,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
    )
    return out


def jpeg_pack_split_native(src, k1, k2, is_zigzag=False):
    """Spectral-split pack: (n, nblocks, k) int16 → three uint8/int8 slabs with
    per-zigzag-position bit widths (12-bit head [0, k1), int8 mid [k1, k2), 4-bit
    nibble tail [k2, k)), or None when any value exceeds its tier's range (the caller
    falls back to a wider pack). k1 and k - k2 must be even; 0 ≤ k1 ≤ k2 ≤ k.
    Zero-width slabs come back as empty arrays. Exact by construction — the device
    unpack (`ops.jpeg` stage 2) reproduces src bit-identically."""
    import numpy as np

    lib = _load()
    if lib is None:
        raise RuntimeError("native jpeg decoder unavailable: %s" % _LIB_ERR)
    src = np.ascontiguousarray(src, dtype=np.int16)
    n, nb, k = src.shape
    k1, k2 = int(k1), int(k2)
    if not 0 <= k1 <= k2 <= k:
        raise ValueError("need 0 <= k1 <= k2 <= k, got k1=%d k2=%d k=%d" % (k1, k2, k))
    if k1 % 2 or (k - k2) % 2:
        raise ValueError("k1 and k - k2 must be even, got k1=%d k2=%d k=%d" % (k1, k2, k))
    if not is_zigzag and k != 64:
        raise ValueError("natural-order pack_split needs trailing dim 64, got %d" % k)
    head = np.empty((n, nb, k1 * 3 // 2), dtype=np.uint8)
    mid = np.empty((n, nb, k2 - k1), dtype=np.int8)
    tail = np.empty((n, nb, (k - k2) // 2), dtype=np.uint8)
    rc = lib.ptpu_jpeg_pack_split(
        src.ctypes.data_as(ctypes.POINTER(ctypes.c_int16)),
        n * nb, k, 1 if is_zigzag else 0, k1, k2,
        head.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        mid.ctypes.data_as(ctypes.POINTER(ctypes.c_int8)),
        tail.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
    )
    return (head, mid, tail) if rc == 0 else None


def jpeg_decode_coeffs_native(data):
    """JPEG bytes → (height, width, [(blocks, qtable, h_samp, v_samp), ...]) via C++.

    ``blocks``: (blocks_y, blocks_x, 64) int16 natural-order quantized coefficients (a
    copy owned by numpy); ``qtable``: (64,) int32 natural order. Raises ValueError on
    malformed/unsupported streams (same contract as the Python oracle) and RuntimeError
    when the native library is unavailable.
    """
    import numpy as np

    lib = _load()
    if lib is None:
        raise RuntimeError("native jpeg decoder unavailable: %s" % _LIB_ERR)
    raw = bytes(data)
    out = _JpegCoeffs()
    rc = lib.ptpu_jpeg_decode_coeffs(raw, len(raw), ctypes.byref(out))
    if rc != 0:
        msg = lib.ptpu_jpeg_error_string(rc).decode()
        if rc in _VALUE_ERRORS:
            raise ValueError(msg)
        raise RuntimeError(msg)
    try:
        comps = []
        for c in range(out.ncomp):
            by, bx = out.blocks_y[c], out.blocks_x[c]
            n = by * bx * 64
            blocks = np.ctypeslib.as_array(out.blocks[c], shape=(n,)).copy()
            blocks = blocks.reshape(by, bx, 64)
            qtable = np.asarray(out.qtables[c], dtype=np.int32).copy()
            comps.append((blocks, qtable, out.h_samp[c], out.v_samp[c]))
        return out.height, out.width, comps
    finally:
        lib.ptpu_jpeg_free_coeffs(ctypes.byref(out))
