// Native JPEG entropy decoder (stage 1 of the two-stage TPU decode): baseline
// (SOF0/SOF1) and progressive (SOF2).
//
// Huffman entropy decoding is sequential and branchy -- the one part of JPEG decode that
// cannot ride the TPU vector units -- so it runs on host as tight C++ instead of the
// pure-Python bit loop (petastorm_tpu/ops/jpeg.py entropy_decode_jpeg, the correctness
// oracle). Output contract is identical: per-component quantized DCT coefficient blocks
// in natural (unzigzagged) order plus natural-order quantization tables; stage 2
// (dequant + IDCT + upsample + color) runs on device as Pallas/XLA.
//
// Replaces the reference's cv2.imdecode host hot spot (petastorm/codecs.py ~L200) for the
// make_reader/make_batch_reader decode path; built by petastorm_tpu/ops/native/__init__.py
// with g++ at first use and called through ctypes (GIL released -> thread-pool parallel).
//
// Supports: 8-bit baseline sequential DCT (SOF0/SOF1, interleaved single scan) AND
// 8-bit progressive DCT (SOF2: DC/AC spectral selection, successive approximation,
// interleaved DC scans, per-component AC scans, EOB runs), 1..4 components, restart
// intervals, 0xFF00 byte stuffing. Rejects lossless/arithmetic/hierarchical modes.

#include <cstdint>
#include <cstdlib>
#include <cstring>

// Error codes (ptpu_jpeg_error_string maps them to messages)
enum {
  PTPU_JPEG_OK = 0,
  PTPU_JPEG_NOT_JPEG = -1,
  PTPU_JPEG_UNSUPPORTED_MODE = -2,
  PTPU_JPEG_CORRUPT = -3,
  PTPU_JPEG_NOT_8BIT = -4,
  PTPU_JPEG_BAD_COMPONENTS = -5,
  PTPU_JPEG_NO_SCAN = -6,
  PTPU_JPEG_OOM = -7,
  PTPU_JPEG_LAYOUT_MISMATCH = -8,
};

namespace {

// zigzag scan position k -> natural (row-major u,v) index
const int kZigzagToNatural[64] = {
    0,  1,  8,  16, 9,  2,  3,  10, 17, 24, 32, 25, 18, 11, 4,  5,
    12, 19, 26, 33, 40, 48, 41, 34, 27, 20, 13, 6,  7,  14, 21, 28,
    35, 42, 49, 56, 57, 50, 43, 36, 29, 22, 15, 23, 30, 37, 44, 51,
    58, 59, 52, 45, 38, 31, 39, 46, 53, 60, 61, 54, 47, 55, 62, 63};

struct BitReader {
  const uint8_t* data;
  int64_t len;
  int64_t pos;
  uint64_t buf;
  int cnt;
  bool at_marker;  // hit 0xFF <marker>: stop consuming, pad zero bits (spec allows)

  void init(const uint8_t* d, int64_t l, int64_t p) {
    data = d;
    len = l;
    pos = p;
    buf = 0;
    cnt = 0;
    at_marker = false;
  }

  void fill() {
    // Fast path: append 6-8 bytes at once when none is 0xFF (the overwhelmingly common
    // case mid-scan; byte stuffing and markers take the per-byte path below).
    if (!at_marker && pos + 8 <= len) {
      uint64_t chunk;
      memcpy(&chunk, data + pos, 8);
      uint64_t t = ~chunk;  // a 0xFF byte becomes 0x00 in t
      if (!((t - 0x0101010101010101ULL) & ~t & 0x8080808080808080ULL)) {
        uint64_t be = __builtin_bswap64(chunk);
        if (cnt == 0) {  // whole-word load (shift-by-64 below would be UB)
          buf = be;
          cnt = 64;
          pos += 8;
          return;
        }
        int take = (64 - cnt) >> 3;
        buf = (buf << (8 * take)) | (be >> (64 - 8 * take));
        cnt += 8 * take;
        pos += take;
        return;
      }
    }
    while (cnt <= 56) {
      uint8_t b = 0;
      if (!at_marker && pos < len) {
        b = data[pos];
        if (b == 0xFF) {
          uint8_t nxt = (pos + 1 < len) ? data[pos + 1] : 0xD9;
          if (nxt == 0x00) {
            pos += 2;  // byte-stuffed literal 0xFF
          } else {
            b = 0;  // real marker (RSTn/EOI): freeze pos, feed zeros
            at_marker = true;
          }
        } else {
          pos += 1;
        }
      }
      buf = (buf << 8) | b;
      cnt += 8;
    }
  }

  // One refill guard covers a full (code ≤16 bits, value ≤11 bits) coefficient read.
  inline void ensure28() {
    if (cnt < 28) fill();
  }

  inline uint32_t peek16_raw() { return (uint32_t)((buf >> (cnt - 16)) & 0xFFFF); }

  // Consume n (≥1) already-buffered bits.
  inline int take(int n) {
    cnt -= n;
    return (int)((buf >> cnt) & ((1u << n) - 1));
  }

  // Skip to just past the next RSTn marker; reset bit state.
  void align_restart() {
    buf = 0;
    cnt = 0;
    at_marker = false;
    while (pos + 1 < len) {
      if (data[pos] == 0xFF && data[pos + 1] >= 0xD0 && data[pos + 1] <= 0xD7) {
        pos += 2;
        return;
      }
      pos++;
    }
  }
};

// Two-level Huffman decode: a 10-bit first-level LUT (2KiB, L1-resident — a flat
// 16-bit table is 128KiB/table and both costs a per-image build and misses L1 on the
// random peek pattern) plus the canonical mincode/maxcode/valptr fallback for the rare
// codes longer than 10 bits. LUT entry = (code_length << 8) | symbol; 0 = fallback.
struct HuffTable {
  static const int kLutBits = 10;
  uint16_t lut[1 << kLutBits];
  int32_t maxcode[17];  // per length: largest code value, or -1
  int32_t mincode[17];
  int32_t valptr[17];
  uint8_t symbols[256];
  bool present;

  void build(const uint8_t* counts, const uint8_t* syms) {
    memset(lut, 0, sizeof(lut));
    int total = 0;
    for (int i = 0; i < 16; i++) total += counts[i];
    memcpy(symbols, syms, total);
    uint32_t code = 0;
    int k = 0;
    for (int length = 1; length <= 16; length++) {
      if (counts[length - 1]) {
        valptr[length] = k;
        mincode[length] = (int32_t)code;
        for (int i = 0; i < counts[length - 1]; i++) {
          if (length <= kLutBits) {
            uint32_t first = code << (kLutBits - length);
            uint32_t n = 1u << (kLutBits - length);
            uint16_t v = (uint16_t)((length << 8) | syms[k]);
            for (uint32_t j = 0; j < n; j++) lut[first + j] = v;
          }
          code++;
          k++;
        }
        maxcode[length] = (int32_t)code - 1;
      } else {
        maxcode[length] = -1;
      }
      code <<= 1;
    }
    present = true;
  }

  // 16-bit peek → (length << 8) | symbol, or 0 on invalid code.
  inline uint32_t decode(uint32_t p16) const {
    uint32_t e = lut[p16 >> (16 - kLutBits)];
    if (e) return e;
    for (int l = kLutBits + 1; l <= 16; l++) {
      int32_t c = (int32_t)(p16 >> (16 - l));
      if (maxcode[l] >= 0 && c <= maxcode[l])
        return ((uint32_t)l << 8) | symbols[valptr[l] + (c - mincode[l])];
    }
    return 0;
  }
};

// JPEG EXTEND: map t-bit magnitude to signed value.
inline int extend(int v, int t) {
  return (v >= (1 << (t - 1))) ? v : v - (1 << t) + 1;
}

inline uint16_t be16(const uint8_t* p) { return (uint16_t)((p[0] << 8) | p[1]); }

// Frame component state shared by the baseline and progressive scan decoders.
struct JComp {
  int id, h, v, tq;
  int dc_tbl, ac_tbl;
};

// One progressive scan (ITU-T T.81 §G): DC or AC band, first pass or successive-
// approximation refinement. Coefficients accumulate into blocks16[c] (the padded
// interleaved grid, stride out_bx[c]); scans arrive in any spec-legal order.
// On success sets *end_pos to the next marker after the entropy-coded data.
// Returns a PTPU_JPEG_* code.
struct ProgScanArgs {
  const uint8_t* data;
  int64_t len;
  int64_t start;
  const JComp* comps;
  const int* scan_comps;  // indices into comps, scan order
  int ns;
  int Ss, Se, Ah, Al;
  const HuffTable* huff_dc;  // [4]
  const HuffTable* huff_ac;  // [4]
  int restart_interval;
  int width, height, hmax, vmax, mcus_x, mcus_y;
  int16_t* const* blocks;   // per frame component c: padded grid
  const int* out_bx;        // per frame component: padded blocks_x (row stride)
  int32_t* kmax;            // nullable: per frame component, max zigzag index written
};

inline int prog_find_next_marker(const uint8_t* data, int64_t len, int64_t from,
                                 int64_t* out) {
  for (int64_t p = from; p + 1 < len; p++) {
    if (data[p] == 0xFF && data[p + 1] != 0x00 &&
        !(data[p + 1] >= 0xD0 && data[p + 1] <= 0xD7))
      {
        *out = p;
        return PTPU_JPEG_OK;
      }
  }
  *out = len;
  return PTPU_JPEG_OK;  // no further marker: treated as end of stream
}

int decode_progressive_scan(const ProgScanArgs& a, int64_t* end_pos) {
  BitReader br;
  br.init(a.data, a.len, a.start);
  int pred[4] = {0, 0, 0, 0};
  int eobrun = 0;
  const int p1 = 1 << a.Al;
  const int m1 = -(1 << a.Al);
  int mcu_count = 0;

  auto restart_check = [&]() {
    if (a.restart_interval && mcu_count && mcu_count % a.restart_interval == 0) {
      br.align_restart();
      pred[0] = pred[1] = pred[2] = pred[3] = 0;
      eobrun = 0;
    }
  };

  if (a.Ss == 0) {
    // ---- DC scan (Se must be 0) ----
    if (a.Se != 0) return PTPU_JPEG_CORRUPT;
    if (a.ns > 1) {
      // interleaved DC scan: full-frame MCU traversal over the scan components
      for (int my = 0; my < a.mcus_y; my++) {
        for (int mx = 0; mx < a.mcus_x; mx++) {
          restart_check();
          for (int s = 0; s < a.ns; s++) {
            int c = a.scan_comps[s];
            const JComp& comp = a.comps[c];
            for (int v = 0; v < comp.v; v++) {
              for (int hh = 0; hh < comp.h; hh++) {
                int brow = my * comp.v + v;
                int bcol = mx * comp.h + hh;
                int16_t* blk = a.blocks[c] + ((size_t)brow * a.out_bx[c] + bcol) * 64;
                br.ensure28();
                if (a.Ah == 0) {
                  uint32_t e = a.huff_dc[comp.dc_tbl].decode(br.peek16_raw());
                  if (!e) return PTPU_JPEG_CORRUPT;
                  br.cnt -= e >> 8;
                  int t = e & 0xFF;
                  if (t > 11) return PTPU_JPEG_CORRUPT;
                  if (t) pred[c] += extend(br.take(t), t);
                  blk[0] = (int16_t)(pred[c] * p1);  // value << Al
                } else {
                  if (br.take(1)) blk[0] = (int16_t)(blk[0] | p1);
                }
              }
            }
          }
          mcu_count++;
        }
      }
    } else {
      // single-component DC scan: non-interleaved block geometry
      int c = a.scan_comps[0];
      const JComp& comp = a.comps[c];
      int cw = (a.width * comp.h + a.hmax - 1) / a.hmax;   // ceil(X * Hi / Hmax)
      int ch = (a.height * comp.v + a.vmax - 1) / a.vmax;
      int wb = (cw + 7) / 8, hb = (ch + 7) / 8;
      for (int brow = 0; brow < hb; brow++) {
        for (int bcol = 0; bcol < wb; bcol++) {
          restart_check();
          int16_t* blk = a.blocks[c] + ((size_t)brow * a.out_bx[c] + bcol) * 64;
          br.ensure28();
          if (a.Ah == 0) {
            uint32_t e = a.huff_dc[comp.dc_tbl].decode(br.peek16_raw());
            if (!e) return PTPU_JPEG_CORRUPT;
            br.cnt -= e >> 8;
            int t = e & 0xFF;
            if (t > 11) return PTPU_JPEG_CORRUPT;
            if (t) pred[c] += extend(br.take(t), t);
            blk[0] = (int16_t)(pred[c] * p1);
          } else {
            if (br.take(1)) blk[0] = (int16_t)(blk[0] | p1);
          }
          mcu_count++;
        }
      }
    }
  } else {
    // ---- AC scan: always single-component (T.81 §G.1.1.1.1) ----
    if (a.ns != 1) return PTPU_JPEG_UNSUPPORTED_MODE;
    if (a.Se > 63 || a.Ss > a.Se) return PTPU_JPEG_CORRUPT;
    int c = a.scan_comps[0];
    const JComp& comp = a.comps[c];
    const HuffTable& ac = a.huff_ac[comp.ac_tbl];
    int cw = (a.width * comp.h + a.hmax - 1) / a.hmax;
    int ch = (a.height * comp.v + a.vmax - 1) / a.vmax;
    int wb = (cw + 7) / 8, hb = (ch + 7) / 8;
    for (int brow = 0; brow < hb; brow++) {
      for (int bcol = 0; bcol < wb; bcol++) {
        restart_check();
        int16_t* blk = a.blocks[c] + ((size_t)brow * a.out_bx[c] + bcol) * 64;
        if (a.Ah == 0) {
          // first pass over this band
          if (eobrun > 0) {
            eobrun--;
          } else {
            int k = a.Ss;
            while (k <= a.Se) {
              br.ensure28();
              uint32_t e = ac.decode(br.peek16_raw());
              if (!e) return PTPU_JPEG_CORRUPT;
              br.cnt -= e >> 8;
              int r = (e & 0xFF) >> 4, s = e & 0xF;
              if (s == 0) {
                if (r != 15) {
                  eobrun = (1 << r) - 1;
                  if (r) {
                    br.ensure28();
                    eobrun += br.take(r);
                  }
                  break;  // end of band for this block
                }
                k += 16;
              } else {
                if (s > 10) return PTPU_JPEG_CORRUPT;
                k += r;
                if (k > a.Se) return PTPU_JPEG_CORRUPT;
                blk[kZigzagToNatural[k]] = (int16_t)(extend(br.take(s), s) * p1);
                if (a.kmax && k > a.kmax[c]) a.kmax[c] = k;
                k++;
              }
            }
          }
        } else {
          // refinement pass (libjpeg jdphuff.c decode_mcu_AC_refine structure)
          int k = a.Ss;
          if (eobrun == 0) {
            while (k <= a.Se) {
              br.ensure28();
              uint32_t e = ac.decode(br.peek16_raw());
              if (!e) return PTPU_JPEG_CORRUPT;
              br.cnt -= e >> 8;
              int r = (e & 0xFF) >> 4, s = e & 0xF;
              int newval = 0;
              if (s == 0) {
                if (r != 15) {
                  // NOT (1<<r)-1: the tail handler below consumes the current
                  // block's remaining correction bits and decrements (libjpeg
                  // decode_mcu_AC_refine); with the -1 form an r==0 EOB would skip
                  // those bits and desynchronize the stream
                  eobrun = (1 << r);
                  if (r) {
                    br.ensure28();
                    eobrun += br.take(r);
                  }
                  break;  // tail correction below consumes the rest of the band
                }
                // r == 15: skip over 16 zero-history coefficients
              } else {
                if (s != 1) return PTPU_JPEG_CORRUPT;
                br.ensure28();
                newval = br.take(1) ? p1 : m1;
              }
              while (k <= a.Se) {
                int16_t* cf = blk + kZigzagToNatural[k];
                if (*cf != 0) {
                  br.ensure28();
                  if (br.take(1) && (*cf & p1) == 0)
                    *cf = (int16_t)(*cf + (*cf >= 0 ? p1 : m1));
                } else {
                  if (r == 0) break;
                  r--;
                }
                k++;
              }
              if (s && k <= a.Se) {
                blk[kZigzagToNatural[k]] = (int16_t)newval;
                if (a.kmax && k > a.kmax[c]) a.kmax[c] = k;
              }
              k++;
            }
          }
          if (eobrun > 0) {
            // correction bits for the remaining nonzero history in the band
            while (k <= a.Se) {
              int16_t* cf = blk + kZigzagToNatural[k];
              if (*cf != 0) {
                br.ensure28();
                if (br.take(1) && (*cf & p1) == 0)
                  *cf = (int16_t)(*cf + (*cf >= 0 ? p1 : m1));
              }
              k++;
            }
            eobrun--;
          }
        }
        mcu_count++;
      }
    }
  }
  return prog_find_next_marker(a.data, a.len, br.pos > a.start ? br.pos : a.start,
                               end_pos);
}

}  // namespace

extern "C" {

typedef struct {
  int32_t height;
  int32_t width;
  int32_t ncomp;
  int32_t h_samp[4];
  int32_t v_samp[4];
  int32_t blocks_y[4];
  int32_t blocks_x[4];
  int16_t* blocks[4];      // malloc'ed: blocks_y*blocks_x*64 int16, natural order
  uint16_t qtables[4][64]; // natural order
} PtpuJpegCoeffs;

// Decode layout: everything that shapes the stacked coefficient buffers (the batch
// API requires every stream in a batch to share one).
typedef struct {
  int32_t height;
  int32_t width;
  int32_t ncomp;
  int32_t h_samp[4];
  int32_t v_samp[4];
  int32_t blocks_y[4];
  int32_t blocks_x[4];
} PtpuJpegLayout;

void ptpu_jpeg_free_coeffs(PtpuJpegCoeffs* out) {
  if (!out) return;
  for (int i = 0; i < 4; i++) {
    free(out->blocks[i]);
    out->blocks[i] = nullptr;
  }
}

const char* ptpu_jpeg_error_string(int code) {
  switch (code) {
    case PTPU_JPEG_OK: return "ok";
    case PTPU_JPEG_NOT_JPEG: return "Not a JPEG (missing SOI)";
    case PTPU_JPEG_UNSUPPORTED_MODE:
      return "Unsupported JPEG mode (lossless/arithmetic/non-interleaved-baseline)";
    case PTPU_JPEG_CORRUPT: return "Corrupt JPEG stream";
    case PTPU_JPEG_NOT_8BIT: return "Only 8-bit baseline JPEG supported";
    case PTPU_JPEG_BAD_COMPONENTS: return "Unsupported component count/sampling";
    case PTPU_JPEG_NO_SCAN: return "No SOS marker found";
    case PTPU_JPEG_OOM: return "Out of memory";
    case PTPU_JPEG_LAYOUT_MISMATCH:
      return "JPEG layout differs from the batch layout";
    default: return "Unknown error";
  }
}

// Core decoder. When ``dst``/``qdst`` are given (batch mode) the coefficient blocks are
// written into the caller's buffers (dst[c]: blocks_y*blocks_x*64 int16 each, qdst:
// ncomp*64 uint16 natural order) after verifying the stream's layout equals ``expect``;
// nothing is allocated and nothing must be freed. Otherwise blocks are malloc'ed into
// ``out`` (ptpu_jpeg_free_coeffs frees them). ``kmax`` (nullable, per component) is
// raised to the largest ZIGZAG index this stream writes a coefficient at — free to
// track during entropy decode, and it lets the caller ship only the nonzero zigzag
// prefix to the device.
static int decode_impl(const uint8_t* data, int64_t len, PtpuJpegCoeffs* out,
                       const PtpuJpegLayout* expect, int16_t* const* dst,
                       uint16_t* qdst, int32_t* kmax) {
  memset(out, 0, sizeof(*out));
  if (len < 4 || data[0] != 0xFF || data[1] != 0xD8) return PTPU_JPEG_NOT_JPEG;

  int32_t qt_zz[4][64];  // DQT tables in zigzag order as parsed
  bool qt_present[4] = {false, false, false, false};
  static thread_local HuffTable huff_dc[4], huff_ac[4];  // ~10KiB; off-stack, re-entrant
  for (int i = 0; i < 4; i++) {
    huff_dc[i].present = false;
    huff_ac[i].present = false;
  }

  JComp comps[4];
  int ncomp = 0;
  int height = 0, width = 0;
  bool have_frame = false;
  bool progressive = false;
  bool allocated = false;
  int scans_done = 0;
  int hmax = 1, vmax = 1, mcus_x = 0, mcus_y = 0;
  int restart_interval = 0;

  int64_t pos = 2;
  int rc = PTPU_JPEG_NO_SCAN;

  while (pos < len) {
    if (data[pos] != 0xFF) {
      pos++;
      continue;
    }
    if (pos + 1 >= len) break;
    uint8_t marker = data[pos + 1];
    pos += 2;
    if (marker == 0xD8 || marker == 0x01 || (marker >= 0xD0 && marker <= 0xD7)) continue;
    if (marker == 0xD9) break;  // EOI
    if (pos + 2 > len) {
      rc = PTPU_JPEG_CORRUPT;
      break;
    }
    int seglen = be16(data + pos);
    if (seglen < 2 || pos + seglen > len) {
      rc = PTPU_JPEG_CORRUPT;
      break;
    }
    const uint8_t* seg = data + pos + 2;
    int segbytes = seglen - 2;

    if (marker == 0xDB) {  // DQT
      int s = 0;
      while (s < segbytes) {
        int pq = seg[s] >> 4, tq = seg[s] & 0xF;
        s += 1;
        if (tq > 3) {
          rc = PTPU_JPEG_CORRUPT;
          goto done;
        }
        if (pq) {
          if (s + 128 > segbytes) {
            rc = PTPU_JPEG_CORRUPT;
            goto done;
          }
          for (int i = 0; i < 64; i++) qt_zz[tq][i] = be16(seg + s + 2 * i);
          s += 128;
        } else {
          if (s + 64 > segbytes) {
            rc = PTPU_JPEG_CORRUPT;
            goto done;
          }
          for (int i = 0; i < 64; i++) qt_zz[tq][i] = seg[s + i];
          s += 64;
        }
        qt_present[tq] = true;
      }
    } else if (marker == 0xC0 || marker == 0xC1 || marker == 0xC2) {
      // SOF0/SOF1 baseline, SOF2 progressive
      if (segbytes < 6) {
        rc = PTPU_JPEG_CORRUPT;
        goto done;
      }
      int precision = seg[0];
      if (precision != 8) {
        rc = PTPU_JPEG_NOT_8BIT;
        goto done;
      }
      if (have_frame) {
        // a second frame header is illegal (T.81: one frame per non-hierarchical
        // stream) and would re-derive geometry the coefficient buffers no longer
        // match — reject instead of writing through stale pointers/strides
        rc = PTPU_JPEG_CORRUPT;
        goto done;
      }
      progressive = (marker == 0xC2);
      height = be16(seg + 1);
      width = be16(seg + 3);
      ncomp = seg[5];
      if (ncomp < 1 || ncomp > 4 || segbytes < 6 + 3 * ncomp) {
        rc = PTPU_JPEG_BAD_COMPONENTS;
        goto done;
      }
      for (int i = 0; i < ncomp; i++) {
        comps[i].id = seg[6 + 3 * i];
        comps[i].h = seg[7 + 3 * i] >> 4;
        comps[i].v = seg[7 + 3 * i] & 0xF;
        comps[i].tq = seg[8 + 3 * i];
        if (comps[i].h < 1 || comps[i].h > 4 || comps[i].v < 1 || comps[i].v > 4 ||
            comps[i].tq > 3) {
          rc = PTPU_JPEG_BAD_COMPONENTS;
          goto done;
        }
        if (comps[i].h > hmax) hmax = comps[i].h;
        if (comps[i].v > vmax) vmax = comps[i].v;
      }
      mcus_x = (width + 8 * hmax - 1) / (8 * hmax);
      mcus_y = (height + 8 * vmax - 1) / (8 * vmax);
      have_frame = true;
    } else if (marker == 0xC4) {  // DHT
      int s = 0;
      while (s + 17 <= segbytes) {
        int tc = seg[s] >> 4, th = seg[s] & 0xF;
        if (th > 3 || tc > 1) {
          rc = PTPU_JPEG_CORRUPT;
          goto done;
        }
        const uint8_t* counts = seg + s + 1;
        int total = 0;
        for (int i = 0; i < 16; i++) total += counts[i];
        if (s + 17 + total > segbytes) {
          rc = PTPU_JPEG_CORRUPT;
          goto done;
        }
        if (tc == 0)
          huff_dc[th].build(counts, seg + s + 17);
        else
          huff_ac[th].build(counts, seg + s + 17);
        s += 17 + total;
      }
    } else if (marker == 0xDD) {  // DRI
      if (segbytes < 2) {
        rc = PTPU_JPEG_CORRUPT;
        goto done;
      }
      restart_interval = be16(seg);
    } else if (marker == 0xC3 || marker == 0xC5 || marker == 0xC6 ||
               marker == 0xC7 || marker == 0xC9 || marker == 0xCA || marker == 0xCB ||
               marker == 0xCD || marker == 0xCE || marker == 0xCF) {
      rc = PTPU_JPEG_UNSUPPORTED_MODE;  // lossless / arithmetic / hierarchical
      goto done;
    } else if (marker == 0xDA) {  // SOS
      if (!have_frame || segbytes < 1) {
        rc = PTPU_JPEG_CORRUPT;
        goto done;
      }
      int ns = seg[0];
      if (ns < 1 || ns > 4 || segbytes < 1 + 2 * ns + 3) {
        rc = PTPU_JPEG_CORRUPT;
        goto done;
      }
      int scan_comps[4];
      for (int i = 0; i < ns; i++) {
        int cs = seg[1 + 2 * i];
        int found = -1;
        for (int c = 0; c < ncomp; c++)
          if (comps[c].id == cs) found = c;
        if (found < 0) {
          rc = PTPU_JPEG_CORRUPT;
          goto done;
        }
        int td = seg[2 + 2 * i] >> 4;
        int ta = seg[2 + 2 * i] & 0xF;
        if (td > 3 || ta > 3) {
          // Td/Ta are 2-bit per T.81 B.2.3; huff_dc/huff_ac are 4 entries, so
          // an unvalidated nibble from a corrupt SOS indexed out of bounds
          // (heap OOB read, crash depending on heap layout — found by the
          // fuzz corpus under ASan)
          rc = PTPU_JPEG_CORRUPT;
          goto done;
        }
        comps[found].dc_tbl = td;
        comps[found].ac_tbl = ta;
        scan_comps[i] = found;
      }
      int Ss = seg[1 + 2 * ns];
      int Se = seg[2 + 2 * ns];
      int Ah = seg[3 + 2 * ns] >> 4;
      int Al = seg[3 + 2 * ns] & 0xF;

      if (!allocated) {
        // first scan: verify layout, set up (or adopt) coefficient storage
        for (int c = 0; c < ncomp; c++) {
          if (!qt_present[comps[c].tq]) {
            rc = PTPU_JPEG_CORRUPT;
            goto done;
          }
        }
        out->height = height;
        out->width = width;
        out->ncomp = ncomp;
        if (expect && (height != expect->height || width != expect->width ||
                       ncomp != expect->ncomp)) {
          rc = PTPU_JPEG_LAYOUT_MISMATCH;
          goto done;
        }
        for (int c = 0; c < ncomp; c++) {
          int bx = mcus_x * comps[c].h;
          int by = mcus_y * comps[c].v;
          out->h_samp[c] = comps[c].h;
          out->v_samp[c] = comps[c].v;
          out->blocks_y[c] = by;
          out->blocks_x[c] = bx;
          if (expect && (comps[c].h != expect->h_samp[c] ||
                         comps[c].v != expect->v_samp[c] ||
                         by != expect->blocks_y[c] || bx != expect->blocks_x[c])) {
            rc = PTPU_JPEG_LAYOUT_MISMATCH;
            goto done;
          }
          if (dst) {
            out->blocks[c] = dst[c];
            memset(dst[c], 0, (size_t)by * bx * 64 * sizeof(int16_t));
          } else {
            out->blocks[c] = (int16_t*)calloc((size_t)by * bx * 64, sizeof(int16_t));
            if (!out->blocks[c]) {
              rc = PTPU_JPEG_OOM;
              goto done;
            }
          }
          const int32_t* zz = qt_zz[comps[c].tq];
          uint16_t* qout = qdst ? qdst + (size_t)c * 64 : out->qtables[c];
          for (int k = 0; k < 64; k++)
            qout[kZigzagToNatural[k]] = (uint16_t)zz[k];
        }
        allocated = true;
      }

      if (progressive) {
        // table presence: DC-first scans need DC tables; AC scans need the AC table;
        // DC refinement (Ah>0, Ss==0) is raw bits, no table
        for (int i = 0; i < ns; i++) {
          const JComp& sc = comps[scan_comps[i]];
          if (Ss == 0 && Ah == 0 && !huff_dc[sc.dc_tbl].present) {
            rc = PTPU_JPEG_CORRUPT;
            goto done;
          }
          if (Ss > 0 && !huff_ac[sc.ac_tbl].present) {
            rc = PTPU_JPEG_CORRUPT;
            goto done;
          }
        }
        ProgScanArgs pargs;
        pargs.data = data;
        pargs.len = len;
        pargs.start = pos + seglen;
        pargs.comps = comps;
        pargs.scan_comps = scan_comps;
        pargs.ns = ns;
        pargs.Ss = Ss;
        pargs.Se = Se;
        pargs.Ah = Ah;
        pargs.Al = Al;
        pargs.huff_dc = huff_dc;
        pargs.huff_ac = huff_ac;
        pargs.restart_interval = restart_interval;
        pargs.width = width;
        pargs.height = height;
        pargs.hmax = hmax;
        pargs.vmax = vmax;
        pargs.mcus_x = mcus_x;
        pargs.mcus_y = mcus_y;
        pargs.blocks = out->blocks;
        pargs.out_bx = out->blocks_x;
        pargs.kmax = kmax;
        int64_t next_pos = 0;
        rc = decode_progressive_scan(pargs, &next_pos);
        if (rc != PTPU_JPEG_OK) goto done;
        scans_done++;
        rc = PTPU_JPEG_NO_SCAN;  // re-armed; success is decided at EOI
        pos = next_pos;
        continue;  // keep parsing markers: DHT/DRI/SOS/EOI follow
      }

      // ---- baseline: one interleaved scan covering every component ----
      if (ns != ncomp) {
        // non-interleaved multi-scan baseline: rare; the codec's host_stage_decode
        // catches the resulting ValueError and falls back to full cv2 host decode
        rc = PTPU_JPEG_UNSUPPORTED_MODE;
        goto done;
      }
      for (int c = 0; c < ncomp; c++) {
        if (!huff_dc[comps[c].dc_tbl].present || !huff_ac[comps[c].ac_tbl].present) {
          rc = PTPU_JPEG_CORRUPT;
          goto done;
        }
      }

      BitReader br;
      br.init(data, len, pos + seglen);
      int pred[4] = {0, 0, 0, 0};
      int mcu_count = 0;
      for (int my = 0; my < mcus_y; my++) {
        for (int mx = 0; mx < mcus_x; mx++) {
          if (restart_interval && mcu_count && mcu_count % restart_interval == 0) {
            br.align_restart();
            pred[0] = pred[1] = pred[2] = pred[3] = 0;
          }
          for (int c = 0; c < ncomp; c++) {
            const HuffTable& dc_tab = huff_dc[comps[c].dc_tbl];
            const HuffTable& ac_tab = huff_ac[comps[c].ac_tbl];
            for (int v = 0; v < comps[c].v; v++) {
              for (int hh = 0; hh < comps[c].h; hh++) {
                int brow = my * comps[c].v + v;
                int bcol = mx * comps[c].h + hh;
                int16_t* blk =
                    out->blocks[c] + ((size_t)brow * out->blocks_x[c] + bcol) * 64;
                // DC (code ≤16 + magnitude ≤11 bits: one refill guard covers both)
                br.ensure28();
                uint32_t e = dc_tab.decode(br.peek16_raw());
                if (!e) {
                  rc = PTPU_JPEG_CORRUPT;
                  goto done;
                }
                br.cnt -= e >> 8;
                int t = e & 0xFF;
                if (t > 11) {  // 8-bit baseline DC category ≤ 11; larger → corrupt DHT
                  rc = PTPU_JPEG_CORRUPT;
                  goto done;
                }
                if (t) pred[c] += extend(br.take(t), t);
                blk[0] = (int16_t)pred[c];
                // AC
                int k = 1;
                while (k < 64) {
                  br.ensure28();
                  e = ac_tab.decode(br.peek16_raw());
                  if (!e) {
                    rc = PTPU_JPEG_CORRUPT;
                    goto done;
                  }
                  br.cnt -= e >> 8;
                  int r = (e & 0xFF) >> 4, s = e & 0xF;
                  if (s == 0) {
                    if (r == 15) {
                      k += 16;
                      continue;
                    }
                    break;  // EOB
                  }
                  if (s > 10) {  // 8-bit baseline AC size ≤ 10; also keeps the 28-bit
                    rc = PTPU_JPEG_CORRUPT;  // ensure28 window sufficient (16+10 < 28)
                    goto done;
                  }
                  k += r;
                  if (k > 63) break;
                  blk[kZigzagToNatural[k]] = (int16_t)extend(br.take(s), s);
                  if (kmax && k > kmax[c]) kmax[c] = k;
                  k++;
                }
              }
            }
          }
          mcu_count++;
        }
      }
      rc = PTPU_JPEG_OK;
      goto done;
    }
    pos += seglen;
  }
  // progressive streams succeed at EOI (or end of data) once any scan landed
  if (progressive && allocated && scans_done > 0) rc = PTPU_JPEG_OK;

done:
  if (rc != PTPU_JPEG_OK && !dst) ptpu_jpeg_free_coeffs(out);
  return rc;
}

int ptpu_jpeg_decode_coeffs(const uint8_t* data, int64_t len, PtpuJpegCoeffs* out) {
  return decode_impl(data, len, out, nullptr, nullptr, nullptr, nullptr);
}

// Parse only as far as the frame header; fills the decode layout without touching the
// entropy-coded scan. Used by the batch API to size the stacked buffers.
int ptpu_jpeg_parse_layout(const uint8_t* data, int64_t len, PtpuJpegLayout* out) {
  memset(out, 0, sizeof(*out));
  if (len < 4 || data[0] != 0xFF || data[1] != 0xD8) return PTPU_JPEG_NOT_JPEG;
  int64_t pos = 2;
  while (pos < len) {
    if (data[pos] != 0xFF) {
      pos++;
      continue;
    }
    if (pos + 1 >= len) break;
    uint8_t marker = data[pos + 1];
    pos += 2;
    if (marker == 0xD8 || marker == 0x01 || (marker >= 0xD0 && marker <= 0xD7)) continue;
    if (marker == 0xD9) break;
    if (pos + 2 > len) return PTPU_JPEG_CORRUPT;
    int seglen = be16(data + pos);
    if (seglen < 2 || pos + seglen > len) return PTPU_JPEG_CORRUPT;
    const uint8_t* seg = data + pos + 2;
    int segbytes = seglen - 2;
    if (marker == 0xC0 || marker == 0xC1 || marker == 0xC2) {  // baseline + progressive
      if (segbytes < 6) return PTPU_JPEG_CORRUPT;
      if (seg[0] != 8) return PTPU_JPEG_NOT_8BIT;
      out->height = be16(seg + 1);
      out->width = be16(seg + 3);
      out->ncomp = seg[5];
      if (out->ncomp < 1 || out->ncomp > 4 || segbytes < 6 + 3 * out->ncomp)
        return PTPU_JPEG_BAD_COMPONENTS;
      int hmax = 1, vmax = 1;
      for (int i = 0; i < out->ncomp; i++) {
        out->h_samp[i] = seg[7 + 3 * i] >> 4;
        out->v_samp[i] = seg[7 + 3 * i] & 0xF;
        if (out->h_samp[i] < 1 || out->h_samp[i] > 4 || out->v_samp[i] < 1 ||
            out->v_samp[i] > 4)
          return PTPU_JPEG_BAD_COMPONENTS;
        if (out->h_samp[i] > hmax) hmax = out->h_samp[i];
        if (out->v_samp[i] > vmax) vmax = out->v_samp[i];
      }
      int mcus_x = (out->width + 8 * hmax - 1) / (8 * hmax);
      int mcus_y = (out->height + 8 * vmax - 1) / (8 * vmax);
      for (int i = 0; i < out->ncomp; i++) {
        out->blocks_x[i] = mcus_x * out->h_samp[i];
        out->blocks_y[i] = mcus_y * out->v_samp[i];
      }
      return PTPU_JPEG_OK;
    }
    if (marker == 0xC3 || marker == 0xC5 || marker == 0xC6 ||
        marker == 0xC7 || marker == 0xC9 || marker == 0xCA || marker == 0xCB ||
        marker == 0xCD || marker == 0xCE || marker == 0xCF)
      return PTPU_JPEG_UNSUPPORTED_MODE;
    pos += seglen;
  }
  return PTPU_JPEG_NO_SCAN;
}

// Batched decode: n streams (stream i = datas[i][0..lens[i])), all expected to share
// ``expect``'s layout, written into caller-allocated stacked buffers:
//   out_blocks[c] : (n, blocks_y[c]*blocks_x[c], 64) int16, C-contiguous
//   out_qtabs     : (n, ncomp, 64) uint16, natural order
//   out_kmax      : per component (size 4), max ZIGZAG index any stream wrote —
//                   coefficients at zigzag positions > out_kmax[c] are all zero, so
//                   the caller may ship only the prefix (ptpu_jpeg_zigzag_truncate)
// status[i] = PTPU_JPEG_OK or the stream's error code (its slice is left zeroed; the
// caller re-decodes failed rows individually). Returns the number of failed streams.
// One call decodes a whole row group with the GIL released.
int ptpu_jpeg_decode_batch(const uint8_t* const* datas, const int64_t* lens, int32_t n,
                           const PtpuJpegLayout* expect, int16_t* const* out_blocks,
                           uint16_t* out_qtabs, int32_t* out_kmax, int32_t* status) {
  size_t stride[4];
  for (int c = 0; c < expect->ncomp && c < 4; c++)
    stride[c] = (size_t)expect->blocks_y[c] * expect->blocks_x[c] * 64;
  for (int c = 0; c < 4; c++) out_kmax[c] = 0;
  int failures = 0;
  for (int32_t i = 0; i < n; i++) {
    int16_t* dst[4] = {nullptr, nullptr, nullptr, nullptr};
    for (int c = 0; c < expect->ncomp && c < 4; c++)
      dst[c] = out_blocks[c] + (size_t)i * stride[c];
    PtpuJpegCoeffs tmp;
    int32_t kmax_local[4] = {0, 0, 0, 0};
    int rc = decode_impl(datas[i], lens[i], &tmp, expect, dst,
                         out_qtabs + (size_t)i * expect->ncomp * 64, kmax_local);
    status[i] = rc;
    if (rc != PTPU_JPEG_OK) {
      failures++;
      for (int c = 0; c < expect->ncomp && c < 4; c++)
        memset(dst[c], 0, stride[c] * sizeof(int16_t));
    } else {
      // merge only successful streams: a corrupt stream's partial garbage writes are
      // zeroed above and must not inflate the row group's kmax
      for (int c = 0; c < expect->ncomp && c < 4; c++)
        if (kmax_local[c] > out_kmax[c]) out_kmax[c] = kmax_local[c];
    }
  }
  return failures;
}

// Pack the zigzag prefix: src (nblocks, 64) int16 natural order → dst (nblocks, k)
// int16 where dst[b, j] = src[b, zigzag_to_natural(j)]. Coefficients beyond zigzag
// index k-1 are dropped (the caller guarantees they are zero via out_kmax). Reads only
// the needed elements — ~k/64 of the bytes a numpy fancy-gather touches.
void ptpu_jpeg_zigzag_truncate(const int16_t* src, int16_t* dst, int64_t nblocks,
                               int32_t k) {
  for (int64_t b = 0; b < nblocks; b++) {
    const int16_t* s = src + b * 64;
    int16_t* d = dst + b * k;
    for (int32_t j = 0; j < k; j++) d[j] = s[kZigzagToNatural[j]];
  }
}

// 12-bit coefficient pack: src (nvals,) int16 → dst (nvals * 3 / 2,) uint8, two
// values per 3 bytes, little-endian nibble layout:
//   dst[0] = v0 & 0xFF;  dst[1] = ((v0 >> 8) & 0xF) | ((v1 & 0xF) << 4);
//   dst[2] = (v1 >> 4) & 0xFF
// (values stored as 12-bit two's complement). Returns 0 on success, -1 when any
// |value| exceeds the 12-bit range (caller ships int16 instead; dst contents are
// then unspecified). nvals must be even — the caller packs whole (block, k) rows
// with even k. Quantized DCT coefficients exceed ±2047 only at extreme qualities
// (quant step 1–2 with saturated content), so the fallback is rare but mandatory.
int32_t ptpu_jpeg_pack12(const int16_t* src, uint8_t* dst, int64_t nvals) {
  for (int64_t i = 0; i < nvals; i += 2) {
    int16_t a = src[i], b = src[i + 1];
    if (a < -2048 || a > 2047 || b < -2048 || b > 2047) return -1;
    uint16_t ua = (uint16_t)a & 0xFFF;
    uint16_t ub = (uint16_t)b & 0xFFF;
    uint8_t* d = dst + (i / 2) * 3;
    d[0] = (uint8_t)(ua & 0xFF);
    d[1] = (uint8_t)(((ua >> 8) & 0xF) | ((ub & 0xF) << 4));
    d[2] = (uint8_t)((ub >> 4) & 0xFF);
  }
  return 0;
}

// Per-zigzag-position max |coefficient| over a stack of blocks: out[j] = max_b
// |block_b[zigzag position j]| for j in [0, k). ``is_zigzag`` says whether block rows
// are already zigzag-prefix packs of width k (ptpu_jpeg_zigzag_truncate output) or
// natural-order 64-wide rows (k must then be 64). The spectral range profile drives
// the per-position bit-width transfer split (ptpu_jpeg_pack_split): high zigzag
// positions carry heavily-quantized values that fit 8 or 4 bits even on sharp
// photographic content that defeats zigzag truncation outright.
void ptpu_jpeg_specmax(const int16_t* src, int64_t nblocks, int32_t k,
                       int32_t is_zigzag, int32_t* out) {
  for (int32_t j = 0; j < k; j++) out[j] = 0;
  for (int64_t b = 0; b < nblocks; b++) {
    const int16_t* s = src + b * k;
    for (int32_t j = 0; j < k; j++) {
      int32_t v = is_zigzag ? s[j] : s[kZigzagToNatural[j]];
      if (v < 0) v = -v;
      if (v > out[j]) out[j] = v;
    }
  }
}

// Spectral-split coefficient pack, one pass: block row (zigzag order, width k) ->
// three slabs with per-position bit widths chosen by the caller from the specmax
// profile:
//   head: zigzag positions [0, k1)  -> 12-bit pairs (ptpu_jpeg_pack12 layout), k1 even
//   mid : positions [k1, k2)        -> int8
//   tail: positions [k2, k)         -> 4-bit two's-complement nibble pairs
//         (low nibble = even position), k - k2 even
// ``is_zigzag`` as in ptpu_jpeg_specmax. Returns 0 on success; -1/-2/-3 when a value
// exceeds its tier's range (head/mid/tail respectively — caller falls back to a wider
// pack; dst contents are then unspecified). Exact by construction: the unpacked
// values are bit-identical to src.
int32_t ptpu_jpeg_pack_split(const int16_t* src, int64_t nblocks, int32_t k,
                             int32_t is_zigzag, int32_t k1, int32_t k2,
                             uint8_t* head, int8_t* mid, uint8_t* tail) {
  const int64_t head_stride = (int64_t)(k1 / 2) * 3;
  const int64_t mid_stride = k2 - k1;
  const int64_t tail_stride = (k - k2) / 2;
  for (int64_t b = 0; b < nblocks; b++) {
    const int16_t* s = src + b * k;
    uint8_t* hd = head + b * head_stride;
    int8_t* md = mid + b * mid_stride;
    uint8_t* tl = tail + b * tail_stride;
    for (int32_t j = 0; j < k1; j += 2) {
      int16_t a = is_zigzag ? s[j] : s[kZigzagToNatural[j]];
      int16_t c = is_zigzag ? s[j + 1] : s[kZigzagToNatural[j + 1]];
      if (a < -2048 || a > 2047 || c < -2048 || c > 2047) return -1;
      uint16_t ua = (uint16_t)a & 0xFFF;
      uint16_t uc = (uint16_t)c & 0xFFF;
      uint8_t* d = hd + (j / 2) * 3;
      d[0] = (uint8_t)(ua & 0xFF);
      d[1] = (uint8_t)(((ua >> 8) & 0xF) | ((uc & 0xF) << 4));
      d[2] = (uint8_t)((uc >> 4) & 0xFF);
    }
    for (int32_t j = k1; j < k2; j++) {
      int16_t a = is_zigzag ? s[j] : s[kZigzagToNatural[j]];
      if (a < -128 || a > 127) return -2;
      md[j - k1] = (int8_t)a;
    }
    for (int32_t j = k2; j < k; j += 2) {
      int16_t a = is_zigzag ? s[j] : s[kZigzagToNatural[j]];
      int16_t c = is_zigzag ? s[j + 1] : s[kZigzagToNatural[j + 1]];
      if (a < -8 || a > 7 || c < -8 || c > 7) return -3;
      tl[(j - k2) / 2] = (uint8_t)(((uint8_t)a & 0xF) | (((uint8_t)c & 0xF) << 4));
    }
  }
  return 0;
}

}  // extern "C"
