"""Device inflate stage for the compressed-page pass-through (ISSUE 14).

The CODAG thesis (PAPERS.md) is that decompression is bandwidth-bound and
belongs on the accelerator: ship the ~storage-ratio compressed pages over
PCIe, inflate in HBM. This module is the device half of
:mod:`petastorm_tpu.io.pagedec` — same two-stage split as the JPEG path
(:mod:`petastorm_tpu.ops.jpeg`):

- :func:`snappy_inflate_pages` — the snappy LZ token machine as a **Pallas
  kernel**, one page per grid program (the CODAG block-parallel shape: a
  page is sequential, pages are independent). The token walk is a
  ``lax.while_loop`` byte machine inside the kernel; bounds violations latch
  a per-page ``ok`` flag instead of reading out of range.
- :func:`rle_expand` — RLE/bit-packed hybrid dictionary-index expansion as
  the two-phase CODAG shape: a sequential run-table scan (runs ≪ values)
  followed by a **vectorized Pallas extraction kernel** (bit-window shift +
  mask + RLE/packed select over all values at once), then a device gather
  through the inflated dictionary.
- :func:`inflate_column` — the loader-facing entry: a
  :class:`~petastorm_tpu.io.pagedec.PassthroughColumn` window → the decoded
  ``jax.Array`` in HBM, page tables and compressed bytes being the only H2D
  traffic.

Like the JPEG kernels, everything runs in Pallas **interpret mode on CPU
topologies** (tested that way in CI); the numpy reference decoders in
``io/pagedec.py`` are the bit-identity twin — any per-page ``ok=False``
(corruption, unsupported shape like bit widths over 24) falls back to the
host reference path, which validates fully and raises the classified
:class:`~petastorm_tpu.errors.PagedecCorruptError`.
"""
from __future__ import annotations

import functools

import numpy as np


def _use_interpret():
    import jax

    return jax.default_backend() == "cpu"


# -- snappy LZ token machine -----------------------------------------------------------
#
# Format: varint uncompressed length, then tagged elements. tag & 3:
#   0 literal  (len from tag>>2, 60..63 select 1..4 extra length bytes)
#   1 copy     (len 4..11 from tag bits 2-4, offset 11 bits: tag bits 5-7 + 1 byte)
#   2 copy     (len 1..64 from tag>>2, offset 2 bytes LE)
#   3 copy     (len 1..64 from tag>>2, offset 4 bytes LE)
# Copies may overlap their own output (offset < len): the byte-serial inner
# loop IS the semantics, exactly like the host reference.

def _snappy_machine(comp, comp_len, out_cap):
    """Decode one snappy page: ``comp`` (src_cap,) uint8 → ((out_cap,) uint8,
    produced length, ok). Pure jnp/lax — runs inside the Pallas kernel body
    (one grid program per page) and under ``vmap`` in the fallback path."""
    import jax.numpy as jnp
    from jax import lax

    src_cap = comp.shape[0]
    comp = comp.astype(jnp.int32)

    def rd(i):
        # clamped gather: the ok flag (checked by every consumer) carries the
        # violation; the read itself can never leave the buffer
        return comp[jnp.clip(i, 0, src_cap - 1)]

    # preamble: varint uncompressed length (<= 5 bytes)
    def pre_body(state):
        pos, shift, val, done, ok = state
        b = rd(pos)
        val = val | ((b & 0x7F) << shift)
        done = (b & 0x80) == 0
        ok = ok & (pos < comp_len) & (shift <= 28)
        return pos + 1, shift + 7, val, done, ok

    pos, _, out_len, _, ok = lax.while_loop(
        lambda s: (~s[3]) & s[4],
        pre_body, (jnp.int32(0), jnp.int32(0), jnp.int32(0), False, True))
    ok = ok & (out_len <= out_cap)

    out = jnp.zeros((out_cap,), jnp.uint8)

    def copy_byte(k, state):
        out, dst, src_off = state
        v = out[jnp.clip(dst - src_off + k, 0, out_cap - 1)]
        out = out.at[jnp.clip(dst + k, 0, out_cap - 1)].set(v)
        return out, dst, src_off

    def lit_byte(k, state):
        out, dst, src = state
        v = rd(src + k).astype(jnp.uint8)
        out = out.at[jnp.clip(dst + k, 0, out_cap - 1)].set(v)
        return out, dst, src

    def step(state):
        src, dst, out, ok = state
        tag = rd(src)
        t = tag & 3

        def literal(_):
            n0 = tag >> 2
            extra = jnp.where(n0 >= 60, n0 - 59, 0)  # 0..4 length bytes
            # extra-byte mask without a dynamic 1 << 32 (implementation-
            # defined in int32; an array table would be a captured constant
            # pallas refuses): shifts stay <= 24, the 4-byte case keeps 31
            # bits — a >=2GB literal in a page-sized stream is corruption
            # the bounds checks below reject anyway
            mask = jnp.where(
                extra >= 4, jnp.int32(0x7FFFFFFF),
                (jnp.int32(1) << (8 * jnp.minimum(extra, 3))) - 1)
            word = (rd(src + 1) | (rd(src + 2) << 8) | (rd(src + 3) << 16)
                    | ((rd(src + 4) & 0x7F) << 24))
            ln = jnp.where(n0 >= 60, word & mask, n0) + 1
            start = src + 1 + extra
            # ln >= 1: a corrupt length must latch ok=False, never step the
            # cursors backwards (a negative ln with ok still True would let
            # the while_loop cycle forever)
            good = (ln >= 1) & (start + ln <= comp_len) \
                & (dst + ln <= out_len)
            new_out, _, _ = lax.fori_loop(
                0, jnp.where(good, ln, 0), lit_byte, (out, dst, start))
            return start + ln, dst + ln, new_out, ok & good

        def copy(_):
            ln = jnp.where(t == 1, ((tag >> 2) & 0x7) + 4, (tag >> 2) + 1)
            off = jnp.where(
                t == 1, ((tag >> 5) << 8) | rd(src + 1),
                jnp.where(t == 2, rd(src + 1) | (rd(src + 2) << 8),
                          rd(src + 1) | (rd(src + 2) << 8)
                          | (rd(src + 3) << 16) | (rd(src + 4) << 24)))
            consumed = jnp.where(t == 1, 2, jnp.where(t == 2, 3, 5))
            good = (src + consumed <= comp_len) & (off > 0) & (off <= dst) \
                & (dst + ln <= out_len)
            new_out, _, _ = lax.fori_loop(
                0, jnp.where(good, ln, 0), copy_byte, (out, dst, off))
            return src + consumed, dst + ln, new_out, ok & good

        return lax.cond(t == 0, literal, copy, None)

    src, dst, out, ok = lax.while_loop(
        lambda s: (s[0] < comp_len) & (s[1] < out_len) & s[3],
        step, (pos, jnp.int32(0), out, ok))
    ok = ok & (dst == out_len) & (src == comp_len)
    return out, out_len, ok


def _snappy_pages_kernel(comp_ref, meta_ref, out_ref, ok_ref):
    """Pallas kernel body: one grid program inflates one page. ``meta`` is
    the page table row [comp_len, out_len]."""
    import jax.numpy as jnp

    comp = comp_ref[0, :]
    comp_len = meta_ref[0, 0]
    out, _n, ok = _snappy_machine(comp, comp_len, out_ref.shape[1])
    out_ref[0, :] = out
    ok_ref[0, 0] = jnp.where(ok, jnp.int32(1), jnp.int32(0))


@functools.lru_cache(maxsize=64)
def _snappy_pages_fn(n_pages, src_cap, out_cap, interpret):
    import jax
    from jax.experimental import pallas as pl

    def fn(comp, meta):
        return pl.pallas_call(
            _snappy_pages_kernel,
            out_shape=(jax.ShapeDtypeStruct((n_pages, out_cap), np.uint8),
                       jax.ShapeDtypeStruct((n_pages, 1), np.int32)),
            grid=(n_pages,),
            in_specs=[
                pl.BlockSpec((1, src_cap), lambda i: (i, 0)),
                pl.BlockSpec((1, 2), lambda i: (i, 0)),
            ],
            out_specs=(pl.BlockSpec((1, out_cap), lambda i: (i, 0)),
                       pl.BlockSpec((1, 1), lambda i: (i, 0))),
            interpret=interpret,
        )(comp, meta)

    return jax.jit(fn)


def snappy_inflate_pages(comp, meta, out_cap, interpret=None):
    """Inflate a batch of snappy pages on device.

    ``comp``: (n_pages, src_cap) uint8, zero-padded compressed pages.
    ``meta``: (n_pages, 2) int32 — [compressed_len, uncompressed_len] rows.
    Returns ``(raw (n_pages, out_cap) uint8, ok (n_pages,) bool)``.
    """
    import jax.numpy as jnp

    interpret = _use_interpret() if interpret is None else interpret
    n, src_cap = comp.shape
    fn = _snappy_pages_fn(n, src_cap, int(out_cap), bool(interpret))
    out, ok = fn(jnp.asarray(comp), jnp.asarray(meta, jnp.int32))
    return out, ok[:, 0] != 0


def stored_pages(comp, meta, out_cap):
    """The UNCOMPRESSED-codec twin of :func:`snappy_inflate_pages`: pages are
    already raw — pad/truncate to the output layout (pure device reshuffle,
    no kernel needed)."""
    import jax.numpy as jnp

    comp = jnp.asarray(comp)
    n, src_cap = comp.shape
    meta = jnp.asarray(meta, jnp.int32)
    if src_cap < out_cap:
        comp = jnp.pad(comp, ((0, 0), (0, out_cap - src_cap)))
    else:
        comp = comp[:, :out_cap]
    idx = jnp.arange(out_cap)[None, :]
    out = jnp.where(idx < meta[:, 1:2], comp, 0).astype(jnp.uint8)
    ok = meta[:, 0] == meta[:, 1]
    return out, ok


# -- RLE/bit-packed hybrid expansion ---------------------------------------------------

_MAX_BIT_WIDTH = 24  # 4-byte windows cover shift(<=7)+bw bits; wider -> host path


def _rle_run_scan(data, data_len, total, max_runs, bit_width):
    """Phase 1 (sequential, runs ≪ values): parse the hybrid run stream into
    a bounded run table. Returns (run_end, is_packed, rle_value,
    packed_bit_base, n_runs, ok)."""
    import jax.numpy as jnp
    from jax import lax

    cap = data.shape[0]
    data = data.astype(jnp.int32)

    def rd(i):
        return data[jnp.clip(i, 0, cap - 1)]

    byte_width = (bit_width + 7) // 8  # static

    def varint(pos, ok):
        def body(state):
            p, shift, val, done, ok = state
            b = rd(p)
            val = val | ((b & 0x7F) << shift)
            return p + 1, shift + 7, val, (b & 0x80) == 0, \
                ok & (p < data_len) & (shift <= 28)

        p, _, val, _, ok = lax.while_loop(
            lambda s: (~s[3]) & s[4], body,
            (pos, jnp.int32(0), jnp.int32(0), False, ok))
        return val, p, ok

    run_end = jnp.full((max_runs,), jnp.iinfo(jnp.int32).max, jnp.int32)
    is_packed = jnp.zeros((max_runs,), jnp.int32)
    rle_value = jnp.zeros((max_runs,), jnp.int32)
    bit_base = jnp.zeros((max_runs,), jnp.int32)

    def body(state):
        pos, filled, nruns, run_end, is_packed, rle_value, bit_base, ok = state
        header, pos, ok = varint(pos, ok)
        packed = (header & 1) == 1

        groups = header >> 1
        packed_n = groups * 8
        packed_bytes = groups * bit_width  # bytes per 8 values == bit_width
        rle_run = header >> 1
        v = jnp.int32(0)
        for k in range(byte_width):
            v = v | (rd(pos + k) << (8 * k))
        count = jnp.where(packed, packed_n, rle_run)
        consumed = jnp.where(packed, packed_bytes, byte_width)
        ok = ok & (pos + consumed <= data_len) & (count > 0) \
            & (nruns < max_runs)
        idx = jnp.clip(nruns, 0, max_runs - 1)
        # a packed run's trailing values beyond `total` are spec-legal padding
        run_end = run_end.at[idx].set(jnp.minimum(filled + count, total))
        is_packed = is_packed.at[idx].set(packed.astype(jnp.int32))
        rle_value = rle_value.at[idx].set(jnp.where(packed, 0, v))
        bit_base = bit_base.at[idx].set(pos * 8)
        return (pos + consumed, filled + count, nruns + 1,
                run_end, is_packed, rle_value, bit_base, ok)

    init = (jnp.int32(0), jnp.int32(0), jnp.int32(0),
            run_end, is_packed, rle_value, bit_base, True)
    pos, filled, nruns, run_end, is_packed, rle_value, bit_base, ok = \
        lax.while_loop(lambda s: (s[1] < total) & s[7], body, init)
    ok = ok & (filled >= total)
    return run_end, is_packed, rle_value, bit_base, nruns, ok


def _extract_kernel(win_ref, shift_ref, sel_ref, rlev_ref, mask_ref, out_ref):
    """Phase 2 Pallas kernel (vectorized VPU work): little-endian 4-byte
    windows → ``(word >> shift) & mask`` for packed values, the run's RLE
    value otherwise."""
    import jax.numpy as jnp

    w = win_ref[:, :].astype(jnp.int32)
    word = w[:, 0] | (w[:, 1] << 8) | (w[:, 2] << 16) | (w[:, 3] << 24)
    packed = (word >> shift_ref[:, 0]) & mask_ref[0, 0]
    out_ref[:, 0] = jnp.where(sel_ref[:, 0] != 0, packed, rlev_ref[:, 0])


@functools.lru_cache(maxsize=64)
def _extract_fn(n, interpret):
    import jax
    from jax.experimental import pallas as pl

    block = 1024
    padded = ((n + block - 1) // block) * block

    def fn(win, shift, sel, rlev, mask):
        import jax.numpy as jnp

        pad = padded - n
        if pad:
            win = jnp.pad(win, ((0, pad), (0, 0)))
            shift = jnp.pad(shift, ((0, pad), (0, 0)))
            sel = jnp.pad(sel, ((0, pad), (0, 0)))
            rlev = jnp.pad(rlev, ((0, pad), (0, 0)))
        out = pl.pallas_call(
            _extract_kernel,
            out_shape=jax.ShapeDtypeStruct((padded, 1), jnp.int32),
            grid=(padded // block,),
            in_specs=[
                pl.BlockSpec((block, 4), lambda i: (i, 0)),
                pl.BlockSpec((block, 1), lambda i: (i, 0)),
                pl.BlockSpec((block, 1), lambda i: (i, 0)),
                pl.BlockSpec((block, 1), lambda i: (i, 0)),
                pl.BlockSpec((1, 1), lambda i: (0, 0)),
            ],
            out_specs=pl.BlockSpec((block, 1), lambda i: (i, 0)),
            interpret=interpret,
        )(win, shift, sel, rlev, mask)
        return out[:n, 0]

    return jax.jit(fn)


def rle_expand(data, data_len, bit_width, total, interpret=None):
    """RLE/bit-packed hybrid stream → ``total`` int32 values on device.

    ``data``: (cap,) uint8 (the values section after the bit-width byte,
    zero-padded); ``bit_width`` is static (host-read from the inflated page's
    first byte). Returns ``(values (total,) int32, ok)``."""
    import jax.numpy as jnp

    interpret = _use_interpret() if interpret is None else interpret
    bit_width = int(bit_width)
    if bit_width == 0:
        return jnp.zeros((total,), jnp.int32), jnp.asarray(True)
    if bit_width > _MAX_BIT_WIDTH:
        return jnp.zeros((total,), jnp.int32), jnp.asarray(False)
    data = jnp.asarray(data)
    max_runs = max(8, total)  # worst case: 1-value RLE runs
    run_end, is_packed, rle_value, bit_base, nruns, ok = _rle_run_scan(
        data, data_len, total, max_runs, bit_width)
    i = jnp.arange(total, dtype=jnp.int32)
    rid = jnp.searchsorted(run_end, i, side="right").astype(jnp.int32)
    rid = jnp.clip(rid, 0, max_runs - 1)
    run_start = jnp.where(rid == 0, 0, run_end[jnp.clip(rid - 1, 0,
                                                        max_runs - 1)])
    local = i - run_start
    bitpos = bit_base[rid] + local * bit_width
    byte_off = bitpos >> 3
    shift = (bitpos & 7).astype(jnp.int32)
    cap = data.shape[0]
    gather = jnp.clip(byte_off[:, None] + jnp.arange(4)[None, :], 0, cap - 1)
    win = data[gather]
    mask = jnp.asarray([[(1 << bit_width) - 1]], jnp.int32)
    fn = _extract_fn(int(total), bool(interpret))
    values = fn(win, shift[:, None], is_packed[rid][:, None],
                rle_value[rid][:, None], mask)
    return values, ok


def bitcast_values(raw_bytes, dtype):
    """(n*itemsize,) uint8 → (n,) ``dtype`` on device (little-endian, which
    both the CPU and TPU hosts are).

    On x64-disabled runtimes jax canonicalizes 8-byte dtypes: the classic
    path's ``device_put(np.int64 column)`` delivers int32 by value-truncation,
    which for little-endian two's complement IS the low word — so INT64
    bitcasts to (n, 2) int32 word pairs and keeps the low word, byte-identical
    to the classic delivery. FLOAT64 would need a value-rounding conversion a
    bitcast cannot express — it raises :class:`DeviceInflateError` and the
    column takes the host-reference fallback (still compressed on the wire,
    host-decoded before the transfer)."""
    import jax
    import jax.numpy as jnp

    dtype = np.dtype(dtype)
    k = dtype.itemsize
    n = raw_bytes.shape[0] // k
    words = raw_bytes[:n * k].reshape(n, k)
    x64 = bool(jax.config.jax_enable_x64)
    if k == 8 and not x64:
        if dtype.kind == "f":
            raise DeviceInflateError(
                "float64 device inflate needs jax_enable_x64 (host fallback)")
        pairs = jax.lax.bitcast_convert_type(
            words.reshape(n, 2, 4), jnp.int32)
        out = pairs[:, 0]
        return out.astype(jnp.uint32) if dtype.kind == "u" else out
    return jax.lax.bitcast_convert_type(words, jnp.dtype(dtype.name))


# -- loader-facing orchestration -------------------------------------------------------

def _pack_pages(chunk, pages):
    """Host prep of the device transfer: pad the COMPRESSED page payloads into
    one (n, src_cap) matrix + the (n, 2) page table. These bytes (plus the
    table) are exactly the H2D traffic the pass-through ships."""
    src_cap = max(p.comp_size for p in pages)
    out_cap = max(p.uncomp_size for p in pages)
    comp = np.zeros((len(pages), src_cap), np.uint8)
    meta = np.zeros((len(pages), 2), np.int32)
    for i, p in enumerate(pages):
        payload = np.frombuffer(chunk.buf, np.uint8, count=p.comp_size,
                                offset=p.payload_offset)
        comp[i, :p.comp_size] = payload
        meta[i] = (p.comp_size, p.uncomp_size)
    return comp, meta, out_cap


def _inflate_chunk_pages(chunk, pages, interpret):
    """All of ``pages`` (+ data pages' raw bytes) inflated on device:
    returns (raw (n, out_cap) uint8, meta, ok_all)."""
    comp, meta, out_cap = _pack_pages(chunk, pages)
    if chunk.codec == "SNAPPY":
        raw, ok = snappy_inflate_pages(comp, meta, out_cap, interpret)
    else:
        raw, ok = stored_pages(comp, meta, out_cap)
    return raw, meta, ok


class DeviceInflateError(Exception):
    """Internal: the device path bailed (ok flag latched false / unsupported
    width) — the caller falls back to the host reference, which validates
    fully and raises the classified error if the bytes are actually bad."""


def inflate_window(chunk, skip, take, interpret=None):
    """Rows ``[skip, skip+take)`` of one
    :class:`~petastorm_tpu.io.pagedec.PassthroughChunk`, inflated on device
    from the COVERING pages only (plus the dictionary page when one exists)
    — cutting a row group into many batches ships and decodes each data
    page at most twice (boundary pages), never the whole chunk per batch.
    Raises :class:`DeviceInflateError` when any page's ok flag latches
    false."""
    import jax.numpy as jnp

    interpret = _use_interpret() if interpret is None else interpret
    p0, p1, base = chunk.covering_pages(skip, take)
    data_pages = list(chunk.pages[p0:p1])
    pages = ([chunk.dict_page] if chunk.dict_page is not None else []) \
        + data_pages
    raw, meta, ok = _inflate_chunk_pages(chunk, pages, interpret)
    if not bool(jnp.all(ok)):
        raise DeviceInflateError("page inflate kernel latched ok=False")
    pos = 0
    dict_vals = None
    from petastorm_tpu.io import pagedec as _pd

    if chunk.dict_page is not None:
        dict_raw = raw[0, :chunk.dict_page.uncomp_size]
        dict_vals = bitcast_values(dict_raw, chunk.dtype)
        if chunk.dict_page.num_values > dict_vals.shape[0]:
            raise DeviceInflateError("dictionary page shorter than its values")
        dict_vals = dict_vals[:chunk.dict_page.num_values]
        pos = 1
    outs = []
    for i, page in enumerate(data_pages):
        body = raw[pos + i, :page.uncomp_size]
        off = 0
        if chunk.max_def:
            if page.uncomp_size < 4:
                raise DeviceInflateError("page too short for level block")
            # the level-block length is part of the page layout: read the 4
            # prefix bytes on host from the DEVICE array (4-byte D2H, not a
            # decode) — offsets must be static for the slicing below
            head = np.asarray(body[:4]).view("<u4")[0]
            off = 4 + int(head)
            if off > page.uncomp_size:
                raise DeviceInflateError("level block past page end")
        values = body[off:]
        if page.encoding == _pd.ENC_PLAIN:
            need = page.num_values * chunk.dtype.itemsize
            if values.shape[0] < need:
                raise DeviceInflateError("PLAIN page shorter than its values")
            outs.append(bitcast_values(values[:need], chunk.dtype))
        else:  # RLE_DICTIONARY / PLAIN_DICTIONARY
            if dict_vals is None:
                raise DeviceInflateError("dictionary page missing")
            if values.shape[0] < 1:
                raise DeviceInflateError("empty dictionary-index body")
            bit_width = int(np.asarray(values[0]))
            idx, ok = rle_expand(values[1:], int(values.shape[0] - 1),
                                 bit_width, page.num_values, interpret)
            if not bool(ok):
                raise DeviceInflateError("RLE expansion latched ok=False")
            in_range = jnp.all((idx >= 0) & (idx < dict_vals.shape[0]))
            if not bool(in_range):
                raise DeviceInflateError("dictionary index out of range")
            outs.append(dict_vals[idx])
    full = outs[0] if len(outs) == 1 else jnp.concatenate(outs)
    return full[skip - base:skip - base + take]


def inflate_chunk(chunk, interpret=None):
    """All rows of one chunk (the full-range window) — test/CLI convenience."""
    return inflate_window(chunk, 0, chunk.num_rows, interpret)


def inflate_column(column, interpret=None):
    """The loader's device inflate: a
    :class:`~petastorm_tpu.io.pagedec.PassthroughColumn` window → the decoded
    device array for exactly its rows, one covering-pages inflate per
    window. Raises :class:`DeviceInflateError` for the caller's host
    fallback."""
    import jax.numpy as jnp

    outs = []
    for chunk, skip, take in column.parts:
        if take == 0:
            continue
        outs.append(inflate_window(chunk, skip, take, interpret))
    if not outs:
        return jnp.zeros((0,), jnp.dtype(column.dtype.name))
    return outs[0] if len(outs) == 1 else jnp.concatenate(outs)
