"""Fused on-device image prep: uint8 → normalized bfloat16 (+ augmentations).

Replaces the reference's CPU per-image post-decode work (cv2 output → numpy → framework
tensor) with a Pallas TPU kernel fused into the input pipeline: dequantize (/255), per-channel
mean/std normalize, and dtype cast happen in one VMEM pass; random horizontal flip rides the
same jit. On CPU test topologies the kernel runs in interpret mode (same code path).

Layout: NHWC with C innermost; the kernel views an image batch as (N, H*W*C) rows and tiles
rows × a 128-multiple lane dim — HBM-bandwidth-bound, so one fused pass is the win.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np


def _use_interpret():
    return jax.default_backend() == "cpu"


def _normalize_kernel(x_ref, mean_ref, inv_std_ref, out_ref):
    # Mosaic has no direct uint8->float32 cast; widen via int32 first
    x = x_ref[:].astype(jnp.int32).astype(jnp.float32) * (1.0 / 255.0)
    out_ref[:] = ((x - mean_ref[:]) * inv_std_ref[:]).astype(out_ref.dtype)


@functools.partial(jax.jit, static_argnames=("out_dtype",))
def normalize_images(images, mean, std, out_dtype=jnp.bfloat16):
    """(N, H, W, C) uint8 → (N, H, W, C) ``out_dtype``, (x/255 - mean) / std fused.

    ``mean``/``std``: per-channel (C,) floats.
    """
    from jax.experimental import pallas as pl

    n, h, w, c = images.shape
    row = h * w * c
    # pad the flattened row dim to a lane multiple; channel params tile along it
    lane = 128
    padded = ((row + lane - 1) // lane) * lane
    flat = images.reshape(n, row)
    if padded != row:
        flat = jnp.pad(flat, ((0, 0), (0, padded - row)))
    reps = padded // c if padded % c == 0 else None
    mean_row = jnp.tile(jnp.asarray(mean, jnp.float32), padded // c) if reps \
        else jnp.resize(jnp.asarray(mean, jnp.float32), (padded,))
    inv_std_row = 1.0 / (jnp.tile(jnp.asarray(std, jnp.float32), padded // c) if reps
                         else jnp.resize(jnp.asarray(std, jnp.float32), (padded,)))

    block_n = min(n, 8)
    grid = ((n + block_n - 1) // block_n,)
    out = pl.pallas_call(
        _normalize_kernel,
        out_shape=jax.ShapeDtypeStruct((n, padded), out_dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_n, padded), lambda i: (i, 0)),
            pl.BlockSpec((1, padded), lambda i: (0, 0)),
            pl.BlockSpec((1, padded), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((block_n, padded), lambda i: (i, 0)),
        interpret=_use_interpret(),
    )(flat, mean_row[None], inv_std_row[None])
    return out[:, :row].reshape(n, h, w, c)


@functools.partial(jax.jit, static_argnames=("flip", "out_dtype"))
def normalize_and_augment(images, mean, std, key, flip=True, out_dtype=jnp.bfloat16):
    """Fused train-time prep: normalize + per-image random horizontal flip."""
    out = normalize_images(images, mean, std, out_dtype=out_dtype)
    if flip:
        flips = jax.random.bernoulli(key, 0.5, (images.shape[0],))
        flipped = out[:, :, ::-1, :]
        out = jnp.where(flips[:, None, None, None], flipped, out)
    return out


@functools.partial(jax.jit, static_argnames=("brightness", "contrast", "saturation"))
def color_jitter(images, key, brightness=0.4, contrast=0.4, saturation=0.4):
    """Per-image random brightness/contrast/saturation (torchvision-style ranges:
    factor ~ U[1-x, 1+x]); float images in, same dtype out. All elementwise — XLA
    fuses the three adjustments into one HBM pass alongside whatever follows."""
    dtype = images.dtype
    x = images.astype(jnp.float32)
    kb, kc, ks = jax.random.split(key, 3)
    n = x.shape[0]

    def factors(k, span):
        return jax.random.uniform(k, (n, 1, 1, 1), minval=1.0 - span,
                                  maxval=1.0 + span)

    if brightness:
        x = x * factors(kb, brightness)
    if contrast:
        mean = jnp.mean(x, axis=(1, 2, 3), keepdims=True)
        x = (x - mean) * factors(kc, contrast) + mean
    if saturation:
        gray = jnp.mean(x, axis=-1, keepdims=True)
        x = (x - gray) * factors(ks, saturation) + gray
    return jnp.clip(x, 0.0, 255.0).astype(dtype)


def random_crop(images, key, crop_h, crop_w):
    """Per-image random crop via a single dynamic gather (static output shape)."""
    n, h, w, c = images.shape
    kh, kw = jax.random.split(key)
    top = jax.random.randint(kh, (n,), 0, h - crop_h + 1)
    left = jax.random.randint(kw, (n,), 0, w - crop_w + 1)
    rows = top[:, None] + jnp.arange(crop_h)[None, :]          # (n, crop_h)
    cols = left[:, None] + jnp.arange(crop_w)[None, :]          # (n, crop_w)
    batch = jnp.arange(n)[:, None, None]
    return images[batch, rows[:, :, None], cols[:, None, :], :]
