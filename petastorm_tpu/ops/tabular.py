"""Declarative tabular preprocessing engine (ISSUE 9).

``TransformSpec`` takes an opaque per-batch pandas callable — the framework
cannot plan it, fuse it, cache its statistics, or offload it, and running it
forces a writable copy of the whole batch plus an Arrow→pandas→Arrow round
trip per row group ("Efficient Tabular Data Preprocessing of ML Pipelines",
PAPERS.md: preprocessing dominates end-to-end time for recommender/tabular
workloads). This module replaces the callable with a small **declarative op
set** composed into a :class:`FeaturePipeline` that slots in wherever a
``TransformSpec`` goes:

========================  ============================================================
op                        semantics
========================  ============================================================
:class:`Normalize`        ``(x - min) / (max - min)`` → float; min/max from
                          row-group statistics when omitted
:class:`Standardize`      ``(x - mean) / std`` → float; mean/std from one cached
                          streaming statistics pass when omitted
:class:`Clip`             ``clip(x, lo, hi)``, dtype preserved
:class:`Cast`             ``astype(dtype)``
:class:`FillNull`         NaN → ``value`` (numeric float columns)
:class:`Bucketize`        quantile/explicit boundaries → int bucket ids
:class:`HashField`        deterministic 32-bit FNV-1a hash → ``[0, num_buckets)``
:class:`VocabLookup`      categorical value → vocabulary index (OOV → ``default``)
:class:`FeatureCross`     hash-combine of N int columns → ``[0, num_buckets)``
========================  ============================================================

The **planner** (:meth:`FeaturePipeline.compile`) validates the op graph
against the Unischema (unknown fields, dtype contracts — statically mirrored
by graftlint GL-S001), derives the post-transform schema by populating
``edit_fields``/``removed_fields`` so the stock
:func:`petastorm_tpu.transform.transform_schema` applies unchanged, **fuses**
adjacent element-wise ops on the same column into one single-materialization
pass, and compiles to both execution targets:

- **host**: vectorized numpy kernels run inside the workers — columnar in,
  columnar out, no pandas round trip. Untouched columns pass through as the
  original zero-copy views; a mutated column is materialized exactly once per
  fused stage (via the PR-6 ``LeasedBatch.writable()`` CoW escalation when the
  container supports it), so the read path never needs a whole-batch writable
  copy (see ``reader._spec_wants_writable``).
- **device**: one jittable ``fn(batch) -> batch`` riding the existing
  ``TransformSpec(device=True)`` loader seam, so XLA fuses the feature math
  into the input pipeline. Hash/cross arithmetic is fixed-width uint32 on both
  targets so host and device produce identical ids (JAX disables 64-bit ints
  by default).

Ops that need dataset statistics resolve them through
:mod:`petastorm_tpu.io.statscache`: min/max ride the existing row-group
statistics plumbing (``metadata.aggregate_column_stats`` — no data pre-pass
when the parquet footers cover them); mean/std, quantiles and vocabularies run
one streaming pre-pass whose result is cached per (dataset, pipeline)
fingerprint.

Per-fused-stage timing lands on the PR-3 default registry as
``ptpu_transform_seconds{op=...}`` histograms plus ``ptpu_transform_rows_total``,
so ``DataLoader.bottleneck_report()`` finally sees inside the transform stage.
``petastorm-tpu-bench tabular`` measures the fused-vectorized path against the
equivalent per-batch pandas callable with value-identity and census checks.
"""
from __future__ import annotations

import threading
import time

import numpy as np

from petastorm_tpu.obs import provenance as _prov
from petastorm_tpu.transform import TransformSpec
from petastorm_tpu.unischema import UnischemaField


class PipelineValidationError(ValueError):
    """An op graph that cannot run against the given Unischema (unknown field,
    dtype contract violation, unresolvable statistic). Raised at plan time —
    never from inside a worker."""


# --------------------------------------------------------------------------------------
# Statistics requirements
# --------------------------------------------------------------------------------------


class StatRequirement:
    """One statistic an op needs before it can compile: ``kind`` is one of
    ``min|max|mean|std|quantiles|vocab``, ``param`` carries the kind's knob
    (bucket count / vocab size). ``key`` is the stable identity used both for
    the resolved-statistics dict and the statscache fingerprint."""

    __slots__ = ("field", "kind", "param")

    def __init__(self, field, kind, param=None):
        self.field = field
        self.kind = kind
        self.param = param

    @property
    def key(self):
        if self.param is None:
            return "%s:%s" % (self.kind, self.field)
        return "%s:%s:%s" % (self.kind, self.field, self.param)

    def __repr__(self):
        return "<StatRequirement %s>" % self.key


# --------------------------------------------------------------------------------------
# Hashing primitive (host/device identical)
# --------------------------------------------------------------------------------------

_FNV_OFFSET = np.uint32(2166136261)
_FNV_PRIME = np.uint32(16777619)


def _hash_u32_host(arr, seed=0):
    """Vectorized FNV-1a-style 32-bit hash of an integer column. All arithmetic
    wraps in uint32 — numerically identical to :func:`_hash_u32_device` so a
    pipeline compiled to either target yields the same ids."""
    x = np.asarray(arr).astype(np.int64, copy=False).view(np.uint64)
    h = np.full(x.shape, _FNV_OFFSET ^ np.uint32(seed), dtype=np.uint32)
    for shift in (0, 8, 16, 24):
        byte = ((x >> np.uint64(shift)) & np.uint64(0xFF)).astype(np.uint32)
        h = (h ^ byte) * _FNV_PRIME  # uint32 multiply wraps mod 2**32
    return h


def _hash_u32_device(arr, seed=0):
    import jax.numpy as jnp

    x = arr.astype(jnp.int32).view(jnp.uint32)
    h = jnp.full(x.shape, jnp.uint32(int(_FNV_OFFSET) ^ (seed & 0xFFFFFFFF)),
                 dtype=jnp.uint32)
    prime = jnp.uint32(int(_FNV_PRIME))
    for shift in (0, 8, 16, 24):
        byte = ((x >> jnp.uint32(shift)) & jnp.uint32(0xFF)).astype(jnp.uint32)
        h = (h ^ byte) * prime
    return h


def _crc32_table():
    """The standard reflected CRC-32 (IEEE 802.3) lookup table as uint32 —
    byte-for-byte what ``zlib.crc32`` uses, so the vectorized sweep below is
    value-identical to the per-element loop it replaced (pinned in
    tests/test_tabular.py)."""
    poly = np.uint32(0xEDB88320)
    table = np.arange(256, dtype=np.uint32)
    for _ in range(8):
        table = np.where(table & np.uint32(1),
                         (table >> np.uint32(1)) ^ poly,
                         table >> np.uint32(1)).astype(np.uint32)
    return table


_CRC32_TABLE = _crc32_table()


def _encode_string_cell(v):
    """One cell's hash bytes. Object columns may carry non-string scalars
    (decimals, big ints); those hash by their repr — deterministic, never
    by-magnitude allocation."""
    if isinstance(v, str):
        return v.encode("utf-8")
    if isinstance(v, (bytes, bytearray, memoryview)):
        return bytes(v)
    if v is None:
        return b""
    return repr(v).encode("utf-8")


def _hash_strings_scalar(values, seed=0):
    """The per-element ``zlib.crc32`` loop — PR 9's declared slow lane, kept
    as the identity oracle for the vectorized sweep and as the timing twin
    ``petastorm-tpu-bench tabular`` measures against."""
    import zlib

    out = np.empty(len(values), dtype=np.uint32)
    for i, v in enumerate(values):
        out[i] = zlib.crc32(_encode_string_cell(v), seed) & 0xFFFFFFFF
    return out


#: slicing-by-4 tables, built on first use (two merged 64K-entry uint32
#: tables = 512 KB): T0..T3 are the standard slice tables (T0 = the classic
#: byte table; T_{k+1}[b] advances T_k[b] one zero byte), merged pairwise so
#: one 16-bit gather covers two bytes — a 4-byte word costs two gathers + a
#: handful of elementwise passes instead of four full byte rounds
_crc32_slice4 = None


def _crc32_slice4_tables():
    global _crc32_slice4
    if _crc32_slice4 is None:
        t0 = _CRC32_TABLE
        mask = np.uint32(0xFF)
        eight = np.uint32(8)
        t1 = t0[t0 & mask] ^ (t0 >> eight)
        t2 = t0[t1 & mask] ^ (t1 >> eight)
        t3 = t0[t2 & mask] ^ (t2 >> eight)
        i = np.arange(65536, dtype=np.uint32)
        lo = t3[i & mask] ^ t2[(i >> eight) & mask]      # bytes 0-1 of a word
        hi = t1[i & mask] ^ t0[(i >> eight) & mask]      # bytes 2-3
        _crc32_slice4 = (lo, hi)
    return _crc32_slice4


#: widest byte matrix the vectorized path accepts: beyond this the padding
#: tax (every row materialized at maxlen) outweighs the vectorization win
#: and the C loop is faster — long-tail string columns keep the scalar lane
_MATRIX_HASH_MAX_WIDTH = 32


def _hash_strings_matrix(values, seed):
    """The byte-matrix fast lane, or ``None`` when ``values`` is ineligible
    (non-strings, non-ASCII, NUL-bearing, or wider than the padding budget).

    One ``np.asarray`` bulk-encodes the column into a UCS4 codepoint matrix
    (no per-element ``.encode()`` loop); for all-ASCII content the uint8 view
    IS the utf-8 byte matrix. Rows are length-sorted so the still-active rows
    form a contiguous prefix at every position, then the CRC register
    advances COLUMN-WISE: one slicing-by-4 step per 4-byte word column (two
    16-bit table gathers), plus up to three masked byte steps for the ragged
    tails. Values are bit-identical to ``zlib.crc32`` (pinned in
    tests/test_tabular.py)."""
    n = len(values)
    try:
        arr = np.asarray(values)
    except Exception:  # noqa: BLE001 — exotic mixed input: scalar lane
        return None
    if arr.dtype.kind != "U" or arr.ndim != 1 or arr.dtype.itemsize == 0:
        return None
    maxlen = arr.dtype.itemsize // 4
    if maxlen > _MATRIX_HASH_MAX_WIDTH:
        return None
    cp = arr.view(np.uint32).reshape(n, maxlen)
    if (cp >= 128).any():
        return None  # non-ASCII: utf-8 bytes != codepoints
    lengths = np.count_nonzero(cp, axis=1)
    pylen = np.fromiter(map(len, values), dtype=np.intp, count=n)
    if not (lengths == pylen).all():
        return None  # embedded/trailing NULs: numpy 'U' storage is lossy
    init = np.uint32(seed & 0xFFFFFFFF) ^ np.uint32(0xFFFFFFFF)
    order = np.argsort(-lengths, kind="stable")
    bm = cp.astype(np.uint8)[order]
    pad = (-maxlen) % 4
    if pad:
        bm = np.concatenate([bm, np.zeros((n, pad), np.uint8)], axis=1)
    # column-contiguous word view: the sweep reads one word column per step
    wcol = np.ascontiguousarray(bm.view("<u4").T)
    sorted_lengths = lengths[order]
    full_words = sorted_lengths // 4
    word_steps = int(full_words[0]) if n else 0  # sorted: row 0 is longest
    # rows with full_words > w form a prefix (length-descending sort)
    alive = np.searchsorted(-full_words, -np.arange(word_steps), side="left")
    crc = np.full(n, init, dtype=np.uint32)
    tlo, thi = _crc32_slice4_tables()
    m16 = np.uint32(0xFFFF)
    s16 = np.uint32(16)
    for w in range(word_steps):
        k = alive[w]
        c = crc[:k]
        x = c ^ wcol[w][:k]
        crc[:k] = tlo[x & m16] ^ thi[(x >> s16) & m16]
    # ragged tails: per row, the len%4 bytes after its last full word — at
    # most three masked byte rounds (zero padding is never processed: the
    # word sweep covers full words only, so pad bytes stay untouched)
    tails = sorted_lengths % 4
    base = full_words * 4
    t0 = _CRC32_TABLE
    m8 = np.uint32(0xFF)
    s8 = np.uint32(8)
    for m in range(3):
        sel = np.nonzero(tails > m)[0]
        if not len(sel):
            break
        b = bm[sel, base[sel] + m].astype(np.uint32)
        c = crc[sel]
        crc[sel] = (c >> s8) ^ t0[(c ^ b) & m8]
    out = np.empty(n, dtype=np.uint32)
    out[order] = crc
    return out ^ np.uint32(0xFFFFFFFF)


def _hash_strings_host(values, seed=0):
    """crc32 of a string/bytes column (ISSUE 13 satellite, closing PR 9's
    declared slow lane): the all-ASCII short-string shape — id/category/email
    columns, the hot tabular case — takes the vectorized byte-matrix lane
    (:func:`_hash_strings_matrix`, measured ~1.4-1.9x the loop in
    ``petastorm-tpu-bench tabular``); everything else (non-ASCII, bytes,
    None/decimal cells, long-tail widths) falls back to the per-element C
    loop, which padding-heavy matrices cannot beat. Both lanes produce
    bit-identical ``zlib.crc32`` values (pinned), so the dispatch is
    invisible to pipelines."""
    n = len(values)
    if n == 0:
        return np.empty(0, dtype=np.uint32)
    out = _hash_strings_matrix(values, seed)
    if out is not None:
        return out
    return _hash_strings_scalar(values, seed)


# --------------------------------------------------------------------------------------
# The op set
# --------------------------------------------------------------------------------------


class Op:
    """Base declarative op. Subclasses declare:

    - ``elementwise`` — fusable into a single-pass chain with its neighbors
      on the same column (the fused chain materializes ONE working array and
      every subsequent op runs in place on it).
    - :meth:`validate` — plan-time checks against the evolving field map.
    - :meth:`result_field` — the post-op :class:`UnischemaField` (None =
      field unchanged, e.g. ``Clip`` fused mid-chain).
    - :meth:`requirements` — the :class:`StatRequirement` list still
      unresolved (empty once parameters are explicit or bound).
    - :meth:`apply_inplace` / :meth:`apply` — host kernels; ``apply_device``
      — the jnp expression for the device target.
    """

    elementwise = False
    name = "op"

    def __init__(self, field, out=None):
        self.field = field
        self.out = out or field

    def input_fields(self):
        return (self.field,)

    def validate(self, fields):
        f = fields.get(self.field)
        if f is None:
            raise PipelineValidationError(
                "%s: input field %r is not in the schema (known: %s)"
                % (type(self).__name__, self.field, sorted(fields)))
        return f

    def _require_numeric(self, f):
        if np.dtype(f.numpy_dtype).kind not in "biuf":
            raise PipelineValidationError(
                "%s: field %r has non-numeric dtype %s"
                % (type(self).__name__, f.name, np.dtype(f.numpy_dtype)))

    def result_field(self, fields):
        return None

    def requirements(self):
        return ()

    def bind(self, stats):
        """Fill statistics-derived parameters from the resolved ``stats``
        dict (keyed by :attr:`StatRequirement.key`)."""

    def __repr__(self):
        return "%s(%r -> %r)" % (type(self).__name__, self.field, self.out)


class _ElementwiseOp(Op):
    """Numeric element-wise op: validated numeric, fused with neighbors."""

    elementwise = True
    #: dtype the fused chain must be working in for this op (None = keep)
    work_dtype = None

    def validate(self, fields):
        f = super().validate(fields)
        self._require_numeric(f)
        return f

    def apply_inplace(self, work):
        raise NotImplementedError

    def apply_device(self, x):
        raise NotImplementedError


class Normalize(_ElementwiseOp):
    """Min-max scale to ``[0, 1]``: ``(x - min) / (max - min)``. ``min``/``max``
    resolve from parquet row-group statistics when omitted (no data pre-pass
    needed when the footers cover the column)."""

    name = "normalize"

    def __init__(self, field, out=None, min=None, max=None, dtype=np.float32):
        super().__init__(field, out)
        self.min = min
        self.max = max
        self.dtype = np.dtype(dtype)
        if self.dtype.kind != "f":
            raise PipelineValidationError(
                "normalize(%r): output dtype must be floating, got %s"
                % (field, self.dtype))
        self.work_dtype = self.dtype

    def requirements(self):
        reqs = []
        if self.min is None:
            reqs.append(StatRequirement(self.field, "min"))
        if self.max is None:
            reqs.append(StatRequirement(self.field, "max"))
        return reqs

    def bind(self, stats):
        if self.min is None:
            self.min = stats["min:%s" % self.field]
        if self.max is None:
            self.max = stats["max:%s" % self.field]

    def result_field(self, fields):
        f = fields[self.field]
        return UnischemaField(self.out, self.dtype, f.shape, None, f.nullable)

    def _scale(self):
        span = float(self.max) - float(self.min)
        return 1.0 / span if span else 1.0

    def apply_inplace(self, work):
        work -= np.asarray(self.min, dtype=work.dtype)
        work *= np.asarray(self._scale(), dtype=work.dtype)

    def apply_device(self, x):
        return (x - float(self.min)) * self._scale()


class Standardize(_ElementwiseOp):
    """Z-score: ``(x - mean) / std``. ``mean``/``std`` come from one cached
    streaming statistics pass when omitted."""

    name = "standardize"

    def __init__(self, field, out=None, mean=None, std=None, dtype=np.float32):
        super().__init__(field, out)
        self.mean = mean
        self.std = std
        self.dtype = np.dtype(dtype)
        if self.dtype.kind != "f":
            raise PipelineValidationError(
                "standardize(%r): output dtype must be floating, got %s"
                % (field, self.dtype))
        self.work_dtype = self.dtype

    def requirements(self):
        reqs = []
        if self.mean is None:
            reqs.append(StatRequirement(self.field, "mean"))
        if self.std is None:
            reqs.append(StatRequirement(self.field, "std"))
        return reqs

    def bind(self, stats):
        if self.mean is None:
            self.mean = stats["mean:%s" % self.field]
        if self.std is None:
            self.std = stats["std:%s" % self.field]

    def result_field(self, fields):
        f = fields[self.field]
        return UnischemaField(self.out, self.dtype, f.shape, None, f.nullable)

    def _inv_std(self):
        return 1.0 / float(self.std) if float(self.std) else 1.0

    def apply_inplace(self, work):
        work -= np.asarray(float(self.mean), dtype=work.dtype)
        work *= np.asarray(self._inv_std(), dtype=work.dtype)

    def apply_device(self, x):
        return (x - float(self.mean)) * self._inv_std()


class Clip(_ElementwiseOp):
    """``clip(x, lo, hi)`` — dtype preserved (fuses into whatever chain it
    sits in)."""

    name = "clip"

    def __init__(self, field, lo, hi, out=None):
        super().__init__(field, out)
        self.lo = lo
        self.hi = hi

    def result_field(self, fields):
        if self.out == self.field:
            return None  # in-place: dtype/shape unchanged
        f = fields[self.field]
        return UnischemaField(self.out, f.numpy_dtype, f.shape, None,
                              f.nullable)

    def apply_inplace(self, work):
        np.clip(work, self.lo, self.hi, out=work)

    def apply_device(self, x):
        import jax.numpy as jnp

        return jnp.clip(x, self.lo, self.hi)


class Cast(_ElementwiseOp):
    """``astype(dtype)`` — folded into the fused chain's single
    materialization when adjacent to other element-wise ops."""

    name = "cast"

    def __init__(self, field, dtype, out=None):
        super().__init__(field, out)
        self.dtype = np.dtype(dtype)
        if self.dtype.kind not in "biuf":
            raise PipelineValidationError(
                "cast(%r): target dtype must be numeric/bool, got %s"
                % (field, self.dtype))
        self.work_dtype = self.dtype

    def result_field(self, fields):
        f = fields[self.field]
        return UnischemaField(self.out, self.dtype, f.shape, None, f.nullable)

    def apply_inplace(self, work):
        pass  # the chain already materialized into self.dtype

    def apply_device(self, x):
        import jax.numpy as jnp

        # device arrays live under JAX's 64-bit-disabled defaults
        dt = {np.dtype(np.float64): jnp.float32,
              np.dtype(np.int64): jnp.int32}.get(self.dtype, self.dtype)
        return x.astype(dt)


class FillNull(_ElementwiseOp):
    """NaN → ``value`` on float columns; the result field drops nullability."""

    name = "fill_null"

    def __init__(self, field, value, out=None):
        super().__init__(field, out)
        self.value = value

    def validate(self, fields):
        f = super().validate(fields)
        if np.dtype(f.numpy_dtype).kind != "f":
            raise PipelineValidationError(
                "fill_null(%r): only float columns carry NaN nulls on the "
                "columnar path; field dtype is %s (use Cast first, or encode "
                "nulls upstream)" % (self.field, np.dtype(f.numpy_dtype)))
        return f

    def result_field(self, fields):
        f = fields[self.field]
        return UnischemaField(self.out, f.numpy_dtype, f.shape, None, False)

    def apply_inplace(self, work):
        np.copyto(work, np.asarray(self.value, dtype=work.dtype),
                  where=np.isnan(work))

    def apply_device(self, x):
        import jax.numpy as jnp

        return jnp.where(jnp.isnan(x), jnp.asarray(self.value, x.dtype), x)


class Bucketize(Op):
    """Value → bucket id via ``searchsorted`` over ``boundaries`` (or
    dataset quantiles when ``num_buckets`` is given instead). Output ids lie
    in ``[0, len(boundaries)]`` — an **integer** field by contract (enforced
    here and statically by graftlint GL-S001)."""

    name = "bucketize"

    def __init__(self, field, boundaries=None, num_buckets=None, out=None,
                 dtype=np.int32):
        super().__init__(field, out)
        if (boundaries is None) == (num_buckets is None):
            raise PipelineValidationError(
                "bucketize(%r): pass exactly one of boundaries= or "
                "num_buckets=" % field)
        self.boundaries = None if boundaries is None \
            else np.asarray(boundaries, dtype=np.float64)
        self.num_buckets = num_buckets
        self.dtype = np.dtype(dtype)
        if self.dtype.kind not in "iu":
            raise PipelineValidationError(
                "bucketize(%r): bucket ids need an integer output dtype, got "
                "%s" % (field, self.dtype))

    def validate(self, fields):
        f = super().validate(fields)
        self._require_numeric(f)
        return f

    def requirements(self):
        if self.boundaries is None:
            return [StatRequirement(self.field, "quantiles", self.num_buckets)]
        return ()

    def bind(self, stats):
        if self.boundaries is None:
            self.boundaries = np.asarray(
                stats["quantiles:%s:%s" % (self.field, self.num_buckets)],
                dtype=np.float64)

    def result_field(self, fields):
        f = fields[self.field]
        return UnischemaField(self.out, self.dtype, f.shape, None, False)

    def apply(self, col):
        return np.searchsorted(
            self.boundaries, np.asarray(col, dtype=np.float64),
            side="right").astype(self.dtype, copy=False)

    def apply_device(self, x):
        import jax.numpy as jnp

        idx = jnp.searchsorted(jnp.asarray(self.boundaries, jnp.float32),
                               x.astype(jnp.float32), side="right")
        return idx.astype(jnp.int32)


class HashField(Op):
    """Deterministic 32-bit hash of a column into ``[0, num_buckets)``.
    Integer columns hash vectorized (identical ids on host and device);
    string/bytes columns take a per-element crc32 (host only)."""

    name = "hash"

    def __init__(self, field, num_buckets, out=None, seed=0, dtype=np.int64):
        super().__init__(field, out)
        self.num_buckets = int(num_buckets)
        if self.num_buckets <= 0:
            raise PipelineValidationError(
                "hash(%r): num_buckets must be positive" % field)
        self.seed = int(seed)
        self.dtype = np.dtype(dtype)
        if self.dtype.kind not in "iu":
            raise PipelineValidationError(
                "hash(%r): hashed ids need an integer output dtype, got %s"
                % (field, self.dtype))

    def validate(self, fields):
        f = super().validate(fields)
        kind = np.dtype(f.numpy_dtype).kind
        if kind not in "biuUSO":
            raise PipelineValidationError(
                "hash(%r): cannot hash dtype %s (integer or string columns "
                "only)" % (self.field, np.dtype(f.numpy_dtype)))
        return f

    def result_field(self, fields):
        f = fields[self.field]
        return UnischemaField(self.out, self.dtype, f.shape, None, False)

    def apply(self, col):
        arr = np.asarray(col)
        if arr.dtype.kind in "biu":
            h = _hash_u32_host(arr, self.seed)
        else:
            h = _hash_strings_host(arr.ravel().tolist(),
                                   self.seed).reshape(arr.shape)
        return (h % np.uint32(self.num_buckets)).astype(self.dtype, copy=False)

    def apply_device(self, x):
        import jax.numpy as jnp

        h = _hash_u32_device(x, self.seed)
        return (h % jnp.uint32(self.num_buckets)).astype(jnp.int32)


class VocabLookup(Op):
    """Categorical value → vocabulary index. An explicit ``vocab`` (sequence,
    index = position) or a computed one (``max_size`` most frequent values,
    frequency-descending, from the cached statistics pass). Out-of-vocabulary
    values map to ``default``."""

    name = "vocab"

    def __init__(self, field, vocab=None, max_size=None, out=None, default=-1,
                 dtype=np.int64):
        super().__init__(field, out)
        if (vocab is None) == (max_size is None):
            raise PipelineValidationError(
                "vocab(%r): pass exactly one of vocab= or max_size=" % field)
        self.vocab = None if vocab is None else list(vocab)
        self.max_size = max_size
        self.default = int(default)
        self.dtype = np.dtype(dtype)
        if self.dtype.kind not in "iu":
            raise PipelineValidationError(
                "vocab(%r): vocabulary indices need an integer output dtype, "
                "got %s" % (field, self.dtype))
        self._sorted = None
        self._order = None

    def requirements(self):
        if self.vocab is None:
            return [StatRequirement(self.field, "vocab", self.max_size)]
        return ()

    def bind(self, stats):
        if self.vocab is None:
            self.vocab = list(stats["vocab:%s:%s" % (self.field,
                                                     self.max_size)])

    def result_field(self, fields):
        f = fields[self.field]
        return UnischemaField(self.out, self.dtype, f.shape, None, False)

    def _tables(self):
        if self._sorted is None:
            vocab = np.asarray(self.vocab)
            order = np.argsort(vocab, kind="stable")
            self._sorted = vocab[order]
            self._order = order.astype(np.int64)
        return self._sorted, self._order

    def apply(self, col):
        arr = np.asarray(col)
        svocab, order = self._tables()
        if svocab.dtype.kind in "US" and arr.dtype.kind not in "US":
            arr = arr.astype(svocab.dtype.kind)  # object str column → unicode
        idx = np.searchsorted(svocab, arr)
        idx = np.clip(idx, 0, len(svocab) - 1)
        hit = svocab[idx] == arr
        out = np.where(hit, order[idx], self.default)
        return out.astype(self.dtype, copy=False)

    def apply_device(self, x):
        import jax.numpy as jnp

        svocab, order = self._tables()
        if svocab.dtype.kind not in "biuf":
            raise PipelineValidationError(
                "vocab(%r): string vocabularies cannot run on the device "
                "target — hash the column instead, or keep the pipeline on "
                "the host" % self.field)
        sv = jnp.asarray(svocab)
        idx = jnp.clip(jnp.searchsorted(sv, x), 0, len(svocab) - 1)
        hit = sv[idx] == x
        return jnp.where(hit, jnp.asarray(order, jnp.int32)[idx],
                         jnp.int32(self.default)).astype(jnp.int32)


class FeatureCross(Op):
    """Hash-combine N integer (or previously hashed) columns into one crossed
    id in ``[0, num_buckets)`` — uint32 arithmetic, host/device identical."""

    name = "cross"

    def __init__(self, fields, num_buckets, out, seed=0, dtype=np.int64):
        if not fields or len(fields) < 2:
            raise PipelineValidationError(
                "cross: needs at least two input fields, got %r" % (fields,))
        super().__init__(fields[0], out)
        self.fields = tuple(fields)
        self.num_buckets = int(num_buckets)
        self.seed = int(seed)
        self.dtype = np.dtype(dtype)
        if self.dtype.kind not in "iu":
            raise PipelineValidationError(
                "cross%r: crossed ids need an integer output dtype, got %s"
                % (tuple(fields), self.dtype))

    def input_fields(self):
        return self.fields

    def validate(self, fields):
        for name in self.fields:
            f = fields.get(name)
            if f is None:
                raise PipelineValidationError(
                    "cross: input field %r is not in the schema (known: %s)"
                    % (name, sorted(fields)))
            if np.dtype(f.numpy_dtype).kind not in "biu":
                raise PipelineValidationError(
                    "cross: field %r has dtype %s — cross integer columns "
                    "(HashField string columns first)"
                    % (name, np.dtype(f.numpy_dtype)))
        return fields[self.fields[0]]

    def result_field(self, fields):
        f = fields[self.fields[0]]
        return UnischemaField(self.out, self.dtype, f.shape, None, False)

    def apply_multi(self, cols):
        h = _hash_u32_host(cols[0], self.seed)
        for col in cols[1:]:
            h = (h * _FNV_PRIME) ^ _hash_u32_host(col, self.seed)
        return (h % np.uint32(self.num_buckets)).astype(self.dtype, copy=False)

    def apply_device_multi(self, cols):
        import jax.numpy as jnp

        h = _hash_u32_device(cols[0], self.seed)
        prime = jnp.uint32(int(_FNV_PRIME))
        for col in cols[1:]:
            h = (h * prime) ^ _hash_u32_device(col, self.seed)
        return (h % jnp.uint32(self.num_buckets)).astype(jnp.int32)


# --------------------------------------------------------------------------------------
# Per-op metrics (ptpu_transform_*)
# --------------------------------------------------------------------------------------

_metrics_lock = threading.Lock()
_op_seconds = {}   # op label -> Histogram on the default registry
_rows_counter = None


def _stage_metrics(label):
    """(seconds histogram, rows counter) for one fused-stage label — resolved
    once per process (workers stay picklable: nothing registry-shaped lives on
    pipeline instances). The rows counter is assigned BEFORE the histogram is
    published, so the lock-free fast path can never observe (hist, None)."""
    global _rows_counter
    hist = _op_seconds.get(label)
    if hist is None:
        from petastorm_tpu.obs.metrics import default_registry

        with _metrics_lock:
            hist = _op_seconds.get(label)
            if hist is None:
                reg = default_registry()
                if _rows_counter is None:
                    _rows_counter = reg.counter(
                        "ptpu_transform_rows_total",
                        help="rows through the declarative transform stage")
                hist = reg.histogram(
                    "ptpu_transform_seconds",
                    help="declarative transform time per fused stage, by op",
                    op=label)
                _op_seconds[label] = hist
    return hist, _rows_counter


def transform_op_stats():
    """``{op label: histogram summary}`` snapshot of the per-op transform
    timings recorded in THIS process (thread/dummy pools; process-pool
    children keep their own registries). Consumed by the bottleneck analyzer
    so the transform stage is no longer opaque."""
    with _metrics_lock:
        items = list(_op_seconds.items())
    return {label: hist.snapshot() for label, hist in items
            if hist.count}


# --------------------------------------------------------------------------------------
# Compiled plan stages
# --------------------------------------------------------------------------------------


class _FusedStage:
    """A maximal run of adjacent element-wise ops on ONE column, compiled to a
    single-materialization pass: the working array is created once (``astype``
    — or the lease CoW escalation when the container offers ``writable`` and
    the dtype already matches) and every op mutates it in place."""

    def __init__(self, ops, source, out, out_dtype):
        self.ops = list(ops)
        self.source = source
        self.out = out
        self.out_dtype = out_dtype
        self.label = "+".join(op.name for op in self.ops)

    def inputs(self):
        return (self.source,)

    def apply(self, container):
        col = container[self.source]
        col = np.asarray(col)
        if self.out == self.source and col.dtype == self.out_dtype \
                and hasattr(container, "writable"):
            # in-place rewrite of a leased column: ONE CoW copy (counted as
            # lease_cow), untouched columns stay zero-copy views
            work = container.writable(self.source)
        else:
            work = col.astype(self.out_dtype)  # the single materialization
            if not work.flags.writeable or work.base is not None:
                work = np.array(work)  # same-dtype astype may return a view
        for op in self.ops:
            op.apply_inplace(work)
        return work

    def apply_device(self, batch):
        x = batch[self.source]
        for op in self.ops:
            x = op.apply_device(x)
        if self.out_dtype is not None:
            import jax.numpy as jnp

            dt = {np.dtype(np.float64): jnp.float32,
                  np.dtype(np.int64): jnp.int32}.get(np.dtype(self.out_dtype),
                                                     self.out_dtype)
            x = x.astype(dt)
        return x


class _OpStage:
    """A non-fusable op (bucketize/hash/vocab/cross) as its own stage."""

    def __init__(self, op):
        self.op = op
        self.out = op.out
        self.label = op.name

    def inputs(self):
        return tuple(op_inputs(self.op))

    def apply(self, container):
        if isinstance(self.op, FeatureCross):
            return self.op.apply_multi([np.asarray(container[n])
                                        for n in self.op.fields])
        return self.op.apply(container[self.op.field])

    def apply_device(self, batch):
        if isinstance(self.op, FeatureCross):
            return self.op.apply_device_multi([batch[n]
                                               for n in self.op.fields])
        return self.op.apply_device(batch[self.op.field])


def op_inputs(op):
    return op.input_fields()


# --------------------------------------------------------------------------------------
# FeaturePipeline
# --------------------------------------------------------------------------------------


class FeaturePipeline(TransformSpec):
    """A declarative transform: an ordered list of ops, planned and compiled
    against the read schema. Slots in anywhere a :class:`TransformSpec` does
    (``make_reader``/``make_batch_reader`` ``transform_spec=``); the reader
    factories call :meth:`compile` after resolving the read schema and any
    dataset statistics, and :func:`petastorm_tpu.transform.transform_schema`
    then consumes the derived ``edit_fields``/``removed_fields`` unchanged.

    ``device=True`` compiles the SAME op list to one jittable
    ``fn(batch) -> batch`` riding the existing ``TransformSpec(device=True)``
    loader seam (XLA fuses it into the input pipeline).
    """

    declarative = True  # the marker the read path branches on (transform.py)

    def __init__(self, ops, selected_fields=None, removed_fields=None,
                 device=False):
        super().__init__(func=None, edit_fields=None,
                         removed_fields=removed_fields,
                         selected_fields=selected_fields, device=device)
        self.ops = list(ops)
        for op in self.ops:
            if not isinstance(op, Op):
                raise PipelineValidationError(
                    "FeaturePipeline ops must be tabular Op instances; got %r"
                    % (op,))
        self.compiled = False
        self._plan = []
        #: requirement key -> "rowgroup-stats" | "data-pass" | "cached" —
        #: how each statistic was resolved (observability + tests)
        self.stats_info = {}

    # -- planning -----------------------------------------------------------------------

    def required_statistics(self, schema):
        """Unresolved :class:`StatRequirement` list, validated against
        ``schema`` — statistics are computed over STORED columns, so an op
        whose stat input was already written by an EARLIER op (renamed or
        transformed in place: stored-column statistics no longer describe
        the runtime values) must carry explicit parameters."""
        written = set()
        reqs = []
        for op in self.ops:
            for req in op.requirements():
                if req.field in written:
                    raise PipelineValidationError(
                        "%s(%r): statistics-dependent parameters on a field "
                        "an earlier op already transformed cannot be computed "
                        "from the stored dataset — pass them explicitly"
                        % (type(op).__name__, req.field))
                if req.field not in schema.fields:
                    raise PipelineValidationError(
                        "%s: input field %r is not in the schema (known: %s)"
                        % (type(op).__name__, req.field,
                           sorted(schema.fields)))
                reqs.append(req)
            written.add(op.out)
        return reqs

    def compile(self, schema, statistics=None):
        """Validate the op graph against ``schema``, bind resolved
        ``statistics``, derive the post-transform schema edits, and fuse the
        plan. Idempotent; raises :class:`PipelineValidationError` on any
        contract violation."""
        statistics = statistics or {}
        fields = dict(schema.fields)
        edits = []
        for op in self.ops:
            missing = [r.key for r in op.requirements()
                       if r.key not in statistics]
            if missing:
                raise PipelineValidationError(
                    "%s(%r): unresolved statistics %s — compile through the "
                    "reader factories (which run the statistics pass), or "
                    "pass the parameters explicitly"
                    % (type(op).__name__, op.field, missing))
            op.bind(statistics)
            op.validate(fields)
            new_field = op.result_field(fields)
            if new_field is not None:
                fields[new_field.name] = new_field
                edits.append(new_field)
        for removed in self.removed_fields:
            if removed not in fields:
                raise PipelineValidationError(
                    "removed_fields names %r, which is not a schema or "
                    "derived field" % removed)
        if self.selected_fields is not None:
            missing = set(self.selected_fields) - set(fields)
            if missing:
                raise PipelineValidationError(
                    "selected_fields %r not present after the pipeline"
                    % sorted(missing))
        # last edit per name wins (same contract as transform_schema's dict)
        by_name = {f.name: f for f in edits}
        self.edit_fields = list(by_name.values())
        self._plan = self._fuse(schema)
        self.func = self._device_call if self.device else self._host_call
        self.compiled = True
        return self

    def _fuse(self, schema):
        """Adjacent element-wise ops chained on the same column collapse into
        one :class:`_FusedStage` (op N+1 reads op N's output) — one
        materialization, the rest in place.

        A chain runs entirely in ONE working dtype (set by its first
        dtype-declaring op, or the column's dtype); an op that needs a
        DIFFERENT working dtype ends the chain and starts a new one, so the
        fused semantics always equal the unfused sequence — in particular
        ``Standardize → Cast(int)`` must not run the float math in integer
        arithmetic."""
        plan = []
        run = []           # accumulating elementwise ops
        run_source = None
        run_dtype = None   # the chain's working (= materialization) dtype
        dtypes = {name: np.dtype(f.numpy_dtype)
                  for name, f in schema.fields.items()}

        def flush():
            nonlocal run_dtype
            if not run:
                return
            out_dtype = run_dtype if run_dtype is not None \
                else np.dtype(np.float64)
            plan.append(_FusedStage(run[:], run_source, run[-1].out, out_dtype))
            dtypes[run[-1].out] = out_dtype
            run.clear()
            run_dtype = None

        for op in self.ops:
            if op.elementwise:
                want = None if op.work_dtype is None else np.dtype(op.work_dtype)
                # only an IN-PLACE op (out == field) may extend a chain: a
                # mid-chain rename would fuse away an intermediate output the
                # derived schema declares
                if run and op.field == run[-1].out and op.out == op.field \
                        and (want is None or want == run_dtype):
                    run.append(op)        # extends the chain in place
                    continue
                flush()
                run.append(op)
                run_source = op.field
                run_dtype = want if want is not None \
                    else dtypes.get(op.field)
            else:
                flush()
                plan.append(_OpStage(op))
                dtypes[op.out] = op.dtype
        flush()
        return plan

    # -- execution ----------------------------------------------------------------------

    def _finalize(self, result):
        if self.selected_fields is not None:
            if hasattr(result, "writable"):
                # lease container: subset in place so the leases stay attached
                for name in list(result.keys()):
                    if name not in self.selected_fields:
                        result.pop(name)
                return result
            return {name: result[name] for name in self.selected_fields}
        for removed in self.removed_fields:
            result.pop(removed, None)
        return result

    def apply_columns(self, columns):
        """Host target: columnar batch in, columnar batch out. Untouched
        columns pass through as the original (possibly zero-copy read-only)
        arrays; each fused stage materializes exactly one working array. A
        :class:`~petastorm_tpu.io.lease.LeasedBatch` input is transformed in
        its own container (outputs set alongside the leased views, mutated
        columns escalated per-column via ``writable()``) so its leases keep
        protecting the untouched columns."""
        if not self.compiled:
            raise PipelineValidationError(
                "FeaturePipeline was not compiled — open it through "
                "make_reader/make_batch_reader, or call compile(schema)")
        result = columns if hasattr(columns, "writable") \
            else dict(columns.items())
        if not self._plan:
            return self._finalize(result)
        rows = None
        for stage in self._plan:
            t0 = time.perf_counter()
            out = stage.apply(result)
            result[stage.out] = out
            dt = time.perf_counter() - t0
            hist, _rows_total = _stage_metrics(stage.label)
            hist.observe(dt)
            if _prov.ACTIVE is not None:  # fused-stage timing (ISSUE 10)
                _prov.add_span("transform.%s" % stage.label, t0, dt)
            if rows is None:
                rows = len(out) if hasattr(out, "__len__") else 0
        if rows:
            _stage_metrics(self._plan[0].label)[1].inc(rows)
        return self._finalize(result)

    def apply_rows(self, rows):
        """Per-row-path host target: the row dicts are columnarized ONCE, the
        compiled columnar kernels run over the whole window, and fresh row
        dicts are rebuilt — replacing the per-row ``func(dict(r))`` loop the
        opaque callable forces (ISSUE 9 satellite: the NGram path applies the
        transform once over the window's columnar form)."""
        if not rows or not self._plan:
            return [self._finalize(dict(r)) for r in rows]
        available = set(rows[0].keys())
        needed = set()
        for stage in self._plan:
            needed.update(n for n in stage.inputs() if n in available)
        merged = {}
        for name in needed:
            values = [r.get(name) for r in rows]
            try:
                merged[name] = np.asarray(values)
            except (ValueError, TypeError):
                arr = np.empty(len(values), dtype=object)
                arr[:] = values
                merged[name] = arr
        out_cols = {}
        for stage in self._plan:
            t0 = time.perf_counter()
            out = stage.apply(merged)
            merged[stage.out] = out
            out_cols[stage.out] = out
            dt = time.perf_counter() - t0
            hist, _rows_total = _stage_metrics(stage.label)
            hist.observe(dt)
            if _prov.ACTIVE is not None:  # fused-stage timing (ISSUE 10)
                _prov.add_span("transform.%s" % stage.label, t0, dt)
        _stage_metrics(self._plan[0].label)[1].inc(len(rows))
        new_rows = []
        for i, r in enumerate(rows):
            nr = dict(r)
            for name, col in out_cols.items():
                nr[name] = col[i]
            if self.selected_fields is not None:
                nr = {name: nr[name] for name in self.selected_fields}
            else:
                for removed in self.removed_fields:
                    nr.pop(removed, None)
            new_rows.append(nr)
        return new_rows

    def _host_call(self, columns):
        """``TransformSpec.func`` shape for the host target (bound method —
        picklable with the pipeline, so process-pool workers carry it)."""
        return self.apply_columns(columns)

    def _device_call(self, batch):
        """The jittable device function (``TransformSpec(device=True)`` seam):
        every stage is jnp expressions over the batch dict, so one ``jax.jit``
        — the loader's — fuses the whole pipeline into the input step."""
        result = dict(batch)
        for stage in self._plan:
            result[stage.out] = stage.apply_device(result)
        return self._finalize(result)

    def device_fn(self, schema):
        """Compile (if needed) and return the jittable device function —
        the hook :class:`petastorm_tpu.loader.DataLoader` uses when a
        pipeline is passed directly as ``device_transform=``."""
        if not self.compiled:
            reqs = self.required_statistics(schema)
            if reqs:
                raise PipelineValidationError(
                    "device pipeline needs dataset statistics %s — open the "
                    "reader with transform_spec=FeaturePipeline(..., "
                    "device=True) so the factory resolves them"
                    % [r.key for r in reqs])
            self.compile(schema)
        return self._device_call

    def __repr__(self):
        return "FeaturePipeline(%s%s)" % (
            ", ".join(repr(op) for op in self.ops),
            ", device=True" if self.device else "")
