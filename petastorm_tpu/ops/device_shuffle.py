"""HBM-resident shuffle buffer: on-device sample decorrelation (SURVEY.md §8 L6).

The reference shuffles rows in host python (``RandomShufflingBuffer``); at TPU batch rates
that costs host CPU and H2D bandwidth. This buffer keeps a fixed-size ring of rows in device
HBM and serves random batches by a single fused gather (one XLA ``take`` per column), with
deterministic multi-host semantics: every process uses the same PRNG key stream, so sampling
indices agree across hosts even though each host holds different shard data.

All state transitions are pure jitted functions (donate-friendly); the class is a thin
host-side cursor wrapper.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, donate_argnums=(0,))
def _insert(store, batch, cursor):
    """Overwrite ring rows [cursor, cursor+b) (wrapping) with the batch."""
    cap = next(iter(store.values())).shape[0]
    b = next(iter(batch.values())).shape[0]
    idx = (cursor + jnp.arange(b)) % cap
    return {k: store[k].at[idx].set(batch[k].astype(store[k].dtype)) for k in store}


@functools.partial(jax.jit, static_argnames=("batch_size",))
def _sample(store, key, filled, batch_size):
    cap = next(iter(store.values())).shape[0]
    idx = jax.random.randint(key, (batch_size,), 0, jnp.maximum(filled, 1))
    idx = idx % cap
    return {k: v[idx] for k, v in store.items()}


class DeviceShuffleBuffer:
    """Fixed-capacity device ring + random-gather sampling.

    >>> buf = DeviceShuffleBuffer(capacity=4096, example_batch=batch, key=key)
    >>> buf.insert(batch)          # O(b) scatter in HBM
    >>> out = buf.sample(256)      # O(b) gather, decorrelated rows
    """

    def __init__(self, capacity, example_batch, key, sharding=None):
        self.capacity = int(capacity)
        self._key = key
        self._cursor = 0
        self._filled = 0
        store = {}
        for name, arr in example_batch.items():
            shape = (self.capacity,) + tuple(arr.shape[1:])
            z = jnp.zeros(shape, arr.dtype)
            if sharding is not None:
                z = jax.device_put(z, sharding)
            store[name] = z
        self._store = store

    @property
    def filled(self):
        return self._filled

    def insert(self, batch):
        b = len(next(iter(batch.values())))
        if b > self.capacity:
            raise ValueError("batch of %d exceeds capacity %d" % (b, self.capacity))
        self._store = _insert(self._store, batch, jnp.int32(self._cursor))
        self._cursor = (self._cursor + b) % self.capacity
        self._filled = min(self.capacity, self._filled + b)
        return self

    def sample(self, batch_size):
        if self._filled == 0:
            raise ValueError("sampling from an empty shuffle buffer")
        self._key, sub = jax.random.split(self._key)
        return _sample(self._store, sub, jnp.int32(self._filled), batch_size)
