"""HBM-resident shuffle buffer: on-device sample decorrelation (SURVEY.md §8 L6).

The reference shuffles rows in host python with a retrieve-and-remove buffer
(petastorm/reader_impl/shuffling_buffer.py ~L80); at TPU batch rates that costs host CPU
and re-pays H2D bandwidth. This buffer keeps a fixed-size ring of rows in device HBM and
runs a **streaming exchange**: each incoming (already-transferred) batch picks ``b``
DISTINCT random slots of the full ring, emits the rows currently in those slots, and
writes the incoming rows into them — one fused gather + one fused scatter per batch,
``O(batch)`` HBM traffic, no host involvement.

Semantics are epoch-honest (the reference's retrieve-and-remove contract, not sampling
with replacement): every inserted row is emitted exactly once — displaced rows ARE the
output, and ``drain()`` flushes the residue as a permutation. A row lingers in the ring
for a geometric number of exchanges (mean ≈ capacity/batch), giving a decorrelation
window of ~``capacity`` rows.

Multi-host: every process folds the same seed, so slot indices agree across hosts; with
globally-sharded stores the gather/scatter run SPMD and decorrelate rows ACROSS shards
(host-side buffers cannot do that at all — shard mixing would need a network hop).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _partial_fisher_yates(idx, key, b):
    """First ``b`` steps of a Fisher–Yates pass over the persistent permutation ``idx``.

    Returns (new_idx, slots): ``slots`` is a uniformly random ordered ``b``-subset of
    ``[0, cap)`` — regardless of the permutation ``idx`` starts as — and ``new_idx`` is
    again a permutation, so successive draws stay uniform. Cost is ``O(b)`` updates on
    the donated carry (vs ``O(capacity)`` for a full ``jax.random.permutation``), which
    keeps the per-exchange cost flat as the ring grows to HBM scale.
    """
    cap = idx.shape[0]
    bits = jax.random.bits(key, (b,), jnp.uint32)
    span = (cap - jnp.arange(b)).astype(jnp.uint32)
    # modulo draw of j_i ∈ [i, cap); bias ≤ cap/2**32 per draw — immaterial for shuffle
    js = jnp.arange(b, dtype=jnp.int32) + (bits % span).astype(jnp.int32)

    def step(carry, args):
        i, j = args
        vi = carry[i]
        vj = carry[j]
        return carry.at[i].set(vj).at[j].set(vi), vj

    return jax.lax.scan(step, idx, (jnp.arange(b, dtype=jnp.int32), js))


@functools.partial(jax.jit, donate_argnums=(0, 1))
def _exchange(store, idx, batch, key):
    """Pick ``b`` distinct slots; emit their rows; overwrite them with ``batch``."""
    b = next(iter(batch.values())).shape[0]
    idx, slots = _partial_fisher_yates(idx, key, b)
    out = {k: store[k][slots] for k in store}
    new_store = {k: store[k].at[slots].set(batch[k].astype(store[k].dtype))
                 for k in store}
    return new_store, idx, out


@functools.partial(jax.jit, donate_argnums=(0,))
def _fill(store, batch, cursor):
    """Warmup: write the batch at [cursor, cursor+b) (no wrap — warmup never overflows
    because capacity is a multiple of the batch size)."""
    b = next(iter(batch.values())).shape[0]
    idx = cursor + jnp.arange(b)
    return {k: store[k].at[idx].set(batch[k].astype(store[k].dtype)) for k in store}


class DeviceShuffleBuffer:
    """Fixed-capacity HBM ring with exact, without-replacement streaming shuffle.

    >>> buf = DeviceShuffleBuffer(capacity=4096, seed=0)
    >>> for batch in device_batches:          # {name: jax.Array}, equal leading dim
    ...     out = buf.push(batch)             # None during warmup, else a shuffled batch
    ...     if out is not None: consume(out)
    >>> for out in buf.drain():               # flush the residue, permuted
    ...     consume(out)

    ``capacity`` is rounded up to a multiple of the first batch's row count so warmup
    fills exactly. All rows pushed are eventually emitted exactly once (union of push
    outputs + drain == union of inputs). A batch SHORTER than the first batch is only
    legal as the final push of a stream (the loader's ``last_batch='partial'`` tail);
    pushing again after a short warmup batch raises — silently continuing would
    scatter past the ring and lose rows.

    ``shardings``: optional ``callable(name, zeros) -> Sharding | None`` laying out
    each ring column (the loader passes its batch sharding adapted per column), so the
    ring splits across devices like the batches do instead of replicating a full copy
    per device; the store is then created directly in that layout (no transient
    single-device allocation).
    """

    def __init__(self, capacity, seed=0, shardings=None):
        if capacity <= 0:
            raise ValueError("capacity must be positive")
        self._requested_capacity = int(capacity)
        self.capacity = None  # fixed at first push (rounded up to batch multiple)
        self._key = jax.random.PRNGKey(int(seed))
        self._fill_rows = 0
        self._store = None
        self._idx = None
        self._batch_rows = None
        self._shardings = shardings
        self._short_warmup = False

    @property
    def filled(self):
        return self._fill_rows

    def _init_store(self, batch):
        b = next(iter(batch.values())).shape[0]
        self._batch_rows = b
        self._short_warmup = False  # buffer may be re-filled after a drain()
        self.capacity = -(-self._requested_capacity // b) * b
        store = {}
        for name, arr in batch.items():
            shape = (self.capacity,) + tuple(arr.shape[1:])
            s = self._shardings(name, arr) if self._shardings is not None else None
            if s is not None:
                # allocate straight into the target layout — jnp.zeros-then-device_put
                # would transiently materialize the full ring on one device
                store[name] = jax.jit(
                    functools.partial(jnp.zeros, shape, arr.dtype),
                    out_shardings=s)()
            else:
                store[name] = jnp.zeros(shape, arr.dtype)
        self._store = store
        self._idx = jnp.arange(self.capacity, dtype=jnp.int32)

    def push(self, batch):
        """Insert a device batch; returns the displaced batch once warm, else None."""
        if self._store is None:
            self._init_store(batch)
        b = next(iter(batch.values())).shape[0]
        if set(batch) != set(self._store):
            raise ValueError(
                "batch columns %s do not match buffer columns %s"
                % (sorted(batch), sorted(self._store)))
        if self._fill_rows < self.capacity:
            if b > self._batch_rows:
                raise ValueError(
                    "warmup batches must not exceed the first batch's row count (%d), "
                    "got %d" % (self._batch_rows, b))
            if self._short_warmup:
                raise ValueError(
                    "a batch shorter than the first batch's row count is only legal "
                    "as the FINAL push of a stream (warmup scatters would overrun "
                    "the ring and lose rows); drain() after the short batch")
            if b < self._batch_rows:
                self._short_warmup = True
            self._store = _fill(self._store, batch, jnp.int32(self._fill_rows))
            self._fill_rows += b
            return None
        if b > self._batch_rows:
            # an oversized batch would wrap the Fisher–Yates span (uint32) and the
            # clamped scatter would silently drop rows — refuse loudly instead
            raise ValueError(
                "batches must not exceed the first batch's row count (%d), got %d"
                % (self._batch_rows, b))
        self._key, sub = jax.random.split(self._key)
        self._store, self._idx, out = _exchange(self._store, self._idx, batch, sub)
        return out

    def drain(self, batch_rows=None):
        """Emit the resident rows as a fresh permutation, in batches of ``batch_rows``
        (default: the push batch size; the final batch may be short). The buffer is
        empty afterwards."""
        if self._store is None or self._fill_rows == 0:
            return
        b = batch_rows or self._batch_rows
        self._key, sub = jax.random.split(self._key)
        # one permutation over the filled prefix (host-static size: one compile per
        # distinct drain fill — happens once per stream end)
        perm = jax.random.permutation(sub, self._fill_rows)
        shuffled = {k: v[perm] for k, v in self._store.items()}
        filled = self._fill_rows
        self._store = None
        self._idx = None
        self._fill_rows = 0
        for start in range(0, filled, b):
            yield {k: v[start:start + b] for k, v in shuffled.items()}
