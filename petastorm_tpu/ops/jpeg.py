"""Two-stage JPEG decode: host entropy decode → device dequant+IDCT+color (SURVEY.md §8,
hard part #1).

Huffman entropy decoding is sequential and branchy — a poor fit for TPU vector units — but
it is only ~10% of JPEG decode FLOPs. The split:

- **Stage 1 (host)**: :func:`entropy_decode_jpeg` parses a baseline JPEG and Huffman-decodes
  the scan into *quantized DCT coefficient blocks* per component (pure python/numpy here; a
  native decoder can swap in behind the same output contract).
- **Stage 2 (device)**: :func:`decode_jpeg_device_stage` runs dequantization, 8×8 inverse
  DCT (one (N,64)@(64,64) matmul per plane — MXU work), level shift, chroma upsampling and
  YCbCr→RGB as one jitted program; the IDCT matmul is a Pallas kernel on TPU.

The classic full-host path stays available via ``CompressedImageCodec`` (cv2), which is also
the correctness oracle for the tests.
"""
from __future__ import annotations

import dataclasses
import functools
import struct
import threading

import numpy as np

# -- zigzag order (JPEG spec, Figure A.6) ----------------------------------------------

ZIGZAG = np.array([
    0, 1, 8, 16, 9, 2, 3, 10, 17, 24, 32, 25, 18, 11, 4, 5,
    12, 19, 26, 33, 40, 48, 41, 34, 27, 20, 13, 6, 7, 14, 21, 28,
    35, 42, 49, 56, 57, 50, 43, 36, 29, 22, 15, 23, 30, 37, 44, 51,
    58, 59, 52, 45, 38, 31, 39, 46, 53, 60, 61, 54, 47, 55, 62, 63,
], dtype=np.int32)
UNZIGZAG = np.argsort(ZIGZAG)


@dataclasses.dataclass
class JpegComponent:
    blocks: np.ndarray      # (blocks_y, blocks_x, 64) int32, natural (unzigzagged) order
    qtable: np.ndarray      # (64,) int32, natural order
    h_samp: int
    v_samp: int


@dataclasses.dataclass
class JpegPlanes:
    height: int
    width: int
    components: list        # [Y, Cb, Cr] or [Y]
    #: set by the row-group batched stage 1: ``(coeffs_tuple, qtabs_array, row_index)``
    #: where each component's ``blocks`` is a zero-copy view into ``coeffs_tuple[c]``.
    #: Lets :func:`stack_jpeg_coefficients` re-assemble batches by slicing/gathering the
    #: parent buffers instead of np.stack over per-row objects.
    batch_ref: tuple | None = None
    #: per component, the max ZIGZAG index with any nonzero coefficient (from the
    #: native batch decode) — lets the device transfer ship only the zigzag prefix.
    kmax: tuple | None = None
    #: ``(ncomp, 64)`` int32 per-zigzag-position max |coefficient| over the row group
    #: (shared array across the group's rows) — drives the per-position bit-width
    #: transfer split. None when stage 1 did not profile the spectrum.
    specmax: object = None

    def detach(self):
        """Return an equivalent ``JpegPlanes`` that owns its own coefficient copies.

        A ``batch_ref`` row pins its ENTIRE row group's stacked buffers alive (its
        blocks are views); long-lived rows — e.g. stragglers in a shuffling buffer that
        interleaves many row groups — must be detached so host memory scales with rows
        in flight, not row groups touched. ``specmax`` stays shared (tiny, immutable)."""
        if self.batch_ref is None:
            return self
        comps = [
            JpegComponent(c.blocks.copy(), c.qtable.copy(), c.h_samp, c.v_samp)
            for c in self.components
        ]
        return JpegPlanes(self.height, self.width, comps, batch_ref=None,
                          kmax=self.kmax, specmax=self.specmax)

    def __reduce__(self):
        # pickle (process-pool IPC, disk cache) must ship ONLY this row: the default
        # reduce would serialize batch_ref's entire row-group buffers per row
        d = self.detach()
        return (JpegPlanes, (d.height, d.width, d.components, None, d.kmax, d.specmax))


class _HuffTable:
    __slots__ = ("lookup", "max_len")

    def __init__(self, counts, symbols):
        self.lookup = {}
        code = 0
        k = 0
        self.max_len = 0
        for length in range(1, 17):
            for _ in range(counts[length - 1]):
                self.lookup[(length, code)] = symbols[k]
                self.max_len = length
                code += 1
                k += 1
            code <<= 1


class _BitReader:
    """MSB-first bit reader over an entropy-coded segment with 0xFF00 byte-stuffing."""

    __slots__ = ("data", "pos", "bitbuf", "bitcnt")

    def __init__(self, data, pos):
        self.data = data
        self.pos = pos
        self.bitbuf = 0
        self.bitcnt = 0

    def _fill(self):
        while self.bitcnt <= 24:
            if self.pos >= len(self.data):
                b = 0  # pad with zeros past the end (spec allows)
            else:
                b = self.data[self.pos]
                if b == 0xFF:
                    nxt = self.data[self.pos + 1] if self.pos + 1 < len(self.data) else 0xD9
                    if nxt == 0x00:
                        self.pos += 2  # byte-stuffed 0xFF data byte
                    else:
                        # restart or real marker: stop feeding real bytes, pad zeros
                        # (align_restart advances past RSTn when the caller asks)
                        b = 0
                else:
                    self.pos += 1
            self.bitbuf = (self.bitbuf << 8) | b
            self.bitcnt += 8

    def read_bit(self):
        if self.bitcnt == 0:
            self._fill()
        self.bitcnt -= 1
        return (self.bitbuf >> self.bitcnt) & 1

    def read_bits(self, n):
        v = 0
        for _ in range(n):
            v = (v << 1) | self.read_bit()
        return v

    def align_restart(self):
        """Skip to just past the next RSTn marker; reset bit state."""
        self.bitbuf = 0
        self.bitcnt = 0
        d = self.data
        while self.pos + 1 < len(d):
            if d[self.pos] == 0xFF and 0xD0 <= d[self.pos + 1] <= 0xD7:
                self.pos += 2
                return
            self.pos += 1

    def decode_huff(self, table):
        length = 0
        code = 0
        while length < 16:
            code = (code << 1) | self.read_bit()
            length += 1
            sym = table.lookup.get((length, code))
            if sym is not None:
                return sym
        raise ValueError("Invalid Huffman code in JPEG stream")


def _extend(v, t):
    """JPEG EXTEND: map t-bit magnitude to signed value."""
    return v if v >= (1 << (t - 1)) else v - (1 << t) + 1


def entropy_decode_jpeg(data):
    """Baseline-JPEG stage 1: bytes → :class:`JpegPlanes` of quantized DCT blocks."""
    if data[:2] != b"\xff\xd8":
        raise ValueError("Not a JPEG (missing SOI)")
    pos = 2
    qtables = {}
    huff_dc, huff_ac = {}, {}
    frame = None
    restart_interval = 0
    while pos < len(data):
        if data[pos] != 0xFF:
            pos += 1
            continue
        marker = data[pos + 1]
        pos += 2
        if marker in (0xD8, 0x01) or 0xD0 <= marker <= 0xD7:
            continue
        if marker == 0xD9:  # EOI
            break
        (seglen,) = struct.unpack(">H", data[pos: pos + 2])
        seg = data[pos + 2: pos + seglen]
        if marker == 0xDB:  # DQT
            s = 0
            while s < len(seg):
                pq, tq = seg[s] >> 4, seg[s] & 0xF
                s += 1
                if pq:
                    q = np.frombuffer(seg[s: s + 128], dtype=">u2").astype(np.int32)
                    s += 128
                else:
                    q = np.frombuffer(seg[s: s + 64], dtype=np.uint8).astype(np.int32)
                    s += 64
                qtables[tq] = q  # kept in zigzag order; unzigzagged in _decode_scan
        elif marker == 0xC0 or marker == 0xC1:  # SOF0/1 baseline
            precision, h, w, nc = seg[0], struct.unpack(">H", seg[1:3])[0], \
                struct.unpack(">H", seg[3:5])[0], seg[5]
            if precision != 8:
                raise ValueError("Only 8-bit baseline JPEG supported")
            comps = []
            for i in range(nc):
                cid, samp, tq = seg[6 + 3 * i], seg[7 + 3 * i], seg[8 + 3 * i]
                comps.append({"id": cid, "h": samp >> 4, "v": samp & 0xF, "tq": tq})
            frame = {"h": h, "w": w, "comps": comps}
        elif marker in (0xC2, 0xC3, 0xC5, 0xC6, 0xC7, 0xC9, 0xCA, 0xCB, 0xCD, 0xCE, 0xCF):
            raise ValueError("Unsupported JPEG mode (progressive/lossless); marker %02x"
                             % marker)
        elif marker == 0xC4:  # DHT
            s = 0
            while s < len(seg):
                tc, th = seg[s] >> 4, seg[s] & 0xF
                counts = list(seg[s + 1: s + 17])
                total = sum(counts)
                symbols = list(seg[s + 17: s + 17 + total])
                table = _HuffTable(counts, symbols)
                (huff_dc if tc == 0 else huff_ac)[th] = table
                s += 17 + total
        elif marker == 0xDD:  # DRI
            restart_interval = struct.unpack(">H", seg[:2])[0]
        elif marker == 0xDA:  # SOS
            ns = seg[0]
            scan = []
            for i in range(ns):
                cs, tables = seg[1 + 2 * i], seg[2 + 2 * i]
                scan.append({"id": cs, "dc": tables >> 4, "ac": tables & 0xF})
            return _decode_scan(data, pos + seglen, frame, scan, qtables,
                                huff_dc, huff_ac, restart_interval)
        pos += seglen
    raise ValueError("No SOS marker found")


def _decode_scan(data, pos, frame, scan, qtables, huff_dc, huff_ac, restart_interval):
    h, w, comps = frame["h"], frame["w"], frame["comps"]
    hmax = max(c["h"] for c in comps)
    vmax = max(c["v"] for c in comps)
    mcus_x = -(-w // (8 * hmax))
    mcus_y = -(-h // (8 * vmax))
    out = []
    for c in comps:
        bx = mcus_x * c["h"]
        by = mcus_y * c["v"]
        out.append(np.zeros((by, bx, 64), np.int32))

    reader = _BitReader(data, pos)
    pred = [0] * len(comps)
    mcu_count = 0
    for my in range(mcus_y):
        for mx in range(mcus_x):
            if restart_interval and mcu_count and mcu_count % restart_interval == 0:
                reader.align_restart()
                pred = [0] * len(comps)
            for ci, c in enumerate(comps):
                sc = next(s for s in scan if s["id"] == c["id"])
                dc_t, ac_t = huff_dc[sc["dc"]], huff_ac[sc["ac"]]
                for v in range(c["v"]):
                    for hh in range(c["h"]):
                        block = np.zeros(64, np.int32)
                        t = reader.decode_huff(dc_t)
                        diff = _extend(reader.read_bits(t), t) if t else 0
                        pred[ci] += diff
                        block[0] = pred[ci]
                        k = 1
                        while k < 64:
                            rs = reader.decode_huff(ac_t)
                            r, s = rs >> 4, rs & 0xF
                            if s == 0:
                                if r == 15:
                                    k += 16
                                    continue
                                break  # EOB
                            k += r
                            if k > 63:
                                break
                            block[k] = _extend(reader.read_bits(s), s)
                            k += 1
                        out[ci][my * c["v"] + v, mx * c["h"] + hh] = block[UNZIGZAG]
            mcu_count += 1

    components = []
    for ci, c in enumerate(comps):
        q = qtables[c["tq"]][UNZIGZAG].astype(np.int32)
        components.append(JpegComponent(out[ci], q, c["h"], c["v"]))
    return JpegPlanes(height=h, width=w, components=components)


# -- stage 2: device ------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _idct_basis():
    """(64, 64) flattened 2-D IDCT basis: pixels_flat = coeffs_flat @ B."""
    a = np.zeros((8, 8), np.float64)
    for u in range(8):
        alpha = np.sqrt(0.25) if u else np.sqrt(0.125)
        for p in range(8):
            a[u, p] = alpha * np.cos((2 * p + 1) * u * np.pi / 16.0)
    return np.kron(a, a).astype(np.float32)  # rows (u,v) -> cols (p,q)


def _idct_kernel(coef_ref, basis_ref, out_ref):
    import jax.numpy as jnp

    out_ref[:] = jnp.dot(coef_ref[:], basis_ref[:],
                         preferred_element_type=jnp.float32) + 128.0


def idct_blocks(coeffs, qtable):
    """(N, 64) quantized coefficients → (N, 64) pixel blocks (dequant + IDCT + shift).

    Pallas matmul on TPU; interpret mode on CPU topologies.
    """
    import jax.numpy as jnp

    scaled = coeffs.astype(jnp.float32) * qtable.astype(jnp.float32)[None, :]
    return _idct_scaled(scaled)


def _blocks_to_plane(pixels, blocks_y, blocks_x):
    """(by*bx, 64) → (by*8, bx*8) spatial plane."""
    import jax.numpy as jnp

    p = pixels.reshape(blocks_y, blocks_x, 8, 8)
    return jnp.transpose(p, (0, 2, 1, 3)).reshape(blocks_y * 8, blocks_x * 8)


def _fancy_upsample2(plane, axis):
    """libjpeg 'fancy' 2x upsampling along ``axis``: triangle filter (3*near + far) / 4,
    edges clamped — matches libjpeg/cv2 output much closer than pixel doubling."""
    import jax.numpy as jnp

    plane = jnp.moveaxis(plane, axis, 0)
    prev = jnp.concatenate([plane[:1], plane[:-1]], axis=0)
    nxt = jnp.concatenate([plane[1:], plane[-1:]], axis=0)
    even = (3.0 * plane + prev) * 0.25
    odd = (3.0 * plane + nxt) * 0.25
    out = jnp.stack([even, odd], axis=1).reshape((-1,) + plane.shape[1:])
    return jnp.moveaxis(out, 0, axis)


def ycbcr_to_rgb(y, cb, cr):
    """JFIF YCbCr → RGB (float in, float out, unclamped)."""
    import jax.numpy as jnp

    r = y + 1.402 * (cr - 128.0)
    g = y - 0.344136 * (cb - 128.0) - 0.714136 * (cr - 128.0)
    b = y + 1.772 * (cb - 128.0)
    return jnp.stack([r, g, b], axis=-1)


def decode_jpeg_device_stage(planes):
    """Stage 2: :class:`JpegPlanes` → (h, w, 3) uint8 RGB ``jax.Array`` (grayscale → 3ch)."""
    import jax.numpy as jnp

    outs = []
    for comp in planes.components:
        by, bx, _ = comp.blocks.shape
        pix = idct_blocks(jnp.asarray(comp.blocks.reshape(-1, 64)),
                          jnp.asarray(comp.qtable))
        # libjpeg range-limits every sample at IDCT output, before upsampling/color
        pix = jnp.clip(jnp.round(pix), 0.0, 255.0)
        outs.append(_blocks_to_plane(pix, by, bx))
    hmax = max(c.h_samp for c in planes.components)
    vmax = max(c.v_samp for c in planes.components)
    full = []
    for comp, plane in zip(planes.components, outs):
        ry, rx = vmax // comp.v_samp, hmax // comp.h_samp
        for axis, r in ((0, ry), (1, rx)):
            if r == 2:
                plane = _fancy_upsample2(plane, axis)  # libjpeg triangle filter
            elif r > 1:
                plane = jnp.repeat(plane, r, axis=axis)
        full.append(plane[: planes.height, : planes.width])
    if len(full) == 1:
        y = jnp.clip(full[0], 0, 255).astype(jnp.uint8)
        return jnp.stack([y, y, y], axis=-1)
    rgb = ycbcr_to_rgb(full[0], full[1], full[2])
    return jnp.clip(jnp.round(rgb), 0, 255).astype(jnp.uint8)


def decode_jpeg(data):
    """Full two-stage decode: JPEG bytes → (h, w, 3) uint8 RGB on device."""
    return decode_jpeg_device_stage(entropy_decode_jpeg(data))


# -- fast stage 1 (native C++ behind the same contract) --------------------------------


def entropy_decode_jpeg_fast(data):
    """Stage 1 via the compiled C++ decoder (petastorm_tpu/ops/native/jpeg_decoder.cpp);
    falls back to the pure-Python oracle when the native build is unavailable.

    This is the data-plane entry point: ctypes releases the GIL so reader thread pools
    run stage-1 decode truly in parallel. Raises ValueError on streams the two-stage
    path cannot handle (lossless/arithmetic, CMYK, corrupt) — the codec layer catches that and
    falls back to full host decode per stream."""
    from petastorm_tpu.ops import native

    if native.native_available():
        height, width, comps = native.jpeg_decode_coeffs_native(data)
        planes = JpegPlanes(
            height=height,
            width=width,
            components=[JpegComponent(blocks, qtable, h, v)
                        for blocks, qtable, h, v in comps],
        )
    else:
        planes = entropy_decode_jpeg(data)
    if len(planes.components) not in (1, 3):
        # stage 2 models grayscale and YCbCr only; 2-component or Adobe CMYK streams
        # must not reach the jitted decoder (wrong colors / shape errors inside jit)
        raise ValueError(
            "Unsupported JPEG component count %d (expected 1 or 3)"
            % len(planes.components)
        )
    return planes


def entropy_decode_jpeg_batch(blobs):
    """Row-group batched stage 1: list of JPEG byte strings → list of :class:`JpegPlanes`
    (or ``None`` per stream the batch decoder could not handle — caller re-decodes those
    individually).

    One native call decodes every same-layout stream straight into stacked coefficient
    buffers (no per-image ctypes overhead, no copies, GIL released throughout); each
    returned ``JpegPlanes`` holds zero-copy views into those buffers plus a ``batch_ref``
    so downstream batching can slice the parent arrays directly.

    Raises RuntimeError when the native decoder is unavailable and ValueError when the
    first stream has no usable baseline layout (callers fall back to the per-image path).
    """
    from petastorm_tpu.ops import native

    if not native.native_available():
        raise RuntimeError("native jpeg decoder unavailable: %s" % native.native_error())
    layout, coeffs, qtabs, kmax, status = native.jpeg_decode_coeffs_batch_native(blobs)
    height, width, comps_layout = layout
    if len(comps_layout) not in (1, 3):
        raise ValueError(
            "Unsupported JPEG component count %d (expected 1 or 3)" % len(comps_layout)
        )
    qtabs = qtabs.astype(np.int32)  # per-image contract dtype (one cast per row group)
    # Spectral range profile, one native pass per component over the stacked buffers
    # (memory-bound, GIL released; failed streams' slices are zeroed so they cannot
    # inflate it). Shared across the group's rows — drives the split-pack transfer.
    specmax = np.stack([native.jpeg_specmax_native(c) for c in coeffs])
    out = []
    for i in range(len(blobs)):
        if status[i] != 0:
            out.append(None)
            continue
        comps = [
            JpegComponent(coeffs[c][i].reshape(by, bx, 64), qtabs[i, c], h, v)
            for c, (h, v, by, bx) in enumerate(comps_layout)
        ]
        out.append(JpegPlanes(height, width, comps, batch_ref=(coeffs, qtabs, i),
                              kmax=kmax, specmax=specmax))
    return out


# -- batched stage 2 (one device dispatch per image batch) -----------------------------


def _layout_key(planes):
    """Hashable decode layout: everything that shapes the compiled program."""
    return (
        planes.height,
        planes.width,
        tuple(
            (c.h_samp, c.v_samp, c.blocks.shape[0], c.blocks.shape[1])
            for c in planes.components
        ),
    )


def _idct_scaled(scaled):
    """(N, 64) dequantized float32 coefficients → (N, 64) pixel blocks (+128 level shift)."""
    import jax
    from jax.experimental import pallas as pl
    import jax.numpy as jnp

    n = scaled.shape[0]
    basis = jnp.asarray(_idct_basis())
    block_n = 512
    padded_n = ((n + block_n - 1) // block_n) * block_n
    if padded_n != n:
        scaled = jnp.pad(scaled, ((0, padded_n - n), (0, 0)))
    out = pl.pallas_call(
        _idct_kernel,
        out_shape=jax.ShapeDtypeStruct((padded_n, 64), jnp.float32),
        grid=(padded_n // block_n,),
        in_specs=[
            pl.BlockSpec((block_n, 64), lambda i: (i, 0)),
            pl.BlockSpec((64, 64), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((block_n, 64), lambda i: (i, 0)),
        interpret=jax.default_backend() == "cpu",
    )(scaled, basis)
    return out[:n]


@functools.lru_cache(maxsize=32)
def _batched_stage2(layout, ks=None, packed=None, split=None):
    """Layout-specialized jitted decoder: stacked coefficient arrays → (n, h, w, 3)
    uint8 RGB. One Pallas IDCT dispatch per component for the WHOLE batch (vs one jit
    per image — VERDICT r1 #1). The batch size is taken from the input shapes, so jit's
    own shape specialization handles varying group sizes.

    ``ks`` (per component, multiples of 8) selects the zigzag-truncated transfer
    variant: inputs arrive as ``(n, blocks, k)`` zigzag-prefix packs (all dropped
    coefficients are zero — ``kmax`` contract) and are zero-padded + inverse-permuted
    back to natural order on device, fused into the same program. Bit-identical
    output; ~k/64 of the H2D bytes.

    ``packed`` (per component, bool) selects the 12-bit transfer variant on top:
    inputs arrive as ``(n, blocks, k*3//2)`` uint8 (two coefficients per 3 bytes,
    ``ptpu_jpeg_pack12`` layout) and are unpacked to int16 with fused integer ops
    before the pad/unpermute. Exact for |coeff| ≤ 2047 (the native packer verifies
    and falls back to int16 otherwise) — so output stays bit-identical at 75% of
    even the truncated H2D bytes.

    ``split`` (per component, None or ``(k1, k2)``) selects the spectral bit-width
    split instead: the component arrives as a tuple of slabs — 12-bit pairs for
    zigzag positions [0, k1), int8 for [k1, k2), 4-bit nibble pairs for [k2, k) —
    chosen from the row group's measured per-position ranges (``specmax``). Unpack is
    fused integer ops; order is always zigzag, so the pad/unpermute applies even at
    k = 64. Bit-identical output; sharp photographic content that defeats zigzag
    truncation still drops to ~half the 12-bit bytes (high positions are heavily
    quantized). A split entry overrides ``packed`` for that component."""
    import jax
    import jax.numpy as jnp

    height, width, comp_layout = layout
    hmax = max(h for h, _v, _by, _bx in comp_layout)
    vmax = max(v for _h, v, _by, _bx in comp_layout)
    # HOST constant, deliberately: a device array closed over by ``fn`` would be
    # lowered via a D2H fetch at every new layout variant's compile — measured
    # MINUTES when that fetch queues behind in-flight transfers on a degraded
    # service (r4 bench hang, faulthandler: _array_mlir_constant_handler → _value)
    unzig = np.asarray(UNZIGZAG)

    def unpack12(u8):
        # (n, blocks, m*3) uint8 → (n, blocks, 2m) int32, 12-bit two's complement
        triples = u8.reshape(u8.shape[0], u8.shape[1], -1, 3)
        b0 = triples[..., 0].astype(jnp.int32)
        b1 = triples[..., 1].astype(jnp.int32)
        b2 = triples[..., 2].astype(jnp.int32)
        lo = b0 | ((b1 & 0xF) << 8)
        hi = (b1 >> 4) | (b2 << 4)
        pair = jnp.stack([lo, hi], axis=-1)
        pair = pair - ((pair & 0x800) << 1)  # sign-extend 12-bit
        return pair.reshape(u8.shape[0], u8.shape[1], -1)

    def unpack4(u8):
        # (n, blocks, m) uint8 → (n, blocks, 2m) int32, 4-bit two's complement
        b = u8.astype(jnp.int32)
        lo = b & 0xF
        hi = (b >> 4) & 0xF
        pair = jnp.stack([lo, hi], axis=-1)
        pair = pair - ((pair & 0x8) << 1)  # sign-extend 4-bit
        return pair.reshape(u8.shape[0], u8.shape[1], -1)

    def fn(coeffs, qtabs):
        n = (coeffs[0][0] if isinstance(coeffs[0], tuple) else coeffs[0]).shape[0]
        planes = []
        for ci, ((h_samp, v_samp, by, bx), coef, qtab) in enumerate(
                zip(comp_layout, coeffs, qtabs)):
            # coef: (n, by*bx, 64) int16 natural order — or (n, by*bx, ks[ci])
            # zigzag prefix when this component was truncated, or the 12-bit uint8
            # pack of either, or the split-pack slab tuple; qtab: (n, 64) int32
            # (per-image: quality may vary)
            k_ship = ks[ci] if ks is not None else 64
            if split is not None and split[ci] is not None:
                head, mid, tail = coef
                parts = []
                if head.shape[-1]:
                    parts.append(unpack12(head))
                if mid.shape[-1]:
                    parts.append(mid.astype(jnp.int32))
                if tail.shape[-1]:
                    parts.append(unpack4(tail))
                coef = parts[0] if len(parts) == 1 else jnp.concatenate(parts, axis=-1)
                if k_ship < 64:
                    coef = jnp.pad(coef, ((0, 0), (0, 0), (0, 64 - k_ship)))
                coef = jnp.take(coef, unzig, axis=-1)
            else:
                if packed is not None and packed[ci]:
                    coef = unpack12(coef)
                if ks is not None and ks[ci] < 64:
                    coef = jnp.pad(coef, ((0, 0), (0, 0), (0, 64 - ks[ci])))
                    coef = jnp.take(coef, unzig, axis=-1)
            scaled = coef.astype(jnp.float32) * qtab.astype(jnp.float32)[:, None, :]
            pix = _idct_scaled(scaled.reshape(n * by * bx, 64))
            pix = jnp.clip(jnp.round(pix), 0.0, 255.0)  # libjpeg range-limits at IDCT out
            plane = pix.reshape(n, by, bx, 8, 8)
            plane = jnp.transpose(plane, (0, 1, 3, 2, 4)).reshape(n, by * 8, bx * 8)
            ry, rx = vmax // v_samp, hmax // h_samp
            for axis, r in ((1, ry), (2, rx)):
                if r == 2:
                    plane = _fancy_upsample2(plane, axis)
                elif r > 1:
                    plane = jnp.repeat(plane, r, axis=axis)
            planes.append(plane[:, :height, :width])
        if len(planes) == 1:
            y = jnp.clip(planes[0], 0, 255).astype(jnp.uint8)
            return jnp.stack([y, y, y], axis=-1)
        rgb = ycbcr_to_rgb(planes[0], planes[1], planes[2])
        return jnp.clip(jnp.round(rgb), 0, 255).astype(jnp.uint8)

    # NOTE on donation (VERDICT r3 #4 asked to try it): the slab buffers cannot
    # alias into the (n, h, w, 3) uint8 output — XLA input-output aliasing needs
    # size-compatible pairs — so donate_argnums only produces "donated buffers were
    # not usable" warnings on TPU (measured; no perf or memory change). The real
    # dispatch win is the explicit async device_put in ``_stage_inputs``.
    return jax.jit(fn)


def stack_jpeg_coefficients(planes_list):
    """Stack same-layout :class:`JpegPlanes` into per-component batch arrays.

    Returns ``(coeffs, qtabs)``: tuples with one ``(n, by*bx, 64)`` int16 and one
    ``(n, 64)`` int array per component — the host-side staging format the batched
    device stage consumes.

    Fast path: rows produced by :func:`entropy_decode_jpeg_batch` carry a ``batch_ref``
    into their row group's stacked buffers; when every row shares one parent, batches
    are a slice (consecutive rows — zero copy) or one fancy-index gather of the parent
    instead of an np.stack over hundreds of per-row objects."""
    ref = planes_list[0].batch_ref
    if ref is not None:
        parent_coeffs, parent_qtabs, _ = ref
        idx = np.empty(len(planes_list), dtype=np.intp)
        ok = True
        for j, p in enumerate(planes_list):
            r = p.batch_ref
            if r is None or r[0] is not parent_coeffs:
                ok = False
                break
            idx[j] = r[2]
        if ok:
            n = len(idx)
            first = int(idx[0])
            consecutive = int(idx[-1]) == first + n - 1 and \
                np.array_equal(idx, np.arange(first, first + n))
            coeffs = []
            qtabs = []
            for c in range(len(planes_list[0].components)):
                parent = parent_coeffs[c]
                qt = parent_qtabs[:, c, :]
                if consecutive:
                    coeffs.append(parent[first:first + n])
                    qtabs.append(qt[first:first + n])
                else:
                    coeffs.append(parent[idx])
                    qtabs.append(qt[idx])
            return tuple(coeffs), tuple(qtabs)
    ncomp = len(planes_list[0].components)
    coeffs = []
    qtabs = []
    for c in range(ncomp):
        coeffs.append(np.stack(
            [p.components[c].blocks.reshape(-1, 64) for p in planes_list]
        ))
        qtabs.append(np.stack([p.components[c].qtable for p in planes_list]))
    return tuple(coeffs), tuple(qtabs)


def resize_image_batch(img, target):
    """(n, h, w, c) uint8 device batch → (n, *target, c), bilinear, no antialiasing
    (tracks ``cv2.resize(..., INTER_LINEAR)``, the reference host resize idiom —
    identical sampling grid on upscale, same no-prefilter choice on downscale; values
    differ from cv2 only by float rounding). No-op when already at ``target``."""
    import jax
    import jax.numpy as jnp

    h, w = int(target[0]), int(target[1])
    if img.shape[1] == h and img.shape[2] == w:
        return img
    out = jax.image.resize(
        img.astype(jnp.float32), (img.shape[0], h, w, img.shape[3]),
        method="linear", antialias=False)
    return jnp.clip(jnp.round(out), 0, 255).astype(jnp.uint8)


def decode_jpeg_batch(planes_list, resize_to=None, sharding=None):
    """Batched stage 2: list of :class:`JpegPlanes` → (n, h, w, 3) uint8 ``jax.Array``.

    Without ``resize_to`` all images must share height/width (resize on write, or use
    padded-shape fields); mixed chroma samplings are grouped and decoded per-group,
    then re-gathered in input order on device.

    ``resize_to=(h, w)`` lifts the uniform-size requirement for mixed-size stores
    (raw ImageNet-style corpora): each same-layout group decodes at its stored size
    and is bilinearly resized ON DEVICE to the target (``resize_image_batch``), so
    every batch leaves with one static shape regardless of composition.

    ``sharding``: optional batch-axis sharding (e.g. the loader's). Coefficient slabs
    are placed across its devices before the stage-2 jit, so decode runs SPMD — one
    batch shard per device — and the output is already laid out for consumption
    (single-layout batches; mixed-layout re-gathers may reshard)."""
    import jax.numpy as jnp

    if not planes_list:
        raise ValueError("decode_jpeg_batch: empty batch")
    sizes = {(p.height, p.width) for p in planes_list}
    if len(sizes) > 1 and resize_to is None:
        raise ValueError(
            "decode_jpeg_batch requires a uniform image size per batch, got %s. "
            "Pass resize_to=(h, w) (DataLoader(device_decode_resize=...)) to decode "
            "mixed sizes with an on-device resize, resize on write, or decode on "
            "host via CompressedImageCodec.decode." % sizes
        )
    groups = {}
    for i, p in enumerate(planes_list):
        groups.setdefault(_layout_key(p), []).append(i)
    if len(groups) == 1:
        layout, = groups
        out = _decode_group(layout, planes_list, sharding=sharding)
        return resize_image_batch(out, resize_to) if resize_to is not None else out
    parts = []
    order = []
    for layout, indices in groups.items():
        group = [planes_list[i] for i in indices]
        decoded = _decode_group(layout, group, sharding=sharding)
        if resize_to is not None:
            decoded = resize_image_batch(decoded, resize_to)
        parts.append(decoded)
        order.extend(indices)
    stacked = jnp.concatenate(parts, axis=0)
    inverse = np.argsort(np.asarray(order))
    return stacked[jnp.asarray(inverse)]


#: Coarse zigzag-prefix buckets: few distinct compiled variants (compile churn is the
#: real cost — each (layout, ks) is a full XLA program), still 75%/50% H2D savings.
#: 64 means "ship the full spectrum for this component" (no pack, no device permute).
_K_BUCKETS = (16, 32, 64)

#: Per-layout sticky buckets: ks only ever GROWS, so content variation across row
#: groups costs at most len(_K_BUCKETS)-1 recompiles per component over the process
#: lifetime instead of one per distinct kmax. Updated from loader transfer threads —
#: the compare-and-grow must be atomic or two concurrent loaders can interleave
#: read-modify-write and transiently shrink a layout's ks (ADVICE r2: extra XLA
#: recompiles, though never wrong output).
_STICKY_KS: dict = {}
_STICKY_KS_LOCK = threading.Lock()


def _truncation_ks(group, layout=None):
    """Per-component zigzag-prefix buckets for a same-layout group, or None when
    truncation is unavailable (a row without kmax) or useless (every component at
    full width)."""
    kms = [p.kmax for p in group]
    if any(km is None for km in kms):
        return None
    ncomp = len(group[0].components)

    def bucket(kcount):
        for b in _K_BUCKETS:
            if kcount <= b:
                return b
        return 64

    ks = [bucket(max(km[c] for km in kms) + 1) for c in range(ncomp)]
    if layout is not None:
        with _STICKY_KS_LOCK:
            prev = _STICKY_KS.get(layout)
            if prev is not None:
                ks = [max(a, b) for a, b in zip(ks, prev)]
            _STICKY_KS[layout] = ks
    if all(k >= 64 for k in ks):
        return None
    return tuple(ks)


#: Per-(layout, component) components observed to exceed the 12-bit coefficient
#: range: packing is disabled STICKY for them (one overflow means the content class
#: can overflow again — flip-flopping would churn XLA recompiles). Guarded by the
#: same lock as _STICKY_KS.
_PACK12_DISABLED: set = set()

#: Per-layout sticky split points: layout → list of per-component ``(k1, k2)``.
#: Like _STICKY_KS, both only ever GROW (larger = wider tiers = always safe), so
#: content variation across row groups costs a bounded number of XLA recompiles.
#: Guarded by _STICKY_KS_LOCK.
_STICKY_SPLIT: dict = {}

#: Per-(layout, component) split-pack disablement: a range failure here means the
#: specmax bound was violated (mixed provenance rows) — fall back to pack12 sticky.
_SPLIT_DISABLED: set = set()

#: Cumulative coefficient-transfer accounting: ``raw`` = what full int16 coefficients
#: would ship, ``shipped`` = actual bytes after truncation/split/pack. Lets bench
#: artifacts report the REALIZED byte reduction, not the modeled one. Guarded by
#: _STICKY_KS_LOCK.
_TRANSFER_BYTES = {"shipped": 0, "raw": 0}


def transfer_byte_counters(reset=False):
    """Snapshot (optionally reset) the cumulative coefficient-transfer accounting:
    ``{"shipped": bytes_actually_shipped, "raw": int16_equivalent_bytes}``."""
    with _STICKY_KS_LOCK:
        out = dict(_TRANSFER_BYTES)
        if reset:
            _TRANSFER_BYTES["shipped"] = 0
            _TRANSFER_BYTES["raw"] = 0
    return out


def _batch_specmax(group):
    """The group's combined ``(ncomp, 64)`` spectral range profile, or None when any
    row lacks one. Rows of one row group share the profile ARRAY, so the common case
    is a single identity check; mixed-parent groups take the elementwise max."""
    vecs = []
    seen = set()
    for p in group:
        sm = p.specmax
        if sm is None:
            return None
        if id(sm) not in seen:
            seen.add(id(sm))
            vecs.append(sm)
    return vecs[0] if len(vecs) == 1 else np.maximum.reduce(vecs)


def _round_up4(x):
    return (x + 3) & ~3


def _split_points(profile, ks, layout):
    """Per-component spectral split ``(k1, k2)`` (or None = plain pack12 is as good)
    from the measured per-position ranges. Positions ≥ k1 fit int8, positions ≥ k2
    fit 4 bits; both bucketed to multiples of 4 (pack alignment + bounded recompiles)
    and sticky-grown per layout."""
    ncomp = profile.shape[0]
    out = []
    for ci in range(ncomp):
        k = ks[ci] if ks is not None else 64
        mx = profile[ci]

        def low_bound(lim):
            j = k
            while j > 0 and mx[j - 1] <= lim:
                j -= 1
            return j

        k1 = min(_round_up4(low_bound(127)), k)
        k2 = min(max(_round_up4(low_bound(7)), k1), k)
        out.append((k1, k2))
    with _STICKY_KS_LOCK:
        prev = _STICKY_SPLIT.get(layout)
        if prev is not None:
            out = [(max(a1, b1), max(max(a2, b2), max(a1, b1)))
                   for (a1, a2), (b1, b2) in zip(out, prev)]
        _STICKY_SPLIT[layout] = out
    spec = []
    for ci, (k1, k2) in enumerate(out):
        k = ks[ci] if ks is not None else 64
        k1, k2 = min(k1, k), min(k2, k)
        # k1 == k means every position needs 12 bits: identical bytes to pack12,
        # without its natural-order no-permute fast path — use pack12 instead
        spec.append(None if k1 >= k else (k1, k2))
    return spec


def _batch_axis_shards(sharding):
    """Distinct batch-axis slice count under ``sharding`` (1 = not batch-sharded);
    single shared definition with the loader's layout checks."""
    from petastorm_tpu.parallel.mesh import batch_axis_shard_count

    return batch_axis_shard_count(sharding)


def _stage_inputs(tree, sharding, n):
    """Explicit async ``device_put`` of host staging slabs ahead of the stage-2 jit.

    With a batch-axis ``sharding`` (trailing axes replicated) the decode runs SPMD
    over every device instead of serializing on the default chip (VERDICT r3 #2: on
    a pod host with 4–8 local chips, single-device dispatch makes one chip the decode
    bottleneck while its siblings idle, then pays an extra D2D hop at assembly); an
    indivisible batch falls back to the default device — correct, just unscaled.
    Either way the H2D enqueues immediately — before jit dispatch overhead — so the
    next batch's transfer overlaps the current batch's decode and the jit receives
    device-resident buffers (donation evaluated and rejected: see
    ``_batched_stage2``)."""
    import jax

    shards = _batch_axis_shards(sharding) if sharding is not None else 0
    if shards <= 1 or n % shards != 0:
        return jax.device_put(tree)
    import jax.sharding as jsh

    axis = sharding.spec[0]

    def put(a):
        spec = jsh.PartitionSpec(axis, *([None] * (a.ndim - 1)))
        return jax.device_put(a, jsh.NamedSharding(sharding.mesh, spec))

    return jax.tree_util.tree_map(put, tree)


def _decode_group(layout, group, sharding=None):
    """One same-layout group → device decode. Transfer narrowing, exact and
    composable: (a) ship only the zigzag prefix when the batch's kmax says the rest
    of the spectrum is zero; (b) split what ships into per-position bit widths from
    the row group's measured spectral ranges (12-bit head / int8 mid / 4-bit tail);
    (c) 12-bit-pack components the split can't help. Sharp photographic content
    defeats (a) (kmax ≈ 63) but (b) still halves the 12-bit bytes — high zigzag
    positions are heavily quantized; smooth content composes (a)+(b).

    ``sharding``: optional batch-axis sharding; staged inputs are placed across its
    devices so dequant+IDCT+upsample+color runs SPMD (one shard of the batch per
    device) instead of on the default device only."""
    coeffs, qtabs = stack_jpeg_coefficients(group)
    from petastorm_tpu.ops import native

    if not native.native_available():
        # un-narrowed transfer still counts: ratio must read ~1.0 here, not "no data"
        full = sum(c.nbytes for c in coeffs)
        with _STICKY_KS_LOCK:
            _TRANSFER_BYTES["raw"] += full
            _TRANSFER_BYTES["shipped"] += full
        coeffs, qtabs = _stage_inputs((coeffs, qtabs), sharding, coeffs[0].shape[0])
        return _batched_stage2(layout)(coeffs, qtabs)
    ks = _truncation_ks(group, layout)
    if ks is not None:
        coeffs = tuple(
            native.jpeg_zigzag_truncate_native(c, k) if k < 64 else c
            for c, k in zip(coeffs, ks)
        )
    profile = _batch_specmax(group)
    if profile is None:
        # Some row lacks a stage-1 profile (per-image fallback decode merged into the
        # group): recover the split savings with one memory-bound pass over the
        # already-stacked (possibly truncated) batch instead of forfeiting them.
        vecs = []
        for ci, c in enumerate(coeffs):
            if ks is not None and ks[ci] < 64:
                v = native.jpeg_specmax_native(c, is_zigzag=True)
                v = np.pad(v, (0, 64 - ks[ci]))
            else:
                v = native.jpeg_specmax_native(c)
            vecs.append(v)
        profile = np.stack(vecs)
    split = [None] * len(coeffs)
    candidate = _split_points(profile, ks, layout)
    with _STICKY_KS_LOCK:
        for ci, s in enumerate(candidate):
            if s is not None and (layout, ci) not in _SPLIT_DISABLED:
                split[ci] = s
    packed = []
    shipped = []
    for ci, c in enumerate(coeffs):
        if split[ci] is not None:
            k1, k2 = split[ci]
            is_zig = ks is not None and ks[ci] < 64
            slabs = native.jpeg_pack_split_native(c, k1, k2, is_zigzag=is_zig)
            if slabs is not None:
                packed.append(False)
                shipped.append(slabs)
                continue
            # Range exceeded despite the specmax bound: provenance-mixed rows.
            # Disable sticky for this component and fall through to pack12.
            split[ci] = None
            with _STICKY_KS_LOCK:
                _SPLIT_DISABLED.add((layout, ci))
        p = None
        with _STICKY_KS_LOCK:
            enabled = (layout, ci) not in _PACK12_DISABLED
        if enabled:
            p = native.jpeg_pack12_native(c)
            if p is None:  # 12-bit range exceeded: sticky int16 for this component
                with _STICKY_KS_LOCK:
                    _PACK12_DISABLED.add((layout, ci))
        packed.append(p is not None)
        shipped.append(p if p is not None else c)
    n = coeffs[0].shape[0]
    raw_bytes = sum(n * by * bx * 64 * 2 for _h, _v, by, bx in layout[2])
    shipped_bytes = sum(
        sum(a.nbytes for a in s) if isinstance(s, tuple) else s.nbytes
        for s in shipped)
    with _STICKY_KS_LOCK:
        _TRANSFER_BYTES["raw"] += raw_bytes
        _TRANSFER_BYTES["shipped"] += shipped_bytes
    shipped, qtabs = _stage_inputs((tuple(shipped), qtabs), sharding, n)
    return _batched_stage2(layout, ks, tuple(packed), tuple(split))(
        shipped, qtabs)
