"""Chaos plane: deterministic fault injection for the read pipeline (ISSUE 7).

At pod scale the input pipeline is the component that fails most often and
matters least — a dead decode child or one corrupt row group must never take
down a v5e-256 training job. PR 5 built *detection* (heartbeats, the stall
watchdog) and the repo has recovery primitives scattered across layers
(transient-IO retry, elastic child respawn, dead-child slab reclaim); this
package is what *proves* them: a seeded :class:`FaultPlan` of
:class:`FaultRule` evaluated at named hook sites threaded through the real
seams (``reader._retry_io``, readahead background reads, pool dispatch, wire
decode, the in-child work loop), injecting transient/permanent IO errors,
latency, corrupted wire bytes, child kills, and hangs — deterministically.

Usage::

    from petastorm_tpu.chaos import FaultPlan, FaultRule, armed

    plan = FaultPlan([
        FaultRule("reader.read", "raise_transient", nth=3, times=2),
        FaultRule("child.item", "kill", item_key="ordinal=5", times=1),
    ], seed=7)
    with armed(plan):
        ...   # run the pipeline; recovery machinery absorbs the faults

Zero overhead unarmed: every hook site is ``if chaos.ACTIVE is not None``.
Arming also exports the plan as ``PTPU_CHAOS_SPEC`` so process-pool children
spawned while armed inherit it (their in-child sites — ``child.item``,
``reader.read`` inside the child — evaluate their own per-process copy).
Every injection is counted (``ptpu_degradations_total{cause=
"chaos_injected"}``) and recorded into any live flight recorder, so a chaos
run's flight record reads like an incident timeline.

The acceptance harness lives in ``petastorm_tpu/benchmark/chaos.py``
(``petastorm-tpu-bench chaos``); the recovery policy it validates in
:mod:`petastorm_tpu.recovery`. See docs/robustness.md.
"""
from __future__ import annotations

import contextlib
import os

from petastorm_tpu.chaos.plan import (  # noqa: F401
    ChaosError,
    FaultPlan,
    FaultRule,
    allow_kill,
    item_key,
    kill_allowed,
)

#: the armed plan, or None (the default — hook sites check exactly this)
ACTIVE = None

_ENV_SPEC = "PTPU_CHAOS_SPEC"


def arm(plan, propagate=True):
    """Arm ``plan`` process-wide. With ``propagate`` (default) the plan is
    also exported as ``PTPU_CHAOS_SPEC`` so pool children spawned from now on
    arm themselves at bootstrap. Returns the plan."""
    global ACTIVE
    ACTIVE = plan
    if propagate and plan is not None:
        os.environ[_ENV_SPEC] = plan.to_json()
    return plan


def disarm():
    """Disarm fault injection (and stop propagating to new children)."""
    global ACTIVE
    ACTIVE = None
    os.environ.pop(_ENV_SPEC, None)


@contextlib.contextmanager
def armed(plan, propagate=True):
    """``with armed(plan): ...`` — arm for the block, disarm after (even when
    the block raises, so one failed scenario cannot poison the next)."""
    arm(plan, propagate=propagate)
    try:
        yield plan
    finally:
        disarm()


def arm_from_env(in_child=False):
    """Arm from ``PTPU_CHAOS_SPEC`` when present (pool-child bootstrap; also
    how the chaos harness arms its scenario subprocesses). ``in_child=True``
    additionally opts this process into the ``kill`` action. Returns the
    armed plan or None."""
    spec = os.environ.get(_ENV_SPEC)
    if not spec:
        return None
    plan = FaultPlan.from_json(spec)
    if in_child:
        allow_kill(True)
    # never re-export: this process inherited the spec from its parent
    return arm(plan, propagate=False)
