"""Deterministic, seeded fault injection for the data pipeline (ISSUE 7).

A :class:`FaultPlan` is a list of :class:`FaultRule` — each a *site pattern*
(fnmatch glob over the named hook sites threaded through the real seams), a
*trigger* (nth matching hit / seeded probability / item-key substring), and an
*action* (raise a transient or permanent IO error, inject latency, corrupt
wire bytes, kill the worker process mid-item, hang). The plan is evaluated at
each hook site via :func:`FaultPlan.hit`; when no plan is armed every site
costs exactly one ``is None`` check (the same contract as tracing/health).

Determinism: triggers never consult wall clock or ``random`` module state.
``nth`` counts matching hits per rule per process; ``probability`` is a pure
function of ``(plan seed, rule index, hit number)`` via crc32 — so a scenario
replays identically given the same plan and the same per-process hit sequence.
(Across a concurrent pool the *interleaving* of hits can vary; the chaos
harness therefore keys poison/kill rules by ``item_key``, which is stable
whatever thread or child processes the item.)

Hook sites (see docs/robustness.md for the full table):

=================  ====================================================
site               seam
=================  ====================================================
reader.read        ``_WorkerBase._read_columns_once`` — every synchronous
                   row-group read attempt (retry attempts hit again)
reader.read_run    ``_WorkerBase._read_run_once`` — coalesced ranged reads
io.readahead       ``ReadaheadPool._read_task_body`` — background reads
worker.item        Thread/Sync executor, around ``worker(item)``
pool.dispatch      ``ProcessExecutor._drive_child`` before the item ships
pool.recv          ``ProcessExecutor._recv_result`` before each receive
wire.decode        ``serializers.py`` — one hit per wire payload decode
                   (the only site where ``corrupt`` mutates real bytes)
child.item         ``_child_worker`` loop, in-child around ``worker(item)``
                   (the only site where ``kill`` takes the process down)
dataset.mutate     ``DatasetWatcher.poll_once`` (ISSUE 11) — once per watch
                   tick when a mutator is attached; the only site where the
                   ``remove_file``/``rewrite_file``/``append_piece`` actions
                   mutate a real dataset
transport.send     ``transport/tcp.py`` (ISSUE 15) — one hit per outbound
                   wire frame of a READY tcp link (parent and child evaluate
                   their own per-process plan copies); where the
                   ``net.partition``/``net.reset``/``net.slow``/
                   ``net.corrupt_frame`` actions live
transport.recv     same, per inbound frame (before the crc check, so
                   ``net.corrupt_frame`` is caught by the trailer)
=================  ====================================================

Every injected fault is recorded: a ``ptpu_degradations_total{cause=
"chaos_injected"}`` count, a ``chaos`` event in any live flight recorder
(ISSUE 5), and an in-memory ledger (:meth:`FaultPlan.injections`) the chaos
harness asserts against.
"""
from __future__ import annotations

import fnmatch
import json
import threading
import time
import zlib

_ACTIONS = ("raise_transient", "raise_permanent", "latency", "corrupt",
            "kill", "hang", "remove_file", "rewrite_file", "append_piece",
            "net.partition", "net.reset", "net.slow", "net.corrupt_frame")

#: network fault actions (ISSUE 15): evaluated at the framed transport's
#: ``transport.send``/``transport.recv`` hook sites, where the payload is one
#: raw wire frame. ``net.partition`` opens a drop window of ``latency_s``
#: seconds on the firing rule — every frame matching that rule's site pattern
#: inside the window returns :data:`DROPPED`; the transport then DROPS
#: heartbeat frames (starving the peer's half-open detector — the partition's
#: observable signal) but STALLS app frames at the send site until the window
#: closes or the link dies under them (reliable-transport semantics: TCP
#: retransmits through a partition, so data is delayed or the connection is
#: torn down — never silently lost). ``net.reset`` raises a
#: ``ConnectionResetError`` the transport turns into a REAL socket teardown
#: (mid-frame reset); ``net.slow`` sleeps ``latency_s`` per frame;
#: ``net.corrupt_frame`` flips a byte the receiver's crc32 trailer catches.
_NET_ACTIONS = ("net.partition", "net.reset", "net.slow", "net.corrupt_frame")


class _Dropped:
    """Sentinel returned by :meth:`FaultPlan.hit` when a ``net.partition``
    window swallowed the frame — transports check ``payload is DROPPED`` and
    pretend the frame was sent/never arrived."""

    def __repr__(self):
        return "<chaos DROPPED frame>"


DROPPED = _Dropped()

#: dataset-mutation actions (ISSUE 11): evaluated at the ``dataset.mutate``
#: hook site, where the payload is a mutator object (e.g.
#: :class:`petastorm_tpu.dataset.mutate.LocalDatasetMutator`) exposing a
#: method per action; ``rule.target`` is the JSON spec handed to it
_MUTATE_ACTIONS = ("remove_file", "rewrite_file", "append_piece")

#: process-role flag: ``kill`` only ever takes down a pool child (or a process
#: that explicitly opted in, e.g. the chaos harness's subprocesses) — firing
#: ``os._exit`` inside the training/driver process would kill the job the
#: chaos plane exists to protect.
_kill_allowed = False


def allow_kill(value=True):
    """Mark this process as killable by the ``kill`` action (pool children
    call this when arming from the environment)."""
    global _kill_allowed
    _kill_allowed = bool(value)


def kill_allowed():
    return _kill_allowed


class ChaosError(RuntimeError):
    """A chaos action could not execute as configured (e.g. ``kill`` evaluated
    in a process that did not opt in) — always a plan-authoring error."""


class FaultRule:
    """One injection rule: site pattern × trigger × action.

    Parameters
    ----------
    site : str
        fnmatch pattern over hook-site names (``"reader.*"``, ``"child.item"``).
    action : str
        One of ``raise_transient`` (a ``ConnectionResetError`` — classified
        transient by the retry machinery), ``raise_permanent`` (a
        ``FileNotFoundError`` — never retried), ``latency`` (sleep
        ``latency_s``), ``corrupt`` (flip a byte in the site's payload — only
        meaningful at ``wire.decode``), ``kill`` (``os._exit`` — pool children
        only), ``hang`` (sleep ``hang_s``, the stall-watchdog's prey), or a
        ``transport.*`` network fault (ISSUE 15): ``net.partition`` (drop
        every frame matching this rule's site pattern for ``latency_s``
        seconds), ``net.reset`` (mid-frame connection reset), ``net.slow``
        (per-frame latency), ``net.corrupt_frame`` (byte flip caught by the
        receiver's crc32 trailer).
    nth : int, optional
        Fire on the Nth matching hit (1-based), counted per rule per process.
    every : int, optional
        Fire on every Nth matching hit (combines with ``nth`` as an offset:
        ``nth=2, every=3`` fires on hits 2, 5, 8, ...).
    probability : float, optional
        Fire with this probability — deterministic per ``(seed, rule, hit)``.
    item_key : str, optional
        Only hits whose key contains this substring match (and count).
    times : int, optional
        Total-fire budget (None = unlimited).
    latency_s / hang_s / message :
        Action parameters.
    target : optional
        Dataset-mutation action spec (JSON-serializable; see
        :mod:`petastorm_tpu.dataset.mutate` for the shapes the
        ``remove_file``/``rewrite_file``/``append_piece`` actions take).
    """

    __slots__ = ("site", "action", "nth", "every", "probability", "item_key",
                 "times", "latency_s", "hang_s", "message", "target")

    def __init__(self, site, action, nth=None, every=None, probability=None,
                 item_key=None, times=None, latency_s=0.05, hang_s=3600.0,
                 message=None, target=None):
        if action not in _ACTIONS:
            raise ValueError("action must be one of %s, got %r"
                             % (_ACTIONS, action))
        if nth is not None and int(nth) < 1:
            raise ValueError("nth is 1-based (the first matching hit is 1)")
        if probability is not None and not (0.0 <= float(probability) <= 1.0):
            raise ValueError("probability must be in [0, 1]")
        self.site = site
        self.action = action
        self.nth = None if nth is None else int(nth)
        self.every = None if every is None else max(1, int(every))
        self.probability = None if probability is None else float(probability)
        self.item_key = item_key
        self.times = None if times is None else int(times)
        self.latency_s = float(latency_s)
        self.hang_s = float(hang_s)
        self.message = message
        self.target = target

    def to_spec(self):
        return {name: getattr(self, name) for name in self.__slots__}

    @classmethod
    def from_spec(cls, spec):
        return cls(**spec)

    def __repr__(self):
        trig = []
        if self.nth is not None:
            trig.append("nth=%d" % self.nth)
        if self.every is not None:
            trig.append("every=%d" % self.every)
        if self.probability is not None:
            trig.append("p=%g" % self.probability)
        if self.item_key is not None:
            trig.append("key~%r" % self.item_key)
        return "<FaultRule %s %s %s>" % (self.site, self.action,
                                         " ".join(trig) or "always")


def _coin(seed, rule_idx, hit_no, probability):
    """Deterministic biased coin: crc32 of the identifying triple, uniform
    over 2**32 (no ``random`` state, no wall clock — replayable)."""
    h = zlib.crc32(("%d|%d|%d" % (seed, rule_idx, hit_no)).encode("ascii"))
    return (h & 0xFFFFFFFF) / 4294967296.0 < probability


def item_key(item):
    """Stable key for a dispatched plan item: the tagged ``(epoch, ordinal,
    (piece, partition))`` shape the reader dispatches resolves to
    ``"epoch=E ordinal=O <path>:<row_group>"``; anything else keys by repr.
    ``FaultRule.item_key`` substring-matches against this."""
    try:
        if isinstance(item, tuple) and len(item) == 3:
            epoch, ordinal, inner = item
            piece = inner[0] if isinstance(inner, tuple) and inner else inner
            path = getattr(piece, "path", None)
            rg = getattr(piece, "row_group", None)
            if path is not None:
                return "epoch=%s ordinal=%s %s:%s" % (epoch, ordinal, path, rg)
            return "epoch=%s ordinal=%s %r" % (epoch, ordinal, inner)
    except Exception:  # noqa: BLE001 — a key must never fail the dispatch
        pass  # graftlint: disable=GL-O002 (falls through to the repr key)
    return repr(item)


class FaultPlan:
    """A seeded set of :class:`FaultRule` evaluated at the pipeline's hook
    sites. Thread-safe (hits come from every pipeline thread); pickle/JSON
    round-trippable (the plan crosses the pool handshake via the
    ``PTPU_CHAOS_SPEC`` environment variable — see :func:`..arm`)."""

    def __init__(self, rules, seed=0, max_ledger=4096):
        self._rules = list(rules)
        for r in self._rules:
            if not isinstance(r, FaultRule):
                raise TypeError("FaultPlan takes FaultRule instances, got %r"
                                % type(r).__name__)
        self.seed = int(seed)
        self._lock = threading.Lock()
        self._hits = [0] * len(self._rules)   # matching hits per rule
        self._fires = [0] * len(self._rules)  # executed actions per rule
        #: rule idx -> monotonic deadline of an OPEN net.partition window:
        #: frames matching that rule's site pattern are dropped until then
        self._drop_until = {}
        self._dropped_frames = 0
        self._ledger = []
        self._max_ledger = int(max_ledger)

    @property
    def rules(self):
        return list(self._rules)

    # -- evaluation (the per-site hook) -------------------------------------------------

    def hit(self, site, key=None, payload=None):
        """Evaluate every rule against one hook-site hit.

        May sleep (``latency``/``hang``), raise (``raise_*``), exit the
        process (``kill``, opted-in processes only), return a corrupted copy
        of ``payload`` (``corrupt``/``net.corrupt_frame``), or return
        :data:`DROPPED` (an open ``net.partition`` window swallowed the
        frame); returns ``payload`` unchanged when nothing fires. Hook sites
        call this only when a plan is armed."""
        if self._drop_until and self._in_drop_window(site):
            return DROPPED
        for idx, rule in enumerate(self._rules):
            if not fnmatch.fnmatchcase(site, rule.site):
                continue
            if rule.item_key is not None and (key is None
                                              or rule.item_key not in key):
                continue
            with self._lock:
                self._hits[idx] += 1
                hit_no = self._hits[idx]
                if not self._should_fire(rule, idx, hit_no):
                    continue
                self._fires[idx] += 1
            payload = self._execute(rule, idx, site, key, payload)
        return payload

    def _in_drop_window(self, site):
        """Is ``site`` inside an open ``net.partition`` window? Expired
        windows are pruned; dropped frames are counted (not ledgered — a
        partition drops heartbeats at wire rate and would flood it)."""
        now = time.monotonic()
        with self._lock:
            for idx, deadline in list(self._drop_until.items()):
                if now >= deadline:
                    del self._drop_until[idx]
                    continue
                if fnmatch.fnmatchcase(site, self._rules[idx].site):
                    self._dropped_frames += 1
                    return True
        return False

    def _should_fire(self, rule, idx, hit_no):
        """Caller holds the lock. Trigger conditions compose conjunctively."""
        if rule.times is not None and self._fires[idx] >= rule.times:
            return False
        if rule.every is not None:
            anchor = rule.nth if rule.nth is not None else rule.every
            if hit_no < anchor or (hit_no - anchor) % rule.every != 0:
                return False
        elif rule.nth is not None and hit_no != rule.nth:
            return False
        if rule.probability is not None and not _coin(
                self.seed, idx, hit_no, rule.probability):
            return False
        return True

    def _execute(self, rule, idx, site, key, payload):
        self._record(rule, idx, site, key)
        action = rule.action
        if action == "latency":
            time.sleep(rule.latency_s)
            return payload
        if action == "hang":
            # sleep in small slices so a disarm() (or the process being killed
            # by the heal tier) ends the hang promptly instead of pinning the
            # thread for the full duration after the scenario moved on
            deadline = time.monotonic() + rule.hang_s
            while time.monotonic() < deadline:
                if _current_plan() is not self:
                    return payload
                time.sleep(min(0.05, max(0.0, deadline - time.monotonic())))
            return payload
        if action == "raise_transient":
            raise ConnectionResetError(
                rule.message or "chaos-injected transient IO error at %s (%s)"
                % (site, key))
        if action == "raise_permanent":
            raise FileNotFoundError(
                rule.message or "chaos-injected permanent IO error at %s (%s)"
                % (site, key))
        if action in ("corrupt", "net.corrupt_frame"):
            return _corrupt_payload(payload, self.seed, idx)
        if action == "net.slow":
            time.sleep(rule.latency_s)
            return payload
        if action == "net.reset":
            # the transport turns this into a REAL socket teardown (so the
            # peer observes it too): exactly a mid-frame connection reset
            raise ConnectionResetError(
                rule.message or "chaos net.reset at %s (%s)" % (site, key))
        if action == "net.partition":
            # open the drop window (latency_s doubles as its duration) and
            # swallow the triggering frame; subsequent frames matching this
            # rule's site pattern vanish until the window closes
            with self._lock:
                self._drop_until[idx] = time.monotonic() + rule.latency_s
                self._dropped_frames += 1
            return DROPPED
        if action in _MUTATE_ACTIONS:
            # the dataset.mutate hook site passes a mutator object as the
            # payload; the action is a method call on it with the rule's spec
            method = getattr(payload, action, None)
            if method is None:
                raise ChaosError(
                    "chaos %r fired at %s without a dataset mutator payload; "
                    "mutation rules target the 'dataset.mutate' site of a "
                    "watcher with a mutator attached (DatasetWatcher."
                    "set_mutator)" % (action, site))
            method(rule.target)
            return payload
        if action == "kill":
            if not _kill_allowed:
                raise ChaosError(
                    "chaos 'kill' action fired at %s in a process that did not "
                    "opt in (allow_kill); kill rules target in-child sites "
                    "like 'child.item'" % site)
            import os as _os

            _os._exit(137)  # SIGKILL-like: no teardown, exactly a crashed child
        raise ChaosError("unknown chaos action %r" % action)  # unreachable

    def _record(self, rule, idx, site, key):
        entry = {"site": site, "action": rule.action, "rule": idx,
                 "key": key, "t": time.time()}
        with self._lock:
            if len(self._ledger) < self._max_ledger:
                self._ledger.append(entry)
        from petastorm_tpu.obs import flight as _flight
        from petastorm_tpu.obs.log import degradation

        for recorder in _flight.active_recorders():
            recorder.record("chaos", site=site, action=rule.action, key=key)
        degradation(
            "chaos_injected",
            "chaos plane injected %s at %s (key=%s, rule %d)", rule.action,
            site, key, idx)

    # -- inspection ---------------------------------------------------------------------

    def injections(self):
        """The in-process injection ledger (site/action/rule/key dicts, in
        order). A pool child's injections live in ITS process — the harness
        observes those through the degradation/flight record instead."""
        with self._lock:
            return list(self._ledger)

    def stats(self):
        with self._lock:
            return {
                "hits": list(self._hits),
                "fires": list(self._fires),
                "injected_total": sum(self._fires),
                "dropped_frames": self._dropped_frames,
            }

    # -- (de)serialization --------------------------------------------------------------

    def to_json(self):
        return json.dumps({"seed": self.seed,
                           "rules": [r.to_spec() for r in self._rules]})

    @classmethod
    def from_json(cls, text):
        spec = json.loads(text)
        return cls([FaultRule.from_spec(r) for r in spec["rules"]],
                   seed=spec.get("seed", 0))

    def __repr__(self):
        return "<FaultPlan seed=%d rules=%r>" % (self.seed, self._rules)


def _corrupt_payload(payload, seed, rule_idx):
    """Flip one byte in the largest buffer of ``payload`` (a list of wire
    frames, or a single bytes-like). Returns a corrupted COPY — the original
    buffers may be views into shared memory someone else still owns."""
    if payload is None:
        raise ChaosError(
            "chaos 'corrupt' fired at a site with no byte payload; corrupt "
            "rules target 'wire.decode'")
    frames = list(payload) if isinstance(payload, (list, tuple)) else [payload]
    sizes = [len(memoryview(f).cast("B")) if f is not None else 0
             for f in frames]
    target = max(range(len(frames)), key=lambda i: sizes[i])
    if sizes[target] == 0:
        raise ChaosError("chaos 'corrupt' fired on an empty payload")
    buf = bytearray(memoryview(frames[target]).cast("B"))
    pos = zlib.crc32(("corrupt|%d|%d" % (seed, rule_idx)).encode("ascii")) \
        % len(buf)
    buf[pos] ^= 0xFF
    frames[target] = bytes(buf)
    if isinstance(payload, (list, tuple)):
        return type(payload)(frames) if isinstance(payload, tuple) else frames
    return frames[0]


def _current_plan():
    """The armed plan (import indirection so ``hang`` can notice disarm)."""
    from petastorm_tpu import chaos

    return chaos.ACTIVE
