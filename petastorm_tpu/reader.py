"""Reader core: ``make_reader`` / ``make_batch_reader`` / ``Reader``.

Capability parity with petastorm/reader.py (``make_reader`` ~L60, ``make_batch_reader`` ~L200,
``Reader`` ~L330: filtering, sharding, epochs, reset/stop/join, context manager) and the two
worker types (petastorm/py_dict_reader_worker.py ~L40 ``PyDictReaderWorker``,
petastorm/arrow_reader_worker.py ~L60 ``ArrowReaderWorker``), redesigned per SURVEY.md §8:

- Scheduling is a pure deterministic :class:`petastorm_tpu.plan.EpochPlan` (resumable,
  zero-communication multi-host sharding) instead of a ventilator thread.
- Workers return plain python/numpy payloads (no ZeroMQ, no pickled namedtuples); namedtuple
  wrapping happens on the consumer side so results cross process boundaries cheaply.
- The batch path keeps data columnar end-to-end (Arrow → numpy dict) — the layout
  ``petastorm_tpu.loader.DataLoader`` assembles into globally-sharded ``jax.Array`` batches.

``filters`` are applied at two levels (reference ``pq.ParquetDataset`` + ``filters``
semantics, petastorm/reader.py ~L330): hive ``key=value`` partition directories are pruned
from scheduling BEFORE any file is opened (:mod:`petastorm_tpu.partitions`), and the
remaining clauses run as vectorized row-level masks (DNF tuples like pyarrow's) in the
workers. Partition columns materialize as ordinary row/batch values.
"""
from __future__ import annotations

import logging
import os
import random
import re
import threading
import time
import weakref

import numpy as np

from petastorm_tpu import chaos as _chaos
from petastorm_tpu.obs import provenance as _prov
from petastorm_tpu.cache import make_cache
from petastorm_tpu.io import IoOptions
from petastorm_tpu.errors import (
    PERMANENT_IO_ERRORS as _PERMANENT_IO_ERRORS,
    DecodeFieldError,
    NoDataAvailableError,
    PieceRemovedError,
    PieceRewrittenError,
)
from petastorm_tpu.fs import get_filesystem_and_path_or_paths
from petastorm_tpu.metadata import (
    get_schema,
    infer_or_load_unischema,
    load_row_groups,
)
from petastorm_tpu.ngram import NGram
from petastorm_tpu.plan import EpochPlan, shard_indices
from petastorm_tpu.recovery import (
    QuarantinedItem,
    QuarantineEntry,
    QuarantineReport,
    RecoveryOptions,
    count_quarantined,
)
from petastorm_tpu.transform import transform_schema
from petastorm_tpu.unischema import Unischema, UnischemaField
from petastorm_tpu.serializers import SHM_LEASE_KEY as _SHM_LEASE_KEY
from petastorm_tpu.utils import decode_row
from petastorm_tpu.workers import make_executor

logger = logging.getLogger(__name__)


# --------------------------------------------------------------------------------------
# Workers (picklable module-level classes; one instance shared by all pool workers)
# --------------------------------------------------------------------------------------


class _Tagged:
    """Wraps a worker so results carry their (epoch, ordinal) dispatch tag — the bookkeeping
    exact resume needs (picklable for the process pool). Forwards the async-IO
    surface (``prefetch``/``close``/``io_stats``/``set_trace``) so executors and
    pool children talk to the tagged wrapper as if it were the worker."""

    def __init__(self, worker, tenant=None):
        self._worker = worker
        #: resolved TenantContext (ISSUE 18) — pickles into pool children and
        #: read by ProcessExecutor.start to seed the child env (PTPU_TENANT)
        self.tenant_context = tenant

    def __call__(self, tagged_item):
        epoch, ordinal, item = tagged_item
        ctx = self.tenant_context
        if ctx is None:
            return (epoch, ordinal, self._worker(item))
        # activate the tenant around the worker call so every IO charge on
        # this thread (tier bytes, arena admits, hedges) bills the owner, and
        # meter the worker-seconds the item actually consumed
        from petastorm_tpu.obs import tenant as _tenant_mod

        with _tenant_mod.activate(ctx):
            # the executor's begin_item ran BEFORE this activation, so stamp
            # the tenant annotation here — per-tenant attribution folds
            # filter on it
            from petastorm_tpu.obs import provenance as _prov

            _prov.annotate("tenant", ctx.tenant)
            t0 = time.perf_counter()
            try:
                return (epoch, ordinal, self._worker(item))
            finally:
                _tenant_mod.charge("worker_s", time.perf_counter() - t0,
                                   label=ctx.tenant)

    def prefetch(self, tagged_items):
        """Readahead hint: strip the dispatch tags, hand the plan items down."""
        fn = getattr(self._worker, "prefetch", None)
        if fn is not None:
            fn([tagged[2] for tagged in tagged_items])

    def close(self):
        fn = getattr(self._worker, "close", None)
        if fn is not None:
            fn()

    def io_stats(self):
        fn = getattr(self._worker, "io_stats", None)
        return fn() if fn is not None else {}

    def set_trace(self, tracer):
        fn = getattr(self._worker, "set_trace", None)
        if fn is not None:
            fn(tracer)

    def set_health(self, monitor):
        fn = getattr(self._worker, "set_health", None)
        if fn is not None:
            fn(monitor)

    # -- live knob seam (ISSUE 13/14): forwarded so the pool control frame's
    # -- apply_<knob>() dispatch reaches the real worker inside a child

    def apply_readahead_depth(self, depth):
        fn = getattr(self._worker, "apply_readahead_depth", None)
        return fn(depth) if fn is not None else None

    def apply_readahead_bytes(self, nbytes):
        fn = getattr(self._worker, "apply_readahead_bytes", None)
        return fn(nbytes) if fn is not None else None

    def apply_remote_max_inflight(self, max_inflight):
        fn = getattr(self._worker, "apply_remote_max_inflight", None)
        return fn(max_inflight) if fn is not None else None

    def apply_hedge_quantile(self, quantile):
        fn = getattr(self._worker, "apply_hedge_quantile", None)
        return fn(quantile) if fn is not None else None

    def apply_pagedec(self, mode):
        fn = getattr(self._worker, "apply_pagedec", None)
        return fn(mode) if fn is not None else None

    def apply_arena_bytes(self, nbytes):
        fn = getattr(self._worker, "apply_arena_bytes", None)
        return fn(nbytes) if fn is not None else None

    def live_io_knobs(self):
        fn = getattr(self._worker, "live_io_knobs", None)
        return fn() if fn is not None else {}


#: Exception-module roots of the storage client stacks fsspec-bridged filesystems
#: raise through pyarrow (gcsfs.retry.HttpError, botocore errors, aiohttp client
#: errors, google.api_core exceptions, ...). Most of these do NOT derive from
#: OSError, so classification is by origin: an error born in the storage layer is
#: worth the bounded retries — a genuinely permanent one just fails a little later.
_TRANSIENT_ERROR_MODULES = frozenset(
    ("gcsfs", "s3fs", "adlfs", "fsspec", "aiohttp", "aiobotocore", "botocore",
     "urllib3", "requests", "google", "azure"))


def _is_transient_io_error(exc):
    """Retry-worthy? OSErrors are (minus the permanent subclasses); anything raised
    by a storage client stack is; everything else (corrupt parquet → ArrowInvalid,
    user code errors) fails fast."""
    if isinstance(exc, _PERMANENT_IO_ERRORS):
        return False
    if isinstance(exc, OSError):
        return True
    mod = (type(exc).__module__ or "").split(".")[0]
    return mod in _TRANSIENT_ERROR_MODULES


def _close_quietly(pf):
    """Close a cached ParquetFile for real: it wraps an already-open NativeFile
    (``_close_source=False``), so ``close()`` without ``force`` is a no-op and would
    leave the fd/connection to GC."""
    try:
        pf.close(force=True)
    except Exception:  # noqa: BLE001
        pass  # graftlint: disable=GL-O002 (best-effort close of an evicted handle)


def _spec_wants_writable(spec):
    """True when the host transform is an OPAQUE callable that may mutate the
    worker payload in place (the one consumer needing the cache's writable
    escalation). Declarative pipelines are out-of-place by construction."""
    return (spec is not None and not spec.device and spec.func is not None
            and not getattr(spec, "declarative", False))


#: serializes lazy per-process IO-runtime construction (the readahead pool);
#: module-level because worker objects must stay picklable (no instance locks)
_io_init_lock = threading.Lock()

_file_eviction_counter = None


def _count_file_eviction():
    """Bump ``ptpu_io_file_evictions_total`` (resolved once per process)."""
    global _file_eviction_counter
    counter = _file_eviction_counter
    if counter is None:
        from petastorm_tpu.obs.metrics import default_registry

        counter = _file_eviction_counter = default_registry().counter(
            "ptpu_io_file_evictions_total",
            help="cached open-ParquetFile handles closed (LRU bound or "
                 "transient-IO-retry reopen)")
    counter.inc()


class _WorkerBase:
    """Shared row-group loading: column-pruned reads, predicate masking, drop partitions."""

    #: Max cached open parquet files per thread (fd bound: threads × this);
    #: PTPU_MAX_OPEN_FILES overrides for long multi-file epochs on tight ulimits.
    MAX_OPEN_FILES = int(os.environ.get("PTPU_MAX_OPEN_FILES", "") or 64)

    def __init__(self, filesystem, read_schema, stored_schema, predicate, transform_spec,
                 cache, shuffle_row_drop_partitions, filters, seed,
                 device_fields=frozenset(), partition_info=None,
                 io_retries=None, io_retry_backoff_s=None, io_options=None,
                 recovery=None):
        self._fs = filesystem
        self._read_schema = read_schema  # fields to deliver (pre-transform view)
        self._stored_schema = stored_schema  # full stored schema (decode source of truth)
        self._predicate = predicate
        self._transform_spec = transform_spec
        self._cache = cache
        self._drop_partitions = shuffle_row_drop_partitions
        self._filters = filters
        self._seed = seed
        self._device_fields = frozenset(device_fields)  # host-stage-only decode columns
        self._partition_info = partition_info  # hive key=value layout (or None)
        # unified recovery policy (ISSUE 7): the struct is the source of truth;
        # the legacy per-kwarg knobs overlay it when a caller passes them
        self._recovery = RecoveryOptions.resolve(
            recovery, io_retries=io_retries,
            io_retry_backoff_s=io_retry_backoff_s)
        self._io_retries = self._recovery.io_retries
        self._io_retry_backoff_s = self._recovery.io_retry_backoff_s
        self._io_options = IoOptions.normalize(io_options)
        self._local = None  # threading.local built lazily (not picklable)
        self._readahead = None  # ReadaheadPool built lazily per process (threads)
        self._io_closed = False  # latched by close(); reopen() re-arms (reset)
        self._readahead_unavailable = False  # this worker's pool failed to build
        self._io_tracer = None
        self._io_health = None  # optional HealthMonitor for the IO threads
        self._remote = None  # RemoteReadEngine built lazily per process (ISSUE 8)
        self._remote_unavailable = False  # this worker's engine failed to build
        #: live knob overrides (ISSUE 13): applied retunes recorded here so a
        #: LAZILY-built pool/engine starts at the retuned value (and a pool
        #: child spawned after a retune inherits it through the pickle); the
        #: IoOptions struct itself is never mutated (graftlint GL-C004)
        self._knob_overrides = {}
        #: pass-through negative memo (ISSUE 14): (path, column) pairs whose
        #: chunks declined at the PAGE level (no byte saving / unsupported
        #: encoding) — footer eligibility would otherwise re-fetch the raw
        #: span on every read just to decline again. Conservative by design
        #: (one declining chunk mutes the column for the whole file);
        #: invalidate_pieces clears the path's entries on a rewrite.
        self._pagedec_refused = set()

    def __getstate__(self):
        state = dict(self.__dict__)
        state["_local"] = None
        state["_readahead"] = None  # each pool child builds its own IO runtime
        state["_io_closed"] = False
        state["_readahead_unavailable"] = False  # a child retries its own build
        state["_io_tracer"] = None
        state["_io_health"] = None  # owns threads — never crosses the pickle wire
        state["_remote"] = None  # each pool child builds its own GET pool
        state["_remote_unavailable"] = False
        return state

    def _cache_get(self, key, fill):
        """Cache read under the lease contract (ISSUE 6): a lease-aware cache
        (``MemCache``) serves zero-copy READ-ONLY views by default, but an
        OPAQUE host ``TransformSpec`` runs user code against the returned
        payload (pandas frames / row dicts aliasing the cached arrays) that
        may legitimately mutate in place — that consumer "actually writes",
        so the worker escalates to the cache's copy-on-write path up front
        and the copy is charged to the census (``memcache_cow``).

        Declarative :class:`~petastorm_tpu.ops.tabular.FeaturePipeline`
        transforms never mutate delivered payloads in place (each fused stage
        materializes its own output column), so they keep the zero-copy
        read-only serving contract — the ISSUE-9 narrowing of the
        writable-batch request."""
        writable = _spec_wants_writable(self._transform_spec)
        get_writable = getattr(self._cache, "get_writable", None)
        if writable and get_writable is not None:
            return get_writable(key, fill)
        return self._cache.get(key, fill)

    def _footer_cache(self):
        """The process-wide parsed-footer cache (ISSUE 8), or ``None`` when
        ``remote.footer_cache_bytes`` disables it. Shared by every worker
        thread AND the planner's footer scan — each file's footer is read and
        parsed once per process instead of once per thread."""
        budget = self._io_options.remote.footer_cache_bytes
        if not budget:
            return None
        from petastorm_tpu.io.footercache import configure_budget

        return configure_budget(budget)

    def _parquet_file(self, path):
        import pyarrow.parquet as pq

        if self._local is None:
            self._local = threading.local()
        cache = getattr(self._local, "files", None)
        if cache is None:
            from collections import OrderedDict

            cache = self._local.files = OrderedDict()
        pf = cache.get(path)
        if pf is None:
            f = self._fs.open_input_file(path)
            footers = self._footer_cache()
            metadata = None
            if footers is not None:
                # hit: pq.ParquetFile(metadata=...) opens with ZERO footer
                # reads; miss: the parse below populates the shared cache (the
                # handle's size() doubles as the entry's validation token)
                metadata = footers.get(self._fs, path, source=f).metadata
            pf = cache[path] = pq.ParquetFile(f, metadata=metadata)
            # the open handle doubles as the pass-through path's raw-span
            # reader (ISSUE 14): positional read_at calls never disturb the
            # ParquetFile's own cursor
            pf._ptpu_source = f
            while len(cache) > self.MAX_OPEN_FILES:  # LRU-evict to bound open fds
                _, old = cache.popitem(last=False)
                _close_quietly(old)
                _count_file_eviction()
        else:
            cache.move_to_end(path)
        return pf

    # -- mutable-dataset generation enforcement (ISSUE 11) ------------------------------

    def _verify_generation(self, piece):
        """Validate the piece's stamped generation token against the file as
        it exists NOW (dataset watching on — ``piece.generation`` stamped).

        A vanished file raises :class:`PieceRemovedError`; a stat or
        footer-crc mismatch invalidates the piece's footer/open-handle/cache
        entries and raises :class:`PieceRewrittenError` — both permanent
        (never burned as transient retries), both quarantinable under the
        PR-7 policy with their own causes. The hard invariant this enforces:
        a read can only ever deliver rows of the generation stamped into its
        plan item, so one epoch never mixes two generations of one file."""
        from petastorm_tpu.dataset.watch import current_stat_token, stat_token_of

        stat = current_stat_token(self._fs, piece.path)  # raises PieceRemovedError
        stamped_crc = piece.generation.rsplit(".", 1)[1]
        mismatch = stat_token_of(piece.generation) != stat
        if not mismatch and stamped_crc != "-":
            # stat identity held — a size+mtime-colliding rewrite can still
            # hide behind it; the footer crc (pinned to THIS stat identity,
            # so a stale parse cannot vouch for the new bytes) settles it
            from petastorm_tpu.io.footercache import shared_footer_cache

            entry = shared_footer_cache().get(self._fs, piece.path,
                                              stat_token=stat)
            mismatch = ("%08x" % entry.crc) != stamped_crc
        if mismatch:
            # NOT counted here: this runs in the worker — a pool child's
            # registry is invisible to the parent's export. The parent counts
            # ptpu_dataset_generation_conflicts_total when it absorbs the
            # piece_rewritten quarantine marker (Reader._absorb_quarantine).
            self.invalidate_pieces([piece])
            raise PieceRewrittenError(
                "%s row group %d was rewritten under the running reader "
                "(stamped generation %s no longer matches the file); its "
                "cache entries are invalidated and the watcher re-plans the "
                "new generation into a later epoch"
                % (piece.path, piece.row_group, piece.generation))

    def invalidate_pieces(self, pieces):
        """Drop every cache layer's entries for ``pieces`` under their
        stamped generation: the open-handle LRU + shared footer entry, and
        the mem/disk tiers' decoded payloads by exact key. Called by the
        read path on a generation conflict and by the dataset watcher when
        it observes a removal/rewrite."""
        invalidate = getattr(self._cache, "invalidate", None)
        for piece in pieces:
            self._evict_parquet_file(piece.path)
            # a rewritten file may compress differently: let the pass-through
            # re-judge its columns from the fresh bytes
            self._pagedec_refused = {
                t for t in self._pagedec_refused if t[0] != piece.path}
            if invalidate is not None:
                for partition in range(max(1, self._drop_partitions)):
                    invalidate(_cache_key(
                        piece, self._read_schema, self._predicate,
                        self._filters, partition, self._drop_partitions,
                        self._seed, self._device_fields))

    def _evict_parquet_file(self, path):
        """Drop (and close) the cached handle for ``path`` — a transient IO failure may
        leave it holding a dead connection; the retry must reopen from scratch.
        The shared footer entry is invalidated too: if the failure was the
        file being replaced, a retry replanning ranged GETs from the stale
        footer's offsets would fail identically forever."""
        cache = getattr(self._local, "files", None) if self._local is not None else None
        if cache is not None:
            pf = cache.pop(path, None)
            if pf is not None:
                _close_quietly(pf)
                _count_file_eviction()
        footers = self._footer_cache()
        if footers is not None:
            footers.invalidate(path)

    # -- async read path (ISSUE 4) ------------------------------------------------------

    def _readahead_pool(self, create=False):
        """The per-process readahead pool (None when the feature is off).

        Built lazily on the first ``prefetch`` — never pickled (each pool child
        constructs its own), never built by foreground reads (a reader whose
        executor sends no hints stays fully synchronous). A construction
        failure degrades the feature off for this worker with a logged
        ``readahead_unavailable`` cause."""
        if not self._io_options.readahead or self._readahead_unavailable:
            return None
        pool = self._readahead
        if pool is None and create:
            with _io_init_lock:
                pool = self._readahead
                if pool is None and not self._io_closed:
                    from petastorm_tpu.io.readahead import ReadaheadPool

                    opts = self._io_options
                    try:
                        # byte-gap run merging only under object-store request
                        # economics (remote tier active): local reads keep the
                        # PR 4 strict-adjacency behavior
                        gap_ok = self._rowgroup_gap_ok \
                            if opts.remote.active_for(self._fs) else None
                        knobs = self._knob_overrides
                        pool = ReadaheadPool(
                            self._read_columns_sync, read_run_fn=self._read_run,
                            depth=knobs.get("readahead_depth",
                                            opts.readahead_depth),
                            byte_budget=knobs.get("readahead_bytes",
                                                  opts.readahead_bytes),
                            io_threads=knobs.get("io_threads", opts.io_threads),
                            coalesce=opts.coalesce,
                            coalesce_max_run=opts.coalesce_max_run,
                            gap_ok=gap_ok)
                    except Exception as e:  # noqa: BLE001 — degrade to sync reads
                        from petastorm_tpu.obs.log import degradation

                        degradation(
                            "readahead_unavailable",
                            "readahead pool construction failed (%s); reads stay "
                            "synchronous", e)
                        # per-WORKER flag, never the caller-owned IoOptions: one
                        # IoOptions may be shared across readers, and one
                        # worker's failure must not flip the feature off there
                        self._readahead_unavailable = True
                        return None
                    if self._io_tracer is not None:
                        pool.set_trace(self._io_tracer)
                    if self._io_health is not None:
                        pool.set_health(self._io_health)
                    self._readahead = pool
        return pool

    # -- remote tier (ISSUE 8) ----------------------------------------------------------

    def _remote_engine(self, create=False):
        """The per-process ranged-GET engine, or ``None`` when the remote
        tier is off for this filesystem (local reads keep the classic
        ``ParquetFile`` path untouched). Built lazily like the readahead
        pool — never pickled, each pool child constructs its own; a
        construction failure degrades this worker to classic reads with a
        logged ``remote_unavailable`` cause."""
        if self._remote_unavailable:
            return None
        engine = self._remote
        if engine is None and create:
            opts = self._io_options.remote
            if not opts.active_for(self._fs):
                self._remote_unavailable = True  # cheap latch: probe once
                return None
            with _io_init_lock:
                engine = self._remote
                if engine is None and not self._io_closed:
                    try:
                        from petastorm_tpu.io.remote import build_engine

                        engine = build_engine(self._fs, opts)
                    except Exception as e:  # noqa: BLE001 — degrade to classic reads
                        from petastorm_tpu.obs.log import degradation

                        degradation(
                            "remote_unavailable",
                            "remote ranged-GET engine construction failed "
                            "(%s); reads use the classic ParquetFile path", e)
                        self._remote_unavailable = True
                        return None
                    if engine is None:
                        self._remote_unavailable = True
                        return None
                    # live retunes applied before this lazy build (ISSUE 13):
                    # the fresh engine starts at the retuned values
                    knobs = self._knob_overrides
                    if "remote_max_inflight" in knobs:
                        engine.apply_max_inflight(knobs["remote_max_inflight"])
                    if "hedge_quantile" in knobs:
                        engine.apply_hedge_quantile(knobs["hedge_quantile"])
                    self._remote = engine
        return engine

    def _rowgroup_gap_ok(self, prev, piece):
        """Byte-gap predicate for non-adjacent run coalescing: True when the
        hole between two row groups of one file (footer-cache spans) is at
        most the remote tier's ``min_gap_bytes`` — reading it is cheaper
        than a second round trip. Conservative ``False`` when the footer is
        not cached yet."""
        footers = self._footer_cache()
        if footers is None:
            return False
        entry = footers.peek(prev.path)
        if entry is None or piece.row_group >= entry.num_row_groups \
                or prev.row_group >= entry.num_row_groups:
            return False  # stale/foreign footer: never index past its groups
        gap = entry.row_group_span(piece.row_group)[0] \
            - entry.row_group_span(prev.row_group)[1]
        return 0 <= gap <= self._io_options.remote.min_gap_bytes

    def prefetch(self, items):
        """Dispatch lookahead hint: issue background reads for the upcoming plan
        ``items`` (``(piece, partition)`` tuples) so IO overlaps the current
        item's decode. Never raises — a scheduling failure degrades to
        synchronous reads with a logged cause."""
        pool = self._readahead_pool(create=True)
        if pool is None or not items:
            return
        try:
            columns = self._first_read_columns()
            requests = []
            for item in items:
                piece, partition = item
                if self._cache_contains(piece, partition):
                    continue  # the (mem/disk) cache will serve it without a read
                cols = columns
                # pass-through columns (ISSUE 14) are fetched by the
                # foreground read as raw pages — prefetching their DECODED
                # form would read them twice and key-miss besides. peek_only:
                # a prefetch never pays a footer fetch; until the footer is
                # cached the hint simply requests the full (classic) set.
                eligible = self._pagedec_eligible(piece, columns,
                                                  peek_only=True)
                if eligible:
                    cols = [c for c in columns if c not in eligible]
                    if not cols:
                        continue  # nothing classic left to prefetch
                requests.append((piece, cols))
            if requests:
                pool.schedule(requests)
        except Exception as e:  # noqa: BLE001 — prefetch must never fail a read
            from petastorm_tpu.obs.log import degradation

            degradation("readahead_fallback",
                        "prefetch scheduling failed (%s); reads stay synchronous", e)

    def _cache_contains(self, piece, partition):
        key = _cache_key(piece, self._read_schema, self._predicate, self._filters,
                         partition, self._drop_partitions, self._seed,
                         self._device_fields)
        return self._cache.contains(key)

    def _first_read_columns(self):
        """The column selection of this worker's FIRST read for any piece — what
        the readahead must request for its prefetched table to be a hit."""
        raise NotImplementedError

    def close(self):
        """Release the per-process IO runtime (Reader.join / pool-child exit)
        and latch prefetching off — a straggling executor thread mid-loop must
        not rebuild the pool under a teardown. Idempotent; :meth:`reopen`
        (Reader restart) re-arms it."""
        with _io_init_lock:
            self._io_closed = True
            pool, self._readahead = self._readahead, None
            engine, self._remote = self._remote, None
        if pool is not None:
            pool.shutdown()
        if engine is not None:
            engine.shutdown()

    def reopen(self):
        """Re-arm lazy readahead/remote-engine construction after a
        :meth:`close` (the Reader calls this from ``_start`` so ``reset()``
        gets a fresh IO runtime)."""
        with _io_init_lock:
            self._io_closed = False

    def io_stats(self):
        """Live async-IO gauges: readahead + cache tiers + remote engine +
        footer cache (empty dicts when off). Surfaced through
        ``Reader.io_stats()`` for thread/dummy pools."""
        out = {}
        pool = self._readahead
        if pool is not None:
            out.update(pool.stats())
        stats_fn = getattr(self._cache, "stats", None)
        if stats_fn is not None:
            out.update(stats_fn())
        engine = self._remote
        if engine is not None:
            out.update(engine.stats())
        footers = self._footer_cache()
        if footers is not None:
            out.update(footers.stats())
        from petastorm_tpu.io import arena as _arena_mod

        arena_obj = _arena_mod.process_arena()
        if arena_obj is not None:
            out.update(arena_obj.stats())
        return out

    def set_trace(self, tracer):
        self._io_tracer = tracer
        pool = self._readahead
        if pool is not None:
            pool.set_trace(tracer)

    def set_health(self, monitor):
        self._io_health = monitor
        pool = self._readahead
        if pool is not None:
            pool.set_health(monitor)

    # -- live knobs (ISSUE 13) ----------------------------------------------------------
    #
    # The sanctioned retune seam the controller's KnobSet binds to. Each
    # apply records the override (a lazily-built pool/engine starts retuned;
    # pool children spawned after the retune inherit it through the pickle)
    # and forwards to the live component when one exists. The IoOptions
    # struct is never mutated (GL-C004): one options object may be shared
    # across readers, and a retune here must stay this reader's.

    def live_io_knobs(self):
        """The LIVE IO knob values (overrides > live components > options)."""
        opts = self._io_options
        pool = self._readahead
        engine = self._remote
        knobs = self._knob_overrides
        return {
            "readahead_depth": pool.depth if pool is not None
            else knobs.get("readahead_depth", opts.readahead_depth),
            "readahead_bytes": (pool.byte_budget or 0) if pool is not None
            else knobs.get("readahead_bytes", opts.readahead_bytes),
            "io_threads": pool.io_threads if pool is not None
            else knobs.get("io_threads", opts.io_threads),
            "remote_max_inflight": engine.max_inflight if engine is not None
            else knobs.get("remote_max_inflight", opts.remote.max_inflight),
            "hedge_quantile": engine.hedge_quantile if engine is not None
            else knobs.get("hedge_quantile", opts.remote.hedge_quantile),
        }

    def apply_readahead_depth(self, depth):
        """Retune the prefetch window live. The IO thread pool is sized with
        it (bounded) — a deeper window on the configured 2 threads would
        queue, not overlap."""
        depth = max(1, int(depth))
        self._knob_overrides["readahead_depth"] = depth
        io_threads = max(self._io_options.io_threads, min(depth, 16))
        self._knob_overrides["io_threads"] = io_threads
        pool = self._readahead
        if pool is not None:
            pool.apply_depth(depth)
            pool.apply_io_threads(io_threads)
        return depth

    def apply_readahead_bytes(self, nbytes):
        nbytes = max(0, int(nbytes))
        self._knob_overrides["readahead_bytes"] = nbytes
        pool = self._readahead
        if pool is not None:
            pool.apply_byte_budget(nbytes)
        return nbytes

    def apply_remote_max_inflight(self, max_inflight):
        max_inflight = max(1, int(max_inflight))
        self._knob_overrides["remote_max_inflight"] = max_inflight
        engine = self._remote
        if engine is not None:
            engine.apply_max_inflight(max_inflight)
        return max_inflight

    def apply_hedge_quantile(self, quantile):
        quantile = min(0.999, max(0.5, float(quantile)))
        self._knob_overrides["hedge_quantile"] = quantile
        engine = self._remote
        if engine is not None:
            engine.apply_hedge_quantile(quantile)
        return quantile

    def apply_mem_cache_bytes(self, nbytes):
        """Retune the mem tier's budget (the hot-row-group promotion lever);
        a no-op returning 0 when no mem tier exists."""
        mem = getattr(self._cache, "mem", None)
        if mem is None:
            return 0
        return mem.apply_budget(nbytes)

    def apply_arena_bytes(self, nbytes):
        """Retune the host-wide arena budget (ISSUE 17). The budget lives in
        the shared control segment, so one actuation — wherever it lands —
        governs every attached process's admissions; shrinking evicts unheld
        entries host-wide immediately. No-op returning 0 without an arena."""
        from petastorm_tpu.io import arena as _arena_mod

        arena_obj = _arena_mod.process_arena()
        if arena_obj is None:
            return 0
        return arena_obj.set_budget(nbytes)

    # -- compressed-page pass-through (ISSUE 14) ----------------------------------------
    #
    # Eligible fixed-width columns skip pyarrow's host inflate entirely: the
    # raw compressed pages ride the delivery path as opaque
    # PassthroughColumn values and inflate on device in the loader
    # (ops/pagedec_kernels.py). Ineligible columns degrade PER COLUMN to the
    # classic read (cause=pagedec_ineligible, warn-once), so any dataset
    # works unchanged. The whole mode is the IoOptions.pagedec auto/on/off
    # knob — also a live enum Knob the controller can flip back to host
    # inflate (apply_pagedec below).

    #: per-row workers decode rows — pass-through is a batch-path feature
    _pagedec_supported = False

    def live_pagedec(self):
        """The LIVE pagedec mode (override > options) — the knob getter."""
        return self._knob_overrides.get("pagedec", self._io_options.pagedec)

    def apply_pagedec(self, mode):
        """Retune the pass-through mode live (lands on the next read; for
        process pools the retune rides the pool control frame)."""
        mode = str(mode).strip().lower()
        if mode not in ("auto", "on", "off"):
            raise ValueError("pagedec accepts auto/on/off, got %r" % mode)
        self._knob_overrides["pagedec"] = mode
        return mode

    def _pagedec_active(self):
        """Is pass-through live for this worker's reads? ``auto`` engages
        only when a non-CPU jax backend is already initialized in THIS
        process (no PCIe link to save otherwise, and pool children never pay
        a jax import for the probe); ``on`` forces it (the pool wire ships
        compressed either way)."""
        if not self._pagedec_supported:
            return False
        mode = self.live_pagedec()
        if mode == "off":
            return False
        if mode == "on":
            return True
        import sys

        jax = sys.modules.get("jax")
        if jax is None:
            return False
        try:
            return jax.default_backend() != "cpu"
        except Exception:  # noqa: BLE001 — an uninitializable backend = no device
            return False

    def _pagedec_shape_ok(self):
        """Row-selecting features (predicate/filter masks, row-drop
        partitions) and in-worker rewrites (host transforms, NGram windows)
        need decoded rows — the whole read falls back when any is
        configured."""
        return (self._predicate is None and not self._filters
                and self._drop_partitions <= 1
                and self._transform_spec is None
                and getattr(self, "_ngram", None) is None)

    def _pagedec_footer(self, path, peek_only=False):
        """The parsed footer for eligibility classification, or ``None``.
        ``peek_only`` (the prefetch path) never triggers IO — eligibility is
        then simply unknown until the first real open caches the footer."""
        footers = self._footer_cache()
        if footers is not None:
            entry = footers.peek(path)
            if entry is not None:
                return entry.metadata
        if peek_only:
            return None
        engine = self._remote_engine(create=True)
        try:
            if engine is not None:
                return engine.footer(path).metadata
            return self._parquet_file(path).metadata
        except Exception:  # noqa: BLE001 — the classic read will surface it
            return None

    def _pagedec_eligible(self, piece, wanted, peek_only=False):
        """Footer-only eligibility: ``{column: (col_index, Eligibility)}``
        for the eligible subset of ``wanted``. Cheap (no chunk bytes); the
        walker's page-level gate runs after the raw spans arrive."""
        if not self._pagedec_active() or not self._pagedec_shape_ok():
            return {}
        md = self._pagedec_footer(piece.path, peek_only=peek_only)
        if md is None or piece.row_group >= md.num_row_groups:
            return {}
        from petastorm_tpu.io.pagedec import classify_chunk

        names = set(wanted)
        out = {}
        rgmd = md.row_group(piece.row_group)
        for i in range(rgmd.num_columns):
            name = rgmd.column(i).path_in_schema.split(".")[0]
            if name not in names or name in out \
                    or (piece.path, name) in self._pagedec_refused:
                continue
            el = classify_chunk(md, piece.row_group, i)
            if el.eligible:
                out[name] = (i, el)
        return out

    def _pagedec_read(self, piece, eligible):
        """Fetch + walk the eligible columns' raw chunk spans into
        PassthroughColumn values (``io.pagedec`` span + chaos hook site).
        Page-level ineligibility degrades per column; corruption raises
        :class:`~petastorm_tpu.errors.PagedecCorruptError` (permanent,
        quarantine-eligible)."""
        from petastorm_tpu.io.pagedec import (PassthroughColumn, build_chunk,
                                              chunk_byte_range,
                                              pagedec_counters,
                                              shared_page_index)
        from petastorm_tpu.obs.log import degradation

        out = {}
        counters = pagedec_counters()
        with _prov.span("io.pagedec"):
            if _chaos.ACTIVE is not None:
                _chaos.ACTIVE.hit("io.pagedec",
                                  key="%s:%s" % (piece.path, piece.row_group))
            md = self._pagedec_footer(piece.path)
            if md is None:
                # the footer vanished between eligibility and read (cache
                # eviction + a failing re-fetch): degrade to the classic
                # read, whose own path surfaces/classifies the real error
                return {}
            raws = self._pagedec_fetch_raw(piece, eligible)
            rgmd = md.row_group(piece.row_group)
            for name, (col_idx, el) in eligible.items():
                raw = raws.get(name)
                if raw is None:
                    continue
                chunk, reason = build_chunk(raw, el,
                                            expected_values=rgmd.num_rows)
                if chunk is None:
                    counters["fallback_columns"].inc()
                    # mute the column for this file: re-fetching its raw span
                    # on every read just to decline again is pure overhead
                    self._pagedec_refused.add((piece.path, name))
                    degradation(
                        "pagedec_ineligible",
                        "column %r of %s degraded to the classic host-inflate "
                        "path (%s); further per-column fallbacks are counted "
                        "in ptpu_pagedec_fallback_columns_total",
                        name, piece.path, reason)
                    continue
                start, _length = chunk_byte_range(rgmd.column(col_idx))
                shared_page_index().put(
                    piece.path, piece.row_group, name, start,
                    [start + p.header_offset
                     for p in ((chunk.dict_page,) if chunk.dict_page else ())
                     + chunk.pages])
                out[name] = PassthroughColumn.from_chunk(chunk)
        return out

    def _pagedec_fetch_raw(self, piece, eligible):
        """Raw chunk byte spans for the eligible columns: ONE batched
        ranged-GET plan through the remote engine (page-granular splits on
        re-reads), or positional reads on the local open handle."""
        engine = self._remote_engine(create=True)
        if engine is not None:
            return engine.read_raw_column_chunks(
                piece.path, piece.row_group, list(eligible))
        from petastorm_tpu.io.pagedec import chunk_byte_range

        pf = self._parquet_file(piece.path)
        source = getattr(pf, "_ptpu_source", None)
        md = pf.metadata
        rgmd = md.row_group(piece.row_group)
        out = {}
        for name, (col_idx, _el) in eligible.items():
            start, length = chunk_byte_range(rgmd.column(col_idx))
            if source is not None:
                out[name] = bytes(source.read_at(length, start))
            else:
                with self._fs.open_input_file(piece.path) as f:
                    out[name] = bytes(f.read_at(length, start))
        return out

    # -- reads --------------------------------------------------------------------------

    def _read_columns(self, piece, columns):
        """Read a row group restricted to ``columns`` (None = all), serving from
        the readahead pool when the dispatch layer prefetched it (the pool's
        failure semantics mirror the synchronous retry path — see
        petastorm_tpu/io/readahead.py)."""
        pool = self._readahead_pool()
        if pool is not None:
            table = pool.get(piece, columns)
            if table is not None:
                return table
            # a readahead MISS falling to the blocking path is EXPOSED read
            # latency just like a foreground wait — the controller's
            # grow-readahead trigger scale (io/readahead.py stats)
            t0 = time.perf_counter()
            table = self._read_columns_sync(piece, columns)
            pool.note_sync_read(time.perf_counter() - t0)
            return table
        return self._read_columns_sync(piece, columns)

    def _read_columns_sync(self, piece, columns):
        """The blocking read with transient-IO retry. Hive partition columns
        (directory values, not in the file) are appended as constants.

        Transient IO errors (connection resets, timeouts — routine against object
        stores at pod scale) are retried up to ``io_retries`` times with jittered
        exponential backoff, reopening the file each time. The reference has no retry
        anywhere (SURVEY.md §6: a worker exception kills the read); permanent
        conditions (missing file, bad permissions) still fail fast."""
        return self._retry_io(
            lambda: self._read_columns_once(piece, columns), piece.path,
            "%s row group %d" % (piece.path, piece.row_group))

    def _retry_io(self, fn, path, what):
        """One copy of the transient-retry protocol, shared by single-row-group
        and coalesced ranged reads (identical budget either way). Policy comes
        from :class:`~petastorm_tpu.recovery.RecoveryOptions`: ``io_retries``
        extra attempts, jittered exponential backoff capped at
        ``io_retry_max_backoff_s``, and an optional ``read_deadline_s`` wall
        cap across ALL attempts of one read. Every retry is routed through the
        degradation log as ``cause=io_retry`` (counted per occurrence,
        warn-once logging) so a retry storm is visible in
        ``petastorm-tpu-stats`` and the flight record instead of scrolling by
        as ad-hoc warnings."""
        rec = self._recovery
        attempt = 0
        t_first = time.monotonic()
        while True:
            try:
                return fn()
            except Exception as e:  # noqa: BLE001 — classified below
                if not _is_transient_io_error(e) or attempt >= rec.io_retries:
                    raise
                if rec.read_deadline_s and \
                        time.monotonic() - t_first >= rec.read_deadline_s:
                    from petastorm_tpu.obs.log import degradation

                    degradation(
                        "io_retry",
                        "read deadline (%.0fs) exhausted for %s after %d "
                        "attempt(s); raising the last error", rec.read_deadline_s,
                        what, attempt + 1)
                    raise
                self._evict_parquet_file(path)
                _prov.annotate_add("io_retries", 1)
                delay = min(
                    rec.io_retry_backoff_s * (2 ** attempt) * (0.5 + random.random()),
                    rec.io_retry_max_backoff_s)
                from petastorm_tpu.obs.log import degradation

                degradation(
                    "io_retry",
                    "Transient IO error reading %s (%s); retry %d/%d in %.2fs",
                    what, e, attempt + 1, rec.io_retries, delay)
                time.sleep(delay)
                attempt += 1

    def _read_columns_once(self, piece, columns):
        with _prov.span("reader.read"):
            if _chaos.ACTIVE is not None:
                _chaos.ACTIVE.hit("reader.read",
                                  key="%s:%s" % (piece.path, piece.row_group))
            if getattr(piece, "generation", None) is not None:
                self._verify_generation(piece)
            engine = self._remote_engine(create=True)
            if engine is not None:
                # the engine filters unavailable columns against the footer it
                # already resolved — one metadata fetch per read, not two
                table, _ = engine.read_row_groups(piece.path,
                                                  [piece.row_group], columns)
                return self._attach_partitions(table, piece, columns)
            pf = self._parquet_file(piece.path)
            available = set(pf.schema_arrow.names)
            file_columns = columns
            if columns is not None:
                file_columns = [c for c in columns if c in available]
            table = pf.read_row_group(piece.row_group, columns=file_columns)
            return self._attach_partitions(table, piece, columns)

    def _attach_partitions(self, table, piece, columns):
        if self._partition_info:
            from petastorm_tpu.partitions import attach_partition_columns

            table = attach_partition_columns(
                table, piece, self._partition_info,
                wanted=None if columns is None else set(columns))
        return table

    def _read_run(self, pieces, columns):
        """Coalesced ranged read: adjacent row groups of ONE file in a single
        ``read_row_groups`` call, sliced back into per-piece tables (the
        readahead pool's ``read_run_fn``; byte-identical to per-group reads —
        `petastorm-tpu-bench io --smoke` asserts it in CI)."""
        return self._retry_io(
            lambda: self._read_run_once(pieces, columns), pieces[0].path,
            "%s row groups %s" % (pieces[0].path,
                                  [p.row_group for p in pieces]))

    def _read_run_once(self, pieces, columns):
        from petastorm_tpu.io.coalesce import split_run_table

        with _prov.span("reader.read_run"):
            if _chaos.ACTIVE is not None:
                _chaos.ACTIVE.hit(
                    "reader.read_run",
                    key="%s:%s" % (pieces[0].path,
                                   ",".join(str(p.row_group) for p in pieces)))
            if getattr(pieces[0], "generation", None) is not None:
                self._verify_generation(pieces[0])  # one file per run
            row_groups = [p.row_group for p in pieces]
            engine = self._remote_engine(create=True)
            if engine is not None:
                table, entry = engine.read_row_groups(pieces[0].path,
                                                      row_groups, columns)
                sizes = [entry.row_group_rows[rg] for rg in row_groups]
                return [self._attach_partitions(t, piece, columns)
                        for t, piece in zip(split_run_table(table, sizes),
                                            pieces)]
            pf = self._parquet_file(pieces[0].path)
            available = set(pf.schema_arrow.names)
            file_columns = columns
            if columns is not None:
                file_columns = [c for c in columns if c in available]
            table = pf.read_row_groups(row_groups, columns=file_columns)
            sizes = [pf.metadata.row_group(rg).num_rows for rg in row_groups]
            return [self._attach_partitions(t, piece, columns)
                    for t, piece in zip(split_run_table(table, sizes), pieces)]

    def _row_mask(self, table):
        """Boolean keep-mask from filters + predicate over a row-group table (or None)."""
        mask = None
        if self._filters:
            mask = _dnf_mask(table, self._filters)
        if self._predicate is not None:
            cols = {
                name: _column_to_numpy(table, name, self._stored_schema)
                for name in self._predicate.get_fields()
            }
            pmask = np.asarray(self._predicate.do_include_vectorized(cols), dtype=bool)
            mask = pmask if mask is None else (mask & pmask)
        return mask

    def _drop_partition_indices(self, piece, num_rows):
        """Deterministic 1/k row subset for shuffle_row_drop_partitions (reference
        petastorm/reader.py ~L520 + worker ``_read_with_shuffle_row_drop``).

        Seeded with crc32(path) — NOT hash(), which is PYTHONHASHSEED-randomized per
        interpreter and would make partitions computed in different pool processes neither
        tile nor cover the row group."""
        import zlib

        piece_key, partition = piece
        k = self._drop_partitions
        seq = np.random.SeedSequence(
            [0 if self._seed is None else int(self._seed),
             zlib.crc32(piece_key.path.encode("utf-8")) & 0x7FFFFFFF,
             piece_key.row_group]
        )
        perm = np.random.Generator(np.random.PCG64(seq)).permutation(num_rows)
        return np.sort(np.array_split(perm, k)[partition])


class PyDictWorker(_WorkerBase):
    """Per-row decode path (reference ``PyDictReaderWorker``): row group → decoded row dicts.

    Predicate IO saving is kept: predicate columns are read and masked first; remaining columns
    are fetched only when some rows match. NGram windows are assembled in-worker — after the
    TransformSpec runs, against the post-transform schema (``ngram_schema``), matching the
    downstream namedtuple views.
    """

    def __init__(self, *args, ngram=None, ngram_schema=None, **kwargs):
        super().__init__(*args, **kwargs)
        self._ngram = ngram
        self._ngram_schema = ngram_schema

    def __call__(self, item):
        piece, _partition = item
        cache_key = _cache_key(piece, self._read_schema, self._predicate, self._filters,
                               item[1], self._drop_partitions, self._seed,
                               self._device_fields)
        rows = self._cache_get(cache_key, lambda: self._load_rows(item))
        spec = self._transform_spec
        if spec is not None and not spec.device:
            with _prov.span("transform"):
                if getattr(spec, "declarative", False):
                    # compiled declarative pipeline: ONE columnar application
                    # over the whole row group (and thus over each NGram
                    # window's columnar form) instead of a func(dict(r)) per row
                    rows = spec.apply_rows(rows)
                elif spec.func is not None:
                    rows = [spec.func(dict(r)) for r in rows]
        if self._ngram is not None:
            # sort/window on decoded (and transformed) rows; plain dicts for cheap IPC
            return self._form_ngram_dicts(rows)
        return rows

    def _mask_fields(self):
        """Sorted predicate+filter columns — the head read's selection when a
        row mask runs first (empty list = no mask read)."""
        predicate_fields = sorted(self._predicate.get_fields()) if self._predicate else []
        filter_fields = sorted(_dnf_fields(self._filters)) if self._filters else []
        return sorted(set(predicate_fields) | set(filter_fields))

    def _first_read_columns(self):
        # EXACTLY the head read of _load_rows (same _mask_fields source, so the
        # two cannot drift): predicate/filter columns when a mask runs first
        # (IO saving kept), the full wanted set otherwise — a prefetched table
        # is keyed by this list and must match to hit
        return self._mask_fields() or list(self._read_schema.fields.keys())

    def _load_rows(self, item):
        piece, partition = item
        wanted = list(self._read_schema.fields.keys())
        first_pass = self._mask_fields() or None

        if first_pass is not None:
            head = self._read_columns(piece, first_pass)
            mask = self._row_mask(head)
            if mask is not None and not mask.any():
                return []
            # second pass fetches only the columns the head read didn't already
            # decode — straight to the sync path: this key is never prefetched,
            # and routing it through the pool would just count a bogus miss
            remaining = sorted(set(wanted) - set(head.column_names))
            if remaining:
                tail = self._read_columns_sync(piece, remaining)
                table = _merge_tables(head, tail)
            else:
                table = head
        else:
            mask = None
            table = self._read_columns(piece, wanted)

        indices = np.arange(table.num_rows)
        if mask is not None:
            indices = indices[mask]
        if self._drop_partitions > 1:
            keep = self._drop_partition_indices(item, table.num_rows)
            indices = np.intersect1d(indices, keep, assume_unique=False)
        if len(indices) == 0:
            return []
        if len(indices) < table.num_rows:
            table = table.take(indices)
        stored_rows = table.to_pylist()
        decode_view = self._stored_schema.create_schema_view(
            [c for c in table.column_names if c in self._stored_schema.fields]
        )
        try:
            staged = {}
            for name in self._device_fields:
                # whole-row-group batched stage 1 (one native call), same as the batch
                # path; decode_row then just picks up each row's pre-staged payload
                field = decode_view.fields.get(name)
                batch_stage = getattr(field.codec, "host_stage_decode_batch", None) \
                    if field is not None else None
                if batch_stage is not None:
                    try:
                        staged[name] = batch_stage(
                            field, [r.get(name) for r in stored_rows])
                    except DecodeFieldError:
                        raise
                    except Exception as e:  # noqa: BLE001 — decode_row contract
                        raise DecodeFieldError(
                            "Unable to decode field %r: %s" % (name, e)) from e
            rows = []
            for i, r in enumerate(stored_rows):
                prestaged = {name: col[i] for name, col in staged.items()}
                rows.append(decode_row(r, decode_view, self._device_fields, prestaged))
        except DecodeFieldError as e:
            raise _annotate_decode_error(e, piece) from e
        return rows

    def _form_ngram_dicts(self, rows):
        schema = self._ngram_schema if self._ngram_schema is not None else self._read_schema
        windows = self._ngram.form_ngram(rows, schema)
        return [{offset: nt._asdict() for offset, nt in w.items()} for w in windows]


class ArrowWorker(_WorkerBase):
    """Vectorized batch path (reference ``ArrowReaderWorker``): row group → columnar numpy dict.

    Stays columnar the whole way — the shape the JAX loader wants. TransformSpec runs on a
    pandas DataFrame (reference contract). With an ``ngram``, the columnar batch is
    windowed in-worker (post-transform) via :func:`petastorm_tpu.ngram.form_ngram_columns`
    into flat ``offset/field`` columns — a TPU-first extension; the reference's NGram
    exists only on the per-row path (petastorm/ngram.py ~L40).
    """

    #: the batch path delivers columns — the shape the pass-through can ride
    _pagedec_supported = True

    def __init__(self, *args, ngram=None, **kwargs):
        super().__init__(*args, **kwargs)
        self._ngram = ngram

    def __call__(self, item):
        piece, _partition = item
        cache_key = _cache_key(piece, self._read_schema, self._predicate, self._filters,
                               item[1], self._drop_partitions, self._seed,
                               self._device_fields)
        columns = self._cache_get(cache_key, lambda: self._load_columns(item))
        spec = self._transform_spec
        if spec is not None and not spec.device:
            with _prov.span("transform"):
                if getattr(spec, "declarative", False):
                    # compiled declarative pipeline: fused vectorized kernels
                    # over the columnar batch — no pandas round trip, untouched
                    # columns stay the original zero-copy views
                    columns = spec.apply_columns(columns)
                elif spec.func is not None:
                    import pandas as pd

                    pdf = pd.DataFrame(
                        {k: list(v) if v.ndim > 1 else v
                         for k, v in columns.items()})
                    pdf = spec.func(pdf)
                    from petastorm_tpu.utils import stack_as_column

                    columns = {}
                    for name in pdf.columns:
                        series = pdf[name]
                        if series.dtype == object:
                            # tensor rows: one stack; scalar object columns
                            # (strings/decimals) degrade to an object array
                            columns[name] = stack_as_column(series.to_list())
                        else:
                            # no per-row materialization
                            columns[name] = series.to_numpy()
        if self._ngram is not None:
            from petastorm_tpu.ngram import form_ngram_columns

            columns = form_ngram_columns(columns, self._ngram)
        return columns

    def _first_read_columns(self):
        # the batch path reads everything at once: wanted + mask columns
        wanted = list(self._read_schema.fields.keys())
        extra = set()
        if self._predicate:
            extra |= set(self._predicate.get_fields())
        if self._filters:
            extra |= _dnf_fields(self._filters)
        return sorted(set(wanted) | extra)

    def _load_columns(self, item):
        piece, partition = item
        wanted = list(self._read_schema.fields.keys())
        # compressed-page pass-through (ISSUE 14): eligible fixed-width
        # columns ship their raw compressed pages as opaque columnar values;
        # the classic read below fetches only the remainder. Eligibility is
        # footer-only here (cheap) — the page walk inside _pagedec_read may
        # still degrade a column back (per-column fallback).
        passthrough = {}
        eligible = self._pagedec_eligible(piece, wanted)
        if eligible:
            # same transient-retry budget as any other read of this piece;
            # PagedecCorruptError is PERMANENT (fails fast -> quarantinable)
            passthrough = self._retry_io(
                lambda: self._pagedec_read(piece, eligible), piece.path,
                "%s row group %d (pagedec)" % (piece.path, piece.row_group))
        read_columns = self._first_read_columns()
        if passthrough:
            read_columns = [c for c in read_columns if c not in passthrough]
        if not read_columns:
            # every wanted column passed through: nothing left to decode on
            # the host, but the generation contract (ISSUE 11) still holds
            if getattr(piece, "generation", None) is not None:
                self._verify_generation(piece)
            return dict(passthrough)
        table = self._read_columns(piece, read_columns)
        mask = self._row_mask(table)
        indices = np.arange(table.num_rows)
        if mask is not None:
            indices = indices[mask]
        if self._drop_partitions > 1:
            keep = self._drop_partition_indices(item, table.num_rows)
            indices = np.intersect1d(indices, keep)
        if len(indices) < table.num_rows:
            table = table.take(indices)
        out = {}
        for name in wanted:
            if name in table.column_names:
                try:
                    out[name] = _column_to_numpy(table, name, self._read_schema,
                                                 self._device_fields)
                except DecodeFieldError as e:
                    raise _annotate_decode_error(e, piece) from e
                except Exception as e:  # noqa: BLE001 — decode_row contract below
                    field = self._read_schema.fields.get(name)
                    if field is None or field.codec is None:
                        raise  # plain-column conversion bug, not a decode failure
                    raise _annotate_decode_error(
                        DecodeFieldError("Unable to decode field %r: %s" % (name, e)),
                        piece) from e
        out.update(passthrough)
        return out


def _annotate_decode_error(err, piece):
    """Attach the failing row group's identity to a decode error — at pod scale 'which
    file, which group' is the difference between a fixable corpus bug and a mystery."""
    return DecodeFieldError(
        "%s (while decoding %s row group %d)" % (err, piece.path, piece.row_group))


def _merge_tables(head, tail):
    """Column-wise merge of two same-length row-group reads into one table."""
    import pyarrow as pa

    cols = {name: head.column(name) for name in head.column_names}
    cols.update({name: tail.column(name) for name in tail.column_names})
    return pa.table(cols)


def _column_to_numpy(table, name, schema, device_fields=()):
    """Arrow column → numpy array; decodes codec columns, stacks list columns.

    List columns take the vectorized path: flatten the Arrow child buffer straight to
    numpy and reshape — ``to_pylist`` would materialize every element as a Python object
    (~100x slower on image-sized rows, the data-plane hot loop). Codec columns named in
    ``device_fields`` run only the host half of the two-stage decode and come back as an
    object array of staging payloads the JAX loader finishes on device."""
    import pyarrow as pa

    col = table.column(name)
    field = schema.fields.get(name)
    if field is not None and field.codec is not None:
        from petastorm_tpu.codecs import ScalarCodec

        scalar = _scalar_codec_fast_path(col, field)
        if scalar is not None:
            return scalar
        values = None
        if not isinstance(field.codec, ScalarCodec):
            # blob codecs (ndarray/image): zero-copy memoryviews into Arrow buffers
            values = _binary_column_views(col)
        if values is None:
            values = col.to_pylist()
        if name in device_fields:
            from petastorm_tpu.utils import stack_as_column

            batch_stage = getattr(field.codec, "host_stage_decode_batch", None)
            if batch_stage is not None:
                # one native call stages the whole row group (stacked coefficient
                # buffers; per-row payloads are zero-copy views into them)
                staged = batch_stage(field, values)
            else:
                staged = [field.codec.host_stage_decode(field, v) if v is not None
                          else None for v in values]
            return stack_as_column(staged, force_object=True)
        np_dtype = np.dtype(field.numpy_dtype)
        shape_known = field.shape and all(d is not None for d in field.shape)
        if shape_known and np_dtype.kind in "biufc" \
                and not any(v is None for v in values):
            # static-shape tensor column: decode straight into one preallocated array
            # (skips the list-of-arrays + _stack double materialization)
            out = np.empty((len(values),) + tuple(field.shape), dtype=np_dtype)
            decode = field.codec.decode
            for i, v in enumerate(values):
                out[i] = decode(field, v)
            return out
        decoded = [field.codec.decode(field, v) if v is not None else None for v in values]
        return _stack(decoded, field)
    if field is not None and field.shape:
        arr = col.combine_chunks() if isinstance(col, pa.ChunkedArray) else col
        stacked = _list_column_to_numpy(arr, field)
        if stacked is not None:
            return stacked
        return _stack(arr.to_pylist(), field)
    return col.to_numpy(zero_copy_only=False)


def _scalar_codec_fast_path(col, field):
    """Vectorized ScalarCodec decode: plain numeric/bool scalar columns are just an
    Arrow→numpy view + dtype cast — no per-row ``codec.decode`` loop. Returns None when
    the fast path does not apply (nulls, strings/decimals/dates, non-scalar codecs)."""
    from petastorm_tpu.codecs import ScalarCodec

    if type(field.codec) is not ScalarCodec or field.shape:
        return None
    np_dtype = np.dtype(field.numpy_dtype)
    if np_dtype.kind not in "biuf":
        return None
    arr = col.combine_chunks() if hasattr(col, "combine_chunks") else col
    if arr.null_count:
        return None
    out = arr.to_numpy(zero_copy_only=False)
    if out.dtype.kind not in "biuf":
        return None
    return out.astype(np_dtype, copy=False)


def _binary_column_views(col):
    """Binary/string column → list of zero-copy memoryview slices into the Arrow data
    buffer (None entries for nulls). Returns None when the column is not binary-like —
    the caller falls back to ``to_pylist``. Avoids materializing one bytes object per
    row on the decode hot path (VERDICT r1 #4)."""
    import pyarrow as pa

    chunks = col.chunks if isinstance(col, pa.ChunkedArray) else [col]
    out = []
    for chunk in chunks:
        t = chunk.type
        if pa.types.is_binary(t) or pa.types.is_string(t):
            odt = np.int32
        elif pa.types.is_large_binary(t) or pa.types.is_large_string(t):
            odt = np.int64
        else:
            return None
        n = len(chunk)
        if n == 0:
            continue
        bufs = chunk.buffers()
        off = chunk.offset
        offsets = np.frombuffer(bufs[1], dtype=odt, count=off + n + 1)[off:]
        data = memoryview(bufs[2]) if bufs[2] is not None else memoryview(b"")
        if chunk.null_count:
            valid = np.asarray(chunk.is_valid())
            out.extend(
                data[offsets[i]:offsets[i + 1]] if valid[i] else None
                for i in range(n)
            )
        else:
            out.extend(data[offsets[i]:offsets[i + 1]] for i in range(n))
    return out


def _list_column_to_numpy(arr, field):
    """Vectorized (fixed-size or uniform) list-of-numeric column → (n, ...) ndarray.

    Returns None when the fast path does not apply (ragged rows, nulls, non-numeric)."""
    import pyarrow as pa

    shape_known = field.shape and all(d is not None for d in field.shape)
    if arr.null_count:
        return None
    if isinstance(arr.type, pa.FixedSizeListType):
        size = arr.type.list_size
        flat = arr.flatten().to_numpy(zero_copy_only=False)  # offset/slice-safe
        if flat.dtype.kind not in "biufc":  # nested/non-numeric: fall back to to_pylist
            return None
        out = flat.reshape(len(arr), size)
    elif pa.types.is_list(arr.type) or pa.types.is_large_list(arr.type):
        offsets = arr.offsets.to_numpy(zero_copy_only=False)
        lengths = np.diff(offsets)
        if len(lengths) == 0 or not (lengths == lengths[0]).all():
            return None  # ragged: caller falls back to object rows
        flat = arr.flatten().to_numpy(zero_copy_only=False)
        if flat.dtype.kind not in "biufc":
            return None
        out = flat.reshape(len(arr), int(lengths[0]))
    else:
        return None
    if shape_known and int(np.prod(field.shape)) == out.shape[1]:
        out = out.reshape((len(out),) + tuple(field.shape))
    np_dtype = np.dtype(field.numpy_dtype)
    if np_dtype.kind in "biufc" and out.dtype != np_dtype:
        out = out.astype(np_dtype)
    return out


def _stack(values, field):
    """Stack per-row values into one array; ragged/object data degrades to an object array."""
    np_dtype = np.dtype(field.numpy_dtype)
    target = None if np_dtype.kind in "OUSM" else np_dtype
    try:
        return np.asarray(values, dtype=target)
    except (ValueError, TypeError):
        arr = np.empty(len(values), dtype=object)
        arr[:] = values
        return arr


def _dnf_fields(filters):
    fields = set()
    for clause in filters:
        terms = [clause] if isinstance(clause[0], str) else clause
        for name, _op, _val in terms:
            fields.add(name)
    return fields


def _dnf_mask(table, filters):
    """Evaluate pyarrow-style DNF filters [(col, op, val), ...] or [[...], [...]] as a mask."""
    def term_mask(name, op, val):
        col = table.column(name).to_numpy(zero_copy_only=False)
        if op in ("=", "=="):
            return col == val
        if op == "!=":
            return col != val
        if op == "<":
            return col < val
        if op == "<=":
            return col <= val
        if op == ">":
            return col > val
        if op == ">=":
            return col >= val
        if op == "in":
            return np.isin(col, list(val))
        if op in ("not in", "not-in"):
            return ~np.isin(col, list(val))
        raise ValueError("Unsupported filter op %r" % op)

    total = None
    for clause in _dnf_clauses(filters):
        cmask = None
        for name, op, val in clause:
            t = term_mask(name, op, val)
            cmask = t if cmask is None else (cmask & t)
        total = cmask if total is None else (total | cmask)
    return np.asarray(total, dtype=bool)


def _plan_pieces(pieces, filters, predicate, shard_count=None):
    """Plan-time pruning pipeline: hive partition resolution + directory pruning +
    row-group statistics pruning, with predicate-implied clauses conjoined for the
    pruning. Returns ``(pieces, partition_info, filters)`` where ``filters`` is the
    normalized set the workers run as the row-level mask.

    The implied clauses are PLAN-TIME-ONLY: the returned ``filters`` are the user's
    (normalized), so workers don't re-evaluate — or embed in cache keys — value
    lists the predicate itself already enforces as the row mask.

    If the predicate-implied clauses alone prove the plan empty, a minimal piece set
    (one per shard) is retained: a predicate that matches nothing must yield an
    EMPTY read (reference semantics — predicates never fail construction, and the
    retained row groups mask to zero rows), while an over-filtering user
    ``filters`` still raises ``NoDataAvailableError``."""
    out, partition_info, norm_user = _resolve_partitions(pieces, filters)
    out = _prune_by_stats(out, norm_user)
    implied = None
    if predicate is not None:
        from petastorm_tpu.predicates import implied_dnf_filters

        implied = implied_dnf_filters(predicate)
    if implied and out:
        # Sequential pruning passes are equivalent to conjoining the clause sets
        # (satisfiability is checked per term), so the implied clauses prune the
        # already-user-pruned set directly — no DNF cross product needed.
        logger.debug("Predicate-implied pruning clauses: %s", implied)
        kept = out
        if partition_info:
            from petastorm_tpu.partitions import normalize_filters, prune_pieces

            implied = normalize_filters(implied, partition_info)
            kept = prune_pieces(kept, partition_info, implied)
        kept = _prune_by_stats(kept, implied)
        # Never hand a shard zero pieces: round-robin assignment over fewer pieces
        # than shards would fail construction on the starved shards, where the same
        # predicate without pruning yields an empty read there. Pad with unpruned
        # survivors (they mask to zero rows) up to one piece per shard — a bounded
        # waste (one re-read row group per starved shard per epoch) accepted over
        # teaching Reader an empty-plan mode.
        min_pieces = max(1, int(shard_count or 1))
        if len(kept) < min_pieces:
            have = {(p.path, p.row_group) for p in kept}
            extra = [p for p in out if (p.path, p.row_group) not in have]
            kept = kept + extra[:min_pieces - len(kept)]
        out = kept
    return ([p._replace(stats=None) if p.stats else p for p in out],
            partition_info, norm_user)


def _dnf_clauses(filters):
    """Normalize pyarrow-style DNF filters to a list of AND-clauses: accepts both the
    flat ``[(col, op, val), ...]`` form and the ``[[...], [...]]`` OR-of-ANDs form.
    Shared by the row-level mask (``_dnf_mask``) and plan-time statistics pruning
    (``_prune_by_stats``) so their clause semantics cannot drift."""
    return [filters] if isinstance(filters[0][0], str) else filters


def _stable_repr(value):
    """Deterministic repr for cache keys (sets/dicts get sorted)."""
    if isinstance(value, (set, frozenset)):
        return "{%s}" % ",".join(sorted(repr(v) for v in value))
    if isinstance(value, dict):
        return "{%s}" % ",".join(
            "%r:%s" % (k, _stable_repr(v)) for k, v in sorted(value.items())
        )
    if isinstance(value, (list, tuple)):
        return "[%s]" % ",".join(_stable_repr(v) for v in value)
    return repr(value)


_RUN_SALT = None

_ADDR_RE = re.compile(r" at 0x[0-9a-fA-F]+")


def _predicate_key(predicate):
    """Stable identity for a predicate: class + parameters. Callables are keyed by
    bytecode + consts + DEFAULTS + CLOSURE VALUES (ADVICE r1: two lambdas with the same
    bytecode but different captured thresholds must not collide in a persistent cache);
    repr would embed a memory address — unstable across runs and reusable across
    different lambdas."""
    import hashlib

    global _RUN_SALT
    parts = [type(predicate).__name__]
    for name, value in sorted(vars(predicate).items()):
        if callable(value):
            code = getattr(value, "__code__", None)
            payload = None
            if code is not None:
                payload = [code.co_code, repr(code.co_consts).encode("utf-8")]
                defaults = getattr(value, "__defaults__", None)
                if defaults:
                    payload.append(_stable_repr(defaults).encode("utf-8"))
                kwdefaults = getattr(value, "__kwdefaults__", None)
                if kwdefaults:
                    payload.append(_stable_repr(kwdefaults).encode("utf-8"))
                closure = getattr(value, "__closure__", None)
                if closure:
                    try:
                        cells = tuple(c.cell_contents for c in closure)
                        payload.append(_stable_repr(cells).encode("utf-8"))
                    except ValueError:  # unreadable cell: treat as unkeyable
                        payload = None
            if payload is not None and any(_ADDR_RE.search(p.decode("utf-8", "ignore"))
                                           for p in payload[1:]):
                # captured objects whose repr embeds a memory address ('<function f at
                # 0x..>') are unstable across runs AND can collide on address reuse —
                # the exact poisoning class this key exists to prevent; salt instead
                payload = None
            if payload is not None:
                digest = hashlib.sha256(b"\x00".join(payload)).hexdigest()
                parts.append("%s=fn:%s" % (name, digest))
            else:
                # unkeyable callable: salt the key per RUN so in-memory reuse works but
                # a persistent cache from another run can never serve mismatched rows
                # (id() alone can recur across runs — ADVICE r1)
                if _RUN_SALT is None:
                    import os as _os

                    _RUN_SALT = _os.urandom(8).hex()
                parts.append("%s=unkeyable:%s:%d" % (name, _RUN_SALT, id(value)))
        else:
            parts.append("%s=%s" % (name, _stable_repr(value)))
    return "|".join(parts)


def _cache_key(piece, schema, predicate, filters, partition, num_partitions, seed,
               device_fields=frozenset()):
    predicate_key = ""
    if predicate is not None:
        predicate_key = _predicate_key(predicate)
    parts = [
        piece.path,
        str(piece.row_group),
        ",".join(schema.fields.keys()),
        predicate_key,
        repr(filters) if filters else "",
        "%s/%s" % (partition, num_partitions),
        str(seed) if num_partitions > 1 else "",
    ]
    if device_fields:
        # device-staged payloads differ from host-decoded ones — never cross-serve.
        # Appended only when active so pre-existing persistent cache keys stay valid.
        parts.append("dev:%s" % ",".join(sorted(device_fields)))
    generation = getattr(piece, "generation", None)  # duck-typed test pieces
    if generation is not None:
        # generation-scoped caching (ISSUE 11): a rewritten source file — even
        # one colliding on size AND mtime — maps to a NEW key, so no tier can
        # serve the old generation's decoded payload to the new generation's
        # plan items. Appended only when dataset watching stamped a token, so
        # persistent cache keys from watch-less runs stay valid.
        parts.append("gen:%s" % generation)
    return "|".join(parts)


# --------------------------------------------------------------------------------------
# Reader
# --------------------------------------------------------------------------------------


class Reader:
    """Iterates decoded rows (per-row path) or columnar batches (batch path).

    Reference: ``Reader`` petastorm/reader.py ~L330. Context-manager protocol, ``reset()``,
    ``stop()``/``join()``, ``last_row_consumed``; checkpointable via ``state_dict()`` (our
    upgrade — the plan cursor, SURVEY.md §6).
    """

    def __init__(self, filesystem, path, schema, stored_schema, worker, pieces,
                 num_epochs=1, shuffle_row_groups=True, seed=None,
                 cur_shard=None, shard_count=None, shard_seed=None,
                 shuffle_row_drop_partitions=1,
                 reader_pool_type="thread", workers_count=4, results_queue_size=16,
                 is_batched_reader=False, ngram=None, results_timeout_s=300.0,
                 wire_serializer="pickle", worker_respawns=None, io_options=None,
                 recovery=None, provenance=None, watch=None, watch_paths=None,
                 transport=None, tenant=None):
        from petastorm_tpu.obs import tenant as _tenant_mod

        #: resolved TenantContext (ISSUE 18) or None: explicit arg wins, else
        #: the ambient context / PTPU_TENANT env; invalid explicit slugs raise
        self.tenant_context = _tenant_mod.resolve(tenant)
        self._fs = filesystem
        self._path = path
        self.schema = schema
        self._stored_schema = stored_schema
        self._worker = worker
        self.is_batched_reader = is_batched_reader
        self.ngram = ngram
        self._ngram_views = {}
        self._row_type = schema.make_namedtuple_type()

        self.cur_shard = cur_shard
        self.shard_count = shard_count
        shard_idx = shard_indices(len(pieces), cur_shard, shard_count, shard_seed) \
            if shard_count else np.arange(len(pieces))
        sharded = [pieces[int(i)] for i in shard_idx]
        if not sharded and pieces:
            logger.warning("Shard %s/%s received no row groups", cur_shard, shard_count)
        items = [
            (piece, partition)
            for piece in sharded
            for partition in range(max(1, shuffle_row_drop_partitions))
        ]
        if not items:
            raise NoDataAvailableError(
                "No row groups to read (empty dataset, over-filtering selector/predicate, or "
                "an empty shard)"
            )
        self._plan = EpochPlan(items, num_epochs=num_epochs, shuffle=shuffle_row_groups,
                               seed=seed if seed is not None else shard_seed,
                               with_epoch=True)
        self._num_items = len(items)
        self._io_options = IoOptions.normalize(io_options)
        self._recovery = RecoveryOptions.resolve(recovery,
                                                 worker_respawns=worker_respawns)
        #: every plan item skipped as poison under on_poison='quarantine'
        #: (ISSUE 7) — empty (falsy) on a healthy run
        self.quarantine_report = QuarantineReport()
        #: quarantined items whose footer was never readable (row loss is
        #: unquantifiable — ISSUE 8 satellite); surfaced in :meth:`io_stats`
        self._footer_unreadable = 0
        self._pool_args = (reader_pool_type, workers_count, results_queue_size,
                           results_timeout_s, wire_serializer,
                           self._recovery.worker_respawns, self._io_options,
                           self._recovery, transport)
        self._executor = None
        self._results_iter = None
        self._buffer = []
        self._buffer_pos = 0
        self._buffer_tag = None  # (epoch, ordinal) of the row-group feeding _buffer
        self._consumed = {}  # epoch -> set(ordinal): fully-delivered work items
        self._resume_epoch = 0  # every epoch below this is fully consumed
        self.last_row_consumed = False
        self.stopped = False
        #: compressed-page pass-through adoption (ISSUE 14): False (the
        #: default) materializes PassthroughColumn values into decoded
        #: arrays at delivery — loader-less consumers see ordinary batches;
        #: the DataLoader sets True and finishes the inflate itself (on
        #: device when a non-CPU backend is live)
        self.keep_passthrough = False
        #: lease of the CURRENT batch/row-buffer on a view-mode wire — held
        #: until the consumer asks for the next batch (or calls release_batch()
        #: / takes ownership via take_lease())
        self._held_lease = None
        #: every lease this reader ever delivered that is possibly still
        #: retained by a consumer — revoked wholesale when reset() rebuilds the
        #: executor, so stale views raise LeaseRevoked instead of reading a
        #: recycled slab (weak: released leases fall out on their own)
        self._issued_leases = weakref.WeakSet()
        #: optional obs.provenance.ProvenanceRecorder (ISSUE 10): per-item
        #: causal records — deliveries noted here feed batch attribution.
        #: Set BEFORE _start(): the executor begins claiming plan items the
        #: moment it starts, and a recorder attached later (the DataLoader's
        #: set_provenance) misses every item a small plan already drained.
        self._prov = provenance
        #: dataset-watch plane (ISSUE 11): a watcher thread that diffs the
        #: piece set every interval and feeds _apply_plan_delta; None when
        #: the dataset is declared frozen (the default)
        self._drop_partitions = max(1, shuffle_row_drop_partitions)
        self._watcher = None
        if watch is not None:
            from petastorm_tpu.dataset.watch import DatasetWatcher

            self._watcher = DatasetWatcher(filesystem, path, watch,
                                           on_delta=self._apply_plan_delta)
            # known_paths: every file that existed at plan time, including
            # plan-time-pruned ones — the first tick must not re-add what the
            # user's filters/selector excluded
            self._watcher.prime(pieces, known_paths=watch_paths)
        self._start()

    def _start(self):
        (pool_type, workers_count, queue_size, timeout_s, serializer,
         respawns, io_options, recovery, transport) = self._pool_args
        reopen = getattr(self._worker, "reopen", None)
        if reopen is not None:  # reset()/restore after join() closed the IO runtime
            reopen()
        self._executor = make_executor(
            pool_type, workers_count, queue_size, timeout_s, serializer,
            respawns, io_options=io_options, recovery=recovery,
            transport=transport)
        monitor = getattr(self, "_health_monitor", None)
        if monitor is not None:
            # reset()/restore rebuilds the executor — re-attach BEFORE start so
            # a process pool hands its children the monitor-era handshake
            fn = getattr(self._executor, "set_health", None)
            if fn is not None:
                fn(monitor)
        if self._prov is not None:
            # provenance survives reset()'s executor rebuild like health does
            # (join() disarmed an auto-owned recorder; re-arm is idempotent
            # and fails loud if a DIFFERENT recorder took the slot meanwhile)
            self._prov.arm()
            fn = getattr(self._executor, "set_provenance", None)
            if fn is not None:
                fn(self._prov)
        self._executor.start(_Tagged(self._worker, tenant=self.tenant_context),
                             self._plan)
        self._results_iter = self._executor.results()
        self.stopped = False
        watcher = getattr(self, "_watcher", None)
        if watcher is not None:
            # (re)armed LAST: a failed executor start must not leak a watch
            # thread, and reset()/restore restart watching with the stream
            watcher.start()

    def _mark_consumed(self, tag):
        if tag is None:
            return
        epoch, ordinal = tag
        self._consumed.setdefault(epoch, set()).add(ordinal)
        # advance the watermark: epochs below _resume_epoch are fully consumed
        # (bounded state). The per-epoch denominator comes from the PLAN, not
        # a fixed num_items — a mid-run extension (ISSUE 11) grows later
        # epochs without wedging the watermark on earlier ones.
        while len(self._consumed.get(self._resume_epoch, ())) \
                >= self._plan.items_in_epoch(self._resume_epoch):
            del self._consumed[self._resume_epoch]
            self._resume_epoch += 1

    # -- dataset-watch plane (ISSUE 11) --------------------------------------------------

    @property
    def dataset_watcher(self):
        """The live :class:`~petastorm_tpu.dataset.watch.DatasetWatcher`, or
        ``None`` when the reader was opened without ``watch=``."""
        return self._watcher

    def _apply_plan_delta(self, delta):
        """The watcher's delta seam (runs on the watch thread).

        Added files extend the CURRENT epoch (fresh paths cannot mix
        generations); a rewritten file's new generation is deferred to the
        NEXT epoch (the old generation may already have delivered rows this
        epoch); removed/rewritten old-generation pieces get their cache
        entries dropped — their still-pending plan items fail their
        generation check at read time and quarantine as
        ``piece_removed``/``piece_rewritten``, charged to the watermark like
        any other skip."""
        stale = [p for _path, pieces in delta.removed for p in pieces]
        stale += [p for _path, old, _new in delta.rewritten for p in old]
        if stale:
            invalidate = getattr(self._worker, "invalidate_pieces", None)
            if invalidate is not None:
                invalidate(stale)
        extended = False
        added = [p for p in delta.added if self._owns_piece(p)]
        if added:
            self._plan.extend(self._to_items(added), defer=False)
            extended = True
        replanned = [p for _path, _old, new in delta.rewritten for p in new
                     if self._owns_piece(p)]
        if replanned:
            self._plan.extend(self._to_items(replanned), defer=True)
            extended = True
        if extended:
            self._num_items = len(self._plan.items)
            from petastorm_tpu.dataset.watch import watch_metrics

            watch_metrics()["plan_extensions"].inc()

    def _items_identity_crc(self, count):
        """crc32 over the identity (path:row_group:partition) of the first
        ``count`` plan items in ordinal order — what binds a checkpoint's
        consumed-ordinal map to the item order it was taken over."""
        import zlib

        h = 0
        for piece, partition in self._plan.items[:count]:
            h = zlib.crc32(("%s:%s:%s" % (piece.path, piece.row_group,
                                          partition)).encode("utf-8"), h)
        return h & 0xFFFFFFFF

    def _owns_piece(self, piece):
        """Deterministic shard assignment for watch-discovered pieces: every
        host computes the same crc32 hash, so the shards' extensions stay
        disjoint and their union exact — the same zero-communication property
        the initial round-robin sharding has."""
        if not self.shard_count:
            return True
        import zlib

        key = "%s:%s" % (piece.path, piece.row_group)
        return zlib.crc32(key.encode("utf-8")) % self.shard_count \
            == self.cur_shard

    def _to_items(self, pieces):
        return [(piece, partition) for piece in pieces
                for partition in range(self._drop_partitions)]

    def _absorb_quarantine(self, marker):
        """Absorb a :class:`~petastorm_tpu.recovery.QuarantinedItem` marker
        (ISSUE 7): the poisoned plan item is recorded in the quarantine report,
        counted (``ptpu_quarantined_{items,rows}_total``), and — crucially —
        **charged against the consumed-ordinal watermark** exactly like a
        delivered item, so a checkpoint taken after the skip resumes without
        replaying it (and without losing anything else). The consumer never
        sees the marker."""
        epoch, ordinal, inner = marker.item
        piece = inner[0] if isinstance(inner, tuple) and inner else inner
        path = getattr(piece, "path", repr(inner))
        row_group = getattr(piece, "row_group", -1)
        num_rows = getattr(piece, "num_rows", None)
        if num_rows is None or num_rows < 0:
            # planning's KV fast path leaves num_rows=-1 by design (it never
            # opens footers) — resolve the real count from the footer now so
            # the quarantine ledger says how many rows were lost; only when
            # that READ fails is the footer genuinely unreadable (ISSUE 8
            # satellite: this used to collapse to -1 silently either way)
            num_rows = self._resolve_quarantined_rows(path, row_group)
        # dataset-mutation classification (ISSUE 11): a skip caused by the
        # file vanishing or changing generation mid-run gets its own kind and
        # degradation cause — an operator must tell "bad data" apart from
        # "the dataset moved under me" without reading exception chains
        kind = marker.kind
        cause = "quarantined"
        if isinstance(marker.error, PieceRewrittenError):
            kind = cause = "piece_rewritten"
            from petastorm_tpu.dataset.watch import watch_metrics

            # counted HERE (the consumer process), not at the worker's
            # detection site — a pool child's registry never reaches the
            # parent's export/panel
            watch_metrics()["generation_conflicts"].inc()
        elif isinstance(marker.error, PieceRemovedError):
            kind = cause = "piece_removed"
        entry = QuarantineEntry(epoch, ordinal, path, row_group, num_rows,
                                marker.error, marker.attempts, kind)
        self.quarantine_report.add(entry)
        count_quarantined(num_rows)
        if self.tenant_context is not None:
            from petastorm_tpu.obs import tenant as _tenant_mod

            _tenant_mod.charge("quarantined", max(0, num_rows or 0),
                               label=self.tenant_context.tenant)
        from petastorm_tpu.obs.log import degradation

        degradation(
            cause,
            "poison item quarantined after %d attempt(s): %s row group %s "
            "(epoch=%s ordinal=%s, %s) — skipped, charged to the checkpoint "
            "watermark; see Reader.quarantine_report", marker.attempts, path,
            row_group, epoch, ordinal, kind, once=False)
        if self._prov is not None:
            # exactly-once beside delivery: a quarantined item never enters
            # the delivery FIFO, so the ledgers stay disjoint
            self._prov.note_quarantined(epoch, ordinal, marker.attempts,
                                        marker.kind)
        self._mark_consumed((epoch, ordinal))

    def _resolve_quarantined_rows(self, path, row_group):
        """The quarantined row group's row count from its footer (via the
        shared cache — usually already parsed), or -1 with a
        ``footer_unreadable`` degradation when the footer cannot be read or
        does not contain the group (quarantine is rare; one bounded footer
        read per skipped item is worth an exact loss ledger)."""
        try:
            from petastorm_tpu.io.footercache import shared_footer_cache

            entry = shared_footer_cache().get(self._fs, path)
            return entry.row_group_rows[row_group]
        except Exception as e:  # noqa: BLE001 — unreadable/mismatched footer
            self._footer_unreadable += 1
            from petastorm_tpu.obs.log import degradation

            degradation(
                "footer_unreadable",
                "quarantined %s row group %s has no readable footer (%s): the "
                "skipped row count is UNKNOWN (recorded as -1 in the "
                "quarantine report; see Reader.io_stats()['footer_unreadable'])",
                path, row_group, e, once=False)
            return -1

    # -- iteration ----------------------------------------------------------------------

    def __iter__(self):
        return self

    def __next__(self):
        if self.is_batched_reader:
            return self._next_batch()
        return self._next_row()

    def _next_row(self):
        while True:
            if self._buffer_pos < len(self._buffer):
                row = self._buffer[self._buffer_pos]
                self._buffer_pos += 1
                if self._buffer_pos >= len(self._buffer):
                    # last row of this row group delivered -> safe to mark consumed
                    self._mark_consumed(self._buffer_tag)
                    self._buffer_tag = None
                return self._wrap_row(row)
            # moving past the drained buffer: its slab (shm view wire) returns to
            # the ring — rows handed out so far must already be done with
            self.release_batch()
            nxt = next(self._results_iter, None)
            if nxt is None:
                if not getattr(self._executor, "truncated", False):
                    self.last_row_consumed = True
                raise StopIteration
            if isinstance(nxt, QuarantinedItem):
                self._absorb_quarantine(nxt)
                continue
            epoch, ordinal, payload = nxt
            self._held_lease = self._register_lease(
                getattr(payload, "lease", None))
            if not payload:
                self._mark_consumed((epoch, ordinal))  # fully-filtered group
                continue
            if self._prov is not None:
                self._prov.note_delivery(epoch, ordinal, len(payload))
            if self.tenant_context is not None:
                # charged at DELIVERY (the consumer-visible boundary), so
                # per-tenant rows == what the tenant actually received
                from petastorm_tpu.obs import tenant as _tenant_mod

                _tenant_mod.charge("rows", len(payload),
                                   label=self.tenant_context.tenant)
            self._buffer = payload
            self._buffer_pos = 0
            self._buffer_tag = (epoch, ordinal)

    def _wrap_row(self, row):
        if self.ngram is not None:
            out = {}
            for offset, values in row.items():
                view = self._ngram_views.get(offset)
                if view is None:
                    view = self._ngram_views[offset] = self.schema.create_schema_view(
                        self.ngram.get_field_names_at_timestep(offset)
                    )
                out[offset] = view.make_namedtuple(**values)
            return out
        return self._row_type(**{name: row.get(name) for name in self.schema.fields})

    def _next_batch(self):
        # previous batch's slab (shm view wire) returns to the ring: a batch's
        # views stay valid until the consumer asks for the NEXT batch
        self.release_batch()
        while True:
            nxt = next(self._results_iter, None)
            if nxt is None:
                if not getattr(self._executor, "truncated", False):
                    self.last_row_consumed = True
                raise StopIteration
            if isinstance(nxt, QuarantinedItem):
                self._absorb_quarantine(nxt)
                continue
            epoch, ordinal, columns = nxt
            if isinstance(columns, dict):
                self._held_lease = self._register_lease(
                    columns.pop(_SHM_LEASE_KEY, None))
            self._mark_consumed((epoch, ordinal))  # batch delivery is atomic
            if not columns or len(next(iter(columns.values()))) == 0:
                self.release_batch()
                continue  # fully-filtered (or windowless) row group: skip
            if self._prov is not None:
                self._prov.note_delivery(
                    epoch, ordinal, len(next(iter(columns.values()))))
            if self.tenant_context is not None:
                from petastorm_tpu.obs import tenant as _tenant_mod

                _tenant_mod.charge("rows", len(next(iter(columns.values()))),
                                   label=self.tenant_context.tenant)
            if not self.keep_passthrough:
                # no loader adopted the pass-through: this consumer expects
                # decoded arrays — the numpy reference twin IS the designed
                # host decode for loader-less readers (no degradation)
                from petastorm_tpu.io.pagedec import materialize_columns

                columns = materialize_columns(columns)
            if self.ngram is not None:
                # flat 'offset/field' window columns cannot be namedtuple
                # attributes — batched NGram delivers plain dicts
                return dict(columns)
            return self._row_type(**{name: columns.get(name)
                                     for name in self.schema.fields})

    # -- lease-backed wire integration ---------------------------------------------------

    def _register_lease(self, lease):
        """Track a delivered lease for revocation: ``reset()`` rebuilds the
        executor (and with it the slab ring backing any outstanding views), so
        every lease issued by the PREVIOUS executor generation must be revoked
        there — a consumer holding one across the rebuild gets a clear
        :class:`~petastorm_tpu.errors.LeaseRevoked`, never recycled memory."""
        if lease is not None and hasattr(lease, "revoke"):
            self._issued_leases.add(lease)
        return lease

    def take_lease(self):
        """Transfer ownership of the CURRENT batch's lease to the caller (the
        zero-copy DataLoader path): the reader will no longer release it at the
        next fetch — the caller must ``release()`` it when the batch's buffers
        are done (or rely on refcount GC, counted as a leak). Returns ``None``
        when the current delivery is not lease-backed (thread/dummy pools,
        socket wires, per-item slab fallbacks)."""
        lease, self._held_lease = self._held_lease, None
        return lease

    def release_batch(self):
        """Return the current batch's shared-memory slab to the pool's ring (shm
        VIEW wire only; a no-op on every other pool/wire configuration).

        On ``wire_serializer='shm-view'``/``'shm-arrow-view'`` the arrays of the
        most recent batch are zero-copy read-only views into a pool-owned slab.
        They stay valid until the next ``__next__()`` call releases them
        implicitly; consumers that finish a batch early (e.g. right after a
        ``jax.device_put``) call this to return the slab sooner. After the call
        the previous batch's arrays must not be touched."""
        lease, self._held_lease = self._held_lease, None
        if lease is not None:
            lease.release()

    def wire_stats(self):
        """Process-pool wire gauges (shm slab occupancy, bytes through shared
        memory, socket fallbacks, acquire wait) — {} for thread/dummy pools and
        socket wires. Exported through ``PipelineStats`` by the DataLoader."""
        fn = getattr(self._executor, "wire_stats", None)
        return fn() if fn is not None else {}

    def io_stats(self):
        """Async-read-path gauges (readahead hit/miss/pending/bytes, memcache,
        dispatch steals) — live for thread/dummy pools, where the worker shares
        this process; a process pool reports only the parent-side dispatch
        stats (children keep their IO counters in their own registries).
        Exported as ``ptpu_io_*`` families by the DataLoader's collector."""
        out = {}
        fn = getattr(self._worker, "io_stats", None)
        if fn is not None:
            out.update(fn() or {})
        fn = getattr(self._executor, "dispatch_stats", None)
        if fn is not None:
            out.update(fn() or {})
        if self._footer_unreadable:
            out["footer_unreadable"] = self._footer_unreadable
        if self._watcher is not None:
            out.update(self._watcher.stats())
        return out

    def register_metrics(self, registry):
        """Export this reader's wire AND io gauges onto a
        :class:`petastorm_tpu.obs.MetricsRegistry` as live ``ptpu_wire_*`` /
        ``ptpu_io_*`` families (pull-mode — the executor hot path is
        untouched). For readers consumed WITHOUT a ``DataLoader`` (which
        wires this itself via ``metrics=``) — paired with a
        :class:`petastorm_tpu.obs.serve.MetricsServer` over the registry this
        is the scrape seam for loader-less pipelines. Returns the collector
        handles for ``registry.unregister_collector``."""
        return [registry.register_collector("wire", self.wire_stats),
                registry.register_collector("io", self.io_stats)]

    def set_trace(self, tracer):
        """Attach a :class:`petastorm_tpu.trace.TraceRecorder` to the pool wire
        (records ``shm.acquire_wait`` spans) and the worker's readahead pool
        (``io.readahead``/``io.wait`` spans); the DataLoader wires its own."""
        fn = getattr(self._executor, "set_trace", None)
        if fn is not None:
            fn(tracer)
        fn = getattr(self._worker, "set_trace", None)
        if fn is not None:
            fn(tracer)

    def set_provenance(self, recorder):
        """Attach a :class:`petastorm_tpu.obs.provenance.ProvenanceRecorder`
        (ISSUE 10): per-item deliveries/quarantines are noted here and the
        executor records wire spans + merges pool-child item spans onto it.
        Survives ``reset()``'s executor rebuild. The DataLoader wires this
        from ``provenance=``; call it directly for loader-less readers (pair
        with ``recorder.arm()`` so worker-thread spans are captured)."""
        self._prov = recorder
        fn = getattr(self._executor, "set_provenance", None)
        if fn is not None:
            fn(recorder)

    def set_health(self, monitor):
        """Attach a :class:`petastorm_tpu.obs.health.HealthMonitor` (ISSUE 5):
        executor workers / pool drivers heartbeat per work item (pool children
        additionally gain the SIGUSR1 stack-dump hook), and the worker's
        readahead IO threads heartbeat per background read. The DataLoader
        wires this from ``health=``; call it directly for loader-less
        readers."""
        self._health_monitor = monitor  # survives reset()'s executor rebuild
        fn = getattr(self._executor, "set_health", None)
        if fn is not None:
            fn(monitor)
        fn = getattr(self._worker, "set_health", None)
        if fn is not None:
            fn(monitor)

    # -- live knobs (ISSUE 13) -----------------------------------------------------------

    def resize_workers(self, workers_count):
        """Grow/shrink this reader's worker fleet LIVE (thread and process
        pools; ``None`` on the sync pool, which has no fleet). Grow spawns;
        shrink drains between items — never kills mid-item — and returns the
        retiring workers' claims to the dispatcher, so the delivered row set
        and the checkpoint watermark are identical to an un-resized run.
        ``reset()`` rebuilds the executor at the CONFIGURED count (a retune
        is runtime state, not config)."""
        fn = getattr(self._executor, "resize", None)
        if fn is None:
            return None
        return fn(workers_count)

    def live_workers(self):
        """Workers currently running (including ones draining toward a
        shrink target), or ``None`` for pools without a fleet."""
        return getattr(self._executor, "alive_workers", None)

    def apply_readahead_depth(self, depth):
        """Retune the readahead window live: the worker's pool depth (and IO
        threads), AND the dispatcher's per-worker claim lookahead — the claim
        is the prefetch hint window, so depth without lookahead would starve
        the deeper pool of hints."""
        fn = getattr(self._worker, "apply_readahead_depth", None)
        applied = fn(depth) if fn is not None else max(1, int(depth))
        set_lookahead = getattr(self._executor, "set_lookahead", None)
        if set_lookahead is not None and self._io_options.readahead:
            set_lookahead(applied)
        self._broadcast_child_knobs({"readahead_depth": applied})
        return applied

    def apply_readahead_bytes(self, nbytes):
        fn = getattr(self._worker, "apply_readahead_bytes", None)
        applied = fn(nbytes) if fn is not None else max(0, int(nbytes))
        self._broadcast_child_knobs({"readahead_bytes": applied})
        return applied

    def apply_remote_max_inflight(self, max_inflight):
        fn = getattr(self._worker, "apply_remote_max_inflight", None)
        applied = fn(max_inflight) if fn is not None \
            else max(1, int(max_inflight))
        self._broadcast_child_knobs({"remote_max_inflight": applied})
        return applied

    def apply_hedge_quantile(self, quantile):
        fn = getattr(self._worker, "apply_hedge_quantile", None)
        applied = fn(quantile) if fn is not None \
            else min(0.999, max(0.5, float(quantile)))
        self._broadcast_child_knobs({"hedge_quantile": applied})
        return applied

    def apply_pagedec(self, mode):
        """Retune the compressed-page pass-through mode live (ISSUE 14):
        the controller's revert-to-host-inflate lever. Lands on the worker's
        next read; pool children receive it through the control frame."""
        fn = getattr(self._worker, "apply_pagedec", None)
        applied = fn(mode) if fn is not None else str(mode)
        self._broadcast_child_knobs({"pagedec": applied})
        return applied

    def _broadcast_child_knobs(self, knobs):
        """Live cross-process actuation (ISSUE 14 satellite, PR 13's declared
        leftover): a process pool's children own their IO runtimes — the
        parent-side setters above cannot reach them, so the applied values
        also ride a small control frame on the existing pool wire (beside
        the slab-grant protocol) to every ALREADY-RUNNING child. Thread/dummy
        pools share the worker object and need no frame."""
        fn = getattr(self._executor, "broadcast_io_knobs", None)
        if fn is not None:
            fn(dict(knobs))

    @property
    def wire_views(self):
        """True when batches are zero-copy READ-ONLY slab views (shm view wire):
        buffering consumers must detach (copy) columns before the next fetch."""
        return bool(getattr(self._executor, "wire_views", False))

    # -- lifecycle ----------------------------------------------------------------------

    def reset(self):
        """Restart epochs on an existing reader (reference ``Reader.reset`` ~L700).

        Revokes every outstanding lease this reader issued: the executor
        rebuild below recycles the slab ring those leases' views point into, so
        a batch retained across the reset must fail loud
        (:class:`~petastorm_tpu.errors.LeaseRevoked`) rather than read reused
        memory."""
        self.stop()
        self.join()
        for lease in list(self._issued_leases):
            lease.revoke()
        self._plan.reset()
        self._buffer = []
        self._buffer_pos = 0
        self._buffer_tag = None
        self._consumed = {}
        self._resume_epoch = 0
        self.last_row_consumed = False
        self._start()

    def stop(self):
        self.release_batch()  # a held slab must not survive the stream it came from
        if self._executor is not None:
            self._executor.stop()
        self.stopped = True

    def join(self):
        # the watch thread goes first: a delta applied while the executor is
        # tearing down would extend a plan nobody will drain (reset()/restore
        # re-arm it from _start)
        if self._watcher is not None:
            self._watcher.stop()
        # close the worker's IO runtime FIRST: a stop() mid-stream can leave
        # executor threads blocked inside ReadaheadPool.get, and shutdown()
        # releases those waiters (into the degradation-logged sync fallback)
        # so the executor join below doesn't sit out its full timeout. A
        # reset() lazily rebuilds the pool on the next prefetch.
        close = getattr(self._worker, "close", None)
        if close is not None:
            close()
        if self._executor is not None:
            self._executor.join()
        if self._prov is not None and getattr(self._prov, "_auto_disarm",
                                              False):
            # a recorder the factory built for THIS reader releases the
            # process-global slot here (records stay readable; reset()'s
            # _start re-arms) — without this, a stopped reader would pin
            # ACTIVE forever and the next provenance-enabled reader would
            # refuse to arm. Caller-supplied recorders stay armed.
            self._prov.disarm()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.stop()
        self.join()

    # -- checkpoint ---------------------------------------------------------------------

    def state_dict(self):
        """Exact-resume checkpoint: the consumed-work map, not the dispatch cursor.

        Work items prefetched by the pool but not yet delivered are NOT counted, so resume
        replays them — at-least-once delivery at row-group granularity (a partially-consumed
        row group is replayed in full).
        """
        plan_state = self._plan.state_dict()
        state = {
            "plan": {k: plan_state[k] for k in ("seed", "shuffle", "num_epochs", "num_items")},
            "resume_epoch": self._resume_epoch,
            "consumed": {int(e): sorted(v) for e, v in self._consumed.items()},
            # ordinal-identity binding (ISSUE 11): consumed ordinals are only
            # meaningful against THIS item order. A restore into a reader
            # whose first num_items items differ (a file appended between save
            # and restore that sorts BETWEEN existing names shifts every later
            # ordinal) must fail loudly, not silently replay/lose rows.
            "items_crc": self._items_identity_crc(self._num_items),
        }
        if self.shard_count:
            # shard identity travels with the cursor so a pod restore can route each
            # process its own state (petastorm_tpu.checkpoint global payloads) and a
            # mis-wired restore fails loudly instead of replaying the wrong shard
            state["cur_shard"] = self.cur_shard
            state["shard_count"] = self.shard_count
        return state

    def load_state_dict(self, state):
        if "plan" not in state or "consumed" not in state:
            raise ValueError(
                "not a Reader state (keys: %s) — a WeightedSamplingReader/"
                "InMemDataLoader checkpoint must be restored into the matching "
                "object" % sorted(state))
        self.stop()
        self.join()
        if state["plan"]["num_items"] > self._num_items:
            # fewer checkpointed items than planned is legal under mutable
            # datasets (ISSUE 11: files appended after the save are simply
            # unconsumed); MORE means consumed ordinals would dangle
            raise ValueError(
                "Checkpoint was taken over %d work items; reader has %d"
                % (state["plan"]["num_items"], self._num_items)
            )
        ck_shard = state.get("cur_shard")
        if ck_shard is not None and self.shard_count and ck_shard != self.cur_shard:
            raise ValueError(
                "Checkpoint belongs to shard %s/%s but this reader is shard %s/%s — "
                "resuming would replay the wrong rows"
                % (ck_shard, state.get("shard_count"), self.cur_shard, self.shard_count))
        ck_crc = state.get("items_crc")
        if ck_crc is not None and \
                ck_crc != self._items_identity_crc(state["plan"]["num_items"]):
            raise ValueError(
                "Checkpoint's consumed ordinals do not match this reader's "
                "item order (the first %d work items differ — a file added "
                "between save and restore sorts BETWEEN existing names?): "
                "resuming would replay or lose rows. Mutable datasets must "
                "append files that sort after existing ones (e.g. "
                "monotonically-named parts) for cross-restart resume."
                % state["plan"]["num_items"])
        self._resume_epoch = int(state["resume_epoch"])
        self._consumed = {int(e): set(v) for e, v in state["consumed"].items()}
        self._plan.load_state_dict(
            {**state["plan"], "epoch": self._resume_epoch, "pos": 0}
        )
        self._plan.set_skip(self._consumed)
        self._buffer = []
        self._buffer_pos = 0
        self._buffer_tag = None
        self.last_row_consumed = False
        self._start()


# --------------------------------------------------------------------------------------
# Factories
# --------------------------------------------------------------------------------------


def _host_arena_early(io_opts):
    """Create (or join) the host-wide cache arena BEFORE dataset discovery:
    the factory's own footer reads (schema inference, row-group planning) go
    through the shared :class:`FooterCache`, and publishing those parses
    host-wide only happens when :func:`petastorm_tpu.io.arena.process_arena`
    already exists — an arena born later (in ``_build_read_funnel``) would
    miss the metadata plane, and every attaching process would re-read the
    footers it came here to share."""
    if getattr(io_opts, "arena_bytes", 0):
        from petastorm_tpu.io import arena as arena_mod

        arena_mod.host_arena(io_opts.arena_bytes)


def _build_read_funnel(cache, io_opts, num_epochs=None, tenant=None):
    """The tiered read funnel (ISSUE 8): ``MemCache → LocalDiskCache →
    remote`` as ONE :class:`petastorm_tpu.io.tiers.TieredCache` with per-tier
    hit/byte accounting and the ``disk_admit`` admission policy — replacing
    the old ad-hoc ``MemCache(inner=...)`` stacking. The mem tier exists when
    ``io_options.memcache_bytes`` (or PTPU_MEMCACHE_BYTES) asks for one;
    ``num_epochs == 1`` is the scan hint the ``scan-resistant`` policy
    consumes.

    ``io_options.arena_bytes`` (ISSUE 17) additionally creates — or joins —
    this process's host-wide shared cache arena and threads its picklable
    spec into the mem tier, so every pool child (and any co-resident reader)
    maps ONE warm set of decoded columns instead of refilling its own. The
    arena alone implies a mem tier (local-store budget defaults to the arena
    budget); creation failure degrades warn-once inside ``host_arena``."""
    from petastorm_tpu.io.tiers import TieredCache

    arena_obj = None
    if getattr(io_opts, "arena_bytes", 0):
        from petastorm_tpu.io import arena as arena_mod

        arena_obj = arena_mod.host_arena(io_opts.arena_bytes)
    mem = None
    mem_budget = io_opts.memcache_bytes or (
        io_opts.arena_bytes if arena_obj is not None else 0)
    if mem_budget:
        from petastorm_tpu.io.memcache import MemCache

        mem = MemCache(mem_budget,
                       writable_hits=getattr(io_opts, "memcache_writable_hits",
                                             False),
                       arena=arena_obj)
    return TieredCache(mem=mem, disk=cache,
                       disk_admit=io_opts.remote.disk_admit,
                       single_epoch=num_epochs == 1, tenant=tenant)


def _maybe_compile_pipeline(spec, schema, fs, pieces, cache):
    """Plan a declarative :class:`~petastorm_tpu.ops.tabular.FeaturePipeline`
    against the read schema (ISSUE 9): resolve statistics-dependent op
    parameters — parquet row-group statistics when the footers cover them
    (no data pre-pass), one cached streaming pass otherwise — then compile
    to the fused host kernels (or the jittable device function for
    ``device=True``). Opaque ``TransformSpec``\\ s pass through untouched;
    an already-compiled pipeline (reused across readers) is kept as-is."""
    if spec is None or not getattr(spec, "declarative", False) \
            or getattr(spec, "compiled", False):
        return spec
    reqs = spec.required_statistics(schema)
    stats, sources = {}, {}
    if reqs:
        from petastorm_tpu.io.statscache import resolve_statistics

        stats, sources = resolve_statistics(reqs, fs, pieces, cache=cache)
    spec.compile(schema, statistics=stats)
    spec.stats_info = dict(sources)
    return spec


def _resolve_ngram_schema(schema_fields, stored_schema, predicate):
    """Shared NGram policy for both reader factories: which options NGram forbids
    and how its read-schema view is built. Returns ``(ngram-or-None, read_schema)``."""
    if isinstance(schema_fields, NGram):
        if predicate is not None:
            raise ValueError("NGram readers do not support predicates")
        schema_fields.resolve_regex_field_names(stored_schema)
        return schema_fields, schema_fields.make_schema_view(stored_schema)
    if schema_fields:
        return None, stored_schema.create_schema_view(schema_fields)
    return None, stored_schema


def _resolve_device_fields(schema, decode_on_device, ngram=None, transform_spec=None):
    """Fields whose codec decode should stop at the host staging half (stage 1)."""
    if not decode_on_device:
        return frozenset()
    if ngram is not None:
        raise ValueError("decode_on_device is not supported with NGram readers")
    if transform_spec is not None and not transform_spec.device \
            and transform_spec.func is not None:
        raise ValueError(
            "decode_on_device is not compatible with a host transform_spec: the "
            "transform would receive coefficient staging payloads, not decoded images. "
            "Use a device transform (TransformSpec(..., device=True)) or the "
            "DataLoader's device_transform instead."
        )
    fields = frozenset(
        name for name, f in schema.fields.items()
        if f.codec is not None and getattr(f.codec, "device_decodable", False)
    )
    if not fields:
        logger.warning(
            "decode_on_device=True but the read schema has no device-decodable codec "
            "fields (only CompressedImageCodec('jpeg') columns qualify); reading "
            "proceeds fully host-decoded"
        )
    return fields


def make_reader(dataset_url, schema_fields=None, reader_pool_type="thread", workers_count=4,
                results_queue_size=16, shuffle_row_groups=True, shuffle_row_drop_partitions=1,
                predicate=None, rowgroup_selector=None, num_epochs=1,
                cur_shard=None, shard_count=None, shard_seed=None, seed=None,
                cache_type="null", cache_location=None, cache_size_limit=None,
                cache_row_size_estimate=None, cache_extra_settings=None,
                transform_spec=None, filters=None, storage_options=None, filesystem=None,
                results_timeout_s=300.0, decode_on_device=False, wire_serializer=None,
                io_retries=None, io_retry_backoff_s=None, worker_respawns=None,
                io_options=None, recovery=None, provenance=None, watch=None,
                transport=None, tenant=None):
    """Open a petastorm(-tpu) dataset for per-row decoded reading (reference ~L60).

    ``schema_fields`` may be a list of names/regexes/UnischemaFields or an :class:`NGram`.

    ``decode_on_device=True`` routes device-decodable codec columns (JPEG) through the
    two-stage decode: workers run only the native entropy decode, and rows carry DCT
    coefficient staging payloads that :class:`petastorm_tpu.loader.DataLoader` finishes
    on device in one batched Pallas dispatch per batch. Consume such readers through the
    DataLoader (or call ``ops.decode_jpeg_batch`` yourself).

    ``io_retries`` / ``io_retry_backoff_s``: transient row-group read failures
    (connection resets, timeouts against object stores) are retried that many extra
    times (default 2) with jittered exponential backoff before propagating;
    ``io_retries=0`` restores the reference's fail-fast behavior (it has no retry —
    SURVEY.md §6).

    ``worker_respawns``: the process pool's elastic-recovery budget — a child that
    dies mid-item is replaced and its row group re-dispatched up to this many times
    (default 2; 0 = fail fast; the reference has no recovery).

    ``recovery``: a :class:`petastorm_tpu.recovery.RecoveryOptions` (or a dict of
    its fields) unifying the retry/backoff/deadline/respawn policy above — plus
    poison-item quarantine: with ``on_poison="quarantine"`` a plan item that
    repeatedly fails or kills workers is SKIPPED after ``poison_attempts``
    failures instead of crashing the job, surfaced in
    ``Reader.quarantine_report`` and charged to the checkpoint watermark so
    resume replays nothing and loses nothing. Explicitly-passed legacy kwargs
    (``io_retries=``/``io_retry_backoff_s=``/``worker_respawns=``) win over the
    struct. See docs/robustness.md.

    ``io_options``: the async read path's knobs (:class:`petastorm_tpu.io.IoOptions`
    or a dict of its fields) — row-group readahead (default on), adjacent-read
    coalescing, the in-memory decoded-row-group LRU (``memcache_bytes``), and
    work-stealing piece dispatch. See docs/performance.md "Read path".

    ``watch``: the mutable-dataset plane (ISSUE 11) —
    :class:`petastorm_tpu.dataset.WatchOptions`, a dict of its fields, or
    ``True`` for defaults. Stamps a per-file generation token
    (size+mtime+footer-crc) into every plan item and cache key, validates it
    on every read (a rewritten file quarantines as ``piece_rewritten``, a
    deleted one as ``piece_removed`` — under ``recovery.on_poison=
    "quarantine"``), and runs a watcher thread that discovers appended files
    mid-run and extends the epoch plan with checkpoint-watermark exactness.
    See docs/robustness.md "Mutable datasets".

    ``transport``: the process pool's wire (ISSUE 15) — ``'pipe'`` (default;
    today's unix-socket connection, byte-identical) or ``'tcp'`` (framed
    crc32-trailered loopback/LAN sockets with heartbeat half-open detection
    and jittered-backoff reconnect; a link death re-dispatches un-acked items
    through the quarantine path — exactly-once-or-quarantined survives the
    network). Also via ``PTPU_TRANSPORT``. See docs/robustness.md
    "The network fault model".

    ``tenant``: per-tenant accounting (ISSUE 18) — a bounded slug (or
    :class:`petastorm_tpu.obs.TenantContext`) that tags every shared-resource
    metric this reader's batches touch with a ``tenant=`` label; defaults to
    the ambient context / ``PTPU_TENANT`` env, absent ⇒ untagged (zero-cost).
    See docs/observability.md "Tenant accounting".
    """
    from petastorm_tpu.obs import tenant as _tenant_mod

    tenant_ctx = _tenant_mod.resolve(tenant)
    io_opts = IoOptions.normalize(io_options)
    _host_arena_early(io_opts)
    fs, path = get_filesystem_and_path_or_paths(dataset_url, storage_options, filesystem)
    stored_schema = get_schema(fs, path)

    pieces = load_row_groups(fs, path)
    watch_paths = {p.path for p in pieces}  # pre-pruning file set (watch plane)
    pieces = _apply_rowgroup_selector(fs, path, pieces, rowgroup_selector)
    stats_pieces = pieces  # pre-plan view: row-group stats still attached
    pieces, partition_info, filters = _plan_pieces(pieces, filters, predicate,
                                                   shard_count)
    watch = _resolve_watch(watch)
    if watch is not None:
        from petastorm_tpu.dataset.watch import stamp_generation_tokens

        pieces = stamp_generation_tokens(fs, pieces,
                                         footer_crc=watch.footer_crc)
    if partition_info:
        stored_schema = _schema_with_partitions(stored_schema, partition_info)

    ngram, read_schema = _resolve_ngram_schema(schema_fields, stored_schema,
                                               predicate)

    rec = RecoveryOptions.resolve(recovery, io_retries=io_retries,
                                  io_retry_backoff_s=io_retry_backoff_s,
                                  worker_respawns=worker_respawns)
    cache = make_cache(cache_type, cache_location, cache_size_limit,
                       cache_row_size_estimate, cache_extra_settings)
    cache = _build_read_funnel(
        cache, io_opts, num_epochs,
        tenant=tenant_ctx.tenant if tenant_ctx is not None else None)
    transform_spec = _maybe_compile_pipeline(transform_spec, read_schema, fs,
                                             stats_pieces, cache)
    final_schema = read_schema
    if transform_spec is not None and not transform_spec.device:
        final_schema = transform_schema(read_schema, transform_spec)
    device_fields = _resolve_device_fields(read_schema, decode_on_device, ngram,
                                           transform_spec)
    worker = PyDictWorker(
        fs, read_schema, stored_schema, predicate, transform_spec, cache,
        shuffle_row_drop_partitions, filters, seed if seed is not None else shard_seed,
        device_fields=device_fields, partition_info=partition_info,
        recovery=rec, io_options=io_opts,
        ngram=ngram, ngram_schema=final_schema if ngram is not None else None,
    )
    r = Reader(
        fs, path, final_schema, stored_schema, worker, pieces,
        num_epochs=num_epochs, shuffle_row_groups=shuffle_row_groups, seed=seed,
        cur_shard=cur_shard, shard_count=shard_count, shard_seed=shard_seed,
        shuffle_row_drop_partitions=shuffle_row_drop_partitions,
        reader_pool_type=reader_pool_type, workers_count=workers_count,
        results_queue_size=results_queue_size, is_batched_reader=False, ngram=ngram,
        results_timeout_s=results_timeout_s,
        wire_serializer=wire_serializer or "pickle",
        io_options=io_opts, recovery=rec,
        provenance=_prov.resolve(provenance), watch=watch,
        watch_paths=watch_paths, transport=transport, tenant=tenant_ctx,
    )
    r.transform_spec = transform_spec
    r.device_decode_fields = device_fields
    return r


def _resolve_watch(watch):
    """Factory-side normalization of the ``watch=`` kwarg (ISSUE 11)."""
    from petastorm_tpu.dataset.watch import WatchOptions

    return WatchOptions.normalize(watch)


def make_batch_reader(dataset_url_or_urls, schema_fields=None, reader_pool_type="thread",
                      workers_count=4, results_queue_size=16, shuffle_row_groups=True,
                      shuffle_row_drop_partitions=1, predicate=None, num_epochs=1,
                      cur_shard=None, shard_count=None, shard_seed=None, seed=None,
                      cache_type="null", cache_location=None, cache_size_limit=None,
                      cache_row_size_estimate=None, cache_extra_settings=None,
                      transform_spec=None, filters=None, storage_options=None,
                      filesystem=None, results_timeout_s=300.0, decode_on_device=False,
                      wire_serializer=None, io_retries=None, io_retry_backoff_s=None,
                      worker_respawns=None, io_options=None, recovery=None,
                      provenance=None, watch=None, transport=None, tenant=None):
    """Open ANY Parquet store for vectorized columnar batches (reference ~L200).

    ``decode_on_device``: see :func:`make_reader` — device-decodable codec columns come
    back as staging payloads for the DataLoader's batched on-device decode.

    ``io_retries`` / ``io_retry_backoff_s``: see :func:`make_reader` (transient
    read-failure retry with backoff; 0 = reference fail-fast behavior).

    ``recovery``: see :func:`make_reader` — the unified
    :class:`petastorm_tpu.recovery.RecoveryOptions` policy (retry/backoff/
    deadline, respawn budget, poison-item quarantine).

    ``io_options``: see :func:`make_reader` — readahead/coalesce/memcache/work
    stealing knobs for the async read path (docs/performance.md "Read path").

    ``watch``: see :func:`make_reader` — the mutable-dataset plane (ISSUE 11):
    generation-tokened plan items and cache keys, per-read validation, and a
    watcher thread that extends the plan with appended files mid-run (single
    dataset URL only).

    ``wire_serializer``: process-pool result wire format; defaults to ``"arrow"`` here
    (columnar batches ride Arrow IPC — reference ``ArrowTableSerializer`` parity) and
    ``"pickle"`` for :func:`make_reader` row payloads. ``"shm"`` selects the
    shared-memory slab wire (docs/performance.md) — batch results keep their Arrow
    framing but the frames travel through a slab ring instead of the socket
    (``"shm"``/``"shm-view"`` normalize to ``"shm-arrow"``/``"shm-arrow-view"``
    here). Thread/dummy pools share memory and ignore it.

    ``transport``: see :func:`make_reader` — the process pool's wire
    (``'pipe'`` default / ``'tcp'`` framed partition-tolerant sockets,
    ISSUE 15). The shm slab wire is bypassed over tcp (a network link cannot
    carry slab grants); payloads ride the framed socket wire instead.

    ``tenant``: see :func:`make_reader` — per-tenant accounting (ISSUE 18).
    """
    from petastorm_tpu.obs import tenant as _tenant_mod

    tenant_ctx = _tenant_mod.resolve(tenant)
    io_opts = IoOptions.normalize(io_options)
    _host_arena_early(io_opts)
    fs, path = get_filesystem_and_path_or_paths(
        dataset_url_or_urls, storage_options, filesystem
    )
    stored_schema = infer_or_load_unischema(fs, path if not isinstance(path, list) else path[0])

    paths = path if isinstance(path, list) else [path]
    pieces = []
    for p in paths:
        pieces.extend(load_row_groups(fs, p))
    watch_paths = {p.path for p in pieces}  # pre-pruning file set (watch plane)
    stats_pieces = pieces  # pre-plan view: row-group stats still attached
    pieces, partition_info, filters = _plan_pieces(pieces, filters, predicate,
                                                   shard_count)
    watch = _resolve_watch(watch)
    if watch is not None:
        from petastorm_tpu.dataset.watch import stamp_generation_tokens

        pieces = stamp_generation_tokens(fs, pieces,
                                         footer_crc=watch.footer_crc)
    if partition_info:
        stored_schema = _schema_with_partitions(stored_schema, partition_info)

    # NGram here is the TPU-first COLUMNAR path (no reference analog): windows are
    # assembled in-worker as flat 'offset/field' columns via one gather per
    # (offset, field); batches deliver as plain dicts (flat names cannot be
    # namedtuple attributes)
    ngram, read_schema = _resolve_ngram_schema(schema_fields, stored_schema,
                                               predicate)
    rec = RecoveryOptions.resolve(recovery, io_retries=io_retries,
                                  io_retry_backoff_s=io_retry_backoff_s,
                                  worker_respawns=worker_respawns)
    cache = make_cache(cache_type, cache_location, cache_size_limit,
                       cache_row_size_estimate, cache_extra_settings)
    cache = _build_read_funnel(
        cache, io_opts, num_epochs,
        tenant=tenant_ctx.tenant if tenant_ctx is not None else None)
    transform_spec = _maybe_compile_pipeline(transform_spec, read_schema, fs,
                                             stats_pieces, cache)
    final_schema = read_schema
    if transform_spec is not None and not transform_spec.device:
        final_schema = transform_schema(read_schema, transform_spec)
    device_fields = _resolve_device_fields(read_schema, decode_on_device, ngram,
                                           transform_spec=transform_spec)
    worker = ArrowWorker(
        fs, read_schema, stored_schema, predicate, transform_spec, cache,
        shuffle_row_drop_partitions, filters, seed if seed is not None else shard_seed,
        device_fields=device_fields, partition_info=partition_info,
        recovery=rec, io_options=io_opts,
        ngram=ngram,
    )
    r = Reader(
        fs, path, final_schema, stored_schema, worker, pieces,
        num_epochs=num_epochs, shuffle_row_groups=shuffle_row_groups, seed=seed,
        cur_shard=cur_shard, shard_count=shard_count, shard_seed=shard_seed,
        shuffle_row_drop_partitions=shuffle_row_drop_partitions,
        reader_pool_type=reader_pool_type, workers_count=workers_count,
        results_queue_size=results_queue_size, is_batched_reader=True, ngram=ngram,
        results_timeout_s=results_timeout_s,
        wire_serializer={"shm": "shm-arrow", "shm-view": "shm-arrow-view"}.get(
            wire_serializer, wire_serializer) or "arrow",
        io_options=io_opts, recovery=rec,
        provenance=_prov.resolve(provenance), watch=watch,
        watch_paths=watch_paths, transport=transport, tenant=tenant_ctx,
    )
    r.transform_spec = transform_spec
    r.device_decode_fields = device_fields
    return r


def make_service_reader(address, token, job, trainer=None, tenant=None,
                        recovery=None, credits=8, arena=True):
    """Attach to a :class:`petastorm_tpu.service.server.DataService` job
    (ISSUE 19): the disaggregated twin of :func:`make_batch_reader`. Instead
    of decoding locally, the returned
    :class:`~petastorm_tpu.service.client.ServiceReader` consumes the shared
    decode fleet's output — batched columnar delivery with the same
    ``state_dict()`` consumed-watermark checkpoint contract, pluggable into
    :class:`~petastorm_tpu.loader.DataLoader` unchanged.

    ``address``/``token`` come from the service
    (:meth:`~petastorm_tpu.service.server.DataService.trainer_address` /
    ``.token``); ``arena=True`` maps co-hosted payloads zero-copy out of the
    PR 17 host arena. See ``docs/service.md``.
    """
    from petastorm_tpu.service.client import ServiceReader

    return ServiceReader(address, token, job, trainer=trainer, tenant=tenant,
                         recovery=recovery, credits=credits, arena=arena)


def _resolve_partitions(pieces, filters):
    """Hive partitioning at plan time: typed :class:`~petastorm_tpu.partitions.PartitionInfo`
    from the piece paths + directory-level pruning of ``filters`` (reference
    ``pq.ParquetDataset(..., filters=)`` petastorm/reader.py ~L330). Returns
    ``(pieces, info-or-None, filters)`` where filter values on partition columns are
    coerced to the inferred column types (a string-valued filter against an int-typed
    partition must match, both here and in the workers' row-level mask); flat layouts
    pass through untouched."""
    from petastorm_tpu.partitions import (
        build_partition_info,
        normalize_filters,
        prune_pieces,
    )

    info = build_partition_info([p.partition_values or {} for p in pieces])
    if not info:
        return pieces, None, filters
    filters = normalize_filters(filters, info)
    pruned = prune_pieces(pieces, info, filters)
    if len(pruned) < len(pieces):
        logger.info("Hive partition pruning: %d of %d row groups scheduled",
                    len(pruned), len(pieces))
    return pruned, info, filters


def _prune_by_stats(pieces, filters):
    """Row-group statistics pruning (reference: ``pq.ParquetDataset`` consults parquet
    min/max before reading): drop pieces that NO or-clause of the DNF ``filters`` can
    match given their footer statistics. Conservative-correct: absent stats, unknown
    columns, and type mismatches all count as satisfiable — a piece is only dropped on
    a provable contradiction, and the row-level mask still runs for survivors.
    Parquet min/max exclude nulls, so ``!=``/``not in`` prune only groups with a
    recorded null count of zero (null rows MATCH those operators in the row mask).

    Stats survive on the returned pieces so pruning passes chain; the planner
    (``_plan_pieces``) strips them at the end — work items shipped to pool workers
    must not re-pickle per-column bounds."""
    if not pieces:
        return pieces
    if not filters:
        return pieces

    def term_unsat(stats, name, op, val):
        if not stats or name not in stats:
            return False
        mn, mx, nulls = stats[name]
        try:
            if op in ("=", "=="):
                return val < mn or val > mx
            if op == "!=":
                return nulls == 0 and bool(mn == mx == val)
            if op == "<":
                return mn >= val
            if op == "<=":
                return mn > val
            if op == ">":
                return mx <= val
            if op == ">=":
                return mx < val
            if op == "in":
                return all(v < mn or v > mx for v in val)
            if op in ("not in", "not-in"):
                if nulls != 0:
                    return False
                excluded = set(val)
                if bool(mn == mx):
                    return mn in excluded
                if isinstance(mn, (int, np.integer)) and isinstance(mx, (int, np.integer)):
                    # integer stats: unsatisfiable iff the excluded set covers every
                    # possible value in [mn, mx] (span bounded by len(excluded))
                    span = int(mx) - int(mn) + 1
                    return span <= len(excluded) and \
                        all((int(mn) + i) in excluded for i in range(span))
                return False
        except TypeError:  # mixed types (e.g. str filter vs bytes stats): no pruning
            return False
        return False

    kept = [
        p
        for p in pieces
        if any(not any(term_unsat(p.stats, *term) for term in clause)
               for clause in _dnf_clauses(filters))
    ]
    if len(kept) < len(pieces):
        logger.info("Row-group statistics pruning: %d of %d row groups scheduled",
                    len(kept), len(pieces))
    return kept


def _schema_with_partitions(schema, info):
    """Extend a stored/inferred schema with the partition-directory columns (they are
    not in any file's arrow schema but materialize as row values on read)."""
    from petastorm_tpu.partitions import partition_fields

    extra = [f for f in partition_fields(info, nullable=True)
             if f.name not in schema.fields]  # nullable: __HIVE_DEFAULT_PARTITION__ dirs
    if not extra:
        return schema
    return Unischema(schema._name, list(schema.fields.values()) + extra)


def _apply_rowgroup_selector(fs, path, pieces, rowgroup_selector):
    if rowgroup_selector is None:
        return pieces
    from petastorm_tpu.etl.rowgroup_indexing import get_row_group_indexes

    index_dict = get_row_group_indexes(fs, path)
    selected = rowgroup_selector.select_row_groups(index_dict)
    return [p for i, p in enumerate(pieces) if i in selected]
