"""Coalescing planner for the readahead queue: adjacent row groups → one ranged read.

When consecutive plan items hit adjacent row groups of the same file (the
sequential-scan shape: ``shuffle_row_groups=False``, re-epochs, `petastorm-tpu-bench
io`), issuing one ``ParquetFile.read_row_groups([i, i+1, ...])`` instead of N
``read_row_group(i)`` calls collapses N per-call round trips — against an object
store each is a full request — into one ranged read. The resulting concatenated
table is sliced back into per-row-group tables (zero-copy slices), so downstream
consumers cannot tell the difference; `petastorm-tpu-bench io --smoke` asserts
byte-identity in CI.

With shuffled plans the queued window is rarely adjacent and :func:`plan_runs`
naturally degenerates to singleton runs — coalescing never reorders or delays a
read, it only merges what already sits together in the queue.
"""
from __future__ import annotations


def plan_runs(requests, max_run=4):
    """Group ``(piece, columns)`` read requests into coalescible runs.

    A run is a maximal set of requests sharing one file and one column set whose
    row groups form a consecutive range, capped at ``max_run`` row groups (a
    bigger merge would hold too many decoded-table bytes hostage to one read).
    Returns ``[(pieces, columns), ...]`` covering every input request exactly
    once; ``pieces`` within a run are ordered by row group. Input order is
    otherwise preserved (first-seen run order), so the readahead queue's FIFO
    eviction semantics stay intact.
    """
    runs = []
    open_runs = {}  # (path, columns) -> index into runs of the still-growing run
    for piece, columns in requests:
        key = (piece.path, columns)
        idx = open_runs.get(key)
        if idx is not None:
            pieces, _ = runs[idx]
            if len(pieces) < max_run and piece.row_group == pieces[-1].row_group + 1:
                pieces.append(piece)
                continue
        # new run (first for this key, non-adjacent, or the open run is full)
        open_runs[key] = len(runs)
        runs.append(([piece], columns))
    return runs


def split_run_table(table, sizes):
    """Slice a concatenated ranged-read table back into per-row-group tables.

    ``sizes`` are the per-row-group row counts (from the parquet footer
    metadata); slices are zero-copy views. Raises when the sizes don't tile the
    table — a merged read that came back short must fail loudly, not silently
    mis-assign rows to pieces.
    """
    if sum(sizes) != table.num_rows:
        raise ValueError(
            "ranged read returned %d rows but the row-group sizes sum to %d"
            % (table.num_rows, sum(sizes)))
    out = []
    offset = 0
    for size in sizes:
        out.append(table.slice(offset, size))
        offset += size
    return out
