"""Coalescing planners: merge reads whose gap is cheaper than a round trip.

Two layers (ISSUE 4 grown remote-aware by ISSUE 8):

- **Row-group runs** (:func:`plan_runs`): consecutive plan items hitting row
  groups of the same file merge into ONE ``ParquetFile.read_row_groups``
  ranged read, sliced back into per-row-group tables (zero-copy). Originally
  strict-adjacency only; now an optional ``gap_ok(prev_piece, piece)``
  predicate admits *non-adjacent* increasing row groups whose byte gap —
  known from the shared footer cache — is cheaper than a second round trip
  against the store (``pf.read_row_groups([0, 2])`` concatenates in list
  order, so slice-back is unchanged).
- **Byte ranges** (:func:`plan_byte_ranges`): the remote ranged-GET engine's
  planner — column-chunk byte ranges whose gap is at most ``min_gap_bytes``
  merge into one GET, and merged spans larger than ``target_request_bytes``
  split into parallel GETs sized to the store's latency/throughput knee.
  :func:`slice_ranges` cuts the fetched chunks back into the original
  requests, byte-identical.

Both planners only merge/split what is already queued together — they never
reorder or delay a read; `petastorm-tpu-bench io --smoke` and
`petastorm-tpu-bench remote --check` assert byte-identity in CI.
"""
from __future__ import annotations


def plan_runs(requests, max_run=4, gap_ok=None):
    """Group ``(piece, columns)`` read requests into coalescible runs.

    A run is a maximal set of requests sharing one file and one column set
    whose row groups are strictly increasing and pairwise mergeable — adjacent
    (``rg == prev + 1``), or non-adjacent with ``gap_ok(prev_piece, piece)``
    approving the byte gap between them — capped at ``max_run`` row groups (a
    bigger merge would hold too many decoded-table bytes hostage to one read).
    Returns ``[(pieces, columns), ...]`` covering every input request exactly
    once; ``pieces`` within a run are ordered by row group. Input order is
    otherwise preserved (first-seen run order), so the readahead queue's FIFO
    eviction semantics stay intact.
    """
    runs = []
    open_runs = {}  # (path, columns) -> index into runs of the still-growing run
    for piece, columns in requests:
        key = (piece.path, columns)
        idx = open_runs.get(key)
        if idx is not None:
            pieces, _ = runs[idx]
            if len(pieces) < max_run:
                prev = pieces[-1]
                adjacent = piece.row_group == prev.row_group + 1
                bridged = (not adjacent and gap_ok is not None
                           and piece.row_group > prev.row_group
                           and gap_ok(prev, piece))
                if adjacent or bridged:
                    pieces.append(piece)
                    continue
        # new run (first for this key, unmergeable gap, or the open run is full)
        open_runs[key] = len(runs)
        runs.append(([piece], columns))
    return runs


def plan_byte_ranges(ranges, min_gap_bytes=0, target_request_bytes=None):
    """Plan the GETs covering ``[(offset, length), ...]`` byte ranges.

    Overlapping/back-to-back ranges always merge; a gap of at most
    ``min_gap_bytes`` merges too (the wasted gap bytes cost less than a second
    round trip). Merged spans longer than ``target_request_bytes`` split into
    consecutive chunks of at most that size — the parallel GETs the engine
    issues concurrently. Returns ``[(offset, length), ...]`` sorted, disjoint,
    covering every input byte at least once.
    """
    if not ranges:
        return []
    spans = sorted((int(off), int(off) + int(ln)) for off, ln in ranges if ln > 0)
    if not spans:
        return []
    merged = [list(spans[0])]
    for start, end in spans[1:]:
        if start - merged[-1][1] <= max(0, int(min_gap_bytes)):
            merged[-1][1] = max(merged[-1][1], end)
        else:
            merged.append([start, end])
    out = []
    chunk = int(target_request_bytes) if target_request_bytes else 0
    for start, end in merged:
        if chunk <= 0 or end - start <= chunk:
            out.append((start, end - start))
            continue
        pos = start
        while pos < end:
            n = min(chunk, end - pos)
            out.append((pos, n))
            pos += n
    return out


def slice_ranges(chunks, ranges):
    """Reassemble the originally requested ``ranges`` from fetched ``chunks``.

    ``chunks`` is ``[(offset, bytes-like), ...]`` (sorted or not); each
    requested ``(offset, length)`` must be fully covered by the chunks (a
    planner output always covers its input — a short GET fails loudly here,
    never silently mis-slices). Returns one ``memoryview``/``bytes`` per
    request, zero-copy when a request falls inside a single chunk.
    """
    spans = sorted((int(off), memoryview(data)) for off, data in chunks)
    out = []
    for off, ln in ranges:
        out.append(_slice_one(spans, int(off), int(ln)))
    return out


def _slice_one(spans, off, ln):
    end = off + ln
    parts = []
    for start, view in spans:
        stop = start + len(view)
        if stop <= off or start >= end:
            continue
        lo = max(off, start)
        hi = min(end, stop)
        parts.append(view[lo - start:hi - start])
    got = sum(len(p) for p in parts)
    if got != ln:
        raise ValueError(
            "ranged GETs cover %d of the %d bytes requested at offset %d"
            % (got, ln, off))
    if len(parts) == 1:
        return parts[0]
    return b"".join(bytes(p) for p in parts)


def split_run_table(table, sizes):
    """Slice a concatenated ranged-read table back into per-row-group tables.

    ``sizes`` are the per-row-group row counts (from the parquet footer
    metadata); slices are zero-copy views. Raises when the sizes don't tile the
    table — a merged read that came back short must fail loudly, not silently
    mis-assign rows to pieces.
    """
    if sum(sizes) != table.num_rows:
        raise ValueError(
            "ranged read returned %d rows but the row-group sizes sum to %d"
            % (table.num_rows, sum(sizes)))
    out = []
    offset = 0
    for size in sizes:
        out.append(table.slice(offset, size))
        offset += size
    return out
