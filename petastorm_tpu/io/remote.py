"""Object-store-native remote read tier: parallel ranged GETs, hedging, footer GETs.

The PR 4 IO layer hid *local-file* latency (readahead overlaps decode), but
against a cloud object store the read itself is the wrong shape: one
``ParquetFile.read_row_group`` call issues one serial ranged request per
column chunk, every worker thread re-reads each file's footer, and a single
slow replica (the store's fat tail) stalls a whole row group ("Hiding
Latencies in Network-Based Image Loading for Deep Learning", PAPERS.md). This
module is the remote tier (ISSUE 8):

- :class:`RemoteReadEngine` plans the exact column-chunk byte ranges of a
  row-group read from the (shared, cached) footer, **gap-coalesces** them
  (:func:`petastorm_tpu.io.coalesce.plan_byte_ranges` — a gap smaller than
  ``min_gap_bytes`` is cheaper than a second round trip), splits merged spans
  at ``target_request_bytes``, and issues the chunks as **parallel ranged
  GETs** on a bounded pool. The fetched segments back a sparse in-memory
  file; pyarrow parses from it without ever opening the object.
- **Request hedging**: a per-(store, size-class) latency histogram (the PR 5
  straggler/latency plumbing's log-bucketed :class:`~petastorm_tpu.obs.metrics.Histogram`)
  learns what a GET of this size normally costs; an attempt still pending at
  the configured quantile gets a duplicate GET, first responder wins
  (``ptpu_io_hedges_total`` / ``ptpu_io_hedge_wins_total``). The loser is
  drained, its buffer's accounting :class:`~petastorm_tpu.io.lease.Lease`
  released — never delivered (exactly-once preserved; the chaos site
  ``io.remote`` injects tail latency to pin this in tests).
- **Footer GETs**: a cache miss reads the file *tail* (footer-length trailer
  first, one more GET only when the footer outgrows the first window) and
  parses metadata from those bytes alone — never a full open.

Every feature degrades: engine construction failure falls back to the classic
``ParquetFile`` path (``cause="remote_unavailable"``), a read the sparse file
cannot serve falls through to a real ranged read against the store (counted,
never wrong). ``petastorm-tpu-bench remote`` measures all of it under the
:class:`~petastorm_tpu.io.latencyfs.CloudLatencyFS` simulator in CI.
"""
from __future__ import annotations

import threading
import time

from petastorm_tpu.io import _env_bool, _env_float, _env_int
from petastorm_tpu.io.coalesce import plan_byte_ranges, slice_ranges
from petastorm_tpu.obs import provenance as _prov
from petastorm_tpu.obs.log import degradation
from petastorm_tpu.obs.metrics import default_registry

#: pyarrow filesystem type_names that are NOT object stores (auto-enable probe)
_LOCAL_TYPE_NAMES = frozenset({"local", "localfs", "mock", "subtree", "py::fsspec+file"})

#: first tail GET size: covers typical footers in one trip; a footer that
#: outgrows it costs exactly one more ranged GET (and the footer cache makes
#: either once-per-file-per-process, so a lean guess beats a fat one — the
#: guessed bytes are ALL paid per miss at the store's per-byte cost)
_FOOTER_TAIL_GUESS = 32 << 10


class RemoteIoOptions:
    """Knobs for the remote tier — one picklable struct riding on
    :class:`petastorm_tpu.io.IoOptions` (``io_options=dict(remote=...)``).

    ======================  ==============================  =====================
    field                   env var                         meaning
    ======================  ==============================  =====================
    enabled                 PTPU_REMOTE                     ``None`` (default) =
                                                            auto: on when the
                                                            filesystem is not
                                                            local; True/False
                                                            force it
    target_request_bytes    PTPU_REMOTE_TARGET_REQUEST_     split merged spans
                            BYTES                           into parallel GETs of
                                                            at most this (8 MB)
    max_inflight            PTPU_REMOTE_MAX_INFLIGHT        ranged GETs in flight
                                                            per process (8)
    min_gap_bytes           PTPU_REMOTE_MIN_GAP_BYTES       merge reads whose
                                                            byte gap is at most
                                                            this (512 KB)
    hedge                   PTPU_REMOTE_HEDGE               duplicate a GET past
                                                            its deadline (on)
    hedge_quantile          PTPU_REMOTE_HEDGE_QUANTILE      latency-histogram
                                                            quantile that arms
                                                            the deadline (0.95)
    hedge_min_s             PTPU_REMOTE_HEDGE_MIN_S         deadline floor (0.05)
    hedge_min_samples       PTPU_REMOTE_HEDGE_MIN_SAMPLES   observations per
                                                            (store, size class)
                                                            before hedging (20)
    get_timeout_s           PTPU_REMOTE_GET_TIMEOUT_S       wall cap on one GET
                                                            incl. its hedge (300)
    footer_cache_bytes      PTPU_FOOTER_CACHE_BYTES         shared parsed-footer
                                                            budget (64 MB; 0 =
                                                            per-open re-reads)
    disk_admit              PTPU_TIER_DISK_ADMIT            tiered-admission
                                                            policy: ``always``
                                                            (legacy) or
                                                            ``scan-resistant``
                                                            (skip single-epoch
                                                            scans and values the
                                                            memcache admitted)
    ======================  ==============================  =====================
    """

    __slots__ = ("enabled", "target_request_bytes", "max_inflight",
                 "min_gap_bytes", "hedge", "hedge_quantile", "hedge_min_s",
                 "hedge_min_samples", "get_timeout_s", "footer_cache_bytes",
                 "disk_admit")

    def __init__(self, enabled=None, target_request_bytes=None, max_inflight=None,
                 min_gap_bytes=None, hedge=None, hedge_quantile=None,
                 hedge_min_s=None, hedge_min_samples=None, get_timeout_s=None,
                 footer_cache_bytes=None, disk_admit=None):
        self.enabled = _env_tristate("PTPU_REMOTE") if enabled is None \
            else (None if enabled == "auto" else bool(enabled))
        self.target_request_bytes = max(
            64 << 10, _env_int("PTPU_REMOTE_TARGET_REQUEST_BYTES", 8 << 20)
            if target_request_bytes is None else int(target_request_bytes))
        self.max_inflight = max(1, _env_int("PTPU_REMOTE_MAX_INFLIGHT", 8)
                                if max_inflight is None else int(max_inflight))
        self.min_gap_bytes = max(0, _env_int("PTPU_REMOTE_MIN_GAP_BYTES", 512 << 10)
                                 if min_gap_bytes is None else int(min_gap_bytes))
        self.hedge = _env_bool("PTPU_REMOTE_HEDGE", True) \
            if hedge is None else bool(hedge)
        self.hedge_quantile = min(0.999, max(0.5, _env_float(
            "PTPU_REMOTE_HEDGE_QUANTILE", 0.95) if hedge_quantile is None
            else float(hedge_quantile)))
        self.hedge_min_s = max(0.0, _env_float("PTPU_REMOTE_HEDGE_MIN_S", 0.05)
                               if hedge_min_s is None else float(hedge_min_s))
        self.hedge_min_samples = max(1, _env_int(
            "PTPU_REMOTE_HEDGE_MIN_SAMPLES", 20) if hedge_min_samples is None
            else int(hedge_min_samples))
        self.get_timeout_s = max(1.0, _env_float("PTPU_REMOTE_GET_TIMEOUT_S", 300.0)
                                 if get_timeout_s is None else float(get_timeout_s))
        self.footer_cache_bytes = max(0, _env_int(
            "PTPU_FOOTER_CACHE_BYTES", 64 << 20) if footer_cache_bytes is None
            else int(footer_cache_bytes))
        disk_admit = _env_str("PTPU_TIER_DISK_ADMIT", "always") \
            if disk_admit is None else str(disk_admit)
        if disk_admit not in ("always", "scan-resistant"):
            raise ValueError("disk_admit must be 'always' or 'scan-resistant', "
                             "got %r" % disk_admit)
        self.disk_admit = disk_admit

    @classmethod
    def normalize(cls, value):
        """``None`` → defaults (env-aware), dict → kwargs, instance → itself."""
        if value is None:
            return cls()
        if isinstance(value, cls):
            return value
        if isinstance(value, dict):
            return cls(**value)
        raise TypeError("remote io options must be a RemoteIoOptions, a dict of "
                        "its fields, or None; got %r" % type(value).__name__)

    def active_for(self, fs):
        """Is the remote tier on for this filesystem? Explicit ``enabled``
        wins; auto probes the pyarrow ``type_name`` (local stays off)."""
        if self.enabled is not None:
            return self.enabled
        return fs_is_remote(fs)

    def __getstate__(self):
        return {name: getattr(self, name) for name in self.__slots__}

    def __setstate__(self, state):
        for name in self.__slots__:
            setattr(self, name, state.get(name, getattr(type(self)(), name)))

    def __repr__(self):
        return "RemoteIoOptions(%s)" % ", ".join(
            "%s=%r" % (name, getattr(self, name)) for name in self.__slots__)


def _env_tristate(name):
    import os

    raw = os.environ.get(name)
    if raw is None or raw == "" or raw.strip().lower() == "auto":
        return None
    return raw.strip().lower() not in ("0", "false", "no", "off")


def _env_str(name, default):
    import os

    raw = os.environ.get(name)
    return default if raw is None or raw == "" else raw.strip()


def fs_is_remote(fs):
    """Best-effort object-store probe: pyarrow filesystems expose
    ``type_name`` ('local', 'gcs', 's3', 'hdfs', 'py::fsspec+gs', ...)."""
    try:
        type_name = getattr(fs, "type_name", None)
    except Exception:  # noqa: BLE001 - exotic proxies: assume local
        return False
    if not isinstance(type_name, str):
        return False
    return type_name.lower() not in _LOCAL_TYPE_NAMES


# --------------------------------------------------------------------------------------
# Latency model (feeds the hedge deadline)
# --------------------------------------------------------------------------------------

_SIZE_CLASSES = ((64 << 10, "64KB"), (256 << 10, "256KB"), (1 << 20, "1MB"),
                 (4 << 20, "4MB"), (16 << 20, "16MB"))


def size_class(nbytes):
    """Log-spaced request-size bucket label (hedging deadlines are per size
    class: a 16 MB GET is not slow just because it is bigger than a 64 KB
    one)."""
    for bound, label in _SIZE_CLASSES:
        if nbytes <= bound:
            return label
    return ">16MB"


class LatencyModel:
    """Per-(store, size-class) GET latency histograms + the hedge deadline.

    Built on the PR 5 log-bucketed :class:`~petastorm_tpu.obs.metrics.Histogram`
    (same primitive as the straggler detector's worker latencies), registered
    as ``ptpu_io_remote_get_seconds{store=,size_class=}`` so the learned
    distribution is visible in the Prometheus export next to the hedge
    counters it drives.
    """

    def __init__(self, registry=None):
        self._registry = registry if registry is not None else default_registry()
        self._lock = threading.Lock()
        self._hists = {}

    def _hist(self, store, label):
        key = (store, label)
        hist = self._hists.get(key)
        if hist is None:
            with self._lock:
                hist = self._hists.get(key)
                if hist is None:
                    hist = self._registry.histogram(
                        "ptpu_io_remote_get_seconds",
                        help="ranged GET latency by store and request size class",
                        store=store, size_class=label)
                    self._hists[key] = hist
        return hist

    def observe(self, store, nbytes, seconds):
        self._hist(store, size_class(nbytes)).observe(seconds)

    def deadline(self, store, nbytes, quantile, min_samples, floor_s):
        """Seconds after which a pending GET of this size is tail-suspect, or
        ``None`` while the class has too few observations to judge."""
        hist = self._hist(store, size_class(nbytes))
        if hist.count < min_samples:
            return None
        return max(floor_s, hist.percentile(quantile))

    def reset(self):
        """Zero every learned distribution (bench/test scenario isolation —
        the registry families are process-wide, so a fresh model instance
        would resolve to the SAME histograms; resetting them is the only real
        reset)."""
        with self._lock:
            hists = list(self._hists.values())
        for hist in hists:
            hist.reset()


_model_lock = threading.Lock()
_model = None


def shared_latency_model():
    """Process-wide model: every engine (one per worker object) feeds and
    consults the same distributions — N workers learn the store's tail N×
    faster than any one of them would."""
    global _model
    with _model_lock:
        if _model is None:
            _model = LatencyModel()
        return _model


# --------------------------------------------------------------------------------------
# Hedged GET machinery
# --------------------------------------------------------------------------------------


class _GetState:
    """Coordination slot for one logical ranged GET and its possible hedge.

    First completed attempt wins: it parks its payload (under an accounting
    :class:`~petastorm_tpu.io.lease.Lease`) and sets ``done``. A later
    attempt — the drained loser — releases its lease immediately and its
    payload is dropped on the floor: the consumer can never see two copies.
    ``abandoned`` is set by the waiter once the GET's outcome is decided
    (payload taken, or error raised): an attempt landing after that is a
    loser by definition, so even a pathologically late success cannot strand
    a lease."""

    __slots__ = ("lock", "done", "data", "lease", "winner_role", "errors",
                 "outstanding", "hedged", "deadline_s", "abandoned",
                 "exec_start", "exec_started", "tenant")

    def __init__(self):
        self.lock = threading.Lock()
        self.done = threading.Event()
        self.data = None
        self.lease = None
        self.winner_role = None
        self.errors = []
        self.outstanding = 0
        self.hedged = False
        self.deadline_s = None  # hedge deadline relative to EXEC start, or None
        self.abandoned = False
        self.exec_start = None  # monotonic time the primary began EXECUTING
        #: set the instant the primary starts executing (or the attempt dies
        #: before starting) — the supervisor waits on THIS while the GET is
        #: pool-queued, so deadline timing is exact from execution start
        #: instead of drifting by a poll slice
        self.exec_started = threading.Event()
        #: tenant slug captured at ISSUE time on the requesting thread — the
        #: pool threads that execute attempts carry no tenant context
        self.tenant = None

    def take(self):
        """Claim the winning payload (exactly once) and abandon the slot."""
        with self.lock:
            data, self.data = self.data, None
            lease, self.lease = self.lease, None
            self.abandoned = True
        if lease is not None:
            lease.release()
        return data


class RemoteReadEngine:
    """Per-process ranged-GET executor for one filesystem.

    Owns a bounded thread pool (``max_inflight``) — graftlint GL-L001 tracks
    it; :meth:`shutdown` is the closer (idempotent, called from the worker's
    ``close()`` on ``Reader.join``).
    """

    def __init__(self, fs, options=None, footer_cache=None, registry=None,
                 latency_model=None, store_key=None):
        from concurrent.futures import ThreadPoolExecutor

        self._fs = fs
        self._opts = options if options is not None \
            else RemoteIoOptions.normalize(None)
        #: None = refetch the footer per call (the measurable no-cache mode)
        self._footers = footer_cache
        self._model = latency_model if latency_model is not None \
            else shared_latency_model()
        self._store = store_key or str(getattr(fs, "type_name", "remote"))
        self._closed = False
        self._lock = threading.Lock()
        #: LIVE knob state (ISSUE 13): seeded from the options once at
        #: construction and retuned only through the sanctioned apply_*()
        #: seam — the options struct itself is never mutated (GL-C004)
        self._max_inflight = self._opts.max_inflight
        self._hedge_quantile = self._opts.hedge_quantile
        #: GET pools replaced by a live apply_max_inflight() resize: their
        #: in-flight attempts finish on their own threads (never cancelled —
        #: a retune must not fail reads)
        self._retired_pools = []
        self._pool = ThreadPoolExecutor(max_workers=self._max_inflight,
                                        thread_name_prefix="ptpu-remote")
        reg = registry if registry is not None else default_registry()
        self._gets = reg.counter("ptpu_io_remote_gets_total",
                                 help="ranged GETs issued (incl. hedges)")
        self._get_bytes = reg.counter("ptpu_io_remote_bytes_total",
                                      help="bytes fetched by ranged GETs")
        self._hedges = reg.counter(
            "ptpu_io_hedges_total",
            help="duplicate GETs issued past the latency-quantile deadline")
        self._hedge_wins = reg.counter(
            "ptpu_io_hedge_wins_total",
            help="hedged GETs where the duplicate responded first")
        self._sparse_fallbacks = reg.counter(
            "ptpu_io_remote_sparse_fallbacks_total",
            help="reads the prefetched segments could not serve (went to "
                 "storage)")
        self._footer_fetches = reg.counter(
            "ptpu_io_remote_footer_fetches_total",
            help="footers fetched via ranged tail GETs")
        # per-instance tallies for Reader.io_stats() (registry families are
        # process-wide; these are this engine's own)
        self._n = {"gets": 0, "bytes": 0, "hedges": 0, "hedge_wins": 0,
                   "sparse_fallbacks": 0, "footer_fetches": 0}
        self._reg = reg
        self._tenant_twins = {}  # (family, tenant) -> Counter (ISSUE 18)

    def _twin(self, family, tenant):
        """Per-tenant twin of a remote counter — charged beside the untagged
        total so cross-tenant sums reconcile with it exactly."""
        key = (family, tenant)
        c = self._tenant_twins.get(key)
        if c is None:
            with self._lock:
                c = self._tenant_twins.get(key)
                if c is None:
                    c = self._reg.counter(family, tenant=tenant)
                    self._tenant_twins[key] = c
        return c

    # -- footer plane -------------------------------------------------------------------

    def footer(self, path):
        """The parsed footer for ``path`` — cached when a footer cache is
        attached, fetched via ranged tail GETs otherwise (never a full
        open)."""
        if self._footers is not None:
            entry = self._footers.peek(path)
            if entry is not None:
                self._footers.count_hit()
                return entry
        metadata, size = self._fetch_footer(path)
        if self._footers is not None:
            self._footers.count_miss()
            return self._footers.put(path, metadata, size)
        from petastorm_tpu.io.footercache import FooterEntry

        return FooterEntry(metadata, size)

    def _fetch_footer(self, path):
        import pyarrow as pa
        import pyarrow.parquet as pq

        info = self._fs.get_file_info(path)
        size = int(info.size)
        guess = min(size, _FOOTER_TAIL_GUESS)
        tail = self.fetch_ranges(path, [(size - guess, guess)])[0]
        tail = bytes(tail)
        if len(tail) < 8 or tail[-4:] != b"PAR1":
            raise OSError("%s: not a parquet file (bad magic in tail GET)" % path)
        footer_len = int.from_bytes(tail[-8:-4], "little")
        need = footer_len + 8
        if need > len(tail):
            if need > size:
                raise OSError("%s: footer length %d exceeds file size %d"
                              % (path, footer_len, size))
            head = self.fetch_ranges(
                path, [(size - need, need - len(tail))])[0]
            tail = bytes(head) + tail
        metadata = pq.read_metadata(pa.BufferReader(tail))
        self._footer_fetches.inc()
        with self._lock:
            self._n["footer_fetches"] += 1
        return metadata, size

    # -- data plane ---------------------------------------------------------------------

    def read_row_groups(self, path, row_groups, columns):
        """Read ``row_groups`` of ``path`` restricted to top-level ``columns``
        (None = all) through parallel hedged ranged GETs. Returns
        ``(table, footer_entry)`` — the table is the row groups concatenated
        in list order, byte-identical to a ``ParquetFile`` read.

        ``columns`` not present in the file (hive partition columns, schema
        drift) are silently dropped against the footer's arrow schema — the
        same availability filter the classic path applies, resolved from the
        ONE footer this call already holds (a separate ``arrow_names`` round
        would double the metadata fetches in no-cache mode)."""
        import pyarrow as pa
        import pyarrow.parquet as pq

        with _prov.span("io.remote"):  # GETs + stitch, nested in reader.read
            entry = self.footer(path)
            md = entry.metadata
            if columns is not None:
                available = set(md.schema.to_arrow_schema().names)
                columns = [c for c in columns if c in available]
            ranges = column_chunk_ranges(md, row_groups, columns)
            plan = plan_byte_ranges(ranges, self._opts.min_gap_bytes,
                                    self._opts.target_request_bytes)
            chunks = list(zip((off for off, _ in plan),
                              self.fetch_ranges(path, plan)))
            size = entry.size
            if size is None:
                size = int(self._fs.get_file_info(path).size)
            src = _SparseFile(path, size, chunks, self)
            pf = pq.ParquetFile(pa.PythonFile(src, mode="r"), metadata=md)
            table = pf.read_row_groups(list(row_groups), columns=columns)
            return table, entry

    def arrow_names(self, path):
        """Column names of ``path``'s arrow schema — from the cached footer,
        no file open (the worker's column-availability filter)."""
        return list(self.footer(path).metadata.schema.to_arrow_schema().names)

    def read_raw_column_chunks(self, path, row_group, columns):
        """Raw column-chunk byte spans for the compressed-page pass-through
        (ISSUE 14): ``{column: bytes}`` of row group ``row_group``'s chunks,
        fetched as ONE batched hedged ranged-GET plan.

        Splits are **page-granular** when a previous walk cached the chunk's
        page boundaries (:func:`petastorm_tpu.io.pagedec.shared_page_index`
        — Parquet keeps page offsets inline in the data, so first touch
        fetches at request-size granularity and re-reads split exactly at
        page starts, the CODAG-friendly request shape); without the index a
        big chunk splits at ``target_request_bytes`` like any other plan."""
        from petastorm_tpu.io.pagedec import chunk_byte_range, shared_page_index

        with _prov.span("io.remote"):
            entry = self.footer(path)
            rgmd = entry.metadata.row_group(row_group)
            wanted = set(columns)
            plans = []  # (name, [(offset, length), ...])
            target = self._opts.target_request_bytes
            index = shared_page_index()
            for i in range(rgmd.num_columns):
                col = rgmd.column(i)
                name = col.path_in_schema.split(".")[0]
                if name not in wanted or any(p[0] == name for p in plans):
                    continue
                start, length = chunk_byte_range(col)
                if length <= target:
                    plans.append((name, [(start, length)]))
                    continue
                cached = index.get(path, row_group, name)
                cuts = []
                if cached is not None:
                    _chunk_off, page_offsets = cached
                    acc = start
                    for off in page_offsets:
                        if start < off < start + length and off - acc >= target:
                            cuts.append(off)
                            acc = off
                ranges = []
                prev = start
                for cut in cuts:
                    ranges.append((prev, cut - prev))
                    prev = cut
                remaining = start + length - prev
                if cached is None:
                    # no page index yet: plain size-granular slicing
                    pos = prev
                    while remaining > target:
                        ranges.append((pos, target))
                        pos += target
                        remaining -= target
                    prev = pos
                ranges.append((prev, start + length - prev))
                plans.append((name, [r for r in ranges if r[1] > 0]))
            flat = [r for _name, ranges in plans for r in ranges]
            payloads = self.fetch_ranges(path, flat)
            out = {}
            pos = 0
            for name, ranges in plans:
                parts = payloads[pos:pos + len(ranges)]
                pos += len(ranges)
                out[name] = bytes(parts[0]) if len(parts) == 1 \
                    else b"".join(bytes(p) for p in parts)
            return out

    def fetch_ranges(self, path, ranges):
        """Fetch ``[(offset, length), ...]`` as parallel hedged GETs; returns
        the payloads in request order. Ranges are issued as given — callers
        coalesce/split via :func:`plan_byte_ranges` first.

        All primaries are submitted up front (they run concurrently on the
        bounded pool); the CALLER thread then supervises hedge deadlines —
        attempts never wait on attempts, so the pool cannot deadlock on
        itself however large the plan is."""
        if not ranges:
            return []
        t0 = time.monotonic()
        states = []
        for off, ln in ranges:
            states.append(self._start_get(path, off, ln))
        return [self._finish_get(state, path, off, ln, t0)
                for state, (off, ln) in zip(states, ranges)]

    def _start_get(self, path, offset, length):
        """Submit the primary attempt; compute the hedge deadline now (the
        latency model is consulted once, at issue time)."""
        state = _GetState()
        state.outstanding = 1
        from petastorm_tpu.obs import tenant as _tenant_ctx

        state.tenant = _tenant_ctx.current_label()
        if self._opts.hedge:
            state.deadline_s = self._model.deadline(
                self._store, length, self._hedge_quantile,
                self._opts.hedge_min_samples, self._opts.hedge_min_s)
        self._submit_attempt(state, path, offset, length, "primary")
        return state

    def _finish_get(self, state, path, offset, length, t0):
        """Await one logical GET: hedge when its deadline passes, take the
        first responder, raise when every attempt failed.

        Sequential supervision of a fan-out is deliberate: while the caller
        sits on an earlier range, later primaries keep running — a later
        range found past ITS deadline on arrival is hedged immediately. Both
        the hedge deadline and the per-range timeout are measured from the
        attempt's **execution start** (stamped by ``_run_attempt``), not the
        batch submit time: a GET parked in the pool queue behind a big plan
        is waiting on US, not on a slow replica — hedging it would just
        double-load the same saturated pool, and timing it out would fail
        healthy work. ``t0`` only bounds the never-started case (pool died)."""
        never_started_at = t0 + 2 * self._opts.get_timeout_s
        while True:
            now = time.monotonic()
            with state.lock:
                started = state.exec_start
                alive = state.outstanding > 0
            if state.done.is_set():
                break
            if started is None:
                # queued, not executing: its clocks have not started; wake the
                # instant execution begins (an Event, not a poll slice — a
                # slice's worth of drift here would delay every hedge past
                # short tail spikes)
                if now >= never_started_at:
                    break  # pool wedged/shut down: fall through to timeout
                state.exec_started.wait(min(0.5, never_started_at - now))
                continue
            timeout_at = started + self._opts.get_timeout_s
            if now >= timeout_at:
                break
            if state.deadline_s is not None and not state.hedged and alive \
                    and now - started >= state.deadline_s:
                with state.lock:
                    fire = state.outstanding > 0 and not state.hedged
                    if fire:
                        state.outstanding += 1
                        state.hedged = True
                if fire:
                    self._hedges.inc()
                    with self._lock:
                        self._n["hedges"] += 1
                    if state.tenant is not None:
                        self._twin("ptpu_io_hedges_total", state.tenant).inc()
                        from petastorm_tpu.obs import tenant as _tenant_ctx

                        _tenant_ctx.charge("hedged_gets", 1,
                                           label=state.tenant)
                    if _prov.ACTIVE is not None:
                        # supervision runs on the item's own thread, so the
                        # annotation binds to the right record exactly
                        _prov.annotate_add("hedges", 1)
                    self._submit_attempt(state, path, offset, length, "hedge")
                continue
            next_wake = timeout_at
            if state.deadline_s is not None and not state.hedged:
                next_wake = min(next_wake, started + state.deadline_s)
            state.done.wait(max(0.0, next_wake - now))
        # take() abandons the slot, so a pathologically late attempt can only
        # drain — and if the winner landed in the timeout race window, we
        # deliver it rather than strand its lease and raise
        data = state.take()
        if data is not None:
            if _prov.ACTIVE is not None and state.winner_role == "hedge":
                _prov.annotate_add("hedge_wins", 1)
            return data
        if state.errors:
            raise state.errors[-1]
        raise TimeoutError(
            "ranged GET of %s [%d, +%d) still pending after %.0fs"
            % (path, offset, length, self._opts.get_timeout_s))

    def _submit_attempt(self, state, path, offset, length, role):
        try:
            self._pool.submit(self._run_attempt, state, path, offset, length,
                              role)
        except RuntimeError:
            # pool shut down mid-flight (Reader.join raced a straggler read):
            # account the attempt as failed so the waiter is released
            self._attempt_failed(state, OSError(
                "remote engine shut down while fetching %s" % path))

    def _run_attempt(self, state, path, offset, length, role):
        from petastorm_tpu import chaos as _chaos
        from petastorm_tpu.io.lease import Lease

        if role == "primary":
            with state.lock:
                state.exec_start = time.monotonic()
            state.exec_started.set()
        try:
            if _chaos.ACTIVE is not None:
                _chaos.ACTIVE.hit("io.remote",
                                  key="%s:%d+%d#%s" % (path, offset, length, role))
            t0 = time.perf_counter()
            data = self._fetch(path, offset, length)
            dur = time.perf_counter() - t0
        except Exception as e:  # noqa: BLE001 — stored, re-raised at the waiter
            self._attempt_failed(state, e)
            return
        self._model.observe(self._store, length, dur)
        self._gets.inc()
        self._get_bytes.inc(len(data))
        with self._lock:
            self._n["gets"] += 1
            self._n["bytes"] += len(data)
        if state.tenant is not None:
            self._twin("ptpu_io_remote_gets_total", state.tenant).inc()
            self._twin("ptpu_io_remote_bytes_total",
                       state.tenant).inc(len(data))
        lease = Lease(kind="remote_get")
        deliver = False
        with state.lock:
            state.outstanding -= 1
            if state.winner_role is None and not state.abandoned:
                state.winner_role = role
                state.data = data
                state.lease = lease
                deliver = True
        if deliver:
            if role == "hedge":
                self._hedge_wins.inc()
                with self._lock:
                    self._n["hedge_wins"] += 1
                if state.tenant is not None:
                    self._twin("ptpu_io_hedge_wins_total", state.tenant).inc()
            state.done.set()
        else:
            # the drained loser: release the accounting lease, drop the bytes
            # — the winner already delivered the one and only copy
            lease.release()

    def _attempt_failed(self, state, error):
        with state.lock:
            state.errors.append(error)
            state.outstanding -= 1
            last = state.outstanding <= 0 and state.winner_role is None
        if last:
            state.done.set()
        state.exec_started.set()  # wake a supervisor parked on the queue wait

    def _fetch(self, path, offset, length):
        """One ranged GET: its own handle per request — exactly the object
        store's request model (and what keeps attempts independently
        retryable/hedgeable across replicas)."""
        with self._fs.open_input_file(path) as f:
            f.seek(offset)
            return f.read(length)

    # -- live knobs (ISSUE 13) ----------------------------------------------------------

    def apply_max_inflight(self, max_inflight):
        """Resize the GET pool live via a pool swap: new attempts submit to
        a fresh pool of the target width; the old pool's queued/executing
        GETs finish on its own threads (their ``_GetState`` delivery keeps
        the lease accounting exact). The sanctioned retune seam — the
        ``RemoteIoOptions`` struct is never mutated (GL-C004)."""
        from concurrent.futures import ThreadPoolExecutor

        max_inflight = max(1, int(max_inflight))
        with self._lock:
            if self._closed or max_inflight == self._max_inflight:
                return self._max_inflight
            old = self._pool
            self._pool = ThreadPoolExecutor(max_workers=max_inflight,
                                            thread_name_prefix="ptpu-remote")
            self._max_inflight = max_inflight
            # prune retired pools whose threads have all exited — repeated
            # retunes over a long run must not accumulate dead executors
            self._retired_pools = [
                p for p in self._retired_pools
                if any(t.is_alive()
                       for t in getattr(p, "_threads", ()) or ())]
            self._retired_pools.append(old)
        old.shutdown(wait=False)
        return max_inflight

    def apply_hedge_quantile(self, quantile):
        """Retune the hedge-arming latency quantile live (bounded to the
        same [0.5, 0.999] window the options constructor enforces). Takes
        effect at the next GET's deadline computation."""
        quantile = min(0.999, max(0.5, float(quantile)))
        with self._lock:
            self._hedge_quantile = quantile
        return quantile

    @property
    def max_inflight(self):
        return self._max_inflight

    @property
    def hedge_quantile(self):
        return self._hedge_quantile

    # -- lifecycle ----------------------------------------------------------------------

    def shutdown(self):
        """Stop the GET pool(s) (idempotent). In-flight attempts are
        abandoned to finish on their own — their ``_GetState`` delivery keeps
        the lease accounting exact either way."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            pools = [self._pool] + list(self._retired_pools)
        for pool in pools:
            pool.shutdown(wait=False, cancel_futures=True)

    def stats(self):
        with self._lock:
            out = {"remote_%s" % k: v for k, v in self._n.items()}
            # LIVE knob values (ISSUE 13 satellite): dashboards and the
            # controller's feedback read the applied value post-retune
            out["remote_max_inflight"] = self._max_inflight
            out["remote_hedge_quantile"] = self._hedge_quantile
        return out


def column_chunk_ranges(metadata, row_groups, columns):
    """The ``(offset, length)`` byte ranges of the column chunks a
    ``read_row_groups(row_groups, columns=columns)`` call will touch
    (``columns`` match on the top-level field of ``path_in_schema`` — the
    arrow column names the workers select by)."""
    wanted = None if columns is None else set(columns)
    ranges = []
    for rg in row_groups:
        rgmd = metadata.row_group(rg)
        for i in range(rgmd.num_columns):
            col = rgmd.column(i)
            if wanted is not None and \
                    col.path_in_schema.split(".")[0] not in wanted:
                continue
            start = col.data_page_offset
            if col.dictionary_page_offset is not None:
                start = min(start, col.dictionary_page_offset)
            ranges.append((start, col.total_compressed_size))
    return ranges


class _SparseFile:
    """Read-only file over prefetched ``(offset, bytes)`` segments.

    Serves pyarrow's column-chunk reads from memory; anything outside the
    populated segments — pyarrow reading a structure the range planner did
    not anticipate — falls through to one real ranged GET against the store
    (counted ``remote_sparse_fallbacks``; correct, just slower). Wrapped in
    ``pa.PythonFile`` by the engine."""

    def __init__(self, path, size, chunks, engine):
        self._path = path
        self._size = int(size)
        self._segments = sorted((int(off), memoryview(data))
                                for off, data in chunks)
        self._engine = engine
        self._pos = 0
        self._closed = False

    def read(self, nbytes=None):
        if nbytes is None:
            nbytes = self._size - self._pos
        nbytes = max(0, min(int(nbytes), self._size - self._pos))
        if nbytes == 0:
            return b""
        pos = self._pos
        # gather across segments: target-size splitting leaves CONTIGUOUS
        # neighbors, so a column chunk crossing a split boundary still serves
        # from memory (stitched), not from a fallback GET
        parts = []
        need = nbytes
        p = pos
        for start, view in self._segments:
            if need == 0:
                break
            if start > p:
                break  # gap: not covered
            if p < start + len(view):
                take = min(need, start + len(view) - p)
                parts.append(view[p - start:p - start + take])
                p += take
                need -= take
        if need == 0:
            self._pos = pos + nbytes
            if len(parts) == 1:
                return bytes(parts[0])
            return b"".join(bytes(v) for v in parts)
        engine = self._engine
        engine._sparse_fallbacks.inc()
        with engine._lock:
            engine._n["sparse_fallbacks"] += 1
        data = engine._fetch(self._path, pos, nbytes)
        self._pos = pos + len(data)
        return data

    def seek(self, pos, whence=0):
        if whence == 0:
            self._pos = int(pos)
        elif whence == 1:
            self._pos += int(pos)
        elif whence == 2:
            self._pos = self._size + int(pos)
        else:
            raise ValueError("unsupported whence %r" % (whence,))
        self._pos = max(0, min(self._pos, self._size))
        return self._pos

    def tell(self):
        return self._pos

    def size(self):
        return self._size

    def close(self):
        self._closed = True

    @property
    def closed(self):
        return self._closed

    def readable(self):
        return True

    def seekable(self):
        return True

    def writable(self):
        return False


def build_engine(fs, remote_opts, registry=None):
    """Construct the engine + its footer cache per policy: the shared
    process-wide cache when ``footer_cache_bytes`` asks for one, no cache
    (measurable per-read refetch) otherwise. Returns ``None`` when the tier
    is off for this filesystem."""
    if not remote_opts.active_for(fs):
        return None
    footer_cache = None
    if remote_opts.footer_cache_bytes:
        from petastorm_tpu.io.footercache import configure_budget

        footer_cache = configure_budget(remote_opts.footer_cache_bytes)
    return RemoteReadEngine(fs, options=remote_opts, footer_cache=footer_cache,
                            registry=registry)
