"""Process-wide in-memory row-group LRU: hot row groups skip disk AND parse.

``LocalDiskCache`` removes the *network* read on re-epochs but still pays a
file read plus unpickle per hit; for the hottest row groups (small validation
sets iterated every epoch, lookup tables, re-epochs over a cached shard) even
that is wasted work. :class:`MemCache` keeps the **decoded payloads**
(the worker's row lists / column dicts) in one process-wide, byte-budgeted LRU
keyed by the reader's existing ``_cache_key`` — which already encodes path, row
group, schema fields, predicate, filters, drop-partition and device-decode
identity, so an entry can never be served to a mismatched read.

Layering: ``MemCache`` wraps any :class:`petastorm_tpu.cache.CacheBase` (the
disk cache or the null cache) — a miss falls through to the inner cache's
``get`` and the freshly decoded value is admitted on the way back up.

Hits return a **defensive copy** (fresh containers, copied ndarrays): consumers
own their batches and may mutate them (the writable-batch contract of the
default wires), and an aliased cache entry would corrupt every later epoch. The
copy is a straight memcpy — the expensive parts a hit skips are the parquet
parse and codec decode.

The store is process-wide (module-level) so every reader in the process —
including each pool child, which unpickles its worker into its own process —
shares one budget; entries larger than the whole budget are skipped with a
``ptpu_degradations_total{cause="memcache_oversized"}`` entry (the value still
flows to the consumer, uncached).
"""
from __future__ import annotations

import sys
import threading
from collections import OrderedDict

import numpy as np

from petastorm_tpu.cache import CacheBase, NullCache
from petastorm_tpu.obs.log import degradation
from petastorm_tpu.obs.metrics import default_registry


def payload_nbytes(value):
    """Byte estimate of a worker payload (column dict, row list, pyarrow table,
    ndarray, scalars). Conservative-cheap: exact for ndarrays/bytes/tables,
    ``sys.getsizeof`` for the rest — the budget is a guardrail, not an
    allocator."""
    if isinstance(value, np.ndarray):
        if value.dtype == object:
            return int(value.nbytes) + sum(payload_nbytes(v) for v in value.flat)
        return int(value.nbytes)
    if isinstance(value, (bytes, bytearray, memoryview)):
        return len(value)
    if isinstance(value, dict):
        return sum(payload_nbytes(v) for v in value.values()) + 64 * len(value)
    if isinstance(value, (list, tuple)):
        return sum(payload_nbytes(v) for v in value) + 16 * len(value)
    nbytes = getattr(value, "nbytes", None)  # pyarrow.Table and friends
    if isinstance(nbytes, int):
        return nbytes
    return sys.getsizeof(value)


def _defensive_copy(value):
    """Fresh containers + copied ndarrays so a consumer mutating its batch can
    never corrupt the cached original (or vice versa). Immutable leaves
    (bytes, str, numbers) pass through. Object-dtype arrays (ragged/forced
    columns hold per-row ndarrays as ELEMENTS) recurse — ``ndarray.copy()``
    alone would copy the outer array while the element arrays still alias."""
    if isinstance(value, np.ndarray):
        if value.dtype == object:
            out = np.empty(value.shape, dtype=object)
            out_flat, in_flat = out.reshape(-1), value.reshape(-1)
            for i in range(in_flat.size):
                out_flat[i] = _defensive_copy(in_flat[i])
            return out
        return value.copy()
    if isinstance(value, dict):
        return {k: _defensive_copy(v) for k, v in value.items()}
    if isinstance(value, list):
        return [_defensive_copy(v) for v in value]
    if isinstance(value, tuple):
        return tuple(_defensive_copy(v) for v in value)
    return value


class _Store:
    """The process-wide LRU: OrderedDict + byte accounting under one lock."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entries = OrderedDict()  # key -> (value, nbytes)
        self._total = 0
        self._budget = 0
        reg = default_registry()
        self._hits = reg.counter("ptpu_io_memcache_hits_total",
                                 help="row-group reads served from memory")
        self._misses = reg.counter("ptpu_io_memcache_misses_total",
                                   help="memcache misses (fell through to the "
                                        "inner cache / a real read)")
        self._evictions = reg.counter("ptpu_io_memcache_evictions_total",
                                      help="entries LRU-evicted for budget")
        self._bytes_gauge = reg.gauge("ptpu_io_memcache_bytes",
                                      help="decoded payload bytes held in memory")

    def raise_budget(self, budget):
        with self._lock:
            if budget > self._budget:
                self._budget = budget

    def lookup(self, key):
        with self._lock:
            hit = self._entries.get(key)
            if hit is None:
                self._misses.inc()
                return False, None
            self._entries.move_to_end(key)
            self._hits.inc()
            value = hit[0]
        return True, _defensive_copy(value)

    def contains(self, key):
        with self._lock:
            return key in self._entries

    def put(self, key, value):
        """Admit ``value``; returns True when it was stored. The caller must
        then hand its consumer a defensive copy — the stored object must never
        alias a batch the consumer may mutate (the miss-path twin of the
        hit-path copy in :meth:`lookup`)."""
        nbytes = payload_nbytes(value)
        with self._lock:
            if nbytes > self._budget:
                oversized = True
            else:
                oversized = False
                old = self._entries.pop(key, None)
                if old is not None:
                    self._total -= old[1]
                self._entries[key] = (value, nbytes)
                self._total += nbytes
                while self._total > self._budget and self._entries:
                    _, (_, old_bytes) = self._entries.popitem(last=False)
                    self._total -= old_bytes
                    self._evictions.inc()
                self._bytes_gauge.set(self._total)
        if oversized:
            degradation(
                "memcache_oversized",
                "decoded row group of %d bytes exceeds the whole memcache "
                "budget (%d); serving uncached", nbytes, self._budget)
        return not oversized

    def clear(self):
        with self._lock:
            self._entries.clear()
            self._total = 0
            self._bytes_gauge.set(0)

    def stats(self):
        with self._lock:
            count, total = len(self._entries), self._total
        return {
            # 'held_bytes', not 'bytes': the collector exporting these as
            # ptpu_io_<key> must not collide with the registered
            # ptpu_io_memcache_bytes gauge family (duplicate-family scrape)
            "memcache_entries": count,
            "memcache_held_bytes": total,
            "memcache_hits": self._hits.value,
            "memcache_misses": self._misses.value,
            "memcache_evictions": self._evictions.value,
        }


_store_lock = threading.Lock()
_store = None


def shared_store():
    """The process-wide store (created on first use)."""
    global _store
    with _store_lock:
        if _store is None:
            _store = _Store()
        return _store


class MemCache(CacheBase):
    """Byte-budgeted in-memory LRU over decoded row-group payloads, layered in
    front of an inner cache (:class:`LocalDiskCache` or :class:`NullCache`).

    Instances are thin picklable views onto the process-wide store (each pool
    child rebuilds its own store on first use); the budget is the max any
    instance requested. ``clear()`` releases the held bytes — GL-L001 accepts
    it as this type's closer.
    """

    def __init__(self, size_limit_bytes, inner=None, store=None):
        if not size_limit_bytes or int(size_limit_bytes) <= 0:
            raise ValueError("MemCache needs a positive size_limit_bytes; use "
                             "the inner cache alone to disable it")
        self._budget = int(size_limit_bytes)
        self._inner = inner if inner is not None else NullCache()
        #: private-store escape hatch (tests/benchmarks needing isolation from
        #: the process-wide store and its raise-only budget); not picklable —
        #: dropped on pickling, the unpickled instance reverts to the shared one
        self._private_store = store

    def __getstate__(self):
        state = dict(self.__dict__)
        state["_private_store"] = None
        return state

    def _store(self):
        store = self._private_store if self._private_store is not None \
            else shared_store()
        store.raise_budget(self._budget)
        return store

    def get(self, key, fill_cache_func):
        store = self._store()
        hit, value = store.lookup(key)
        if hit:
            return value
        value = self._inner.get(key, fill_cache_func)
        if store.put(key, value):
            # the stored object must not alias the batch we hand out: a
            # consumer mutating it in place (writable-batch contract) would
            # silently poison every later epoch's hit
            return _defensive_copy(value)
        return value

    def contains(self, key):
        return self._store().contains(key) or self._inner.contains(key)

    def clear(self):
        """Release the process-wide store's entries (shared across instances)."""
        self._store().clear()

    def stats(self):
        return self._store().stats()

    def cleanup(self):
        self.clear()
        self._inner.cleanup()
