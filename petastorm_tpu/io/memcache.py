"""Process-wide in-memory row-group LRU: hot row groups skip disk AND parse.

``LocalDiskCache`` removes the *network* read on re-epochs but still pays a
file read plus unpickle per hit; for the hottest row groups (small validation
sets iterated every epoch, lookup tables, re-epochs over a cached shard) even
that is wasted work. :class:`MemCache` keeps the **decoded payloads**
(the worker's row lists / column dicts) in one process-wide, byte-budgeted LRU
keyed by the reader's existing ``_cache_key`` — which already encodes path, row
group, schema fields, predicate, filters, drop-partition and device-decode
identity, so an entry can never be served to a mismatched read.

Layering: ``MemCache`` wraps any :class:`petastorm_tpu.cache.CacheBase` (the
disk cache or the null cache) — a miss falls through to the inner cache's
``get`` and the freshly decoded value is admitted on the way back up.

Serving contract (ISSUE 6, the lease-path rewrite): entries are stored as
READ-ONLY structures under a per-entry :class:`petastorm_tpu.io.lease.Lease`,
and both the miss and the hit path hand out **zero-copy read-only views**
(fresh containers, shared buffers) — no memcpy per hit, no memcpy per admit.
A consumer that mutates a served batch gets an immediate ``ValueError:
assignment destination is read-only`` (fail-loud, same contract as the
``-view`` wires — never silent cache poisoning). The one consumer that
legitimately writes — a host ``TransformSpec`` running user code — escalates
through :meth:`MemCache.get_writable` (copy-on-write: the old defensive deep
copy, charged to the ``memcache_cow`` census site). ``MemCache(...,
writable_hits=True)`` restores the legacy copy-everything behavior wholesale
(the copying baseline ``petastorm-tpu-bench copies`` measures against).

The store is process-wide (module-level) so every reader in the process —
including each pool child, which unpickles its worker into its own process —
shares one budget; entries larger than the whole budget are skipped with a
``ptpu_degradations_total{cause="memcache_oversized"}`` entry (the value still
flows to the consumer, uncached — and stays writable, since nothing aliases
it).
"""
from __future__ import annotations

import sys
import threading
from collections import OrderedDict

import numpy as np

from petastorm_tpu.cache import CacheBase, NullCache
from petastorm_tpu.io.lease import Lease, count_copy, readonly_view
from petastorm_tpu.obs.log import degradation
from petastorm_tpu.obs.metrics import default_registry


def payload_nbytes(value):
    """Byte estimate of a worker payload (column dict, row list, pyarrow table,
    ndarray, scalars). Conservative-cheap: exact for ndarrays/bytes/tables,
    ``sys.getsizeof`` for the rest — the budget is a guardrail, not an
    allocator."""
    if isinstance(value, np.ndarray):
        if value.dtype == object:
            return int(value.nbytes) + sum(payload_nbytes(v) for v in value.flat)
        return int(value.nbytes)
    if isinstance(value, (bytes, bytearray, memoryview)):
        return len(value)
    if isinstance(value, dict):
        return sum(payload_nbytes(v) for v in value.values()) + 64 * len(value)
    if isinstance(value, (list, tuple)):
        return sum(payload_nbytes(v) for v in value) + 16 * len(value)
    nbytes = getattr(value, "nbytes", None)  # pyarrow.Table and friends
    if isinstance(nbytes, int):
        return nbytes
    return sys.getsizeof(value)


def _copied_nbytes(value):
    """Actual buffer bytes a deep copy of ``value`` memcpy's (census measure:
    no container overhead — comparable with the wire sites' raw byte counts)."""
    if isinstance(value, np.ndarray):
        if value.dtype == object:
            return sum(_copied_nbytes(v) for v in value.flat)
        return int(value.nbytes)
    if isinstance(value, (bytes, bytearray, memoryview)):
        return len(value)
    if isinstance(value, dict):
        return sum(_copied_nbytes(v) for v in value.values())
    if isinstance(value, (list, tuple)):
        return sum(_copied_nbytes(v) for v in value)
    return 0


#: leaf types _defensive_copy may pass through untouched (immutable, or numpy
#: scalars which are value-semantics anyway) — resolved once, checked inline in
#: the object-array loop so the escalation hook stays cheap (ISSUE 6 satellite)
_IMMUTABLE_LEAVES = (bytes, str, int, float, complex, bool, type(None),
                     np.generic)


def _defensive_copy(value):
    """Fresh containers + copied ndarrays so a consumer mutating its batch can
    never corrupt the cached original (or vice versa). Since ISSUE 6 this runs
    only as the **copy-on-write escalation hook** (``get_writable`` /
    ``writable_hits=True``), so it must be cheap: non-object ndarrays take the
    single-``copy()`` fast path (one memcpy, no per-element work), and the
    object-array walk (ragged/forced columns hold per-row ndarrays as ELEMENTS
    — an outer ``copy()`` alone would leave them aliased) dispatches each
    element inline instead of recursing through the full type ladder."""
    if isinstance(value, np.ndarray):
        if value.dtype != object:
            return value.copy()  # fast path: one memcpy for the whole column
        out = np.empty(value.shape, dtype=object)
        out_flat, in_flat = out.reshape(-1), value.reshape(-1)
        for i in range(in_flat.size):
            e = in_flat[i]
            if type(e) is np.ndarray and e.dtype != object:
                out_flat[i] = e.copy()  # hot leaf: ragged row tensor
            elif isinstance(e, _IMMUTABLE_LEAVES):
                out_flat[i] = e
            else:
                out_flat[i] = _defensive_copy(e)
        return out
    if isinstance(value, dict):
        return {k: _defensive_copy(v) for k, v in value.items()}
    if isinstance(value, list):
        return [_defensive_copy(v) for v in value]
    if isinstance(value, tuple):
        return tuple(_defensive_copy(v) for v in value)
    return value


class _Store:
    """The process-wide LRU: OrderedDict + byte accounting under one lock.

    Entries hold ``(frozen_value, nbytes, lease)``: the value's ndarrays are
    read-only (frozen at admit), and the per-entry lease carries the
    ``ptpu_lease_*`` accounting — acquired at admit, released at eviction/
    ``clear()`` — so cache-held buffers are visible beside the wire's slab
    leases."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entries = OrderedDict()  # key -> (value, nbytes, lease)
        self._total = 0
        self._budget = 0
        reg = default_registry()
        self._hits = reg.counter("ptpu_io_memcache_hits_total",
                                 help="row-group reads served from memory")
        self._misses = reg.counter("ptpu_io_memcache_misses_total",
                                   help="memcache misses (fell through to the "
                                        "inner cache / a real read)")
        self._evictions = reg.counter("ptpu_io_memcache_evictions_total",
                                      help="entries LRU-evicted for budget")
        self._bytes_gauge = reg.gauge("ptpu_io_memcache_bytes",
                                      help="decoded payload bytes held in memory")

    def raise_budget(self, budget):
        with self._lock:
            if budget > self._budget:
                self._budget = budget

    def set_budget(self, budget):
        """Live budget retune (ISSUE 13): the store is process-wide, so this
        moves the SHARED ceiling — shrinking evicts (LRU-first) down to the
        new budget immediately. Served views stay valid (numpy refcounting);
        the per-entry leases release like any eviction."""
        evicted = []
        with self._lock:
            self._budget = max(0, int(budget))
            while self._total > self._budget and self._entries:
                _, (_, old_bytes, old_lease) = self._entries.popitem(last=False)
                self._total -= old_bytes
                self._evictions.inc()
                evicted.append(old_lease)
            self._bytes_gauge.set(self._total)
        for lease in evicted:
            lease.release()

    @property
    def budget(self):
        with self._lock:
            return self._budget

    def lookup(self, key):
        """(hit?, stored_value) — the STORED read-only structure; the caller
        picks the serve shape (zero-copy views or a CoW escalation copy)."""
        with self._lock:
            hit = self._entries.get(key)
            if hit is None:
                self._misses.inc()
                return False, None
            self._entries.move_to_end(key)
            self._hits.inc()
            value = hit[0]
        return True, value

    def contains(self, key):
        with self._lock:
            return key in self._entries

    def invalidate(self, key):
        """Drop one entry by key (ISSUE 11: a rewritten source file's decoded
        payload must not outlive its generation). Outstanding served views
        stay valid — numpy refcounting keeps the buffers alive; the lease is
        accounting, released like an eviction."""
        with self._lock:
            entry = self._entries.pop(key, None)
            if entry is not None:
                self._total -= entry[1]
                self._bytes_gauge.set(self._total)
        if entry is not None:
            entry[2].release()

    def put(self, key, value, lease=None):
        """Admit ``value`` (already frozen read-only by the caller); returns
        True when it was stored. Because the stored arrays are read-only and
        every serve is a read-only view, storing may SHARE buffers with what
        the consumer receives — mutation is impossible, so the old
        defensive-copy-per-admit is gone.

        ``lease`` carries an externally-owned pin (the arena holder lease for
        a shm-backed entry): the store releases it at eviction/clear exactly
        like its own accounting lease, so an arena entry stays unevictable
        host-wide while this process's cache holds views of it. The caller
        keeps ownership when put returns False (oversized)."""
        nbytes = payload_nbytes(value)
        evicted = []
        with self._lock:
            if nbytes > self._budget:
                oversized = True
            else:
                oversized = False
                old = self._entries.pop(key, None)
                if old is not None:
                    self._total -= old[1]
                    evicted.append(old[2])
                self._entries[key] = (
                    value, nbytes,
                    lease if lease is not None else Lease(kind="memcache"))
                self._total += nbytes
                while self._total > self._budget and self._entries:
                    _, (_, old_bytes, old_lease) = self._entries.popitem(last=False)
                    self._total -= old_bytes
                    self._evictions.inc()
                    evicted.append(old_lease)
                self._bytes_gauge.set(self._total)
        for lease in evicted:
            # safe outside the lock: numpy refcounting keeps an evicted entry's
            # buffers alive for any outstanding served views — the lease here
            # is accounting (ptpu_lease_active mirrors resident entries), not
            # lifetime enforcement
            lease.release()
        if oversized:
            degradation(
                "memcache_oversized",
                "decoded row group of %d bytes exceeds the whole memcache "
                "budget (%d); serving uncached", nbytes, self._budget)
        return not oversized

    def clear(self):
        with self._lock:
            entries, self._entries = self._entries, OrderedDict()
            self._total = 0
            self._bytes_gauge.set(0)
        for _value, _nbytes, lease in entries.values():
            lease.release()

    def stats(self):
        with self._lock:
            count, total = len(self._entries), self._total
        return {
            # 'held_bytes', not 'bytes': the collector exporting these as
            # ptpu_io_<key> must not collide with the registered
            # ptpu_io_memcache_bytes gauge family (duplicate-family scrape)
            "memcache_entries": count,
            "memcache_held_bytes": total,
            # LIVE budget (ISSUE 13 satellite): reports the applied value
            # after a controller retune, not the construction-time one
            "memcache_budget_bytes": self._budget,
            "memcache_hits": self._hits.value,
            "memcache_misses": self._misses.value,
            "memcache_evictions": self._evictions.value,
        }


_store_lock = threading.Lock()
_store = None


def shared_store():
    """The process-wide store (created on first use)."""
    global _store
    with _store_lock:
        if _store is None:
            _store = _Store()
        return _store


class MemCache(CacheBase):
    """Byte-budgeted in-memory LRU over decoded row-group payloads, layered in
    front of an inner cache (:class:`LocalDiskCache` or :class:`NullCache`).

    Instances are thin picklable views onto the process-wide store (each pool
    child rebuilds its own store on first use); the budget is the max any
    instance requested. ``clear()`` releases the held bytes — GL-L001 accepts
    it as this type's closer.

    ``get`` serves zero-copy read-only views; ``get_writable`` is the CoW
    escalation; ``writable_hits=True`` restores the legacy deep-copy-per-serve
    behavior (both directions byte-identical — only mutability and memcpy
    count differ).

    ``arena=`` (an :class:`petastorm_tpu.io.arena.ArenaSpec` or a live
    ``CacheArena``) layers the host-wide shared arena between the local store
    and the inner cache (ISSUE 17): a local miss maps the shared entry as
    zero-copy views pinned by the arena holder lease (released when the local
    entry drops), and a true fill is admitted host-wide on the way back up —
    every other process on the host then serves it without re-decoding. The
    spec is picklable, so pool children carry it through the worker pickle;
    resolution to a mapped arena is lazy per process.
    """

    def __init__(self, size_limit_bytes, inner=None, store=None,
                 writable_hits=False, arena=None):
        if not size_limit_bytes or int(size_limit_bytes) <= 0:
            raise ValueError("MemCache needs a positive size_limit_bytes; use "
                             "the inner cache alone to disable it")
        self._budget = int(size_limit_bytes)
        self._inner = inner if inner is not None else NullCache()
        self._writable_hits = bool(writable_hits)
        #: private-store escape hatch (tests/benchmarks needing isolation from
        #: the process-wide store and its raise-only budget); not picklable —
        #: dropped on pickling, the unpickled instance reverts to the shared one
        self._private_store = store
        if arena is None:
            self._arena_spec, self._arena_obj = None, None
        elif hasattr(arena, "token"):  # ArenaSpec
            self._arena_spec, self._arena_obj = arena, None
        else:  # a live CacheArena (thread pools / the creating reader)
            self._arena_spec, self._arena_obj = arena.spec, arena

    def __getstate__(self):
        state = dict(self.__dict__)
        state["_private_store"] = None
        state["_arena_obj"] = None  # children re-resolve from the spec
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        # worker pickles from pre-arena readers lack the arena fields
        self.__dict__.setdefault("_arena_spec", None)
        self.__dict__.setdefault("_arena_obj", None)

    def _store(self):
        store = self._private_store if self._private_store is not None \
            else shared_store()
        store.raise_budget(self._budget)
        return store

    def _arena(self):
        """The mapped arena for this process, or None (lazy, failure-tolerant:
        an unattachable spec degrades warn-once inside ``resolve``)."""
        if self._arena_spec is None:
            return None
        obj = self._arena_obj
        if obj is not None and not obj._closed:
            return obj
        from petastorm_tpu.io import arena as arena_mod

        obj = arena_mod.resolve(self._arena_spec)
        self._arena_obj = obj
        return obj

    def get(self, key, fill_cache_func, served=None):
        """Zero-copy serve: hits AND the admit path hand out fresh containers
        over the stored READ-ONLY buffers. Only an oversized (uncached) value
        passes through writable. ``served`` (a 1-slot out-list, the tiered
        funnel's attribution channel) is set to ``"arena"`` when the payload
        came off the host-shared mapping rather than this process's store."""
        origin, value = self._fetch(key, fill_cache_func)
        if served is not None and origin in ("arena", "arena_uncached"):
            served[0] = "arena"
        if self._writable_hits:
            # legacy contract: every serve is an owned writable deep copy
            copy = _defensive_copy(value)
            count_copy("memcache_hit" if origin == "mem" else "memcache_admit",
                       _copied_nbytes(copy))
            return copy
        if origin == "uncached":
            return value  # oversized true fill: uncached, nothing aliases it
        return readonly_view(value)

    def _fetch(self, key, fill_cache_func):
        """``(origin, stored_value)`` — the funnel: local store, then the
        host-wide arena, then the inner cache / real fill (admitted back up
        both levels). Origins: ``mem`` local hit; ``arena`` mapped from the
        shared arena and admitted locally; ``arena_uncached`` mapped but the
        local store declined (views stay valid — POSIX mappings outlive the
        name); ``fill`` decoded fresh and admitted; ``uncached`` oversized."""
        store = self._store()
        hit, value = store.lookup(key)
        if hit:
            return "mem", value
        arena_obj = self._arena()
        if arena_obj is not None:
            got = arena_obj.get(("mc", key))
            if got is not None:
                value, lease = got
                if store.put(key, value, lease=lease):
                    return "arena", value
                lease.release()
                return "arena_uncached", value
        value = self._inner.get(key, fill_cache_func)
        frozen = readonly_view(value)
        if arena_obj is not None:
            arena_obj.put(("mc", key), frozen)
        if not store.put(key, frozen):
            return "uncached", value
        return "fill", frozen

    def get_writable(self, key, fill_cache_func, served=None):
        """Copy-on-write escalation: a consumer that will WRITE (host
        TransformSpec) gets an owned writable deep copy of the entry — the one
        remaining memcpy on the memcache path, charged to ``memcache_cow``."""
        origin, value = self._fetch(key, fill_cache_func)
        if served is not None and origin in ("arena", "arena_uncached"):
            served[0] = "arena"
        if origin == "uncached":
            return value  # oversized: uncached and unaliased, already owned
        # anything resident (or arena-mapped) aliases shared buffers —
        # escalate: returning it writable would poison the cached entry
        copy = _defensive_copy(value)
        count_copy("memcache_cow", _copied_nbytes(copy))
        return copy

    def apply_budget(self, size_limit_bytes):
        """Live budget retune (ISSUE 13) — the controller's hot-row-group
        promotion lever: growing the budget lets more hot decoded row groups
        stay resident in the mem tier; shrinking evicts down immediately.
        Moves this instance's budget AND the backing store's shared ceiling
        (the store is process-wide — a retune here is visible to every
        MemCache over it; per-reader isolation needs a private store)."""
        size_limit_bytes = max(1, int(size_limit_bytes))
        self._budget = size_limit_bytes
        store = self._private_store if self._private_store is not None \
            else shared_store()
        store.set_budget(size_limit_bytes)
        return size_limit_bytes

    @property
    def budget(self):
        return self._budget

    def would_admit(self, value):
        """Will :meth:`get`'s admit path actually store ``value``? False for
        oversized payloads (they are served uncached) — the tiered funnel's
        admission policy must not assume the mem tier holds what it in fact
        rejected."""
        return payload_nbytes(value) <= self._budget

    def contains(self, key):
        return self._store().contains(key) or self._inner.contains(key)

    def invalidate(self, key):
        """Keyed invalidation through every layer (ISSUE 11) — including the
        host-shared arena, so a rewritten source file's decoded payload
        cannot be re-mapped by ANY process on the host."""
        self._store().invalidate(key)
        arena_obj = self._arena()
        if arena_obj is not None:
            arena_obj.invalidate(("mc", key))
        self._inner.invalidate(key)

    def clear(self):
        """Release the process-wide store's entries (shared across instances)."""
        self._store().clear()

    def stats(self):
        return self._store().stats()

    def cleanup(self):
        self.clear()
        self._inner.cleanup()
