"""Bounded row-group readahead: the next K reads run while the current table decodes.

A :class:`ReadaheadPool` owns a small IO thread pool and a keyed table of
in-flight/completed background reads. The worker's dispatch layer hands it the
upcoming plan items (``_WorkerBase.prefetch``); when the worker's synchronous
path later asks for the same ``(path, row_group, columns)`` the read is either
done (hit — the worker paid zero read latency) or still in flight (the worker
waits only the *remainder*, recorded as ``io.wait``). Misses fall straight
through to the synchronous read, so the pool can never make a read slower than
the blocking path — and a pool that failed to build degrades the whole feature
to synchronous reads with a ``ptpu_degradations_total{cause=
"readahead_unavailable"}`` entry.

Failure semantics mirror the synchronous path exactly: background single reads
run the worker's full transient-retry loop, and a read that exhausted its
retries re-raises the same exception from :meth:`ReadaheadPool.get` — readahead
must not grant extra retry budget (tests/test_io_retry.py pins this). Only
*cancelled* entries (pool shutdown mid-read) fall back to a synchronous read,
counted as ``cause="readahead_fallback"``.

Bounds: at most ``depth`` background reads pending, and completed-but-unclaimed
tables are LRU-evicted past ``byte_budget`` (a stolen piece's prefetched table,
for example, is reclaimed instead of pinned forever).
"""
from __future__ import annotations

import threading
import time
import weakref
from collections import OrderedDict

from petastorm_tpu.io.coalesce import plan_runs
from petastorm_tpu.obs import provenance as _prov
from petastorm_tpu.obs.log import degradation
from petastorm_tpu.obs.metrics import default_registry

#: pools whose IO threads must be REAPED before interpreter finalization.
#: ``shutdown(wait=False)`` is deliberate for Reader.join (a HUNG read must
#: not block teardown) — but it leaves the IO threads to exit on their own,
#: and they are daemons (they inherit daemon-ness from the executor worker
#: thread whose lazy ``prefetch`` built the pool), so nothing joins them
#: before ``Py_Finalize``. A daemon thread whose thread-state clear is still
#: destroying its thread-local ``ParquetFile`` cache when finalization begins
#: re-enters pyarrow, is force-exited mid-C++ (``PyEval_RestoreThread`` →
#: ``pthread_exit``), and the forced unwind through a noexcept Arrow frame
#: aborts the whole process ("terminate called without an active exception").
#: The exit hook therefore shuts every live pool down and JOINS its threads
#: (bounded — normal reads are milliseconds; a genuinely hung read forfeits
#: the guarantee after the cap rather than hanging exit forever). It must run
#: during *threading* shutdown, not module atexit: concurrent.futures joins
#: every executor thread UNBOUNDEDLY from its own threading-shutdown hook
#: (``_python_exit``), which fires before any ``atexit`` callback — an atexit
#: drain would run after the threads are already dead in the normal case and
#: after a hung read had already wedged ``_python_exit`` in the bad one.
#: ``threading._register_atexit`` callbacks run in reverse registration
#: order, and concurrent.futures registered its hook at import time (before
#: any pool exists), so registering here puts the drain FIRST; threads still
#: alive when the bounded join expires are detached from concurrent.futures'
#: bookkeeping so its unbounded join cannot hang exit on them.
_live_pools_lock = threading.Lock()
_live_pools = weakref.WeakSet()
#: STRONG refs to the executors of shut-down pools whose IO threads may
#: still be exiting. The WeakSet alone has a teardown hole: ``worker.close()``
#: drops the pool reference right after ``shutdown()``, the pool is GC'd out
#: of ``_live_pools``, and the exit drain never joins its still-exiting
#: threads — under CPU contention one can then die mid-``ParquetFile``
#: thread-local cleanup during interpreter finalization (the PR 5 abort,
#: back through the GC window). Entries are pruned once their threads die.
_dying_executors = []
_drain_installed = False


def _install_exit_drain():
    global _drain_installed
    with _live_pools_lock:
        if _drain_installed:
            return
        _drain_installed = True
    # force concurrent.futures' own hook to register BEFORE ours — reversed
    # callback order then runs the drain first, while threads are alive
    import concurrent.futures.thread  # noqa: F401

    register = getattr(threading, "_register_atexit", None)
    if register is not None:
        register(_drain_live_pools)
    else:  # pragma: no cover - Python < 3.9
        import atexit

        atexit.register(_drain_live_pools)


def _executor_threads_alive(executor):
    return any(t.is_alive() for t in getattr(executor, "_threads", ()) or ())


def _drain_live_pools():
    with _live_pools_lock:
        pools = list(_live_pools)
    deadline = time.monotonic() + 10.0
    for pool in pools:
        pool.shutdown()  # cancels pending; only an executing read remains
    for pool in pools:
        pool.drain(max(0.1, deadline - time.monotonic()))
    for pool in pools:
        pool.join_threads(max(0.1, deadline - time.monotonic()))
    # executors of pools already GC'd (their reader closed earlier): their
    # threads exit on their own, but must still be JOINED before
    # finalization or a straggler dies mid-pyarrow cleanup
    with _live_pools_lock:
        dying = list(_dying_executors)
    for executor in dying:
        for t in list(getattr(executor, "_threads", ()) or ()):
            t.join(max(0.05, deadline - time.monotonic()))
    for pool in pools:
        pool.abandon_hung_threads()
    from concurrent.futures import thread as cf_thread

    for executor in dying:
        ReadaheadPool._abandon_pool_threads(executor, cf_thread)


class _CancelledRead(Exception):
    """Internal marker: the pool shut down before this read completed."""


class _Entry:
    __slots__ = ("event", "table", "error", "nbytes", "claimed", "read_span")

    def __init__(self):
        self.event = threading.Event()
        self.table = None
        self.error = None
        self.nbytes = 0
        self.claimed = False
        #: (t0, dur) of the background read that filled this entry — attached
        #: to the claiming item's provenance record (ISSUE 10), so a batch's
        #: attribution sees WHEN its bytes were actually read
        self.read_span = None


def request_key(piece, columns):
    """Identity of one background read: file, row group, the piece's
    generation token (ISSUE 11: two generations of one file — e.g. an old-gen
    item and its deferred rewrite replacement — must never share a prefetched
    table), and the exact column selection (``None`` = all columns)."""
    return (piece.path, piece.row_group, getattr(piece, "generation", None),
            None if columns is None else tuple(columns))


class ReadaheadPool:
    """Per-process prefetcher for row-group reads.

    ``read_fn(piece, columns) -> table`` is the worker's retrying synchronous
    read; ``read_run_fn(pieces, columns) -> [tables]`` (optional) is its
    coalesced ranged read for adjacent row groups. Shut down with
    :meth:`shutdown` — the pool owns live threads (GL-L001 tracks it).
    """

    def __init__(self, read_fn, read_run_fn=None, depth=3, byte_budget=256 << 20,
                 io_threads=2, coalesce=True, coalesce_max_run=4,
                 wait_timeout_s=300.0, registry=None, gap_ok=None):
        from concurrent.futures import ThreadPoolExecutor

        self._read_fn = read_fn
        self._read_run_fn = read_run_fn
        #: optional byte-gap predicate for non-adjacent run merging (ISSUE 8:
        #: built from the footer cache's row-group spans when the remote tier
        #: is active — a sub-min-gap hole is cheaper than a second GET)
        self._gap_ok = gap_ok
        self._depth = max(1, int(depth))
        # 0/negative = unbounded ('no byte cap', matching the memcache_bytes=0
        # convention of 0 being special) — NOT 'hold zero bytes', which would
        # silently veto every schedule() while readahead reports enabled
        self._byte_budget = int(byte_budget) if int(byte_budget) > 0 else None
        self._wait_timeout_s = wait_timeout_s
        self._coalesce = bool(coalesce) and read_run_fn is not None
        self._max_run = max(1, int(coalesce_max_run))
        self._io_threads = max(1, int(io_threads))
        #: IO pools replaced by a live apply_io_threads() resize: their
        #: still-executing reads finish on their own threads, which must be
        #: joined by the exit drain like the active pool's (see the module
        #: comment — a daemon IO thread dying mid-ParquetFile-cleanup during
        #: interpreter finalization aborts the process)
        self._retired_pools = []
        self._lock = threading.Lock()
        self._entries = OrderedDict()  # key -> _Entry (insertion = FIFO age)
        self._pending = 0
        self._held_bytes = 0
        self._closed = False
        self._tracer = None
        self._health = None  # optional HealthMonitor: per-IO-thread heartbeats
        self._active_reads = 0
        self._idle = threading.Event()  # set whenever no read task is running
        self._idle.set()
        # per-instance tallies for stats() (the registry counters below are
        # process-wide families shared across pools — right for export, wrong
        # for one reader's io_stats())
        self._n_hits = 0
        self._n_misses = 0
        self._n_evictions = 0
        self._n_coalesced_reads = 0
        self._n_coalesced_items = 0
        #: cumulative seconds (this pool): background read time, foreground
        #: wait on in-flight prefetches, and miss-fallback sync reads — the
        #: wait + sync sum is the EXPOSED read latency, the controller's
        #: grow-readahead trigger scale
        self._read_s_cum = 0.0
        self._wait_s_cum = 0.0
        self._sync_s_cum = 0.0
        self._pool = ThreadPoolExecutor(max_workers=self._io_threads,
                                        thread_name_prefix="ptpu-io")
        reg = registry if registry is not None else default_registry()
        self._hits = reg.counter("ptpu_io_readahead_hits_total",
                                 help="foreground reads served by readahead")
        self._misses = reg.counter("ptpu_io_readahead_misses_total",
                                   help="foreground reads not prefetched")
        self._evictions = reg.counter("ptpu_io_readahead_evictions_total",
                                      help="prefetched tables dropped for budget")
        self._coalesced_reads = reg.counter(
            "ptpu_io_coalesced_reads_total",
            help="ranged reads that merged >1 adjacent row group")
        self._coalesced_items = reg.counter(
            "ptpu_io_coalesced_items_total",
            help="row groups delivered through merged ranged reads")
        self._depth_gauge = reg.gauge("ptpu_io_readahead_depth",
                                      help="background reads currently in flight")
        self._bytes_gauge = reg.gauge(
            "ptpu_io_readahead_bytes",
            help="completed prefetched table bytes awaiting consumption")
        self._read_hist = reg.histogram("ptpu_io_read_seconds",
                                        help="background row-group read latency")
        self._wait_hist = reg.histogram(
            "ptpu_io_wait_seconds",
            help="foreground wait on an in-flight prefetched read")
        with _live_pools_lock:
            _live_pools.add(self)
        _install_exit_drain()

    def set_trace(self, tracer):
        """Attach a :class:`petastorm_tpu.trace.TraceRecorder`: background reads
        record ``io.readahead`` spans, foreground waits ``io.wait``."""
        self._tracer = tracer

    def set_health(self, monitor):
        """Attach a :class:`petastorm_tpu.obs.health.HealthMonitor`: every IO
        thread heartbeats per background read (busy while reading, ``wait:``
        between tasks), so a read hung against a wedged filesystem trips the
        stall watchdog instead of silently pinning its thread."""
        self._health = monitor

    # -- live knobs (ISSUE 13) ----------------------------------------------------------
    #
    # The sanctioned retune seam: the controller's KnobSet calls these; the
    # pool's IoOptions are never mutated (graftlint GL-C004). All three are
    # thread-safe against concurrent schedule()/get()/_read_task traffic.

    def apply_depth(self, depth):
        """Retune the in-flight background-read bound live. Takes effect at
        the next ``schedule()`` (in-flight reads above a SHRUNK bound finish
        normally — the bound gates admission, it never cancels work)."""
        depth = max(1, int(depth))
        with self._lock:
            self._depth = depth
            self._evict_over_budget()  # the entry-count cap scales with depth
            self._bytes_gauge.set(self._held_bytes)
        return depth

    def apply_byte_budget(self, nbytes):
        """Retune the completed-unclaimed byte budget live (<= 0 = uncapped,
        the construction convention); over-budget tables are evicted now."""
        nbytes = int(nbytes)
        with self._lock:
            self._byte_budget = nbytes if nbytes > 0 else None
            self._evict_over_budget()
            self._bytes_gauge.set(self._held_bytes)
        return 0 if self._byte_budget is None else self._byte_budget

    def apply_io_threads(self, io_threads):
        """Resize the IO thread pool live via a pool swap: new reads submit
        to a fresh pool of the target size; the old pool's queued/executing
        reads finish on its own threads (``shutdown(wait=False)`` without
        cancellation — a retune must never fail reads), which the exit drain
        still joins through ``_retired_pools``."""
        from concurrent.futures import ThreadPoolExecutor

        io_threads = max(1, int(io_threads))
        with self._lock:
            if self._closed or io_threads == self._io_threads:
                return self._io_threads
            old = self._pool
            self._pool = ThreadPoolExecutor(max_workers=io_threads,
                                            thread_name_prefix="ptpu-io")
            self._io_threads = io_threads
            # prune retired pools whose threads have all exited — repeated
            # retunes over a long run must not accumulate dead executors
            self._retired_pools = [
                p for p in self._retired_pools
                if any(t.is_alive()
                       for t in getattr(p, "_threads", ()) or ())]
            self._retired_pools.append(old)
        old.shutdown(wait=False)
        return io_threads

    @property
    def depth(self):
        return self._depth

    @property
    def byte_budget(self):
        return self._byte_budget

    @property
    def io_threads(self):
        return self._io_threads

    def _all_pools(self):
        with self._lock:
            return [self._pool] + list(self._retired_pools)

    # -- scheduling ---------------------------------------------------------------------

    def schedule(self, requests):
        """Queue background reads for ``[(piece, columns), ...]``.

        Already-queued keys are skipped (repeat hints are near-free), the
        pending count is capped at ``depth``, and nothing is queued while the
        completed-unclaimed bytes exceed the budget. Returns the number of
        reads actually queued.
        """
        with self._lock:
            if self._closed or (self._byte_budget is not None
                                and self._held_bytes >= self._byte_budget):
                return 0
            capacity = self._depth - self._pending
            if capacity <= 0:
                return 0
            fresh = []
            for piece, columns in requests:
                if len(fresh) >= capacity:
                    break
                # columns normalized to a hashable tuple once, here: it is the
                # entry key AND the run-grouping key downstream
                columns = None if columns is None else tuple(columns)
                key = request_key(piece, columns)
                if key in self._entries:
                    continue
                self._entries[key] = _Entry()
                fresh.append((piece, columns))
            self._pending += len(fresh)
            self._depth_gauge.set(self._pending)
        if not fresh:
            return 0
        submitted = set()
        try:
            runs = plan_runs(fresh, self._max_run, gap_ok=self._gap_ok) \
                if self._coalesce \
                else [([piece], columns) for piece, columns in fresh]
            for pieces, columns in runs:
                self._pool.submit(self._read_task, pieces, columns)
                submitted.update(request_key(p, columns) for p in pieces)
        except BaseException:
            # roll back the never-submitted registrations: an entry whose read
            # was never issued would park a future get() on an event nobody sets
            with self._lock:
                for piece, columns in fresh:
                    key = request_key(piece, columns)
                    if key not in submitted and \
                            self._entries.pop(key, None) is not None:
                        self._pending -= 1
                self._depth_gauge.set(self._pending)
            raise
        return len(fresh)

    def _read_task(self, pieces, columns):
        with self._lock:
            self._active_reads += 1
            self._idle.clear()
        try:
            self._read_task_body(pieces, columns)
        finally:
            with self._lock:
                self._active_reads -= 1
                if self._active_reads == 0:
                    self._idle.set()

    def _read_task_body(self, pieces, columns):
        monitor = self._health
        hb = None
        if monitor is not None:
            # registered per IO thread (names are unique per thread; register
            # is idempotent so repeat tasks reuse the slot)
            hb = monitor.register(
                "io.%s" % threading.current_thread().name, "io")
            hb.beat("read")
        t0 = time.perf_counter()
        tables = error = None
        try:
            from petastorm_tpu import chaos as _chaos

            if _chaos.ACTIVE is not None:
                _chaos.ACTIVE.hit(
                    "io.readahead",
                    key="%s:%s" % (pieces[0].path,
                                   ",".join(str(p.row_group) for p in pieces)))
            if len(pieces) == 1:
                tables = [self._read_fn(pieces[0], columns)]
            else:
                tables = self._read_run_fn(pieces, columns)
                self._coalesced_reads.inc()
                self._coalesced_items.inc(len(pieces))
                with self._lock:
                    self._n_coalesced_reads += 1
                    self._n_coalesced_items += len(pieces)
        except Exception as e:  # noqa: BLE001 — stored, re-raised at get()
            error = e
            # routed through the degradation log as cause=io_retry (ISSUE 7):
            # a background read that exhausted the shared retry budget used to
            # fail silently here and only surface at the foreground get() —
            # retry storms are now countable in petastorm-tpu-stats and the
            # flight record even when the consumer never claims the entry
            degradation(
                "io_retry",
                "background readahead read of %s row group(s) %s failed (%s); "
                "the foreground read will re-raise it", pieces[0].path,
                [p.row_group for p in pieces], e)
        dur = time.perf_counter() - t0
        self._read_hist.observe(dur)
        tracer = self._tracer
        if tracer is not None:
            tracer.add("io.readahead", t0, dur)
        with self._lock:
            self._read_s_cum += dur
            if not self._closed:
                # in-flight count tracks the READS, not the entries: an entry a
                # timed-out waiter already popped still finished its IO here
                self._pending -= len(pieces)
            for i, piece in enumerate(pieces):
                entry = self._entries.get(request_key(piece, columns))
                if entry is None or entry.event.is_set():
                    # shut down / abandoned while reading — or the key was
                    # abandoned (get timeout) and RE-scheduled, and the fresh
                    # read already filled the new entry: a second fill would
                    # double-count held bytes (the claimer subtracts once)
                    continue
                if error is not None:
                    entry.error = error
                else:
                    entry.table = tables[i]
                    entry.nbytes = getattr(tables[i], "nbytes", 0)
                    entry.read_span = (t0, dur)
                    self._held_bytes += entry.nbytes
                entry.event.set()
            self._evict_over_budget()
            self._depth_gauge.set(self._pending)
            self._bytes_gauge.set(self._held_bytes)
        if hb is not None:
            hb.wait("idle")  # parked in the pool queue until the next task

    def _evict_over_budget(self):
        """Age out completed, unclaimed entries. Caller MUST hold ``self._lock``
        (all call sites do — the analyzer cannot see cross-method ownership).

        Two bounds: tables past the BYTE budget (oldest first), and total
        completed entries past a small COUNT cap. The count cap is what keeps
        abandoned entries from living forever: a stolen piece's prefetched
        table is consumed by nobody, and a read that failed after retries
        leaves an error entry with ``nbytes == 0`` that the byte budget alone
        would never touch (exception objects pin traceback frames — a real
        leak over a long multi-epoch run)."""
        cap = max(8, 4 * self._depth)
        for key in list(self._entries):
            over_bytes = self._byte_budget is not None \
                and self._held_bytes > self._byte_budget
            over_count = len(self._entries) > cap
            if not over_bytes and not over_count:
                break
            entry = self._entries[key]
            if entry.claimed or not entry.event.is_set():
                continue  # a getter owns it / the read is still in flight
            if entry.table is None and not over_count:
                continue  # error entries free no bytes; only the cap drops them
            del self._entries[key]
            self._held_bytes -= entry.nbytes  # graftlint: disable=GL-C001
            self._n_evictions += 1  # graftlint: disable=GL-C001
            self._evictions.inc()

    # -- consumption --------------------------------------------------------------------

    def get(self, piece, columns):
        """The prefetched table for ``(piece, columns)``, or ``None`` on a miss
        (caller reads synchronously). Blocks for an in-flight read (the
        ``io.wait`` remainder). A read that *failed* re-raises its exception —
        the background read already spent the retry budget; a read cancelled by
        shutdown returns ``None`` with a degradation entry (synchronous
        fallback)."""
        key = request_key(piece, columns)
        with self._lock:
            entry = self._entries.get(key)
            if entry is None or entry.claimed:
                self._n_misses += 1
                self._misses.inc()
                if _prov.ACTIVE is not None:
                    _prov.annotate("readahead", "miss")
                return None
            entry.claimed = True
        t0 = time.perf_counter()
        completed = entry.event.wait(self._wait_timeout_s)
        wait = time.perf_counter() - t0
        self._wait_hist.observe(wait)
        with self._lock:
            self._wait_s_cum += wait
        tracer = self._tracer
        if tracer is not None and wait > 1e-6:
            tracer.add("io.wait", t0, wait)
        with self._lock:
            self._entries.pop(key, None)
            if entry.table is not None:
                self._held_bytes -= entry.nbytes
                self._bytes_gauge.set(self._held_bytes)
                self._n_hits += 1
                self._hits.inc()
                if _prov.ACTIVE is not None:
                    # the background read's span (overlapped with earlier
                    # items' decode — the fold charges only serialized time)
                    # plus the claimer's residual wait, on the claiming item
                    _prov.annotate("readahead", "hit")
                    if entry.read_span is not None:
                        _prov.add_span("io.readahead", entry.read_span[0],
                                       entry.read_span[1])
                    if wait > 1e-6:
                        _prov.add_span("io.readahead_wait", t0, wait)
                return entry.table
        if not completed:
            # hung background read: abandon the entry (its late completion is
            # discarded above) and read synchronously
            degradation("readahead_fallback",
                        "readahead read of %s row group %d still pending after "
                        "%.0fs; reading synchronously",
                        piece.path, piece.row_group, self._wait_timeout_s)
            return None
        if isinstance(entry.error, _CancelledRead):
            degradation("readahead_fallback",
                        "readahead cancelled for %s row group %d; reading "
                        "synchronously", piece.path, piece.row_group)
            return None
        raise entry.error

    # -- lifecycle ----------------------------------------------------------------------

    def note_sync_read(self, seconds):
        """Account a miss-fallback synchronous read (the worker times it):
        exposed latency the prefetch window failed to hide."""
        with self._lock:
            self._sync_s_cum += seconds

    def drain(self, timeout_s):
        """Wait (bounded) until no read task is executing. Returns True when
        idle."""
        return self._idle.wait(timeout_s)

    def join_threads(self, timeout_s):
        """Join the IO threads (bounded) — the process-exit path. The threads
        are daemons (see the module exit-hook comment), so this is the only
        join they ever get; it must complete before interpreter finalization
        or their dying thread-local ``ParquetFile`` cleanup aborts inside
        pyarrow."""
        deadline = time.monotonic() + max(0.0, timeout_s)
        for pool in self._all_pools():
            for t in list(getattr(pool, "_threads", ()) or ()):
                t.join(max(0.05, deadline - time.monotonic()))

    def abandon_hung_threads(self):
        """Detach still-alive IO threads from interpreter-exit bookkeeping
        after a bounded join expired: a read hung against a wedged filesystem
        forfeits the clean-teardown guarantee instead of hanging exit forever.
        Two unbounded waits would otherwise block on such a thread —
        concurrent.futures' ``_python_exit`` join (all executor threads,
        daemon or not), and ``threading._shutdown``'s tstate-lock wait (IO
        threads spawned from a non-daemon context, e.g. a pool lazily built
        on the consumer thread)."""
        try:
            from concurrent.futures import thread as cf_thread

            for pool in self._all_pools():
                self._abandon_pool_threads(pool, cf_thread)
        except Exception:
            pass  # graftlint: disable=GL-O002 (best-effort private-API detach at interpreter exit)

    @staticmethod
    def _abandon_pool_threads(pool, cf_thread):
        try:
            for t in list(getattr(pool, "_threads", ()) or ()):
                if not t.is_alive():
                    continue
                cf_thread._threads_queues.pop(t, None)
                lock = getattr(t, "_tstate_lock", None)
                shutdown_locks = getattr(threading, "_shutdown_locks", None)
                if lock is not None and shutdown_locks is not None:
                    with threading._shutdown_locks_lock:
                        shutdown_locks.discard(lock)
        except Exception:
            pass  # graftlint: disable=GL-O002 (best-effort private-API detach at interpreter exit)

    def shutdown(self):
        """Cancel pending reads, release waiters, stop the IO threads.
        Idempotent; the worker calls it from ``close()`` (Reader.join).
        Deliberately does NOT wait for an in-flight read (a hung object-store
        read must not block Reader.join); the module-level exit hook drains
        in-flight reads before interpreter teardown instead."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            entries = list(self._entries.values())
            self._entries.clear()
            self._pending = 0
            self._held_bytes = 0
            self._depth_gauge.set(0)
            self._bytes_gauge.set(0)
        for entry in entries:
            if entry.table is None and entry.error is None:
                entry.error = _CancelledRead()
            entry.event.set()
        executors = self._all_pools()
        for executor in executors:
            executor.shutdown(wait=False, cancel_futures=True)
        # strong-ref the executors until their threads die (pruned here and
        # by later shutdowns): the pool object itself is usually dropped by
        # worker.close() right after this call, and the exit drain must
        # still be able to join any straggling IO thread
        with _live_pools_lock:
            _dying_executors[:] = [ex for ex in _dying_executors
                                   if _executor_threads_alive(ex)]
            _dying_executors.extend(executors)

    def stats(self):
        """Live gauges/counters for ``Reader.io_stats()`` (thread/dummy pools —
        process-pool children keep theirs in their own registries)."""
        with self._lock:
            # key names deliberately differ from this pool's REGISTERED gauge
            # families (ptpu_io_readahead_depth/_bytes): Reader.io_stats feeds
            # a collector that exports ptpu_io_<key>, and a collision would
            # emit duplicate Prometheus families (scrapers reject the scrape)
            return {
                "readahead_pending": self._pending,
                "readahead_held_bytes": self._held_bytes,
                # LIVE knob values (ISSUE 13 satellite): after a controller
                # retune these must report the applied value, not the
                # construction-time configuration
                "readahead_depth_limit": self._depth,
                "readahead_byte_budget": self._byte_budget or 0,
                "readahead_io_threads": self._io_threads,
                # cumulative seconds: window deltas of the EXPOSED series
                # (foreground waits + miss-fallback sync reads) are the
                # controller's exposed-read-latency scale — the time share
                # of wall-clock the prefetch window failed to hide
                "readahead_read_s": round(self._read_s_cum, 4),
                "readahead_wait_s": round(self._wait_s_cum, 4),
                "readahead_exposed_s": round(
                    self._wait_s_cum + self._sync_s_cum, 4),
                "readahead_hits": self._n_hits,
                "readahead_misses": self._n_misses,
                "readahead_evictions": self._n_evictions,
                "coalesced_reads": self._n_coalesced_reads,
                "coalesced_items": self._n_coalesced_items,
            }
