"""Bounded row-group readahead: the next K reads run while the current table decodes.

A :class:`ReadaheadPool` owns a small IO thread pool and a keyed table of
in-flight/completed background reads. The worker's dispatch layer hands it the
upcoming plan items (``_WorkerBase.prefetch``); when the worker's synchronous
path later asks for the same ``(path, row_group, columns)`` the read is either
done (hit — the worker paid zero read latency) or still in flight (the worker
waits only the *remainder*, recorded as ``io.wait``). Misses fall straight
through to the synchronous read, so the pool can never make a read slower than
the blocking path — and a pool that failed to build degrades the whole feature
to synchronous reads with a ``ptpu_degradations_total{cause=
"readahead_unavailable"}`` entry.

Failure semantics mirror the synchronous path exactly: background single reads
run the worker's full transient-retry loop, and a read that exhausted its
retries re-raises the same exception from :meth:`ReadaheadPool.get` — readahead
must not grant extra retry budget (tests/test_io_retry.py pins this). Only
*cancelled* entries (pool shutdown mid-read) fall back to a synchronous read,
counted as ``cause="readahead_fallback"``.

Bounds: at most ``depth`` background reads pending, and completed-but-unclaimed
tables are LRU-evicted past ``byte_budget`` (a stolen piece's prefetched table,
for example, is reclaimed instead of pinned forever).
"""
from __future__ import annotations

import threading
import time
from collections import OrderedDict

from petastorm_tpu.io.coalesce import plan_runs
from petastorm_tpu.obs.log import degradation
from petastorm_tpu.obs.metrics import default_registry


class _CancelledRead(Exception):
    """Internal marker: the pool shut down before this read completed."""


class _Entry:
    __slots__ = ("event", "table", "error", "nbytes", "claimed")

    def __init__(self):
        self.event = threading.Event()
        self.table = None
        self.error = None
        self.nbytes = 0
        self.claimed = False


def request_key(piece, columns):
    """Identity of one background read: file, row group, and the exact column
    selection (``None`` = all columns)."""
    return (piece.path, piece.row_group,
            None if columns is None else tuple(columns))


class ReadaheadPool:
    """Per-process prefetcher for row-group reads.

    ``read_fn(piece, columns) -> table`` is the worker's retrying synchronous
    read; ``read_run_fn(pieces, columns) -> [tables]`` (optional) is its
    coalesced ranged read for adjacent row groups. Shut down with
    :meth:`shutdown` — the pool owns live threads (GL-L001 tracks it).
    """

    def __init__(self, read_fn, read_run_fn=None, depth=3, byte_budget=256 << 20,
                 io_threads=2, coalesce=True, coalesce_max_run=4,
                 wait_timeout_s=300.0, registry=None):
        from concurrent.futures import ThreadPoolExecutor

        self._read_fn = read_fn
        self._read_run_fn = read_run_fn
        self._depth = max(1, int(depth))
        # 0/negative = unbounded ('no byte cap', matching the memcache_bytes=0
        # convention of 0 being special) — NOT 'hold zero bytes', which would
        # silently veto every schedule() while readahead reports enabled
        self._byte_budget = int(byte_budget) if int(byte_budget) > 0 else None
        self._wait_timeout_s = wait_timeout_s
        self._coalesce = bool(coalesce) and read_run_fn is not None
        self._max_run = max(1, int(coalesce_max_run))
        self._lock = threading.Lock()
        self._entries = OrderedDict()  # key -> _Entry (insertion = FIFO age)
        self._pending = 0
        self._held_bytes = 0
        self._closed = False
        self._tracer = None
        # per-instance tallies for stats() (the registry counters below are
        # process-wide families shared across pools — right for export, wrong
        # for one reader's io_stats())
        self._n_hits = 0
        self._n_misses = 0
        self._n_evictions = 0
        self._n_coalesced_reads = 0
        self._n_coalesced_items = 0
        self._pool = ThreadPoolExecutor(max_workers=max(1, int(io_threads)),
                                        thread_name_prefix="ptpu-io")
        reg = registry if registry is not None else default_registry()
        self._hits = reg.counter("ptpu_io_readahead_hits_total",
                                 help="foreground reads served by readahead")
        self._misses = reg.counter("ptpu_io_readahead_misses_total",
                                   help="foreground reads not prefetched")
        self._evictions = reg.counter("ptpu_io_readahead_evictions_total",
                                      help="prefetched tables dropped for budget")
        self._coalesced_reads = reg.counter(
            "ptpu_io_coalesced_reads_total",
            help="ranged reads that merged >1 adjacent row group")
        self._coalesced_items = reg.counter(
            "ptpu_io_coalesced_items_total",
            help="row groups delivered through merged ranged reads")
        self._depth_gauge = reg.gauge("ptpu_io_readahead_depth",
                                      help="background reads currently in flight")
        self._bytes_gauge = reg.gauge(
            "ptpu_io_readahead_bytes",
            help="completed prefetched table bytes awaiting consumption")
        self._read_hist = reg.histogram("ptpu_io_read_seconds",
                                        help="background row-group read latency")
        self._wait_hist = reg.histogram(
            "ptpu_io_wait_seconds",
            help="foreground wait on an in-flight prefetched read")

    def set_trace(self, tracer):
        """Attach a :class:`petastorm_tpu.trace.TraceRecorder`: background reads
        record ``io.readahead`` spans, foreground waits ``io.wait``."""
        self._tracer = tracer

    # -- scheduling ---------------------------------------------------------------------

    def schedule(self, requests):
        """Queue background reads for ``[(piece, columns), ...]``.

        Already-queued keys are skipped (repeat hints are near-free), the
        pending count is capped at ``depth``, and nothing is queued while the
        completed-unclaimed bytes exceed the budget. Returns the number of
        reads actually queued.
        """
        with self._lock:
            if self._closed or (self._byte_budget is not None
                                and self._held_bytes >= self._byte_budget):
                return 0
            capacity = self._depth - self._pending
            if capacity <= 0:
                return 0
            fresh = []
            for piece, columns in requests:
                if len(fresh) >= capacity:
                    break
                # columns normalized to a hashable tuple once, here: it is the
                # entry key AND the run-grouping key downstream
                columns = None if columns is None else tuple(columns)
                key = request_key(piece, columns)
                if key in self._entries:
                    continue
                self._entries[key] = _Entry()
                fresh.append((piece, columns))
            self._pending += len(fresh)
            self._depth_gauge.set(self._pending)
        if not fresh:
            return 0
        submitted = set()
        try:
            runs = plan_runs(fresh, self._max_run) if self._coalesce \
                else [([piece], columns) for piece, columns in fresh]
            for pieces, columns in runs:
                self._pool.submit(self._read_task, pieces, columns)
                submitted.update(request_key(p, columns) for p in pieces)
        except BaseException:
            # roll back the never-submitted registrations: an entry whose read
            # was never issued would park a future get() on an event nobody sets
            with self._lock:
                for piece, columns in fresh:
                    key = request_key(piece, columns)
                    if key not in submitted and \
                            self._entries.pop(key, None) is not None:
                        self._pending -= 1
                self._depth_gauge.set(self._pending)
            raise
        return len(fresh)

    def _read_task(self, pieces, columns):
        t0 = time.perf_counter()
        tables = error = None
        try:
            if len(pieces) == 1:
                tables = [self._read_fn(pieces[0], columns)]
            else:
                tables = self._read_run_fn(pieces, columns)
                self._coalesced_reads.inc()
                self._coalesced_items.inc(len(pieces))
                with self._lock:
                    self._n_coalesced_reads += 1
                    self._n_coalesced_items += len(pieces)
        except Exception as e:  # noqa: BLE001 — stored, re-raised at get()
            error = e
        dur = time.perf_counter() - t0
        self._read_hist.observe(dur)
        tracer = self._tracer
        if tracer is not None:
            tracer.add("io.readahead", t0, dur)
        with self._lock:
            if not self._closed:
                # in-flight count tracks the READS, not the entries: an entry a
                # timed-out waiter already popped still finished its IO here
                self._pending -= len(pieces)
            for i, piece in enumerate(pieces):
                entry = self._entries.get(request_key(piece, columns))
                if entry is None or entry.event.is_set():
                    # shut down / abandoned while reading — or the key was
                    # abandoned (get timeout) and RE-scheduled, and the fresh
                    # read already filled the new entry: a second fill would
                    # double-count held bytes (the claimer subtracts once)
                    continue
                if error is not None:
                    entry.error = error
                else:
                    entry.table = tables[i]
                    entry.nbytes = getattr(tables[i], "nbytes", 0)
                    self._held_bytes += entry.nbytes
                entry.event.set()
            self._evict_over_budget()
            self._depth_gauge.set(self._pending)
            self._bytes_gauge.set(self._held_bytes)

    def _evict_over_budget(self):
        """Age out completed, unclaimed entries. Caller MUST hold ``self._lock``
        (all call sites do — the analyzer cannot see cross-method ownership).

        Two bounds: tables past the BYTE budget (oldest first), and total
        completed entries past a small COUNT cap. The count cap is what keeps
        abandoned entries from living forever: a stolen piece's prefetched
        table is consumed by nobody, and a read that failed after retries
        leaves an error entry with ``nbytes == 0`` that the byte budget alone
        would never touch (exception objects pin traceback frames — a real
        leak over a long multi-epoch run)."""
        cap = max(8, 4 * self._depth)
        for key in list(self._entries):
            over_bytes = self._byte_budget is not None \
                and self._held_bytes > self._byte_budget
            over_count = len(self._entries) > cap
            if not over_bytes and not over_count:
                break
            entry = self._entries[key]
            if entry.claimed or not entry.event.is_set():
                continue  # a getter owns it / the read is still in flight
            if entry.table is None and not over_count:
                continue  # error entries free no bytes; only the cap drops them
            del self._entries[key]
            self._held_bytes -= entry.nbytes  # graftlint: disable=GL-C001
            self._n_evictions += 1  # graftlint: disable=GL-C001
            self._evictions.inc()

    # -- consumption --------------------------------------------------------------------

    def get(self, piece, columns):
        """The prefetched table for ``(piece, columns)``, or ``None`` on a miss
        (caller reads synchronously). Blocks for an in-flight read (the
        ``io.wait`` remainder). A read that *failed* re-raises its exception —
        the background read already spent the retry budget; a read cancelled by
        shutdown returns ``None`` with a degradation entry (synchronous
        fallback)."""
        key = request_key(piece, columns)
        with self._lock:
            entry = self._entries.get(key)
            if entry is None or entry.claimed:
                self._n_misses += 1
                self._misses.inc()
                return None
            entry.claimed = True
        t0 = time.perf_counter()
        completed = entry.event.wait(self._wait_timeout_s)
        wait = time.perf_counter() - t0
        self._wait_hist.observe(wait)
        tracer = self._tracer
        if tracer is not None and wait > 1e-6:
            tracer.add("io.wait", t0, wait)
        with self._lock:
            self._entries.pop(key, None)
            if entry.table is not None:
                self._held_bytes -= entry.nbytes
                self._bytes_gauge.set(self._held_bytes)
                self._n_hits += 1
                self._hits.inc()
                return entry.table
        if not completed:
            # hung background read: abandon the entry (its late completion is
            # discarded above) and read synchronously
            degradation("readahead_fallback",
                        "readahead read of %s row group %d still pending after "
                        "%.0fs; reading synchronously",
                        piece.path, piece.row_group, self._wait_timeout_s)
            return None
        if isinstance(entry.error, _CancelledRead):
            degradation("readahead_fallback",
                        "readahead cancelled for %s row group %d; reading "
                        "synchronously", piece.path, piece.row_group)
            return None
        raise entry.error

    # -- lifecycle ----------------------------------------------------------------------

    def shutdown(self):
        """Cancel pending reads, release waiters, stop the IO threads.
        Idempotent; the worker calls it from ``close()`` (Reader.join)."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            entries = list(self._entries.values())
            self._entries.clear()
            self._pending = 0
            self._held_bytes = 0
            self._depth_gauge.set(0)
            self._bytes_gauge.set(0)
        for entry in entries:
            if entry.table is None and entry.error is None:
                entry.error = _CancelledRead()
            entry.event.set()
        self._pool.shutdown(wait=False, cancel_futures=True)

    def stats(self):
        """Live gauges/counters for ``Reader.io_stats()`` (thread/dummy pools —
        process-pool children keep theirs in their own registries)."""
        with self._lock:
            # key names deliberately differ from this pool's REGISTERED gauge
            # families (ptpu_io_readahead_depth/_bytes): Reader.io_stats feeds
            # a collector that exports ptpu_io_<key>, and a collision would
            # emit duplicate Prometheus families (scrapers reject the scrape)
            return {
                "readahead_pending": self._pending,
                "readahead_held_bytes": self._held_bytes,
                "readahead_hits": self._n_hits,
                "readahead_misses": self._n_misses,
                "readahead_evictions": self._n_evictions,
                "coalesced_reads": self._n_coalesced_reads,
                "coalesced_items": self._n_coalesced_items,
            }
