"""Shared Parquet footer/statistics cache: each file's metadata is read once.

Before ISSUE 8, every worker thread's private ``ParquetFile`` LRU
(``reader.py`` ``_WorkerBase._parquet_file``) re-read and re-parsed each
file's footer on first touch — N workers × M threads × F files footer GETs
against an object store, for bytes that never change ("Optimizing
High-Throughput Distributed Data Pipelines for Reproducible Deep Learning at
Scale", PAPERS.md, makes the metadata plane the first thing to cache).
:class:`FooterCache` is one process-wide, byte-budgeted store of **parsed**
``pyarrow.parquet.FileMetaData`` keyed by ``(path, size-or-etag)``:

- ``_WorkerBase._parquet_file`` passes the cached metadata into
  ``pq.ParquetFile(source, metadata=...)`` — pyarrow then issues **zero**
  footer reads at open (verified: the open touches the file only at
  ``read_row_group*`` time, and only at the column-chunk ranges).
- The planner's footer-scan fallback (``metadata.load_row_groups``) populates
  the same store, so predicate-pushdown statistics and the workers' reads
  share one footer parse per file per process.
- The remote engine (:mod:`petastorm_tpu.io.remote`) fills misses with ranged
  GETs against the file *tail* (footer-length trailer first), never a full
  open — and row-group **byte spans** derived from the metadata drive its
  gap coalescing.

Validation: entries carry the file size observed at parse time; a later open
whose handle reports a different size invalidates the entry (counted
``ptpu_io_footer_cache_invalidations_total``). Object stores expose this as
the etag/generation; pyarrow's filesystem API gives us size-for-free from the
open handle, which catches the realistic mutation (a re-written dataset) with
zero extra round trips. Same-size in-place rewrites — not a thing object
stores can even express non-atomically — are documented as unseen.

Host-wide sharing (ISSUE 17): when this process has a mapped
:mod:`petastorm_tpu.io.arena`, each admitted footer's **serialized thrift
blob** is published under ``("ft", path)`` with the size/stat identity as the
generation token, and a local miss consults the arena before touching
storage — parse-on-map, memoized per process by the local LRU. The whole host
then pays ONE footer read per file instead of one per process.
"""
from __future__ import annotations

import threading
import zlib
from collections import OrderedDict

from petastorm_tpu.obs.metrics import default_registry


def metadata_crc(metadata):
    """crc32 fingerprint of a parsed footer's layout AND statistics facts (row
    counts, byte sizes, per-column chunk offsets/sizes, recorded min/max) —
    the content half of a piece's generation token (ISSUE 11). Catches the
    rewrite that size+mtime cannot: a file regenerated to the same length
    with a colliding mtime still moves its column-chunk offsets/sizes or its
    recorded statistics (the statistics matter for the adversarial case —
    two constant-valued columns compress to byte-identical layouts, but
    their min/max differ)."""
    h = zlib.crc32(("%s|%s|%s" % (metadata.num_rows, metadata.num_row_groups,
                                  metadata.serialized_size)).encode("ascii"))
    for i in range(metadata.num_row_groups):
        rgmd = metadata.row_group(i)
        h = zlib.crc32(("%s|%s" % (rgmd.num_rows,
                                   rgmd.total_byte_size)).encode("ascii"), h)
        for c in range(rgmd.num_columns):
            col = rgmd.column(c)
            h = zlib.crc32(("%s|%s|%s" % (
                col.data_page_offset, col.dictionary_page_offset,
                col.total_compressed_size)).encode("ascii"), h)
            try:
                st = col.statistics
                if st is not None and st.has_min_max:
                    h = zlib.crc32(("%r|%r" % (st.min, st.max)).encode(
                        "utf-8", "replace"), h)
            except Exception:  # noqa: BLE001 — exotic logical types: layout
                pass  # graftlint: disable=GL-O002 (facts above still fold in)
    return h & 0xFFFFFFFF

#: parsed FileMetaData are a few KB to a few hundred KB (wide schemas); the
#: default budget holds ~1k typical ImageNet-Parquet footers
DEFAULT_BUDGET_BYTES = 64 << 20


def _host_arena():
    """This process's mapped cache arena, or None (lazy — the footer cache is
    a module singleton, so it rides :func:`petastorm_tpu.io.arena.process_arena`
    rather than a pickled spec)."""
    from petastorm_tpu.io import arena as arena_mod

    return arena_mod.process_arena()


def _arena_gen(size, stat_token):
    """The arena generation token for a footer blob: the stat identity when
    known (ISSUE 11), else the observed file size — the same validation
    ladder :meth:`FooterCache.lookup` applies locally."""
    if stat_token is not None:
        return "st:%s" % (stat_token,)
    if size is not None:
        return "sz:%d" % int(size)
    return None


def _serialize_metadata(metadata):
    """The footer's thrift bytes (what ``pq.read_metadata`` parses), or None —
    serialization failure just keeps the footer process-local."""
    try:
        import pyarrow as pa

        sink = pa.BufferOutputStream()
        metadata.write_metadata_file(sink)
        return sink.getvalue().to_pybytes()
    except Exception:  # noqa: BLE001 — exotic metadata: stay local
        return None


def _parse_metadata_blob(blob):
    try:
        import pyarrow as pa
        import pyarrow.parquet as pq

        return pq.read_metadata(pa.BufferReader(blob))
    except Exception:  # noqa: BLE001 — torn/foreign blob: treat as a miss
        return None


class FooterEntry:
    """One cached footer: the parsed metadata plus derived planning facts."""

    __slots__ = ("metadata", "size", "nbytes", "num_row_groups",
                 "row_group_rows", "_spans", "stat_token", "_crc")

    def __init__(self, metadata, size, stat_token=None):
        self.metadata = metadata
        self.size = int(size) if size is not None else None
        #: the file's stat identity ("<size>.<mtime_ns>") observed when this
        #: footer was parsed — generation-token validation (ISSUE 11); None
        #: when the caller had no stat to offer (size-only validation applies)
        self.stat_token = stat_token
        self._crc = None
        # serialized thrift size ~ resident parse size (cheap, stable proxy)
        try:
            self.nbytes = int(metadata.serialized_size) or 4096
        except Exception:  # noqa: BLE001 - budget is a guardrail, not an allocator
            self.nbytes = 4096
        self.num_row_groups = metadata.num_row_groups
        self.row_group_rows = tuple(
            metadata.row_group(i).num_rows for i in range(self.num_row_groups))
        self._spans = None

    def row_group_span(self, rg):
        """(start, end) byte span of one row group's column chunks — the unit
        the remote engine's byte-gap coalescing reasons about."""
        if self._spans is None:
            spans = []
            for i in range(self.num_row_groups):
                rgmd = self.metadata.row_group(i)
                start = None
                end = 0
                for c in range(rgmd.num_columns):
                    col = rgmd.column(c)
                    first = col.data_page_offset
                    if col.dictionary_page_offset is not None:
                        first = min(first, col.dictionary_page_offset)
                    start = first if start is None else min(start, first)
                    end = max(end, first + col.total_compressed_size)
                spans.append((start or 0, end))
            self._spans = tuple(spans)
        return self._spans[rg]

    @property
    def crc(self):
        """Lazy :func:`metadata_crc` of this entry's footer (computed once)."""
        if self._crc is None:
            self._crc = metadata_crc(self.metadata)
        return self._crc


class FooterCache:
    """Process-wide byte-budgeted LRU of parsed Parquet footers.

    One instance per process (module-level, like the memcache store): pool
    children each build their own on first use. ``clear()`` releases the held
    bytes — graftlint GL-L001 accepts it as this type's closer.
    """

    def __init__(self, budget_bytes=DEFAULT_BUDGET_BYTES, registry=None):
        self._lock = threading.Lock()
        self._entries = OrderedDict()  # path -> FooterEntry
        self._total = 0
        self._budget = max(0, int(budget_bytes))
        reg = registry if registry is not None else default_registry()
        self._hits = reg.counter(
            "ptpu_io_footer_cache_hits_total",
            help="ParquetFile opens served a cached parsed footer")
        self._misses = reg.counter(
            "ptpu_io_footer_cache_misses_total",
            help="footer reads+parses that went to storage")
        self._evictions = reg.counter(
            "ptpu_io_footer_cache_evictions_total",
            help="parsed footers dropped for budget")
        self._invalidations = reg.counter(
            "ptpu_io_footer_cache_invalidations_total",
            help="cached footers dropped because the file changed size")
        self._bytes_gauge = reg.gauge(
            "ptpu_io_footer_cache_bytes", help="parsed footer bytes held")

    def lookup(self, path, size=None, stat_token=None):
        """The cached :class:`FooterEntry` for ``path``, or ``None``.

        ``size`` (when the caller knows the file's current length — free from
        an open pyarrow handle) validates the entry; a mismatch invalidates
        and misses. ``stat_token`` (the "<size>.<mtime_ns>" half of a
        generation token, ISSUE 11) validates harder: an entry parsed under a
        different stat identity — or under none at all — misses, so a
        same-size rewrite can never serve its predecessor's parsed footer."""
        with self._lock:
            entry = self._entries.get(path)
            stale = False
            if entry is not None and size is not None \
                    and entry.size is not None and entry.size != int(size):
                stale = True
            if entry is not None and stat_token is not None \
                    and entry.stat_token != stat_token:
                stale = True
            if stale:
                del self._entries[path]
                self._total -= entry.nbytes
                self._bytes_gauge.set(self._total)
                self._invalidations.inc()
                entry = None
            if entry is None:
                self._misses.inc()
                return None
            self._entries.move_to_end(path)
            self._hits.inc()
            return entry

    def peek(self, path):
        """The cached entry without touching the hit/miss counters (and
        without size validation — remote callers have no handle to validate
        against; the read path that does, :meth:`lookup`, validates).
        Bumps LRU recency."""
        with self._lock:
            entry = self._entries.get(path)
            if entry is not None:
                self._entries.move_to_end(path)
            return entry

    def count_hit(self):
        """Counter hook for callers composing :meth:`peek` into their own
        hit/miss protocol (the remote engine's footer plane)."""
        self._hits.inc()

    def count_miss(self):
        self._misses.inc()

    def invalidate(self, path):
        """Drop the entry for ``path`` (transient-IO retry: the file may have
        been replaced, and the retry must replan from a fresh footer — the
        same reason ``_evict_parquet_file`` drops the open handle)."""
        with self._lock:
            entry = self._entries.pop(path, None)
            if entry is not None:
                self._total -= entry.nbytes
                self._bytes_gauge.set(self._total)
                self._invalidations.inc()
        arena_obj = _host_arena()
        if arena_obj is not None:
            # the replaced file's blob must not be re-mapped by ANY process
            arena_obj.invalidate(("ft", path))

    def put(self, path, metadata, size=None, stat_token=None, _share=True):
        """Admit a parsed footer; returns its :class:`FooterEntry`. Unless
        the footer just CAME from the arena (``_share=False``), its serialized
        blob is also published host-wide."""
        if _share:
            arena_obj = _host_arena()
            if arena_obj is not None:
                blob = _serialize_metadata(metadata)
                if blob is not None:
                    arena_obj.put_bytes(("ft", path), blob,
                                        gen=_arena_gen(size, stat_token))
        entry = FooterEntry(metadata, size, stat_token=stat_token)
        with self._lock:
            old = self._entries.pop(path, None)
            if old is not None:
                self._total -= old.nbytes
            if self._budget and entry.nbytes > self._budget:
                # a footer bigger than the whole budget: serve it to the
                # caller uncached (same convention as memcache_oversized)
                self._bytes_gauge.set(self._total)
                return entry
            self._entries[path] = entry
            self._total += entry.nbytes
            while self._budget and self._total > self._budget and \
                    len(self._entries) > 1:
                _, evicted = self._entries.popitem(last=False)
                self._total -= evicted.nbytes
                self._evictions.inc()
            self._bytes_gauge.set(self._total)
        return entry

    def get(self, fs, path, source=None, stat_token=None):
        """The footer for ``path``: cached, or read+parsed from ``source``
        (an open pyarrow input file — its ``size()`` doubles as the
        validation token) or from a fresh ``fs.open_input_file``.
        ``stat_token`` additionally pins the entry to a stat identity
        (generation-token reads, ISSUE 11)."""
        size = None
        if source is not None:
            try:
                size = source.size()
            except Exception:  # noqa: BLE001 - validation token is best-effort
                size = None
        entry = self.lookup(path, size, stat_token=stat_token)
        if entry is not None:
            return entry
        # host-shared plane: another process may have parsed this footer
        # already — map its serialized blob, parse once locally, skip storage
        arena_obj = _host_arena()
        if arena_obj is not None:
            blob = arena_obj.get_bytes(("ft", path),
                                       gen=_arena_gen(size, stat_token))
            if blob is not None:
                metadata = _parse_metadata_blob(blob)
                if metadata is not None:
                    return self.put(path, metadata, size,
                                    stat_token=stat_token, _share=False)
        import pyarrow.parquet as pq

        if source is not None:
            pos = source.tell()
            metadata = pq.read_metadata(source)
            source.seek(pos)
        else:
            with fs.open_input_file(path) as f:
                size = f.size()
                metadata = pq.read_metadata(f)
        return self.put(path, metadata, size, stat_token=stat_token)

    def contains(self, path):
        with self._lock:
            return path in self._entries

    def clear(self):
        with self._lock:
            self._entries.clear()
            self._total = 0
            self._bytes_gauge.set(0)

    def stats(self):
        with self._lock:
            count, total = len(self._entries), self._total
        return {
            "footer_cache_entries": count,
            "footer_cache_held_bytes": total,
            "footer_cache_hits": self._hits.value,
            "footer_cache_misses": self._misses.value,
            "footer_cache_evictions": self._evictions.value,
            "footer_cache_invalidations": self._invalidations.value,
        }


_shared_lock = threading.Lock()
_shared = None


def shared_footer_cache():
    """The process-wide cache (created on first use; budget raised on demand
    by :func:`configure_budget`)."""
    global _shared
    with _shared_lock:
        if _shared is None:
            _shared = FooterCache()
        return _shared


def configure_budget(budget_bytes):
    """Raise the shared cache's budget (never lowers — instances share it,
    same convention as the memcache store's ``raise_budget``)."""
    cache = shared_footer_cache()
    with cache._lock:
        if budget_bytes > cache._budget:
            cache._budget = int(budget_bytes)
    return cache
